"""Streaming window feeder: ship capture drains to the aggregation device
DURING the window.

This is the production realization of the boundary the bench measures
(bench.py "steady-state close"): the reference's BPF map absorbs samples
in kernel as they happen (bpf/cpu/cpu.bpf.c:110-116), so its window close
never re-ships the window; here each once-a-second drain is fed to the
dict aggregator's device table as it lands (H2D + the probe/accumulate
kernel ride the otherwise-idle window), and the profiler's window close
is just close_window() — one pack kernel, one packed fetch.

Safety model (SURVEY.md section 7 hard part #5 — device trouble must not
stall the capture loop):

  * Every feed runs under a daemon-thread watchdog with a SHORT timeout
    (the polling thread is stalled while a feed runs; perf rings are
    smaller than a window, so a long stall wraps them and loses samples).
    A failure or hang disables the feeder for a capped-exponential number
    of WINDOWS (2, 4, ... up to 32): mid-window the feeder never retries
    (a wedged device would stall the polling thread again next drain),
    but at window boundaries it re-probes, so a transient hiccup — a
    tunnel blip, a slow compile — costs a few one-shot windows rather
    than forfeiting streaming for the process lifetime. Re-enable waits
    for device_blocked() to clear first (see below).
  * An abandoned (timed-out) feed may still be EXECUTING inside the
    aggregator. Until it actually returns, the aggregator must not be
    touched from any other thread: device_blocked() reports this, and
    the profiler's one-shot path raises into its own watchdog/fallback
    machinery instead of racing the abandoned call (the CPU fallback
    aggregator shares no state with the dict).
  * At window close the fed mass is checked against the snapshot's total;
    any mismatch (a feed died mid-window, a drain raced the boundary)
    discards the fed accumulator and re-aggregates the full snapshot
    one-shot — exactness never depends on the streaming path.

The drain tee and the window boundary both run on the profiler thread
(the sampler's poll() invokes the tee synchronously); only the watchdog
helper threads are extra, and they never mutate feeder state.
"""

from __future__ import annotations

import threading
import time

from parca_agent_tpu.capture.formats import WindowSnapshot
from parca_agent_tpu.capture.live import (
    columns_to_snapshot,
    mapping_table_for_pids,
)
from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.log import get_logger

_log = get_logger("streaming")


class StreamingWindowFeeder:
    """Per-drain feed glue between a LiveSampler (FP mode) and a
    DictAggregator. Wire `sampler.on_drain = feeder.on_drain` and pass
    the feeder to CPUProfiler(streaming_feeder=...)."""

    def __init__(self, aggregator, maps_cache, objs_cache,
                 feed_timeout_s: float = 3.0,
                 first_feed_timeout_s: float = 60.0,
                 reprobe_base_windows: int = 2,
                 reprobe_max_windows: int = 32,
                 prebuild_period_ns: int = 0,
                 prebuild_budget_s: float = 0.25,
                 quarantine=None):
        self._agg = aggregator
        self._maps = maps_cache
        self._objs = objs_cache
        # Ingest containment: the per-drain mini-table build reads the
        # same untrusted /proc inputs as the window-end build; poisoned
        # pids are charged and skipped per drain (runtime/quarantine.py).
        self._quarantine = quarantine
        self._timeout = feed_timeout_s
        # The very FIRST feed attempt of the process gets the longer
        # budget: it includes the XLA compile of the feed program (tens
        # of seconds on a TPU backend, more through a tunnel), so a
        # compile-blind short timeout would trip on EVERY cold start and
        # streaming could never engage at all. The long budget applies
        # exactly once — if that attempt times out (device wedged from
        # boot), every later re-probe runs under the SHORT timeout, so a
        # dead device costs one long capture-loop stall, not one per
        # cooldown. A timed-out-but-healthy first feed keeps compiling
        # in its abandoned daemon thread, so a later 3 s re-probe still
        # lands on the warm program cache and succeeds.
        self._first_timeout = max(feed_timeout_s, first_feed_timeout_s)
        self._first_attempted = False
        self._fed_total = 0          # mass fed into the open window
        self._inflight: threading.Event | None = None  # abandoned feed
        self.disabled = False        # not feeding (cooling down)
        self._cooldown = 0           # windows until re-probe
        self._backoff_base = max(1, reprobe_base_windows)
        self._backoff_max = max(self._backoff_base, reprobe_max_windows)
        self._backoff = self._backoff_base  # next cooldown length
        # Statics amortization: with an encoder attached, each successful
        # feed is followed by a BUDGETED WindowEncoder.build_statics pass,
        # so the pid population discovered during the window has its pprof
        # static sections built while the window is still open — bounding
        # the close-time statics transient (a cold 50k-pid first window
        # otherwise pays the full build inside the close) to one budget.
        # Pure host numpy, and race-free by construction: the sampler's
        # poll() invokes the tee synchronously on the profiler thread,
        # and the profiler's encode also runs on the profiler thread
        # (outside the device watchdog) — tee and encode literally cannot
        # overlap. external_blocked gates the remaining hazard: an
        # abandoned DEVICE aggregation call that shares registry state.
        self._encoder = None
        self._prebuild_fn = None
        self._prebuild_period = prebuild_period_ns
        self._prebuild_budget = prebuild_budget_s
        # Optional external gate (the profiler wires its hang-watchdog
        # state here): while an ABANDONED AGGREGATION call may still be
        # executing inside take_window_if_complete()/window_counts(),
        # neither the aggregator nor the encoder (which reads the
        # aggregator's registry) may be touched from the polling thread,
        # so on_drain skips entirely (the incomplete fed mass then makes
        # the window fall back, which is exactly right).
        self.external_blocked = None
        self.stats = {"drains_fed": 0, "windows_streamed": 0,
                      "windows_fallback": 0, "reprobes": 0,
                      "statics_prebuilt": 0, "last_close_s": 0.0,
                      # Flight-recorder feed/fetch spans (runtime/
                      # trace.py): capture-thread seconds spent in this
                      # window's drain tees, and whether the LAST window
                      # actually streamed (gates the fetch span — a
                      # fallback window must not re-record a stale
                      # last_close_s).
                      "last_window_feed_s": 0.0,
                      "last_window_streamed": 0,
                      # Double-buffer overlap accounting (docs/perf.md
                      # "sub-RTT close"): per-window capture-thread
                      # seconds spent DISPATCHING feeds (the device work
                      # overlaps capture) vs SETTLING the deferred miss
                      # checks (the residual wait, ~a completion check
                      # between drains).
                      "last_window_dispatch_s": 0.0,
                      "last_window_settle_s": 0.0,
                      # Ingest-wall split (docs/perf.md "ingest wall"):
                      # capture-thread seconds this window spent HASHING
                      # feed batches vs COALESCING them to (stack,
                      # weight) pairs — the two costs the native kernel
                      # and the fold exist to shrink. Popped, not read,
                      # like dispatch/settle: a stale value must never
                      # re-count into a later window's spans.
                      "last_window_hash_s": 0.0,
                      "last_window_coalesce_s": 0.0,
                      # Feed endgame (docs/perf.md): capture-thread
                      # seconds this window spent in the cross-drain
                      # carry match (the h1-keyed cache that folds
                      # repeated stacks host-side instead of
                      # re-dispatching them every drain).
                      "last_window_carry_s": 0.0}
        self._window_feed_s = 0.0
        self._window_dispatch_s = 0.0
        self._window_settle_s = 0.0
        self._window_hash_s = 0.0
        self._window_coalesce_s = 0.0
        self._window_carry_s = 0.0

    def _discard_open_window(self) -> None:
        """Drop the aggregator's open-window state across buffer flips:
        fed device mass, host pending corrections, and (on swap-aware
        aggregators) any deferred feed-miss check — dropping those too is
        what keeps recovery exact under double-buffering, since a stale
        miss check settling into a NEW window would inject the discarded
        window's corrections."""
        discard = getattr(self._agg, "discard_open_window", None)
        if discard is not None:
            discard()
            return
        self._agg._fed_total = 0
        self._agg._pending = []
        self._agg._needs_reset = True

    def attach_encoder(self, encoder, prebuild=None) -> None:
        """Wire the profiler's WindowEncoder for statics amortization.
        `prebuild(period_ns, budget_s)` overrides WHERE the budgeted
        build runs: the encode pipeline passes request_prebuild so the
        drain tick only enqueues and the build lands on the encoder
        thread (its thread-ownership contract); by default the build
        runs inline on the polling thread, as before."""
        self._encoder = encoder
        self._prebuild_fn = prebuild

    def _enter_cooldown(self, why: str) -> None:
        """Disable feeding for a capped-exponential number of windows
        (the single degradation path for feed failures, hangs, and
        injected crashes alike — chaos must degrade exactly like real
        trouble)."""
        self.disabled = True
        self._cooldown = self._backoff
        self._backoff = min(self._backoff * 2, self._backoff_max)
        _log.warn(why + "; one-shot window aggregation for the next "
                  "windows", cooldown_windows=self._cooldown)

    def device_blocked(self) -> bool:
        """True while an abandoned feed may still be executing inside the
        aggregator (nothing else may touch it until then)."""
        if self._inflight is None:
            return False
        if self._inflight.is_set():
            self._inflight = None
            return False
        return True

    # -- drain tee (called inside sampler.poll on the profiler thread) -------

    # palint: capture-path — runs synchronously inside the sampler's
    # poll() on the profiler thread; feed work here must be dispatch-
    # only (the aggregator's seeded feed carries the same contract).
    def on_drain(self, cols) -> None:
        if self.disabled:
            return
        if self.external_blocked is not None and self.external_blocked():
            return
        try:
            # Chaos site: the drain tick runs synchronously inside the
            # sampler's poll(), so an injected crash must degrade (the
            # feeder's own cooldown path), never escape into capture.
            faults.inject("actor.feeder")
        except Exception:  # noqa: BLE001 - injected crash -> cooldown
            self._enter_cooldown("injected feeder crash")
            return
        import numpy as np

        # v1d chunks are 6 columns; v1h chunks (capture-side hash carry)
        # tail the drain-computed h1/h2/h3 triple.
        pids, tids, ulen, klen, stacks, counts = cols[:6]
        hashes = tuple(cols[6:9]) if len(cols) >= 9 else None
        if not len(pids):
            return
        t_feed0 = time.perf_counter()
        try:
            try:
                table = mapping_table_for_pids(
                    self._maps, self._objs, np.unique(pids).tolist(),
                    quarantine=self._quarantine)
            except Exception as e:  # noqa: BLE001 - a poisoned maps file
                # (PoisonInput surfaces here only without a registry) must
                # cost this DRAIN, not the capture loop: skip the feed; the
                # fed-mass mismatch makes the window one-shot, exactly
                # right.
                _log.warn("drain mapping build failed; skipping feed",
                          error=repr(e))
                return
            mini = columns_to_snapshot(pids, tids, ulen, klen, stacks,
                                       table, 0, 0, weights=counts,
                                       hashes=hashes)
            if hashes is not None:
                mini, hashes = mini
            if len(mini) == 0:
                return
            if self._fed_total == 0:
                # First feed of a new window: a one-shot fallback window
                # ran window_counts() on this same aggregator between the
                # boundary and now, leaving ITS feed_dispatch/feed_settle
                # timings behind — discard them so the pop below can't
                # credit them to this window's overlap accounting.
                tim = getattr(self._agg, "timings", None)
                if tim is not None:
                    tim.pop("feed_dispatch", None)
                    tim.pop("feed_settle", None)
                    tim.pop("feed_hash", None)
                    tim.pop("feed_coalesce", None)
                    tim.pop("feed_carry", None)
            if self._fed_total == 0 \
                    and (getattr(self._agg, "_fed_total", 0)
                         or getattr(self._agg, "_pending", None)):
                # First feed of a new window with residual open-window
                # state: a one-shot failed partway (its feed dispatched
                # mass and/or registered host-side pending rows, its close
                # never ran). Discard it all — device acc via the reset
                # flag, host mirrors directly — exactly as window_counts
                # guards its own entry (aggregator/dict.py). Without this
                # the residue would ride into the streamed close and
                # inflate counts past the feeder's own fed-mass gate
                # ("_pending" survives an acc reset: the flag only zeroes
                # the device accumulator).
                self._discard_open_window()
            if not self._feed_guarded(mini, hashes):
                # Do NOT try again this window: a wedged device would
                # stall the capture loop on every subsequent drain.
                # Re-probe only at a window boundary, after a
                # capped-exponential cooldown.
                self._enter_cooldown("streaming feed failed")
                return
            # Split the feed's capture-thread cost into dispatch (launch
            # the probe kernel; its device execution overlaps capture)
            # and settle (the PREVIOUS feed's deferred miss check — by
            # now a completion check, not a kernel wait). Popped, not
            # read: feed_settle is only written when an inflight check
            # existed, and a stale value must not re-count.
            tim = getattr(self._agg, "timings", None)
            if tim is not None:
                self._window_dispatch_s += tim.pop("feed_dispatch", 0.0)
                self._window_settle_s += tim.pop("feed_settle", 0.0)
                self._window_hash_s += tim.pop("feed_hash", 0.0)
                self._window_coalesce_s += tim.pop("feed_coalesce", 0.0)
                self._window_carry_s += tim.pop("feed_carry", 0.0)
            self._fed_total += mini.total_samples()
            self.stats["drains_fed"] += 1
            if self._encoder is not None and self._prebuild_period:
                try:
                    if self._prebuild_fn is not None:
                        self._prebuild_fn(self._prebuild_period,
                                          self._prebuild_budget)
                    else:
                        self._encoder.build_statics(
                            self._prebuild_period,
                            budget_s=self._prebuild_budget)
                    self.stats["statics_prebuilt"] += 1
                except Exception as e:  # noqa: BLE001 - never fail the tee
                    _log.warn("statics prebuild failed", error=repr(e))
        finally:
            # Capture-thread seconds this window spent feeding (the
            # flight recorder's feed span reads the per-window total).
            self._window_feed_s += time.perf_counter() - t_feed0

    def _feed_guarded(self, mini: WindowSnapshot, hashes=None) -> bool:
        """One feed under the shared abandonable guard (utils/
        bounded.py — palint bounded-call: this was the last hand-rolled
        copy of the spawn/join/abandon dance PR 5 unified)."""
        from parca_agent_tpu.utils.bounded import bounded_call

        timeout = self._first_timeout if not self._first_attempted \
            else self._timeout
        self._first_attempted = True
        status, out, done, _box = bounded_call(
            lambda: self._agg.feed(mini, hashes=hashes), timeout,
            thread_name="stream-feed")
        if status == "hang":
            # Abandoned: the call may still be mutating the aggregator.
            self._inflight = done
            _log.error("streaming feed hung; abandoning",
                       timeout_s=timeout)
            return False
        if status == "err":
            _log.warn("streaming feed error", error=repr(out))
            return False
        return True

    # -- window boundary (profiler iteration) --------------------------------

    def take_window_if_complete(self, snapshot: WindowSnapshot):
        """If every drain of the window was fed and the fed mass equals
        the snapshot's, return the closed exact counts; else None (the
        caller one-shots the snapshot). Either way the feeder is reset
        for the next window."""
        fed = self._fed_total
        self._fed_total = 0
        self.stats["last_window_feed_s"] = self._window_feed_s
        self._window_feed_s = 0.0
        self.stats["last_window_dispatch_s"] = self._window_dispatch_s
        self._window_dispatch_s = 0.0
        self.stats["last_window_settle_s"] = self._window_settle_s
        self._window_settle_s = 0.0
        self.stats["last_window_hash_s"] = self._window_hash_s
        self._window_hash_s = 0.0
        self.stats["last_window_coalesce_s"] = self._window_coalesce_s
        self._window_coalesce_s = 0.0
        self.stats["last_window_carry_s"] = self._window_carry_s
        self._window_carry_s = 0.0
        self.stats["last_window_streamed"] = 0
        if snapshot.period_ns:
            self._prebuild_period = snapshot.period_ns
        if self.disabled:
            self.stats["windows_fallback"] += 1
            self._cooldown -= 1
            # Re-probe here, at the boundary — never mid-window — and
            # only once any abandoned feed has actually returned (the
            # aggregator may not be touched before then).
            if self._cooldown <= 0 and not self.device_blocked():
                self.disabled = False
                self.stats["reprobes"] += 1
                # The device accumulator may hold residual mass from a
                # one-shot window_counts that failed AFTER its feed
                # dispatched (close raised -> CPU fallback, _needs_reset
                # left False), plus host-pending corrections and a
                # deferred miss check from that feed. Discard all of it
                # so the first streamed feed starts from a clean window.
                self._discard_open_window()
                _log.info("streaming feeder re-enabled; probing next "
                          "window")
            return None
        if fed != snapshot.total_samples():
            # A drain raced the window boundary or a tee was skipped:
            # exactness rules, stream the next window instead. Discard
            # the whole partial window — including any deferred miss
            # check, which would otherwise settle its corrections into
            # the NEXT window.
            self.stats["windows_fallback"] += 1
            self._discard_open_window()
            return None
        t0 = time.perf_counter()
        counts = self._agg.close_window(copy=False)
        self.stats["windows_streamed"] += 1
        self.stats["last_window_streamed"] = 1
        self.stats["last_close_s"] = time.perf_counter() - t0
        # The close settled the window's final feed (and paid its
        # dispatch bookkeeping) AFTER the boundary reset above — pop the
        # timings into the window that just closed, or they'd leak into
        # the next window's first drain.
        tim = getattr(self._agg, "timings", None)
        if tim is not None:
            self.stats["last_window_dispatch_s"] += tim.pop(
                "feed_dispatch", 0.0)
            self.stats["last_window_settle_s"] += tim.pop(
                "feed_settle", 0.0)
            # hash/coalesce are feed-time-only writes, already popped by
            # the drains — popped again here purely so a stale value
            # can never survive into the next window's accounting.
            self.stats["last_window_hash_s"] += tim.pop("feed_hash", 0.0)
            self.stats["last_window_coalesce_s"] += tim.pop(
                "feed_coalesce", 0.0)
            self.stats["last_window_carry_s"] += tim.pop(
                "feed_carry", 0.0)
        self._backoff = self._backoff_base  # healthy again: reset backoff
        return counts
