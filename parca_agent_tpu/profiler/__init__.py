"""Profiler runtime (reference layer L1, pkg/profiler)."""

from parca_agent_tpu.profiler.cpu import CPUProfiler, ProfilerMetrics

__all__ = ["CPUProfiler", "ProfilerMetrics"]
