"""The CPU profiler actor: the 10-second iteration loop.

Role of the reference's pkg/profiler/cpu/cpu.go Run + obtainProfiles
(cpu.go:189-384): every profiling duration, drain the capture source into
a WindowSnapshot, aggregate (pluggable backend — the north-star seam),
symbolize kernel/JIT frames, label, encode pprof, write, and kick off
debuginfo uploads. An iteration failure is non-fatal: logged, surfaced via
last_error, and the loop continues (cpu.go:326-330, SURVEY.md section 5.3).

The capture source protocol is `poll() -> WindowSnapshot | None` (replay,
synthetic, or live sampler); `None` ends the run loop — the replay-driven
agent exits cleanly after the last window, the live sampler never returns
None while running.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Protocol

from parca_agent_tpu.aggregator.base import Aggregator, PidProfile
from parca_agent_tpu.capture.formats import WindowSnapshot
from parca_agent_tpu.pprof.builder import build_pprof
from parca_agent_tpu.utils.log import get_logger

_log = get_logger("profiler")


class CaptureSource(Protocol):
    def poll(self) -> WindowSnapshot | None: ...


@dataclasses.dataclass
class ProfilerMetrics:
    """Counter names mirror the reference's observable metric contract
    (pkg/profiler/cpu/metrics.go:22-65, SURVEY.md section 5.5)."""

    attempts_total: int = 0
    errors_total: int = 0
    profiles_written: int = 0
    samples_aggregated: int = 0
    last_attempt_duration_s: float = 0.0
    last_symbolize_duration_s: float = 0.0
    last_aggregate_duration_s: float = 0.0


class CPUProfiler:
    name = "cpu"

    def __init__(
        self,
        source: CaptureSource,
        aggregator: Aggregator,
        symbolizer=None,
        labels_manager=None,
        profile_writer=None,
        debuginfo=None,
        duration_s: float = 10.0,
        fallback_aggregator: Aggregator | None = None,
        on_iteration: Callable[[int], None] | None = None,
        device_timeout_s: float = 60.0,
        device_retry_windows: int = 30,
        manage_gc: bool = False,
        window_sink: Callable[[WindowSnapshot], None] | None = None,
        fast_encode: bool = False,
        streaming_feeder=None,
    ):
        self._source = source
        self._aggregator = aggregator
        # Fast write path: aggregate counts + vectorized template encoder,
        # no per-pid PidProfile objects or scalar pprof serialization on
        # the hot loop. Profiles ship unsymbolized (the reference agent's
        # contract too — the server symbolizes), so it excludes a local
        # symbolizer.
        self._encoder = None
        if fast_encode:
            if symbolizer is not None:
                raise ValueError(
                    "fast_encode ships unsymbolized profiles; it cannot be "
                    "combined with a local symbolizer")
            if not hasattr(aggregator, "window_counts"):
                raise ValueError(
                    "fast_encode requires a dict-style aggregator "
                    "(window_counts/close_window protocol)")
            from parca_agent_tpu.pprof.window_encoder import WindowEncoder

            self._encoder = WindowEncoder(aggregator)
        # Streaming mode: drains were fed to the device during the window
        # (profiler/streaming.py); close replaces the one-shot aggregate
        # when the feeder confirms it saw the whole window.
        if streaming_feeder is not None and self._encoder is None:
            raise ValueError("streaming_feeder requires fast_encode")
        if streaming_feeder is not None \
                and hasattr(streaming_feeder, "attach_encoder"):
            # Statics amortization: the feeder prebuilds pprof static
            # sections (budgeted) after each drain feed, so the close-time
            # encode's statics transient is bounded even on a cold first
            # window at large pid populations.
            streaming_feeder.attach_encoder(self._encoder)
            # While an abandoned AGGREGATION call (hang watchdog, below)
            # may still be executing inside take_window_if_complete() /
            # window_counts(), it shares registry state the encoder
            # reads; gate the feeder's polling-thread touches on it.
            # (encode() itself runs on the profiler thread OUTSIDE the
            # watchdog — host numpy cannot hang on the device — so an
            # abandoned call can never be inside encode().)
            streaming_feeder.external_blocked = (
                lambda: self._device_inflight is not None
                and not self._device_inflight.is_set())
        self._feeder = streaming_feeder
        self._fallback = fallback_aggregator
        self._device_timeout = device_timeout_s
        self._device_retry_windows = device_retry_windows
        # Hang containment state: the in-flight aggregation call when the
        # device last wedged, and the window count at which it did.
        self._device_inflight = None
        self._device_wedged_at: int | None = None
        self._windows_seen = 0
        self._symbolizer = symbolizer
        self._labels = labels_manager
        self._writer = profile_writer
        self._debuginfo = debuginfo
        self._duration = duration_s
        # Process-global GC stewardship (freeze + explicit boundary
        # collects): only the process owner (the agent CLI) should turn
        # this on; embedders keep CPython's default scheduler.
        self._manage_gc_enabled = manage_gc
        # Optional tee of each window's snapshot (the fleet merger feeds
        # on it); failures there must not fail the iteration.
        self._window_sink = window_sink
        self._on_iteration = on_iteration
        self._stop = threading.Event()
        self.metrics = ProfilerMetrics()
        self.last_error: Exception | None = None
        self.last_profile_started_at: float = 0.0
        # pid -> profiled-ok flag for the status page (reference
        # processLastErrors, cpu.go:461-471).
        self.process_last_errors: dict[int, Exception | None] = {}

    # -- one iteration ------------------------------------------------------

    def obtain_profiles(self, snapshot: WindowSnapshot) -> list[PidProfile]:
        """Aggregate with the configured backend; fall back to the CPU path
        when the device backend fails OR HANGS (SURVEY.md section 7 hard
        part #5: device trouble must not stall the capture loop — and a
        wedged device runtime blocks inside a C call no exception ever
        leaves, observed as multi-minute backend-init hangs on real
        hardware). With a fallback configured, device aggregation runs on
        a watchdog thread bounded by device_timeout_s; on timeout the
        window is aggregated on the CPU and the device is retried only
        after device_retry_windows windows AND once the abandoned call has
        actually returned (the aggregator's state is not touched while a
        wedged call may still be executing inside it)."""
        t0 = time.perf_counter()
        self._windows_seen += 1
        # Device failures are handled (and logged as such) inside
        # _aggregate_guarded; an exception escaping it is a FALLBACK (or
        # no-fallback) failure and must propagate as an iteration error —
        # re-running the fallback here would double the work and blame
        # the wrong backend in the log.
        profiles = self._aggregate_guarded(snapshot)
        self.metrics.last_aggregate_duration_s = time.perf_counter() - t0
        return profiles

    def _aggregate_guarded(self, snapshot: WindowSnapshot):
        return self._guarded(lambda: self._aggregator.aggregate(snapshot),
                             lambda: self._fallback.aggregate(snapshot))

    def _guarded(self, thunk, fallback_thunk):
        """Run thunk on the device backend under the hang watchdog;
        fallback_thunk on failure/hang (see _aggregate_guarded docs)."""
        if self._fallback is None:
            return thunk()

        if self._device_wedged_at is not None:
            # Device previously hung. Only retry after the cooldown and
            # once the abandoned call has finished with the aggregator.
            cooled = (self._windows_seen - self._device_wedged_at
                      >= self._device_retry_windows)
            if not (cooled and self._device_inflight.is_set()):
                return fallback_thunk()
            self._device_wedged_at = None
            self._device_inflight = None
            _log.info("retrying device aggregation after cooldown")

        # A daemon thread, NOT a ThreadPoolExecutor: pool workers are
        # non-daemon and joined at interpreter exit, so one wedged call
        # would block agent shutdown forever. A daemon thread is truly
        # abandonable.
        box: dict = {}
        done = threading.Event()

        def call():
            try:
                box["out"] = thunk()
            except BaseException as e:  # noqa: BLE001 - surfaced below
                box["err"] = e
            finally:
                done.set()

        threading.Thread(target=call, name="aggregate-device",
                         daemon=True).start()
        if done.wait(self._device_timeout):
            if "err" not in box:
                return box["out"]
            _log.warn("device aggregation failed; using CPU fallback",
                      aggregator=type(self._aggregator).__name__,
                      error=repr(box["err"]))
        else:
            self._device_wedged_at = self._windows_seen
            self._device_inflight = done
            _log.error(
                "device aggregation hung; abandoning call and using the "
                "CPU fallback",
                aggregator=type(self._aggregator).__name__,
                timeout_s=self._device_timeout,
                retry_after_windows=self._device_retry_windows)
        return fallback_thunk()

    def run_iteration(self) -> bool:
        """Returns False when the source is exhausted."""
        try:
            snapshot = self._source.poll()
        except Exception as e:
            # Capture trouble is non-fatal, like any other iteration error
            # (cpu.go:326-330): a transient drain failure must not kill the
            # agent. run() waits out the rest of the window, a natural
            # backoff before the retry.
            self.last_error = e
            self.metrics.errors_total += 1
            _log.warn("capture poll failed; retrying next window",
                      error=repr(e))
            return True
        if snapshot is None:
            return False
        self.last_profile_started_at = time.time()
        self.metrics.attempts_total += 1
        t_start = time.perf_counter()
        try:
            if self._encoder is not None:
                n_pids = self._aggregate_encode_write(snapshot)
            else:
                profiles = self.obtain_profiles(snapshot)
                self.metrics.samples_aggregated += snapshot.total_samples()

                if self._symbolizer is not None:
                    t0 = time.perf_counter()
                    self._symbolizer.symbolize(profiles)
                    self.metrics.last_symbolize_duration_s = \
                        time.perf_counter() - t0

                for prof in profiles:
                    self._write_profile(prof)
                n_pids = len(profiles)

            if self._debuginfo is not None:
                objs = []
                mt = snapshot.mappings
                for i, path in enumerate(mt.obj_paths):
                    bid = mt.obj_buildids[i] if i < len(mt.obj_buildids) else ""
                    rows = (mt.objs == i).nonzero()[0]
                    if len(rows) and path:
                        pid = int(mt.pids[rows[0]])
                        objs.append((pid, path, bid))
                self._debuginfo.ensure_uploaded(objs)
            if self._window_sink is not None:
                try:
                    self._window_sink(snapshot)
                except Exception as e:  # noqa: BLE001 - tee must not fail us
                    _log.warn("window sink failed", error=repr(e))
            self.last_error = None
            _log.debug("window aggregated",
                       pids=n_pids,
                       samples=int(snapshot.total_samples()))
        except Exception as e:  # non-fatal (cpu.go:326-330)
            self.last_error = e
            self.metrics.errors_total += 1
            _log.warn("profile iteration failed", error=repr(e))
        self.metrics.last_attempt_duration_s = time.perf_counter() - t_start
        self._manage_gc(self.metrics.attempts_total)
        if self._on_iteration is not None:
            self._on_iteration(self.metrics.attempts_total)
        return True

    # CPython gen-2 collections scan every tracked object; the aggregator
    # mirror holds millions of long-lived ones (stack-key tuples, per-id
    # location lists), so an automatic pass costs hundreds of ms and can
    # land in the middle of a window close (the Go reference never has
    # this problem — its GC is concurrent). Policy: after the first
    # window, freeze the warm state into the permanent generation
    # (excluded from all collection) and DISABLE the automatic scheduler;
    # instead collect explicitly here — a window boundary, nothing
    # latency-sensitive in flight — where the tracked set is only what
    # this window allocated plus registry growth since the last refreeze.
    # Every _GC_REFREEZE windows (~1 h), unfreeze + full-collect +
    # refreeze so garbage that slipped into the frozen set is reclaimed.
    _GC_REFREEZE = 360

    _gc_modified = False

    def _restore_gc(self) -> None:
        """Undo the stewardship on shutdown: the process may outlive the
        profiler (embedding tests, supervised restarts) and must get the
        default collector back."""
        if not self._gc_modified:
            return
        import gc

        self._gc_modified = False
        gc.unfreeze()
        gc.enable()

    def _manage_gc(self, window: int) -> None:
        if not self._manage_gc_enabled:
            return
        import gc

        if window == 1:
            gc.collect()
            gc.freeze()
            gc.disable()
            self._gc_modified = True
        elif window % self._GC_REFREEZE == 0:
            gc.unfreeze()
            gc.collect()
            gc.freeze()
        else:
            gc.collect()

    def _labels_for(self, pid: int) -> dict | None:
        """Label set for a pid; None when relabeling dropped the target."""
        if self._labels is not None:
            return self._labels.label_set("parca_agent_cpu", pid)
        return {"__name__": "parca_agent_cpu", "pid": str(pid)}

    def _write_profile(self, prof: PidProfile) -> None:
        labels = self._labels_for(prof.pid)
        if labels is None:
            self.process_last_errors[prof.pid] = None
            return  # relabeling dropped this target
        try:
            if self._writer is not None:
                # compress=False: the writer owns gzip framing (gzipping
                # here too double-compressed every profile).
                self._writer.write(labels, build_pprof(prof, compress=False))
            self.metrics.profiles_written += 1
            self.process_last_errors[prof.pid] = None
        except Exception as e:
            self.process_last_errors[prof.pid] = e
            raise

    def _aggregate_encode_write(self, snapshot: WindowSnapshot) -> int:
        """Fast path: counts -> vectorized encoder -> writer, no PidProfile
        materialization. ONLY the device call rides the hang watchdog (on
        failure/hang the CPU fallback aggregates and writes through the
        scalar builder); the encoder is host-side numpy — it cannot hang
        on the device, and its slow transients (a post-rotation template
        rebuild is tens of seconds at 50k pids) must not eat the device
        watchdog's budget and read as a wedged device. An encoder FAILURE
        still falls back to the scalar path for that window."""
        t0 = time.perf_counter()
        self._windows_seen += 1  # hang-cooldown clock (obtain_profiles' twin)

        def fast():
            if self._feeder is not None and self._feeder.device_blocked():
                # An abandoned streaming feed may still be executing
                # inside the aggregator; touching it now would race the
                # donation contract. Raise into the watchdog machinery:
                # the CPU fallback shares no state with the dict.
                raise RuntimeError(
                    "abandoned streaming feed still in flight")
            counts = None
            if self._feeder is not None:
                counts = self._feeder.take_window_if_complete(snapshot)
            if counts is None:  # not streamed (or incomplete): one-shot
                counts = self._aggregator.window_counts(snapshot)
            return "counts", counts

        def fallback():
            return "prof", self._fallback.aggregate(snapshot)

        kind, out = self._guarded(fast, fallback)
        if kind == "counts":
            try:
                out = self._encoder.encode(
                    out, snapshot.time_ns, snapshot.window_ns,
                    snapshot.period_ns)
                kind = "enc"
            except Exception as e:  # noqa: BLE001 - window must still ship
                if self._fallback is None:
                    raise
                _log.warn("fast encode failed; scalar fallback for this "
                          "window", error=repr(e))
                kind, out = fallback()
        self.metrics.last_aggregate_duration_s = time.perf_counter() - t0
        self.metrics.samples_aggregated += snapshot.total_samples()
        if kind == "prof":
            for prof in out:
                self._write_profile(prof)
            return len(out)
        n = 0
        for pid, blob in out:
            labels = self._labels_for(pid)
            if labels is None:
                self.process_last_errors[pid] = None
                continue
            try:
                if self._writer is not None:
                    self._writer.write(labels, blob)
                self.metrics.profiles_written += 1
                self.process_last_errors[pid] = None
                n += 1
            except Exception as e:
                self.process_last_errors[pid] = e
                raise
        return n

    # -- actor --------------------------------------------------------------

    def run(self) -> None:
        try:
            while not self._stop.is_set():
                t0 = time.monotonic()
                if not self.run_iteration():
                    return
                elapsed = time.monotonic() - t0
                self._stop.wait(max(0.0, self._duration - elapsed))
        except BaseException as e:
            # Anything escaping run_iteration is a bug, not an iteration
            # failure; record it so the CLI can exit nonzero instead of
            # treating thread death as a clean shutdown.
            self.crashed = e
            raise
        finally:
            self._restore_gc()

    crashed: BaseException | None = None

    def stop(self) -> None:
        self._stop.set()
