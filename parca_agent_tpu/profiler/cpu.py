"""The CPU profiler actor: the 10-second iteration loop.

Role of the reference's pkg/profiler/cpu/cpu.go Run + obtainProfiles
(cpu.go:189-384): every profiling duration, drain the capture source into
a WindowSnapshot, aggregate (pluggable backend — the north-star seam),
symbolize kernel/JIT frames, label, encode pprof, write, and kick off
debuginfo uploads. An iteration failure is non-fatal: logged, surfaced via
last_error, and the loop continues (cpu.go:326-330, SURVEY.md section 5.3).

The capture source protocol is `poll() -> WindowSnapshot | None` (replay,
synthetic, or live sampler); `None` ends the run loop — the replay-driven
agent exits cleanly after the last window, the live sampler never returns
None while running.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Protocol

from parca_agent_tpu.aggregator.base import Aggregator, PidProfile
from parca_agent_tpu.capture.formats import WindowSnapshot
from parca_agent_tpu.pprof.builder import build_pprof
from parca_agent_tpu.runtime import device_telemetry as dtel
from parca_agent_tpu.runtime.quarantine import apply_ladder
from parca_agent_tpu.runtime.trace import NULL_TRACE
from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.log import get_logger

_log = get_logger("profiler")


class CaptureSource(Protocol):
    def poll(self) -> WindowSnapshot | None: ...


@dataclasses.dataclass
class ProfilerMetrics:
    """Counter names mirror the reference's observable metric contract
    (pkg/profiler/cpu/metrics.go:22-65, SURVEY.md section 5.5)."""

    attempts_total: int = 0
    errors_total: int = 0
    profiles_written: int = 0
    samples_aggregated: int = 0
    last_attempt_duration_s: float = 0.0
    last_symbolize_duration_s: float = 0.0
    last_aggregate_duration_s: float = 0.0
    # Encode-path observability (fast_encode mode): how long the last
    # window's pprof encode took (on whichever thread ran it), how many
    # windows hit the pipeline's backpressure fallback, and how many
    # inline encodes were abandoned at the soft deadline.
    last_encode_duration_s: float = 0.0
    encode_backpressure_total: int = 0
    encode_deadline_hits_total: int = 0
    # Abandoned-device-call accounting: how many watchdogged calls that
    # were abandoned at their deadline eventually RETURNED, and how they
    # ended. An abandoned call that later fails used to set box["err"]
    # into the void — now it is logged and counted here.
    device_abandoned_ok_total: int = 0
    device_abandoned_err_total: int = 0


class CPUProfiler:
    name = "cpu"

    def __init__(
        self,
        source: CaptureSource,
        aggregator: Aggregator,
        symbolizer=None,
        labels_manager=None,
        profile_writer=None,
        debuginfo=None,
        duration_s: float = 10.0,
        fallback_aggregator: Aggregator | None = None,
        on_iteration: Callable[[int], None] | None = None,
        device_timeout_s: float = 60.0,
        device_retry_windows: int = 30,
        manage_gc: bool = False,
        window_sink: Callable[[WindowSnapshot], None] | None = None,
        fast_encode: bool = False,
        streaming_feeder=None,
        encode_pipeline: bool = False,
        encode_deadline_s: float | None = None,
        quarantine=None,
        admission=None,
        identity=None,
        device_health=None,
        statics_store=None,
        statics_snapshot_every: int = 6,
        statics_cache_bytes: int = 256 << 20,
        trace_recorder=None,
        hotspot_store=None,
        sinks=None,
        regression=None,
    ):
        self._source = source
        self._aggregator = aggregator
        # Window flight recorder (runtime/trace.py): one trace per
        # window, spans recorded here, in the encode pipeline's worker,
        # and in the encoder. Tracing is fail-open by contract — every
        # recorder entry point swallows its own errors — so nothing in
        # this file guards a tracing call with anything heavier than the
        # NULL_TRACE default.
        self._recorder = trace_recorder
        # Ingest containment (runtime/quarantine.py): the profiler owns
        # the window clock, so it ticks the registry once per iteration
        # and routes aggregated profiles down the degradation ladder
        # before symbolize/write. The same registry instance is shared
        # with the capture source, the feeder, the symbolizer, and the
        # unwind builder — one budget per pid across every ingest site.
        self._quarantine = quarantine
        # Multi-tenant admission (runtime/admission.py): the profiler
        # owns the window clock here too — each window's snapshot usage
        # is charged to its tenants at the top of the iteration, the
        # controller ticks beside the quarantine registry, and the
        # governor reads this loop's own overload signals (close
        # latency, registry rows, encode backpressure). Both entry
        # points are fail-open by the controller's own contract, so the
        # calls ride unguarded.
        self._admission = admission
        # Generation-stamped process identity (process/identity.py):
        # observed once per window, before accounting/aggregation, so a
        # recycled pid invalidates its dead predecessor's state instead
        # of inheriting it.
        self._identity = identity
        # Fast write path: aggregate counts + vectorized template encoder,
        # no per-pid PidProfile objects or scalar pprof serialization on
        # the hot loop. Profiles ship unsymbolized (the reference agent's
        # contract too — the server symbolizes), so it excludes a local
        # symbolizer.
        self._encoder = None
        if fast_encode:
            if symbolizer is not None:
                raise ValueError(
                    "fast_encode ships unsymbolized profiles; it cannot be "
                    "combined with a local symbolizer")
            if not hasattr(aggregator, "window_counts"):
                raise ValueError(
                    "fast_encode requires a dict-style aggregator "
                    "(window_counts/close_window protocol)")
            from parca_agent_tpu.pprof.window_encoder import WindowEncoder

            self._encoder = WindowEncoder(
                aggregator, statics_cache_bytes=statics_cache_bytes)
        # Encode pipeline: window close hands the aggregated counts to a
        # dedicated encoder thread, so capture of window N+1 overlaps
        # encoding/shipping of window N and the encoder's slow transients
        # (cold statics, post-rotation rebuilds) never stall the capture
        # loop. Inline soft deadline: without the pipeline, an encode
        # slower than encode_deadline_s is abandoned to a daemon thread
        # and the window ships via the scalar fallback.
        self._pipeline = None
        # Warm statics + registry snapshot (pprof/statics_store.py): the
        # encode worker persists the statics state on the window clock so
        # a restart adopts instead of cold-building; the capture thread
        # never touches the file. Snapshotting therefore requires the
        # pipeline — without a worker there is no thread that may safely
        # serialize the encoder's statics map off the capture path.
        self._statics_store = statics_store
        # Hotspot rollups (runtime/hotspots.py): each shipped window is
        # folded into mergeable sketch+top-K summaries ON THE ENCODE
        # WORKER — the read path (/hotspots) must add zero work to the
        # capture/close thread, so without the pipeline there is no
        # thread the fold may ride and the store stays unfed.
        self._hotspots = hotspot_store
        if hotspot_store is not None and labels_manager is not None \
                and hotspot_store.labels_for is None:
            hotspot_store.labels_for = self._locked_labels_for
        # Regression sentinel (runtime/regression.py): the judgment
        # rider on the same worker-thread fold clock — each shipped
        # window is attributed by (leaf build-id, tenant) and diffed
        # against frozen baselines. Fail-open inside the sentinel
        # itself (counted fold_errors), so it shares the rollup hook
        # without changing the hotspot fold's re-raise contract.
        self._regression = regression
        if regression is not None and labels_manager is not None \
                and regression.labels_for is None:
            regression.labels_for = self._locked_labels_for
        # Output-backend sinks (sinks/, docs/sinks.md): the registry
        # replaces the hardwired pprof ship with a fan-out whose primary
        # (pprof) IS the pre-sink write path bound below — bytes stay
        # identical — and whose secondaries (autofdo/series) consume the
        # prepared window under the counted fail-open contract. Pipelined
        # windows fan out on the encode worker (emit_window); inline-
        # fallback windows fan out on this thread (_emit_sinks_inline);
        # scalar-path windows are counted as skipped — no prepared rows
        # exist for a sink to read.
        self._sinks = sinks
        if sinks is not None:
            if self._encoder is None:
                raise ValueError("sinks require fast_encode (the sink "
                                 "fan-out reads prepared windows)")
            sinks.bind(ship=self._write_encoded,
                       labels_for=(self._locked_labels_for
                                   if labels_manager is not None else None))
            # Opt the encoder into the inline-path prep stash only when
            # someone will read it — without secondaries it would just
            # pin each window's prepared arrays for nothing.
            self._encoder.track_prep = sinks.has_secondary
        if encode_pipeline:
            if self._encoder is None:
                raise ValueError("encode_pipeline requires fast_encode")
            from parca_agent_tpu.profiler.encode_pipeline import (
                EncodePipeline,
            )

            snapshot = None
            if statics_store is not None:
                snapshot = (lambda period_ns: statics_store.save(
                    self._aggregator, self._encoder, period_ns))
            self._pipeline = EncodePipeline(
                self._encoder, ship=self._ship_encoded,
                snapshot=snapshot,
                snapshot_every=(statics_snapshot_every
                                if statics_store is not None else 0),
                rollup=(self._rollup_window
                        if hotspot_store is not None
                        or regression is not None else None),
                rollup_capture=(self._rollup_capture
                                if hotspot_store is not None
                                or regression is not None else None),
                # The sink context is the same rotation-consistent
                # RegistryView the rollup capture produces; reusing the
                # hook keeps one definition of "safe to read off-thread".
                sink_capture=(self._rollup_capture
                              if sinks is not None
                              and sinks.has_secondary else None))
        else:
            if statics_store is not None:
                _log.warn("statics snapshotting needs the encode pipeline; "
                          "snapshots disabled (adoption still works)")
            if hotspot_store is not None:
                _log.warn("hotspot rollups need the encode pipeline; "
                          "windows will not be folded")
            if regression is not None:
                _log.warn("the regression sentinel needs the encode "
                          "pipeline; windows will not be judged")
        self._encode_deadline = encode_deadline_s
        self._encode_inflight = None   # abandoned inline deadline encode
        self._encode_abandoned = None  # its result box (error inspection)
        # Writes can come from the profiler thread (inline/scalar paths)
        # AND the pipeline's worker (shipping window N while window N+1
        # falls back inline): one lock serializes writer + label lookups
        # + the written-profiles counter.
        self._write_mu = threading.Lock()
        # Streaming mode: drains were fed to the device during the window
        # (profiler/streaming.py); close replaces the one-shot aggregate
        # when the feeder confirms it saw the whole window.
        if streaming_feeder is not None and self._encoder is None:
            raise ValueError("streaming_feeder requires fast_encode")
        if streaming_feeder is not None \
                and hasattr(streaming_feeder, "attach_encoder"):
            # Statics amortization: the feeder prebuilds pprof static
            # sections (budgeted) after each drain feed, so the close-time
            # encode's statics transient is bounded even on a cold first
            # window at large pid populations. With the pipeline on, the
            # budgeted build runs on the ENCODER thread (the encoder's
            # thread-ownership contract); inline it runs on the polling
            # thread as before.
            if self._pipeline is not None:
                streaming_feeder.attach_encoder(
                    self._encoder, prebuild=self._pipeline.request_prebuild)
            else:
                streaming_feeder.attach_encoder(self._encoder)
            # While an abandoned AGGREGATION call (hang watchdog, below)
            # may still be executing inside take_window_if_complete() /
            # window_counts(), it shares registry state the encoder
            # reads; gate the feeder's polling-thread touches on it.
            # Likewise an inline encode abandoned at its soft deadline
            # still owns the encoder's mirrors until it returns.
            streaming_feeder.external_blocked = (
                lambda: (self._device_inflight is not None
                         and not self._device_inflight.is_set())
                or (self._encode_inflight is not None
                    and not self._encode_inflight.is_set()))
        self._feeder = streaming_feeder
        self._fallback = fallback_aggregator
        self._device_timeout = device_timeout_s
        # Device lifecycle state lives in ONE place: the health registry
        # (runtime/device_health.py) owns wedge accounting, cooldowns,
        # the probing/healthy/degraded/dead machine, and the shadow-
        # window promotion gate. The CLI passes a probe-armed registry;
        # embedders get a probe-less default that reproduces the old
        # retry-after-N-windows semantics (cooldown expiry goes straight
        # to the shadow window).
        self._health = device_health
        if self._health is None and fallback_aggregator is not None:
            from parca_agent_tpu.runtime.device_health import (
                STATE_HEALTHY,
                DeviceHealthRegistry,
            )

            self._health = DeviceHealthRegistry(
                probe=None, promote_after=0,
                cooldown_windows=device_retry_windows,
                start_state=STATE_HEALTHY)
        # The abandoned in-flight device call (a wedged call may still be
        # executing inside the aggregator — nothing touches it until the
        # event fires) and its result box, inspected once on completion.
        self._device_inflight = None
        self._device_abandoned: dict | None = None
        self._windows_seen = 0
        self._symbolizer = symbolizer
        self._labels = labels_manager
        self._writer = profile_writer
        self._debuginfo = debuginfo
        self._duration = duration_s
        # Process-global GC stewardship (freeze + explicit boundary
        # collects): only the process owner (the agent CLI) should turn
        # this on; embedders keep CPython's default scheduler.
        self._manage_gc_enabled = manage_gc
        # Optional tee of each window's snapshot (the fleet merger feeds
        # on it); failures there must not fail the iteration.
        self._window_sink = window_sink
        self._on_iteration = on_iteration
        self._stop = threading.Event()
        self.metrics = ProfilerMetrics()
        self.last_error: Exception | None = None
        self.last_profile_started_at: float = 0.0
        # pid -> profiled-ok flag for the status page (reference
        # processLastErrors, cpu.go:461-471).
        self.process_last_errors: dict[int, Exception | None] = {}

    # -- one iteration ------------------------------------------------------

    def obtain_profiles(self, snapshot: WindowSnapshot) -> list[PidProfile]:
        """Aggregate with the configured backend; fall back to the CPU path
        when the device backend fails OR HANGS (SURVEY.md section 7 hard
        part #5: device trouble must not stall the capture loop — and a
        wedged device runtime blocks inside a C call no exception ever
        leaves, observed as multi-minute backend-init hangs on real
        hardware). With a fallback configured, device aggregation runs on
        a watchdog thread bounded by device_timeout_s; on timeout the
        window is aggregated on the CPU and the device-health registry
        demotes the backend — re-trusted only after its cooldown, its
        probe gate, AND one shadow window whose device result matches
        the CPU fallback (and never while the abandoned call may still
        be executing inside the aggregator)."""
        t0 = time.perf_counter()
        self._windows_seen += 1
        # Device failures are handled (and logged as such) inside
        # _aggregate_guarded; an exception escaping it is a FALLBACK (or
        # no-fallback) failure and must propagate as an iteration error —
        # re-running the fallback here would double the work and blame
        # the wrong backend in the log.
        profiles = self._aggregate_guarded(snapshot)
        self.metrics.last_aggregate_duration_s = time.perf_counter() - t0
        return profiles

    def _aggregate_guarded(self, snapshot: WindowSnapshot):
        return self._guarded(lambda: self._aggregator.aggregate(snapshot),
                             lambda: self._fallback.aggregate(snapshot))

    @property
    def _device_wedged_at(self):
        """Window index of the hang the device is currently demoted for
        (None while trusted) — kept for tests and the status page; the
        registry is the single owner of the state."""
        return self._health.wedged_at if self._health is not None else None

    def _inspect_abandoned(self) -> None:
        """An abandoned device call that finally RETURNED: its outcome
        used to be silently discarded (an error set into box["err"] after
        the timeout went nowhere). Inspect it exactly once — log the
        late failure, count ok/err — and release the inflight gate."""
        done = self._device_inflight
        if done is None or not done.is_set():
            return
        box = self._device_abandoned or {}
        if "err" in box:
            self.metrics.device_abandoned_err_total += 1
            _log.warn("abandoned device call completed with an error",
                      aggregator=type(self._aggregator).__name__,
                      error=repr(box["err"]))
        else:
            self.metrics.device_abandoned_ok_total += 1
            _log.info("abandoned device call completed",
                      aggregator=type(self._aggregator).__name__)
        self._device_inflight = None
        self._device_abandoned = None

    def _device_call_clear(self) -> bool:
        return self._device_inflight is None \
            or self._device_inflight.is_set()

    def _watchdog_call(self, thunk):
        """Run thunk under the abandonable bounded-call guard
        (utils/bounded.py) with the device timeout. Returns
        ("ok", out) | ("err", exc) | ("hang", None); a hang leaves the
        call registered as in-flight (the aggregator's state is not
        touched while it may still be executing inside it)."""
        from parca_agent_tpu.utils.bounded import bounded_call

        def site():
            faults.inject("device.dispatch")
            return thunk()

        status, out, done, box = bounded_call(
            site, self._device_timeout, thread_name="aggregate-device")
        if status == "hang":
            self._device_inflight = done
            self._device_abandoned = box
        return status, out

    @staticmethod
    def _shadow_match(dev_out, cpu_out) -> bool:
        """Promotion-gate A/B: does the device result agree with the CPU
        fallback's? Profile lists compare per-pid (mass, unique-stack
        count) digests; the fast path's raw counts compare total window
        mass (the same invariant bench.py's A/B phases assert)."""
        def norm(o):
            if isinstance(o, tuple) and len(o) == 2 \
                    and isinstance(o[0], str):
                kind, payload = o
                if kind == "counts":
                    import numpy as np

                    return int(np.asarray(payload).astype(np.int64).sum())
                return payload
            return o

        a, b = norm(dev_out), norm(cpu_out)
        if isinstance(a, int) or isinstance(b, int):
            def mass(x):
                return x if isinstance(x, int) \
                    else sum(int(p.total()) for p in x)

            return mass(a) == mass(b)
        from parca_agent_tpu.aggregator.tpu import shadow_compare

        return shadow_compare(a, b)

    def _guarded(self, thunk, fallback_thunk):
        """Run thunk on the device backend under the hang watchdog and
        the health registry's demote/promote supervision; fallback_thunk
        while degraded or on failure/hang (see _aggregate_guarded docs).
        Promotion back to the device passes through one SHADOW window:
        both backends aggregate, the results must match, and the window
        ships the CPU result either way."""
        if self._fallback is None:
            return thunk()
        self._inspect_abandoned()
        mode = self._health.window_mode()
        if mode != "fallback" and not self._device_call_clear():
            # The abandoned call still owns the aggregator's state: no
            # device touch (not even a shadow) until it returns.
            mode = "fallback"
        if mode == "fallback":
            self._health.record_fallback_window()
            return fallback_thunk()

        status, out = self._watchdog_call(thunk)

        if mode == "shadow":
            cpu_out = fallback_thunk()
            if status == "hang":
                _log.error("device hung during its shadow window; "
                           "re-demoting", timeout_s=self._device_timeout)
                self._health.record_hang()
            else:
                matched = status == "ok" \
                    and self._shadow_match(out, cpu_out)
                err = repr(out)[:200] if status == "err" else ""
                self._health.record_shadow(matched, error=err)
            return cpu_out

        if status == "ok":
            self._health.record_dispatch_ok()
            return out
        if status == "err":
            _log.warn("device aggregation failed; using CPU fallback",
                      aggregator=type(self._aggregator).__name__,
                      error=repr(out))
            self._health.record_dispatch_error(out)
        else:
            _log.error(
                "device aggregation hung; abandoning call and using the "
                "CPU fallback",
                aggregator=type(self._aggregator).__name__,
                timeout_s=self._device_timeout)
            self._health.record_hang()
        return fallback_thunk()

    def run_iteration(self) -> bool:
        """Returns False when the source is exhausted."""
        t_iter0 = time.perf_counter()
        tr = (self._recorder.begin() if self._recorder is not None
              else NULL_TRACE)
        try:
            with tr.span("drain"):
                snapshot = self._source.poll()
        except Exception as e:
            # Capture trouble is non-fatal, like any other iteration error
            # (cpu.go:326-330): a transient drain failure must not kill the
            # agent. run() waits out the rest of the window, a natural
            # backoff before the retry.
            self.last_error = e
            self.metrics.errors_total += 1
            _log.warn("capture poll failed; retrying next window",
                      error=repr(e))
            tr.finish(error=repr(e)[:200])
            return True
        if snapshot is None:
            tr.discard()  # never a window: not ringed, not histogrammed
            return False
        self.last_profile_started_at = time.time()
        self.metrics.attempts_total += 1
        if self._identity is not None:
            # Generation-stamped identity check BEFORE accounting and
            # aggregation: a recycled pid's stale tenant/quarantine/
            # registry state must be invalidated before any of the new
            # generation's samples resolve through it (fail-open by the
            # tracker's own contract — see process/identity.py).
            self._identity.observe_window(snapshot.pids)
        if self._admission is not None:
            # Per-tenant usage accounting BEFORE the close: the ladder
            # levels this window's profiles ride were set by last tick
            # (admission reacts on the window clock, one window behind —
            # the same cadence as quarantine cooldowns).
            self._admission.account_window(snapshot.pids, snapshot.counts)
        tr.annotate(time_ns=snapshot.time_ns,
                    samples=int(snapshot.total_samples()))
        t_start = time.perf_counter()
        try:
            if self._encoder is not None:
                n_pids = self._aggregate_encode_write(snapshot, tr)
            else:
                # Scalar path spans: close (aggregate), symbolize, ship.
                # The close gauge is set FROM the span duration so the
                # last-value gauge and the histogram can never disagree.
                with tr.span("close") as sp_close:
                    profiles = self.obtain_profiles(snapshot)
                self.metrics.last_aggregate_duration_s = sp_close.duration_s
                self.metrics.samples_aggregated += snapshot.total_samples()

                # Degradation ladder first (level-1 pids lose local
                # symbols, level-2 pids collapse to scalar counts), then
                # symbolize — which itself skips laddered pids, so a
                # degraded profile can never be re-symbolized.
                profiles = apply_ladder(profiles, self._quarantine,
                                        self._admission)

                if self._symbolizer is not None:
                    with tr.span("symbolize") as sp_sym:
                        self._symbolizer.symbolize(profiles)
                    self.metrics.last_symbolize_duration_s = \
                        sp_sym.duration_s

                with tr.span("ship"):
                    for prof in profiles:
                        self._write_profile(prof)
                n_pids = len(profiles)
                tr.annotate(pids=n_pids, path="scalar")

            if self._debuginfo is not None:
                objs = []
                mt = snapshot.mappings
                for i, path in enumerate(mt.obj_paths):
                    bid = mt.obj_buildids[i] if i < len(mt.obj_buildids) else ""
                    rows = (mt.objs == i).nonzero()[0]
                    if len(rows) and path:
                        pid = int(mt.pids[rows[0]])
                        objs.append((pid, path, bid))
                self._debuginfo.ensure_uploaded(objs)
            if self._window_sink is not None:
                try:
                    self._window_sink(snapshot)
                except Exception as e:  # noqa: BLE001 - tee must not fail us
                    _log.warn("window sink failed", error=repr(e))
            self.last_error = None
            _log.debug("window aggregated",
                       pids=n_pids,
                       samples=int(snapshot.total_samples()))
        except Exception as e:  # non-fatal (cpu.go:326-330)
            self.last_error = e
            self.metrics.errors_total += 1
            _log.warn("profile iteration failed", error=repr(e))
            tr.finish(error=repr(e)[:200])
        # Pipelined windows detached their trace (the encode worker
        # completes it after the ship); everything else finishes here.
        tr.finish()
        if self._quarantine is not None:
            # Quarantine time is window time: cooldown/probation advance
            # once per iteration, whether or not the window shipped.
            self._quarantine.tick_window()
        if self._admission is not None:
            # Admission rides the same clock: buckets refill, ladder
            # levels adjust, and the overload governor judges THIS
            # window's close latency / registry growth / encode
            # backpressure (tick_window is fail-open by contract).
            self._admission.tick_window(
                close_latency_s=self.metrics.last_aggregate_duration_s,
                registry_rows=int(
                    getattr(self._aggregator, "_next_id", 0) or 0),
                backlog=(self._pipeline.stats["backpressure_fallbacks"]
                         if self._pipeline is not None else 0))
        if self._health is not None:
            # Same clock for the device-backend state machine: demote
            # cooldowns and re-probe scheduling advance per window.
            self._health.tick_window()
        self.metrics.last_attempt_duration_s = time.perf_counter() - t_start
        # Window-SLO accounting (runtime/device_telemetry.py): the
        # capture thread's busy wall for this window — drain through
        # hand-off plus the per-window ticks above — judged against the
        # configured period. run() sleeps out the remainder, so this is
        # the window's whole non-idle cost on this thread; off-thread
        # kernel seconds are folded in by the telemetry layer itself.
        dtel.tick_window(time.perf_counter() - t_iter0)
        self._manage_gc(self.metrics.attempts_total)
        if self._on_iteration is not None:
            self._on_iteration(self.metrics.attempts_total)
        return True

    # CPython gen-2 collections scan every tracked object; the aggregator
    # mirror holds millions of long-lived ones (stack-key tuples, per-id
    # location lists), so an automatic pass costs hundreds of ms and can
    # land in the middle of a window close (the Go reference never has
    # this problem — its GC is concurrent). Policy: after the first
    # window, freeze the warm state into the permanent generation
    # (excluded from all collection) and DISABLE the automatic scheduler;
    # instead collect explicitly here — a window boundary, nothing
    # latency-sensitive in flight — where the tracked set is only what
    # this window allocated plus registry growth since the last refreeze.
    # Every _GC_REFREEZE windows (~1 h), unfreeze + full-collect +
    # refreeze so garbage that slipped into the frozen set is reclaimed.
    _GC_REFREEZE = 360

    _gc_modified = False

    def _restore_gc(self) -> None:
        """Undo the stewardship on shutdown: the process may outlive the
        profiler (embedding tests, supervised restarts) and must get the
        default collector back."""
        if not self._gc_modified:
            return
        import gc

        self._gc_modified = False
        gc.unfreeze()
        gc.enable()

    def _manage_gc(self, window: int) -> None:
        if not self._manage_gc_enabled:
            return
        import gc

        if not self._gc_modified:
            # First managed window of THIS run (not of the process): a
            # supervised restart re-enters run() after the crash path
            # restored the default collector, and must re-arm here.
            gc.collect()
            gc.freeze()
            gc.disable()
            self._gc_modified = True
        elif window % self._GC_REFREEZE == 0:
            gc.unfreeze()
            gc.collect()
            gc.freeze()
        else:
            gc.collect()

    def _labels_for(self, pid: int) -> dict | None:
        """Label set for a pid; None when relabeling dropped the target."""
        if self._labels is not None:
            return self._labels.label_set("parca_agent_cpu", pid)
        return {"__name__": "parca_agent_cpu", "pid": str(pid)}

    def _locked_labels_for(self, pid: int) -> dict | None:
        """Label lookup under the write lock — the same serialization
        _write_one uses, so the rollup fold (encode worker) and the ship
        paths never race the labels manager's caches."""
        with self._write_mu:
            return self._labels_for(pid)

    # palint: fail-open=caller — the pipeline's hand-off guard counts
    # rollup_errors and ships the window unfolded; swallowing here would
    # leave that exported counter dark.
    def _rollup_capture(self, prep):
        """EncodePipeline rollup-capture hook (PROFILER thread, at window
        hand-off): snapshot the per-id mirror references the fold will
        read, before the next window's first feed can rotate them."""
        from parca_agent_tpu.runtime.hotspots import RegistryView

        return RegistryView(self._aggregator)

    # palint: fail-open=caller — fold_from_aggregator counts fold_errors
    # and RE-RAISES by contract, for the pipeline's worker guard to
    # count rollup_errors; both counters are exported on /metrics.
    def _rollup_window(self, prep, ctx) -> None:
        """EncodePipeline rollup hook (worker thread): fold the shipped
        window's live (id, count) rows into the hotspot store, reading
        per-id state only through the hand-off-time registry view; then
        hand the same view to the regression sentinel. The sentinel
        rides in the finally arm (its fold is internally fail-open and
        never raises), so a hotspot fold failure — which must propagate
        for the pipeline's rollup_errors counter — cannot starve the
        window's judgment."""
        try:
            if self._hotspots is not None:
                self._hotspots.fold_from_aggregator(
                    ctx, prep.idx, prep.vals, prep.time_ns,
                    prep.duration_ns)
        finally:
            if self._regression is not None:
                self._regression.fold_from_prepared(ctx, prep)

    def _write_one(self, pid: int, payload) -> bool:
        """Labels lookup + write + bookkeeping for one profile; False when
        relabeling dropped the target. `payload` is a zero-arg callable so
        dropped targets never pay the serialization. Called from the
        profiler thread (inline/scalar paths) or the pipeline's worker;
        the write lock covers only the shared mutable state (label-cache
        lookup, written counter) — serialization/gzip and writer.write
        run outside it, so a worker-side ship never stalls the capture
        thread's fallback writes behind a multi-MB gzip (writers tolerate
        concurrent write(): FileProfileWriter is one open/write per call,
        RemoteProfileWriter's gzip is pure and its sink buffer locked)."""
        try:
            with self._write_mu:
                labels = self._labels_for(pid)
            if labels is None:
                self.process_last_errors[pid] = None
                return False  # relabeling dropped this target
            if self._writer is not None:
                self._writer.write(labels, payload())
            with self._write_mu:
                self.metrics.profiles_written += 1
            self.process_last_errors[pid] = None
            return True
        except Exception as e:
            self.process_last_errors[pid] = e
            raise

    def _write_profile(self, prof: PidProfile) -> None:
        # compress=False: the writer owns gzip framing (gzipping here too
        # double-compressed every profile).
        self._write_one(prof.pid,
                        lambda: build_pprof(prof, compress=False))

    def _write_encoded(self, out) -> int:
        """Ship [(pid, blob)] from the fast encoder through the writer."""
        n = 0
        for pid, blob in out:
            if self._write_one(pid, lambda b=blob: b):
                n += 1
        return n

    def _ship_encoded(self, out, prep) -> None:
        """EncodePipeline ship hook (worker thread): with sinks
        configured, the registry runs the primary pprof ship (the same
        _write_encoded bound at construction — identical bytes) and
        fans the window out to the secondaries; a secondary failure is
        counted there and never reaches the pipeline's ship guard."""
        if self._sinks is not None:
            self._sinks.emit_window(out, prep)
        else:
            self._write_encoded(out)
        if self._pipeline is not None:
            self.metrics.last_encode_duration_s = \
                self._pipeline.stats["last_encode_s"]

    def _ship_scalar(self, snapshot: WindowSnapshot) -> int:
        """Aggregate + write one window through the scalar path (the
        encode fallback: pipeline backpressure, encoder exceptions, or a
        blown inline deadline)."""
        if self._sinks is not None:
            # No prepared window exists on this path; sinks (secondaries
            # included) cannot see it — counted, so PGO/series coverage
            # gaps during fallback storms are observable.
            self._sinks.count_skipped()
        profiles = self._fallback.aggregate(snapshot)
        for prof in profiles:
            self._write_profile(prof)
        return len(profiles)

    # palint: fail-open
    def _emit_sinks_inline(self, out, snapshot: WindowSnapshot) -> None:
        """Secondary-sink fan-out for an INLINE-encoded window (profiler
        thread: no pipeline, pipeline disabled, or hand-off refused).
        The pprof bytes already shipped through _write_encoded; here the
        secondaries consume the same prepared rows, with a registry view
        captured on this thread — the thread that runs rotation, so the
        capture cannot race it. Fail-open: a sink bug costs sinks one
        window, never the iteration."""
        try:
            if self._sinks is None or not self._sinks.has_secondary:
                return
            prep = getattr(self._encoder, "last_prep", None)
            if prep is None or prep.time_ns != snapshot.time_ns:
                # The encoder did not stash THIS window (e.g. a custom
                # encode path): skip rather than misattribute.
                self._sinks.count_skipped()
                return
            from parca_agent_tpu.runtime.hotspots import RegistryView

            prep.sink_ctx = RegistryView(self._aggregator)
            self._sinks.emit_secondary(out, prep)
        except Exception as e:  # noqa: BLE001 - sinks are best-effort
            self._sinks.count_capture_error()
            _log.warn("inline sink fan-out failed; window skipped for "
                      "secondary sinks", error=repr(e))

    def _aggregate_encode_write(self, snapshot: WindowSnapshot,
                                tr=NULL_TRACE) -> int:
        """Fast path: counts -> vectorized encoder -> writer, no PidProfile
        materialization. ONLY the device call rides the hang watchdog (on
        failure/hang the CPU fallback aggregates and writes through the
        scalar builder); the encoder is host-side numpy — it cannot hang
        on the device, and its slow transients (a post-rotation template
        rebuild is tens of seconds at 50k pids) must not eat the device
        watchdog's budget and read as a wedged device. An encoder FAILURE
        still falls back to the scalar path for that window."""
        self._windows_seen += 1  # hang-cooldown clock (obtain_profiles' twin)

        def fast():
            if self._feeder is not None and self._feeder.device_blocked():
                # An abandoned streaming feed may still be executing
                # inside the aggregator; touching it now would race the
                # donation contract. Raise into the watchdog machinery:
                # the CPU fallback shares no state with the dict.
                raise RuntimeError(
                    "abandoned streaming feed still in flight")
            counts = None
            if self._feeder is not None:
                counts = self._feeder.take_window_if_complete(snapshot)
            if counts is None:  # not streamed (or incomplete): one-shot
                counts = self._aggregator.window_counts(snapshot)
            return "counts", counts

        def fallback():
            return "prof", self._fallback.aggregate(snapshot)

        # The close span is the guarded device call (streaming: the
        # packed close fetch rides inside take_window_if_complete); its
        # duration also sets the aggregate gauge, so gauge and histogram
        # are the same measurement.
        with tr.span("close") as sp_close:
            kind, out = self._guarded(fast, fallback)
        self.metrics.last_aggregate_duration_s = sp_close.duration_s
        if self._feeder is not None and kind == "counts":
            # Streamed windows: the mid-window feed work and the packed
            # close fetch are tracked by the feeder — record them as
            # spans from the SAME numbers its stats export (lockstep).
            fed = self._feeder.stats.get("last_window_feed_s", 0.0)
            if fed:
                tr.add_span("feed", fed)
            # The double-buffer overlap split (docs/perf.md "sub-RTT
            # close"): capture-thread seconds spent DISPATCHING feeds —
            # work whose device execution overlaps capture instead of
            # stalling it. The deferred settle residue is feed minus
            # this span; the overlap is visible in /debug/windows.
            disp = self._feeder.stats.get("last_window_dispatch_s", 0.0)
            if disp:
                tr.add_span("feed_dispatch_overlap", disp)
            # The ingest-wall split (docs/perf.md "ingest wall"): what
            # this window's drains spent HASHING batches vs COALESCING
            # them to (stack, weight) pairs. Same lockstep contract as
            # feed/feed_dispatch_overlap: the feeder resets these per
            # window and pops the aggregator timings that source them,
            # so an empty or fallback window records nothing stale.
            hsh = self._feeder.stats.get("last_window_hash_s", 0.0)
            if hsh:
                tr.add_span("feed_hash", hsh)
            co = self._feeder.stats.get("last_window_coalesce_s", 0.0)
            if co:
                tr.add_span("feed_coalesce", co)
            ca = self._feeder.stats.get("last_window_carry_s", 0.0)
            if ca:
                tr.add_span("feed_carry", ca)
            if self._feeder.stats.get("last_window_streamed", 0):
                tr.add_span("fetch",
                            self._feeder.stats.get("last_close_s", 0.0))
        if kind == "counts":
            # Buffer-flip and delta-fetch spans come from the close that
            # just ran (streamed or one-shot): the aggregator's timings
            # dict carries buffer_flip on every double-buffered close and
            # delta_fetch only when THIS close fetched touched blocks
            # instead of the full prefix (dict.py close_collect).
            tim = getattr(self._aggregator, "timings", None) or {}
            flip = tim.get("buffer_flip", 0.0)
            if flip:
                tr.add_span("buffer_flip", flip)
            delta = tim.get("delta_fetch", 0.0)
            if delta:
                tr.add_span("delta_fetch", delta)
            n_piped = self._submit_to_pipeline(out, snapshot, tr)
            if n_piped is not None:
                self.metrics.samples_aggregated += snapshot.total_samples()
                return n_piped
            try:
                out = self._encode_inline(out, snapshot)
                kind = "enc"
                tr.add_span("encode", self.metrics.last_encode_duration_s)
            except Exception as e:  # noqa: BLE001 - window must still ship
                if getattr(self, "_encode_timed", False):
                    # Only span an encode that actually ran: the
                    # inflight-guard raise happens before any timing and
                    # must not fabricate a sample from the previous
                    # window's gauge value.
                    tr.add_span("encode",
                                self.metrics.last_encode_duration_s,
                                error=repr(e)[:200])
                if self._fallback is None:
                    raise
                _log.warn("fast encode failed; scalar fallback for this "
                          "window", error=repr(e))
                kind, out = fallback()
        self.metrics.samples_aggregated += snapshot.total_samples()
        if kind == "prof":
            tr.annotate(path="scalar-fallback")
            with tr.span("ship"):
                for prof in out:
                    self._write_profile(prof)
            return len(out)
        tr.annotate(path="inline")
        try:
            with tr.span("ship"):
                n = self._write_encoded(out)
        finally:
            # Secondaries run even when the pprof write raised (the
            # iteration guard upstream owns that error): a store outage
            # must not starve the PGO loop — the same try/finally the
            # pipelined route's registry fan-out uses.
            if self._sinks is not None:
                self._emit_sinks_inline(out, snapshot)
        return n

    def _submit_to_pipeline(self, counts, snapshot: WindowSnapshot,
                            tr=NULL_TRACE) -> int | None:
        """Try to hand the closed window to the encode pipeline. Returns
        the handed-off pid count, the scalar-fallback profile count when
        backpressure forced an inline ship, or None when the window must
        take the inline encode path (no pipeline / pipeline disabled /
        backpressure without a fallback aggregator). On a successful
        hand-off the window's trace detaches: the worker records the
        encode/ship spans and completes it after the ship."""
        if self._pipeline is None or self._pipeline.disabled:
            return None
        fb = None
        if self._fallback is not None:
            fb = lambda snap=snapshot: self._ship_scalar(snap)  # noqa: E731
        try:
            n = self._pipeline.submit(counts, snapshot.time_ns,
                                      snapshot.window_ns,
                                      snapshot.period_ns, fallback=fb,
                                      trace=tr)
        except Exception as e:  # noqa: BLE001 - window must still ship
            # prepare() died on the profiler thread (e.g. MemoryError
            # growing mirrors): give this window to the inline path,
            # whose own try/except still ends in the scalar fallback.
            _log.warn("pipeline hand-off failed; inline encode for this "
                      "window", error=repr(e))
            return None
        if n is not None:
            tr.annotate(path="pipeline")
            return n
        # Backpressure: the worker is still encoding the previous window.
        # The encoder's state is its — this window cannot ride it inline,
        # so ship through the scalar path (counted, observable).
        self.metrics.encode_backpressure_total += 1
        if self._fallback is None:
            # No scalar path: wait the worker out (bounded), then retry
            # once — correctness over latency for fallback-less configs.
            self._pipeline.flush(timeout_s=self._encode_deadline or 60.0)
            n = self._pipeline.submit(counts, snapshot.time_ns,
                                      snapshot.window_ns,
                                      snapshot.period_ns, trace=tr)
            if n is None:
                raise RuntimeError(
                    "encode pipeline busy past its flush bound and no "
                    "fallback aggregator is configured")
            tr.annotate(path="pipeline")
            return n
        _log.warn("encode pipeline busy at window close; scalar fallback "
                  "for this window")
        tr.annotate(path="scalar-backpressure")
        with tr.span("ship"):
            return self._ship_scalar(snapshot)

    def _encode_inline(self, counts, snapshot: WindowSnapshot):
        """Encode on the profiler thread (no pipeline, or pipeline
        disabled). With encode_deadline_s set, the encode runs on an
        abandonable daemon thread: a pathological transient (a
        post-rotation template rebuild is tens of seconds at 50k pids)
        costs this window a scalar fallback instead of an unbounded
        capture stall — and the abandoned encode keeps warming the
        template for the windows after it."""
        # False until this WINDOW's encode is actually timed: the
        # inflight-guard raise below exits before any timing, and the
        # trace must not record the previous window's duration as this
        # window's errored encode span.
        self._encode_timed = False
        if self._encode_inflight is not None:
            if not self._encode_inflight.is_set():
                # The abandoned encode still owns the encoder's state.
                raise RuntimeError("abandoned encode still in flight")
            if "err" in (self._encode_abandoned or {}):
                # The abandoned encode DIED mid-flight: the template may
                # be half-mutated (same hazard the pipeline's
                # _fail_window resets for). Drop the mirrors before
                # touching the encoder again.
                _log.warn("abandoned encode failed; resetting encoder",
                          error=repr(self._encode_abandoned["err"]))
                self._encoder.reset()
            self._encode_inflight = None
            self._encode_abandoned = None
        t0 = time.perf_counter()
        self._encode_timed = True
        try:
            if self._encode_deadline is None:
                return self._encoder.encode(
                    counts, snapshot.time_ns, snapshot.window_ns,
                    snapshot.period_ns)
            import numpy as np

            from parca_agent_tpu.utils.bounded import bounded_call

            # The aggregator's counts buffer is only valid for one close;
            # an abandoned encode may still be reading after that.
            counts_copy = np.asarray(counts).copy()
            status, out, done, box = bounded_call(
                lambda: self._encoder.encode(
                    counts_copy, snapshot.time_ns, snapshot.window_ns,
                    snapshot.period_ns),
                self._encode_deadline, thread_name="encode-deadline")
            if status == "hang":
                self._encode_inflight = done
                self._encode_abandoned = box
                self.metrics.encode_deadline_hits_total += 1
                raise RuntimeError(
                    f"encode exceeded the soft deadline "
                    f"({self._encode_deadline}s); scalar fallback")
            if status == "err":
                raise out
            return out
        finally:
            self.metrics.last_encode_duration_s = \
                time.perf_counter() - t0

    # -- actor --------------------------------------------------------------

    def run(self) -> None:
        # Re-runnable under supervision: a crashed profiler actor is
        # restarted by the run group, so a successful re-entry clears the
        # previous crash record.
        self.crashed = None
        try:
            while not self._stop.is_set():
                t0 = time.monotonic()
                faults.inject("actor.profiler")
                if not self.run_iteration():
                    return
                elapsed = time.monotonic() - t0
                self._stop.wait(max(0.0, self._duration - elapsed))
        except BaseException as e:
            # Anything escaping run_iteration is a bug, not an iteration
            # failure; record it so the CLI can exit nonzero instead of
            # treating thread death as a clean shutdown (and so the
            # supervisor can decide to restart this actor).
            self.crashed = e
            raise
        finally:
            # The pipeline is torn down only on a real exit (stop
            # requested or source exhausted): a supervised restart after
            # a crash must find it alive, not stopped. GC stewardship is
            # ALWAYS restored — the process may outlive a crashed,
            # unsupervised profiler, and must not inherit a disabled
            # collector; a supervised re-entry re-arms it in _manage_gc.
            if self.crashed is None and self._pipeline is not None:
                # Clean shutdown flushes the in-flight window: everything
                # aggregated gets shipped before the actor exits.
                self._pipeline.close()
            if self.crashed is None and self._sinks is not None:
                # After the pipeline drained: the sink close is the
                # AutoFDO accumulator's final crash-only flush, so a
                # clean shutdown persists the partial flush interval.
                self._sinks.close()
            self._restore_gc()

    crashed: BaseException | None = None

    def stop(self) -> None:
        self._stop.set()
