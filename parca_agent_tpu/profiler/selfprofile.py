"""Self-profiling: the agent samples its own threads into pprof.

Role of the reference's /debug/pprof/* endpoints and per-component
runtimepprof labels (cmd/parca-agent/main.go:269-275,256): operators
profile the profiler. Go gets this from its runtime; here the agent
runtime is Python threads over native/JAX calls, so the self-profiler is
a sampling wall-clock profiler over `sys._current_frames()` — every
actor thread (profiler, batch, http, discovery-*, encode-pipeline) is
attributed by its thread name via a `thread` sample label, the analog of
the reference's `component` profile labels. The encode-pipeline worker
matters here: with pipelined encoding the per-window pprof serialization
cost moves OFF the profiler thread, and its self-profile attribution is
how an operator verifies the overlap is real (encode samples under
`thread=encode-pipeline`, capture samples under `thread=profiler`).

The output is standard gzipped profile.proto with function/line info, so
any pprof consumer (including this repo's parse_pprof) reads it. Building
it exercises the same wire codec the main profile path uses.
"""

from __future__ import annotations

import gzip
import sys
import threading
import time

from parca_agent_tpu.pprof import proto
from parca_agent_tpu.pprof.builder import (
    F_FILENAME,
    F_ID,
    F_NAME,
    F_SYSTEM_NAME,
    L_KEY,
    L_STR,
    LINE_FUNCTION_ID,
    LINE_LINE,
    LOC_ID,
    LOC_LINE,
    P_DURATION_NANOS,
    P_FUNCTION,
    P_LOCATION,
    P_PERIOD,
    P_PERIOD_TYPE,
    P_SAMPLE,
    P_SAMPLE_TYPE,
    P_STRING_TABLE,
    P_TIME_NANOS,
    S_LABEL,
    S_LOCATION_ID,
    S_VALUE,
    VT_TYPE,
    VT_UNIT,
    _Strings,
)

MAX_SELF_DEPTH = 127  # same stack budget as the capture path


def collect_samples(duration_s: float, hz: float = 100.0,
                    frames_fn=None, clock=time.monotonic,
                    sleep=time.sleep) -> dict:
    """Sample all threads' Python stacks for duration_s at hz.

    Returns {(thread_name, leaf-first ((file, func, line), ...)): count}.
    frames_fn/clock/sleep are injectable for tests.
    """
    frames_fn = frames_fn or sys._current_frames
    me = threading.get_ident()
    counts: dict = {}
    period = 1.0 / hz
    deadline = clock() + duration_s
    while clock() < deadline:
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in frames_fn().items():
            if ident == me:
                continue  # don't profile the profiling thread
            stack = []
            f = frame
            while f is not None and len(stack) < MAX_SELF_DEPTH:
                code = f.f_code
                stack.append((code.co_filename, code.co_name, f.f_lineno))
                f = f.f_back
            if not stack:
                continue
            key = (names.get(ident, f"thread-{ident}"), tuple(stack))
            counts[key] = counts.get(key, 0) + 1
        sleep(period)
    return counts


class _ProfileEmitter:
    """Shared profile.proto emitter for the self-profile flavors: interns
    (file, func, line) stacks into location/function tables, then writes
    the string table + headers once. Both the wall-clock and heap builders
    go through here so a wire-format fix lands in exactly one place."""

    def __init__(self, sample_types: list[tuple[str, str]]):
        self.st = _Strings()
        self.w = proto.Writer()
        for typ, unit in sample_types:
            vt = proto.Writer().varint(VT_TYPE, self.st(typ)).varint(
                VT_UNIT, self.st(unit))
            self.w.message(P_SAMPLE_TYPE, vt.buf)
        self._func_ids: dict[tuple[str, str], int] = {}
        self._loc_ids: dict[tuple[int, int], int] = {}
        self._functions: list[tuple[str, str]] = []
        self._locations: list[tuple[int, int]] = []

    def _loc_for(self, file: str, func: str, line: int) -> int:
        fkey = (file, func)
        fid = self._func_ids.get(fkey)
        if fid is None:
            fid = self._func_ids[fkey] = len(self._functions) + 1
            self._functions.append(fkey)
        lkey = (fid, line)
        lid = self._loc_ids.get(lkey)
        if lid is None:
            lid = self._loc_ids[lkey] = len(self._locations) + 1
            self._locations.append(lkey)
        return lid

    def add_sample(self, stack, values: list[int],
                   labels: dict[str, str] | None = None) -> None:
        """stack: leaf-first ((file, func, line), ...)."""
        sw = proto.Writer()
        sw.packed(S_LOCATION_ID,
                  [self._loc_for(f, fn, ln) for f, fn, ln in stack])
        sw.packed(S_VALUE, values)
        for k, v in (labels or {}).items():
            lw = proto.Writer().varint(L_KEY, self.st(k)).varint(
                L_STR, self.st(v))
            proto.put_tag_bytes(sw.buf, S_LABEL, bytes(lw.buf))
        self.w.message(P_SAMPLE, sw.buf)

    def finish(self, time_ns: int | None = None, duration_ns: int = 0,
               period_type: tuple[str, str] | None = None,
               period: int = 0, compress: bool = True) -> bytes:
        for lid, (fid, line) in enumerate(self._locations, 1):
            lw = proto.Writer().varint(LOC_ID, lid)
            lnw = proto.Writer().varint(LINE_FUNCTION_ID, fid).varint(
                LINE_LINE, line)
            lw.message(LOC_LINE, lnw.buf)
            self.w.message(P_LOCATION, lw.buf)
        for fid, (file, func) in enumerate(self._functions, 1):
            fw = (proto.Writer()
                  .varint(F_ID, fid)
                  .varint(F_NAME, self.st(func))
                  .varint(F_SYSTEM_NAME, self.st(func))
                  .varint(F_FILENAME, self.st(file)))
            self.w.message(P_FUNCTION, fw.buf)
        pt = None
        if period_type is not None:
            pt = proto.Writer().varint(VT_TYPE, self.st(period_type[0])) \
                .varint(VT_UNIT, self.st(period_type[1]))
        for s in self.st.table:
            proto.put_tag_bytes(self.w.buf, P_STRING_TABLE, s.encode())
        self.w.varint(P_TIME_NANOS,
                      time_ns if time_ns is not None else time.time_ns())
        if duration_ns:
            self.w.varint(P_DURATION_NANOS, duration_ns)
        if pt is not None:
            self.w.message(P_PERIOD_TYPE, pt.buf)
        if period:
            self.w.varint(P_PERIOD, period)
        data = self.w.getvalue()
        return gzip.compress(data, 6) if compress else data


def build_self_pprof(counts: dict, duration_s: float, hz: float = 100.0,
                     time_ns: int | None = None,
                     compress: bool = True) -> bytes:
    """Encode collected samples as profile.proto: samples/count +
    cpu/nanoseconds values, leaf-first locations with function+line."""
    period_ns = int(1e9 / hz)
    em = _ProfileEmitter([("samples", "count"), ("cpu", "nanoseconds")])
    for (thread_name, stack), n in sorted(
            counts.items(), key=lambda kv: -kv[1]):
        em.add_sample(stack, [n, n * period_ns], {"thread": thread_name})
    return em.finish(time_ns=time_ns, duration_ns=int(duration_s * 1e9),
                     period_type=("cpu", "nanoseconds"), period=period_ns,
                     compress=compress)


def profile_self(duration_s: float = 10.0, hz: float = 100.0) -> bytes:
    """One-call self profile: sample then encode (the /debug/pprof/profile
    handler body)."""
    t0 = time.time_ns()
    counts = collect_samples(duration_s, hz)
    return build_self_pprof(counts, duration_s, hz, time_ns=t0)


def heap_self(seconds: float = 5.0, top: int = 512,
              sleep=time.sleep) -> bytes:
    """Heap profile via a BOUNDED tracemalloc window (the
    /debug/pprof/heap role): start tracing, wait `seconds`, snapshot the
    allocations still live from that window, then STOP tracing so the
    agent pays the 2-4x allocation overhead only for the window — not
    for the rest of its life. If something else already enabled
    tracemalloc, the snapshot is immediate and tracing is left running
    (it isn't ours to stop)."""
    import tracemalloc

    started_here = not tracemalloc.is_tracing()
    if started_here:
        tracemalloc.start(8)
        sleep(seconds)
    try:
        snapshot = tracemalloc.take_snapshot()
    finally:
        if started_here:
            tracemalloc.stop()
    stats = snapshot.statistics("traceback")[:top]
    counts: dict = {}
    sizes: dict = {}
    for st in stats:
        stack = tuple((fr.filename, "", fr.lineno)
                      for fr in reversed(st.traceback))[:MAX_SELF_DEPTH]
        if not stack:
            continue
        counts[stack] = counts.get(stack, 0) + st.count
        sizes[stack] = sizes.get(stack, 0) + st.size
    em = _ProfileEmitter([("inuse_objects", "count"),
                          ("inuse_space", "bytes")])
    for stack, n in sorted(counts.items(), key=lambda kv: -sizes[kv[0]]):
        em.add_sample(
            tuple((f, fn or f.rsplit("/", 1)[-1], ln)
                  for f, fn, ln in stack),
            [n, sizes[stack]])
    return em.finish(duration_ns=int(seconds * 1e9))
