"""Self-profiling: the agent samples its own threads into pprof.

Role of the reference's /debug/pprof/* endpoints and per-component
runtimepprof labels (cmd/parca-agent/main.go:269-275,256): operators
profile the profiler. Go gets this from its runtime; here the agent
runtime is Python threads over native/JAX calls, so the self-profiler is
a sampling wall-clock profiler over `sys._current_frames()` — every
actor thread (profiler, batch, http, discovery-*) is attributed by its
thread name via a `thread` sample label, the analog of the reference's
`component` profile labels.

The output is standard gzipped profile.proto with function/line info, so
any pprof consumer (including this repo's parse_pprof) reads it. Building
it exercises the same wire codec the main profile path uses.
"""

from __future__ import annotations

import gzip
import sys
import threading
import time

from parca_agent_tpu.pprof import proto
from parca_agent_tpu.pprof.builder import (
    F_FILENAME,
    F_ID,
    F_NAME,
    F_SYSTEM_NAME,
    L_KEY,
    L_STR,
    LINE_FUNCTION_ID,
    LINE_LINE,
    LOC_ID,
    LOC_LINE,
    P_DURATION_NANOS,
    P_FUNCTION,
    P_LOCATION,
    P_PERIOD,
    P_PERIOD_TYPE,
    P_SAMPLE,
    P_SAMPLE_TYPE,
    P_STRING_TABLE,
    P_TIME_NANOS,
    S_LABEL,
    S_LOCATION_ID,
    S_VALUE,
    VT_TYPE,
    VT_UNIT,
    _Strings,
)

MAX_SELF_DEPTH = 127  # same stack budget as the capture path


def collect_samples(duration_s: float, hz: float = 100.0,
                    frames_fn=None, clock=time.monotonic,
                    sleep=time.sleep) -> dict:
    """Sample all threads' Python stacks for duration_s at hz.

    Returns {(thread_name, leaf-first ((file, func, line), ...)): count}.
    frames_fn/clock/sleep are injectable for tests.
    """
    frames_fn = frames_fn or sys._current_frames
    me = threading.get_ident()
    counts: dict = {}
    period = 1.0 / hz
    deadline = clock() + duration_s
    while clock() < deadline:
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in frames_fn().items():
            if ident == me:
                continue  # don't profile the profiling thread
            stack = []
            f = frame
            while f is not None and len(stack) < MAX_SELF_DEPTH:
                code = f.f_code
                stack.append((code.co_filename, code.co_name, f.f_lineno))
                f = f.f_back
            if not stack:
                continue
            key = (names.get(ident, f"thread-{ident}"), tuple(stack))
            counts[key] = counts.get(key, 0) + 1
        sleep(period)
    return counts


def build_self_pprof(counts: dict, duration_s: float, hz: float = 100.0,
                     time_ns: int | None = None,
                     compress: bool = True) -> bytes:
    """Encode collected samples as profile.proto: samples/count +
    cpu/nanoseconds values, leaf-first locations with function+line."""
    st = _Strings()
    w = proto.Writer()

    for typ, unit in (("samples", "count"), ("cpu", "nanoseconds")):
        vt = proto.Writer().varint(VT_TYPE, st(typ)).varint(VT_UNIT, st(unit))
        w.message(P_SAMPLE_TYPE, vt.buf)

    period_ns = int(1e9 / hz)
    func_ids: dict[tuple[str, str], int] = {}
    loc_ids: dict[tuple[int, int], int] = {}
    functions: list[tuple[str, str]] = []
    locations: list[tuple[int, int]] = []

    def loc_for(file: str, func: str, line: int) -> int:
        fkey = (file, func)
        fid = func_ids.get(fkey)
        if fid is None:
            fid = func_ids[fkey] = len(functions) + 1
            functions.append(fkey)
        lkey = (fid, line)
        lid = loc_ids.get(lkey)
        if lid is None:
            lid = loc_ids[lkey] = len(locations) + 1
            locations.append(lkey)
        return lid

    for (thread_name, stack), n in sorted(
            counts.items(), key=lambda kv: -kv[1]):
        sw = proto.Writer()
        sw.packed(S_LOCATION_ID,
                  [loc_for(f, fn, ln) for f, fn, ln in stack])
        sw.packed(S_VALUE, [n, n * period_ns])
        lw = proto.Writer().varint(L_KEY, st("thread")).varint(
            L_STR, st(thread_name))
        proto.put_tag_bytes(sw.buf, S_LABEL, bytes(lw.buf))
        w.message(P_SAMPLE, sw.buf)

    for lid, (fid, line) in enumerate(locations, 1):
        lw = proto.Writer().varint(LOC_ID, lid)
        lnw = proto.Writer().varint(LINE_FUNCTION_ID, fid).varint(
            LINE_LINE, line)
        lw.message(LOC_LINE, lnw.buf)
        w.message(P_LOCATION, lw.buf)

    for fid, (file, func) in enumerate(functions, 1):
        fw = (proto.Writer()
              .varint(F_ID, fid)
              .varint(F_NAME, st(func))
              .varint(F_SYSTEM_NAME, st(func))
              .varint(F_FILENAME, st(file)))
        w.message(P_FUNCTION, fw.buf)

    pt = proto.Writer().varint(VT_TYPE, st("cpu")).varint(
        VT_UNIT, st("nanoseconds"))
    for s in st.table:
        proto.put_tag_bytes(w.buf, P_STRING_TABLE, s.encode())
    w.varint(P_TIME_NANOS,
             time_ns if time_ns is not None else time.time_ns())
    w.varint(P_DURATION_NANOS, int(duration_s * 1e9))
    w.message(P_PERIOD_TYPE, pt.buf)
    w.varint(P_PERIOD, period_ns)

    data = w.getvalue()
    return gzip.compress(data, 6) if compress else data


def profile_self(duration_s: float = 10.0, hz: float = 100.0) -> bytes:
    """One-call self profile: sample then encode (the /debug/pprof/profile
    handler body)."""
    t0 = time.time_ns()
    counts = collect_samples(duration_s, hz)
    return build_self_pprof(counts, duration_s, hz, time_ns=t0)
