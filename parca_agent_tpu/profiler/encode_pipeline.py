"""Double-buffered background pprof encode pipeline.

The profiler's window close used to run aggregate -> encode -> ship on one
thread, so the encoder's slow transients (a ~930 ms cold statics build, a
~300 ms first template layout, a tens-of-seconds post-rotation rebuild at
50k pids) stalled the capture loop and risked perf ring-buffer overflow.
This pipeline moves encode + ship onto a dedicated worker thread:

  * Window close hands the aggregated counts over via submit() — the only
    profiler-thread work is WindowEncoder.prepare() (mirror sync + live
    filter + registry caps), a bounded slice of the old inline cost — and
    capture of window N+1 then overlaps encoding/shipping of window N.
  * The hand-off queue is two slots deep: the window the worker is
    encoding plus the shutdown sentinel. There is deliberately NO deeper
    backlog — a second pending window would need its mirrors synced while
    the worker still reads them. If the worker is still busy at the next
    close, submit() refuses (backpressure) and the caller ships that
    window inline through its scalar fallback, counted and observable.
  * The streaming feeder's drain-tick statics prebuild is routed here too
    (request_prebuild), so ALL encoder-state touches outside prepare()
    happen on the worker thread — the encoder's thread-ownership
    contract (pprof/window_encoder.py module docs). A prebuild in
    progress yields at its next budget batch when a hand-off (or
    shutdown) needs the worker parked.
  * A worker exception ships the failed window through the caller's
    fallback, resets the encoder's mirrors, and disables the pipeline —
    the profiler reverts to its inline path; no window is lost.
  * The warm statics snapshot (pprof/statics_store.py) also rides this
    worker: every snapshot_every-th shipped window, the worker serializes
    the registry + statics state so a restart adopts instead of
    rebuilding. Worker-thread-only by design — the snapshot reads the
    same encoder state prebuilds do, and must never stall capture.
  * close() flushes the in-flight window before stopping the worker, so
    a draining agent ships everything it aggregated.
"""

from __future__ import annotations

import threading
import time

from parca_agent_tpu.runtime.trace import NULL_TRACE
from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.log import get_logger

_log = get_logger("encode-pipeline")

THREAD_NAME = "encode-pipeline"  # self-profile attribution (selfprofile.py)


class EncodePipeline:
    """One worker thread + a two-slot hand-off around a WindowEncoder.

    `ship(out, prep)` is called on the worker thread with the encoded
    [(pid, blob)] list and the _PreparedWindow; blobs are zero-copy
    memoryviews into the template buffer (valid until the next encode —
    i.e. for the whole ship call) unless ship_views=False.
    """

    def __init__(self, encoder, ship, ship_views: bool = True,
                 name: str = THREAD_NAME, snapshot=None,
                 snapshot_every: int = 0, rollup=None,
                 rollup_capture=None, sink_capture=None):
        self._enc = encoder
        self._ship = ship
        self._views = ship_views
        self._name = name
        # Output-backend capture hook (sinks/): `sink_capture(prep)` runs
        # on the PROFILER thread at hand-off and its result rides the
        # prepared window as `prep.sink_ctx` — the rotation-consistent
        # registry view the secondary sinks read on this worker during
        # the ship fan-out. Best-effort: a capture failure is counted
        # and the window ships with sink_ctx=None (frame-reading sinks
        # skip it, the pprof ship is unaffected).
        self._sink_capture = sink_capture
        # Hotspot rollup hook (runtime/hotspots.py): a `rollup(prep, ctx)`
        # callable run on THIS worker thread after every shipped window.
        # `ctx` is whatever `rollup_capture(prep)` returned on the
        # PROFILER thread at hand-off — a rotation-consistent registry
        # view; the fold must read per-id mirrors through it, because a
        # cold-stack rotation (profiler thread, next window's first
        # feed) compacts the live arrays under a still-running fold.
        # Errors are counted, never fatal: a rollup bug costs query
        # freshness, not a window.
        self._rollup = rollup
        self._rollup_capture = rollup_capture
        # Warm statics snapshot hook (pprof/statics_store.py): a
        # `snapshot(period_ns)` callable run on THIS worker thread after
        # every snapshot_every-th shipped window — the one thread that
        # may read the encoder's statics map, and by construction never
        # the capture thread. A snapshot failure is counted, never fatal
        # (the agent just stays cold-restartable one interval longer).
        self._snapshot = snapshot
        self._snapshot_every = snapshot_every
        self._cond = threading.Condition()
        self._window = None   # pending (prep, ctx, fallback, trace) hand-off
        self._prebuild = None        # latest coalesced (period_ns, budget_s)
        self._state = "idle"         # idle | encode | prebuild
        self._handoff = False        # profiler parked the worker
        self._interrupt = threading.Event()  # yields a running prebuild
        self._stopping = False
        self._thread: threading.Thread | None = None
        self.disabled = False
        self.last_error: Exception | None = None
        self.stats = {
            "windows_pipelined": 0,
            "windows_lost": 0,
            "ship_errors": 0,
            "backpressure_fallbacks": 0,
            "prebuilds": 0,
            "encoder_exceptions": 0,
            "last_handoff_s": 0.0,
            "last_encode_s": 0.0,
            "last_ship_s": 0.0,
            "overlap_s_total": 0.0,
            "snapshots_written": 0,
            "snapshot_errors": 0,
            "last_snapshot_s": 0.0,
            "windows_rolled": 0,
            "rollup_errors": 0,
            "last_rollup_s": 0.0,
            "sink_capture_errors": 0,
        }

    # -- profiler-thread API -------------------------------------------------

    def submit(self, counts, time_ns: int, duration_ns: int, period_ns: int,
               fallback=None, trace=NULL_TRACE) -> int | None:
        """Hand one closed window to the worker. Returns the number of
        live pids handed off, or None when the pipeline is disabled or
        still busy with the previous window (backpressure — the caller
        must ship the window itself, normally via its scalar fallback).
        `fallback`, a zero-arg callable, re-aggregates and ships the
        window if the worker dies on it. `trace`, the window's
        WindowTrace, detaches on a successful hand-off: the worker
        records the encode/ship spans and completes it after the ship.
        Profiler thread only."""
        if self.disabled or self._stopping:
            return None
        t0 = time.perf_counter()
        with self._cond:
            if self._state == "encode" or self._window is not None:
                self.stats["backpressure_fallbacks"] += 1
                return None
            # Park the worker: a budgeted prebuild yields at its next
            # batch boundary; nothing new starts while _handoff is set.
            self._handoff = True
            self._interrupt.set()
            while self._state != "idle":
                self._cond.wait()
        try:
            with trace.span("prepare"):
                prep = self._enc.prepare(counts, time_ns, duration_ns,
                                         period_ns)
        except BaseException:
            with self._cond:
                self._handoff = False
                self._interrupt.clear()
                self._cond.notify_all()
            raise
        trace.detach()
        if self._sink_capture is not None:
            # Still the profiler thread (rotation cannot interleave):
            # the captured view brackets the prepared ids exactly, same
            # reasoning as the rollup capture below.
            try:
                prep.sink_ctx = self._sink_capture(prep)
            except Exception as e:  # noqa: BLE001 - sinks are best-effort
                self.stats["sink_capture_errors"] += 1
                _log.warn("sink context capture failed; secondary sinks "
                          "skip this window", error=repr(e))
        rollup_ctx = None
        if self._rollup is not None and self._rollup_capture is not None:
            if self._rollup_capture is self._sink_capture \
                    and prep.sink_ctx is not None:
                # The profiler registers the SAME capture hook for both
                # consumers (one definition of "safe to read
                # off-thread"): reuse the view captured above instead of
                # building an identical one on the hand-off path.
                rollup_ctx = prep.sink_ctx
            else:
                # Still the profiler thread: rotation cannot interleave,
                # so the captured view brackets the prepared ids exactly.
                try:
                    rollup_ctx = self._rollup_capture(prep)
                except Exception as e:  # noqa: BLE001 - best-effort
                    self.stats["rollup_errors"] += 1
                    _log.warn("hotspot rollup capture failed; window "
                              "will ship unfolded", error=repr(e))
        with self._cond:
            # Enqueue and unpark in ONE lock acquisition: clearing
            # _handoff first would let a pending prebuild slip in ahead
            # of the window (with _interrupt already cleared, nothing
            # would yield it) and delay the encode by a whole budget.
            self._window = (prep, rollup_ctx, fallback, trace)
            self._handoff = False
            self._interrupt.clear()
            self._cond.notify_all()
        self._ensure_thread()
        self.stats["last_handoff_s"] = time.perf_counter() - t0
        return len(prep.caps)

    def request_prebuild(self, period_ns: int,
                         budget_s: float = 0.25) -> None:
        """Ask the worker to run one budgeted statics prebuild pass when
        it is next free (the streaming feeder's drain tick). Coalescing:
        only the latest request is kept. Never blocks."""
        if self.disabled or self._stopping or not period_ns:
            return
        with self._cond:
            self._prebuild = (int(period_ns), float(budget_s))
            self._cond.notify_all()
        self._ensure_thread()

    @property
    def busy(self) -> bool:
        with self._cond:
            return self._window is not None or self._state == "encode"

    def flush(self, timeout_s: float = 60.0) -> bool:
        """Block until no window is pending or being encoded (pending
        prebuilds are not waited for). False on timeout."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._window is not None or self._state == "encode":
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(left)
        return True

    def quiesce(self, timeout_s: float = 60.0) -> bool:
        """flush() plus drain any pending prebuild: the worker is fully
        parked on return (tests/bench sequencing). False on timeout."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while (self._window is not None or self._prebuild is not None
                    or self._state != "idle"):
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(left)
        return True

    def close(self, timeout_s: float = 60.0) -> bool:
        """Flush the in-flight window, then stop the worker. False if the
        flush or join timed out."""
        ok = self.flush(timeout_s)
        with self._cond:
            self._stopping = True
            self._interrupt.set()
            self._cond.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout_s)
            ok = ok and not t.is_alive()
        return ok

    # -- worker --------------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._run,
                                            name=self._name, daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while True:
            with self._cond:
                while (not self._stopping and self._window is None
                        and (self._prebuild is None or self._handoff)):
                    self._cond.wait()
                if self._window is not None:
                    job, self._window = ("window", self._window), None
                    self._state = "encode"
                elif self._stopping:
                    return
                else:
                    job, self._prebuild = ("prebuild", self._prebuild), None
                    self._state = "prebuild"
                self._cond.notify_all()
            try:
                if job[0] == "window":
                    self._do_window(*job[1])
                else:
                    period_ns, budget_s = job[1]
                    self._enc.build_statics(period_ns, budget_s=budget_s,
                                            stop=self._interrupt,
                                            prepare_order=True)
                    self.stats["prebuilds"] += 1
            except Exception as e:  # noqa: BLE001 - surfaced via disable
                if job[0] == "window":
                    self._fail_window(e, job[1][2], job[1][3])
                    with self._cond:
                        self._state = "idle"
                        self._cond.notify_all()
                    return  # disabled: the worker's work here is done
                # A prebuild failure is non-fatal: staleness guards still
                # trip, the next pass (or encode) retries the build.
                _log.warn("statics prebuild failed on the encode worker",
                          error=repr(e))
            finally:
                with self._cond:
                    if self._state != "idle":
                        self._state = "idle"
                        self._cond.notify_all()

    def revive(self, reset: bool = False) -> None:
        """Re-arm a pipeline disabled by a worker death (the supervisor's
        probe-revive hook): clear the disabled latch so the next submit()
        restarts the worker thread. The encoder's mirrors were already
        reset by _fail_window; ``reset=True`` forces another reset for
        callers reviving after external encoder surgery. Fail-open
        (palint fail-open-hook): a revive that raises reads as a revive
        failure to the supervisor — count and stay disabled instead."""
        try:
            if reset:
                self._enc.reset()
            self.disabled = False
            self.last_error = None
            _log.info("encode pipeline revived")
        except Exception as e:  # noqa: BLE001 - revive contract
            _log.warn("encoder reset failed during revive; pipeline "
                      "stays disabled until the next probe tick",
                      error=repr(e))

    def _do_window(self, prep, rollup_ctx, fallback,
                   trace=NULL_TRACE) -> None:
        t0 = time.perf_counter()
        # Chaos site: an injected crash here is a worker death — the
        # window ships via the caller's fallback, the pipeline disables,
        # and the supervisor's probe revives it.
        faults.inject("actor.encode")
        # Statics work that runs inside this encode (a cold build, a
        # post-rotation rebuild) is the latency cliff the trace exists
        # for: span it from the encoder's own accumulated-build clock so
        # the span and the encoder's stats can never disagree.
        statics0 = getattr(self._enc, "stats", {}).get(
            "statics_build_s_total", 0.0)
        out = self._enc.encode_prepared(prep, views=self._views)
        enc_s = time.perf_counter() - t0
        self.stats["last_encode_s"] = enc_s
        self.stats["overlap_s_total"] += enc_s
        statics_s = getattr(self._enc, "stats", {}).get(
            "statics_build_s_total", 0.0) - statics0
        if statics_s > 0:
            # histogram=False: the encoder already observed each build
            # call into the "statics" stage histogram; this span is the
            # per-window wide-event view only (double-feeding the same
            # seconds would distort the distribution).
            trace.add_span("statics", statics_s, histogram=False)
        trace.add_span("encode", enc_s)
        t0 = time.perf_counter()
        try:
            self._ship(out, prep)
        except Exception as e:  # noqa: BLE001 - ship != encoder failure
            # A writer error is NOT an encoder failure: the template is
            # healthy, re-shipping via the fallback would duplicate the
            # profiles already written, and disabling the pipeline over a
            # transient I/O error would be self-harm. Mirror the inline
            # path's behavior (a writer raise there loses the rest of the
            # window as an iteration error): log, count, carry on.
            self.stats["ship_errors"] += 1
            _log.warn("pipelined ship failed; window partially shipped",
                      error=repr(e))
            trace.add_span("ship", time.perf_counter() - t0,
                           error=repr(e)[:200])
            trace.complete(error=f"ship failed: {e!r}"[:200])
            return
        ship_s = time.perf_counter() - t0
        self.stats["last_ship_s"] = ship_s
        trace.add_span("ship", ship_s)
        self.stats["windows_pipelined"] += 1
        trace.complete()
        if self._rollup is not None and (rollup_ctx is not None
                                         or self._rollup_capture is None):
            # Hotspot fold on the window clock, after the ship: a fold
            # failure can neither delay nor lose the window, and the
            # capture thread never sees this work at all. A window whose
            # hand-off capture failed (ctx None with a capture hook
            # configured) ships unfolded — folding it off the live
            # aggregator would reopen the rotation race.
            t0 = time.perf_counter()
            try:
                self._rollup(prep, rollup_ctx)
                self.stats["windows_rolled"] += 1
            except Exception as e:  # noqa: BLE001 - rollup is best-effort
                self.stats["rollup_errors"] += 1
                _log.warn("hotspot rollup failed on the encode worker",
                          error=repr(e))
            self.stats["last_rollup_s"] = time.perf_counter() - t0
        if self._snapshot is not None and self._snapshot_every > 0 \
                and self.stats["windows_pipelined"] \
                % self._snapshot_every == 0:
            # Warm statics snapshot on the window clock, on this worker
            # thread, AFTER the ship — so a failed snapshot can neither
            # delay nor duplicate the window. Errors are contained here:
            # letting one escape would read as an encoder death and
            # disable the pipeline over a disk hiccup.
            t0 = time.perf_counter()
            try:
                # The store's save() reports failure as False and a
                # clean skip (disk already current) as "skipped" — only
                # a real write counts as written, so this gauge stays in
                # lockstep with the store's own snapshots_written. The
                # except arm covers custom callables.
                r = self._snapshot(prep.period_ns)
                if r is False:
                    self.stats["snapshot_errors"] += 1
                elif r != "skipped":
                    self.stats["snapshots_written"] += 1
            except Exception as e:  # noqa: BLE001 - snapshot is best-effort
                self.stats["snapshot_errors"] += 1
                _log.warn("statics snapshot failed on the encode worker",
                          error=repr(e))
            self.stats["last_snapshot_s"] = time.perf_counter() - t0

    def _fail_window(self, e: Exception, fallback,
                     trace=NULL_TRACE) -> None:
        """Worker died on a window: disable the pipeline (the profiler
        reverts to its inline path), reset the encoder's possibly
        half-mutated state, and ship the window via the caller's scalar
        fallback so it is not lost. The window's trace completes with
        the error either way — a lost window must be visible in the
        flight recorder, not just in a counter."""
        self.stats["encoder_exceptions"] += 1
        self.last_error = e
        self.disabled = True
        _log.warn("encode pipeline failed; disabling and falling back to "
                  "inline encode", error=repr(e))
        try:
            self._enc.reset()
        except Exception as e2:  # noqa: BLE001 - reset is best-effort
            _log.warn("encoder reset failed after pipeline error",
                      error=repr(e2))
        try:
            if fallback is None:
                self.stats["windows_lost"] += 1
                _log.warn("no fallback for the failed window; window lost")
                trace.annotate(window_lost=True)
                return
            try:
                with trace.span("ship"):
                    fallback()
                trace.annotate(path="scalar-pipeline-fail")
            except Exception as e2:  # noqa: BLE001 - like an iteration error
                self.stats["windows_lost"] += 1
                trace.annotate(window_lost=True)
                _log.warn("scalar fallback for the failed window also "
                          "failed", error=repr(e2))
        finally:
            trace.complete(error=repr(e)[:200])
