"""Container discovery from cgroup names — API-server-free.

The reference discovers containers through the kube API + CRI sockets
(pkg/discovery/kubernetes.go, kubernetes/containerruntimes/*). Neither is
reachable from tests or most dev hosts, so the first-class discoverer here
derives the same `container id -> pids` mapping from /proc/*/cgroup
directly: container runtimes (docker, containerd, cri-o) all embed the
64-hex container id in the cgroup path (the id-extraction role of
containerruntimes.go:83-165). The kube-API discoverer (kubernetes.py)
layers pod metadata on top when a cluster is reachable.
"""

from __future__ import annotations

import dataclasses
import re
import threading
from typing import Callable

from parca_agent_tpu.discovery.manager import Group
from parca_agent_tpu.utils.vfs import VFS, RealFS

_CONTAINER_ID = re.compile(r"([0-9a-f]{64})")
_POD_UID = re.compile(r"pod([0-9a-f]{8}[-_][0-9a-f]{4}[-_][0-9a-f]{4}"
                      r"[-_][0-9a-f]{4}[-_][0-9a-f]{12})")


def parse_container_cgroup(cgroup_text: str) -> dict[str, str]:
    """Extract container id / pod uid labels from one /proc/PID/cgroup."""
    out: dict[str, str] = {}
    for line in cgroup_text.splitlines():
        m = _CONTAINER_ID.search(line)
        if m and "containerid" not in out:
            out["containerid"] = m.group(1)
        p = _POD_UID.search(line)
        if p and "pod_uid" not in out:
            out["pod_uid"] = p.group(1).replace("_", "-")
    return out


@dataclasses.dataclass
class CgroupContainerDiscoverer:
    fs: VFS = dataclasses.field(default_factory=RealFS)
    poll_s: float = 5.0

    def scrape(self) -> list[Group]:
        by_container: dict[str, Group] = {}
        try:
            entries = self.fs.listdir("/proc")
        except OSError:
            return []
        for name in entries:
            if not name.isdigit():
                continue
            pid = int(name)
            try:
                text = self.fs.read_bytes(f"/proc/{pid}/cgroup").decode(
                    errors="replace")
            except OSError:
                continue
            labels = parse_container_cgroup(text)
            cid = labels.get("containerid")
            if not cid:
                continue
            g = by_container.get(cid)
            if g is None:
                g = Group(source=f"cgroup/{cid}", labels=labels, pids=[])
                by_container[cid] = g
            g.pids.append(pid)
            if g.entry_pid == 0 or pid < g.entry_pid:
                g.entry_pid = pid
        return list(by_container.values())

    def run(self, stop: threading.Event,
            up: Callable[[list[Group]], None]) -> None:
        while not stop.is_set():
            up(self.scrape())
            stop.wait(self.poll_s)
