"""Target discovery (reference pkg/discovery)."""

from parca_agent_tpu.discovery.manager import DiscoveryManager, Group
from parca_agent_tpu.discovery.systemd import SystemdDiscoverer
from parca_agent_tpu.discovery.cgroup import CgroupContainerDiscoverer

__all__ = [
    "DiscoveryManager", "Group", "SystemdDiscoverer",
    "CgroupContainerDiscoverer",
]
