"""Per-runtime container-id -> PID resolution (CRI clients).

Role of the reference's kubernetes/containerruntimes tree
(containerruntimes.go:78-81 CRIClient interface; docker/docker.go:65-82,
containerd/containerd.go:73-101, crio/crio.go:79-107): ask the container
runtime itself for a container's main PID. The primary resolution path
here remains the /proc/*/cgroup scan (discovery/cgroup.py) — it needs no
socket permissions and returns EVERY pid in the container — and the
runtime socket is the fallback for containers the scan missed (the
scan/list race, transient /proc read failures). The pid a runtime
returns is in the HOST pid namespace, so the consumer
(kubernetes.PodDiscoverer) validates it against the agent's own /proc
before adopting it — the fallback therefore still requires hostPID; it
does not substitute for it.

Same no-generated-stubs stance as agent/grpc_client.py: the docker client
speaks the engine's HTTP API over its unix socket with stdlib http.client,
and the CRI client hand-encodes the two protobuf messages it needs
(ContainerStatusRequest/Response) with pprof/proto.py, trying
runtime.v1 first and falling back to runtime.v1alpha2 (the generation the
reference pins) for older runtimes.
"""

from __future__ import annotations

import http.client
import json
import socket

from parca_agent_tpu.pprof.proto import Writer, iter_fields
from parca_agent_tpu.utils.log import get_logger

log = get_logger("cri")

DOCKER_SOCKET = "/run/docker.sock"
CONTAINERD_SOCKET = "/run/containerd/containerd.sock"
CONTAINERD_K3S_SOCKET = "/run/k3s/containerd/containerd.sock"
CRIO_SOCKET = "/run/crio/crio.sock"
DEFAULT_TIMEOUT_S = 2.0


class CRIError(RuntimeError):
    pass


class CRITransportError(CRIError):
    """Socket/channel-level failure (runtime down, wrong socket, hang) —
    as opposed to a per-container lookup miss, which is routine churn.
    The distinction drives CRIResolver's client eviction and circuit
    breaker: transport failures heal by rebuilding, lookup misses must
    not tear down a healthy channel."""


def split_runtime_prefix(container_id: str) -> tuple[str, str]:
    """'containerd://<hex>' -> ('containerd', '<hex>'). The runtime name
    is how the reference's Kubernetes client picks which CRI client to
    ask (kubernetes/kubernetes.go PIDFromContainerID dispatch)."""
    runtime, sep, bare = container_id.partition("://")
    if not sep or not bare:
        raise CRIError(f"container id {container_id!r} has no runtime://"
                       " prefix")
    return runtime, bare


class _UnixHTTPConnection(http.client.HTTPConnection):
    """HTTPConnection whose transport is an AF_UNIX stream socket."""

    def __init__(self, path: str, timeout: float):
        super().__init__("localhost", timeout=timeout)
        self._path = path

    def connect(self):
        self.sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self.sock.settimeout(self.timeout)
        self.sock.connect(self._path)


class DockerClient:
    """Engine-API ContainerInspect -> .State.Pid
    (docker/docker.go:65-82; GET /containers/{id}/json)."""

    runtime = "docker"

    def __init__(self, socket_path: str = DOCKER_SOCKET,
                 timeout_s: float = DEFAULT_TIMEOUT_S):
        self._path = socket_path
        self._timeout = timeout_s

    def pid_from_container_id(self, container_id: str) -> int:
        runtime, bare = split_runtime_prefix(container_id)
        if runtime != self.runtime:
            raise CRIError(f"invalid CRI {container_id}, it should be docker")
        conn = _UnixHTTPConnection(self._path, self._timeout)
        try:
            try:
                conn.request("GET", f"/containers/{bare}/json")
                resp = conn.getresponse()
                body = resp.read()
            except OSError as e:  # connect/read failure: engine is down
                raise CRITransportError(
                    f"docker engine at {self._path}: {e}") from e
            if resp.status != 200:
                raise CRIError(
                    f"docker inspect {bare}: HTTP {resp.status} "
                    f"{body[:200]!r}")
        finally:
            conn.close()
        state = (json.loads(body).get("State") or {})
        pid = state.get("Pid")
        if not pid:
            raise CRIError(f"docker inspect {bare}: no running pid in State")
        return int(pid)

    def close(self) -> None:  # connection-per-request; nothing held
        pass


def encode_container_status_request(container_id: str) -> bytes:
    """ContainerStatusRequest{container_id=1, verbose=2}; verbose=true is
    what makes the runtime attach the 'info' JSON carrying the pid
    (containerd.go:80-83)."""
    w = Writer()
    w.message(1, container_id.encode())
    w.varint(2, 1)
    return w.getvalue()


def decode_container_status_info(data: bytes) -> dict[str, str]:
    """ContainerStatusResponse: field 2 is map<string,string> info; each
    map entry is a nested message {key=1, value=2}."""
    info: dict[str, str] = {}
    for field, _wt, val in iter_fields(data):
        if field != 2 or not isinstance(val, bytes):
            continue
        key = value = ""
        for efield, _ewt, eval_ in iter_fields(val):
            if efield == 1 and isinstance(eval_, bytes):
                key = eval_.decode(errors="replace")
            elif efield == 2 and isinstance(eval_, bytes):
                value = eval_.decode(errors="replace")
        info[key] = value
    return info


def encode_container_status_response(info: dict[str, str],
                                     ) -> bytes:
    """The inverse of decode_container_status_info — the fake-runtime test
    servers use this to speak the wire format back."""
    w = Writer()
    for key, value in info.items():
        entry = Writer()
        entry.message(1, key.encode())
        entry.message(2, value.encode())
        w.message(2, entry.getvalue())
    return w.getvalue()


class CRIRuntimeClient:
    """containerd + cri-o share one client: both are CRI gRPC servers and
    both return the pid inside the verbose info JSON
    (containerd.go:73-101, crio.go:79-107)."""

    runtime = "containerd"

    def __init__(self, socket_path: str, timeout_s: float = DEFAULT_TIMEOUT_S,
                 target: str | None = None):
        try:
            import grpc
        except ImportError as e:  # pragma: no cover - grpc is in the image
            raise CRIError("grpc package unavailable") from e
        self._grpc = grpc
        self._timeout = timeout_s
        self._channel = grpc.insecure_channel(target or f"unix:{socket_path}")

    def _container_status(self, bare_id: str) -> dict[str, str]:
        request = encode_container_status_request(bare_id)
        last_err: Exception | None = None
        code = None
        for api in ("runtime.v1", "runtime.v1alpha2"):
            call = self._channel.unary_unary(
                f"/{api}.RuntimeService/ContainerStatus",
                request_serializer=lambda b: b,
                response_deserializer=lambda b: b,
            )
            try:
                return decode_container_status_info(
                    call(request, timeout=self._timeout))
            except self._grpc.RpcError as e:
                last_err = e
                code = getattr(e, "code", lambda: None)()
                if code != self._grpc.StatusCode.UNIMPLEMENTED:
                    break  # real failure; don't mask it with the fallback
        if code in (self._grpc.StatusCode.UNAVAILABLE,
                    self._grpc.StatusCode.DEADLINE_EXCEEDED):
            raise CRITransportError(
                f"ContainerStatus({bare_id}): runtime unreachable: "
                f"{last_err}")
        raise CRIError(f"ContainerStatus({bare_id}) failed: {last_err}")

    def pid_from_container_id(self, container_id: str) -> int:
        runtime, bare = split_runtime_prefix(container_id)
        if runtime != self.runtime:
            raise CRIError(
                f"invalid CRI {container_id}, it should be {self.runtime}")
        info = self._container_status(bare)
        if "info" not in info:
            raise CRIError(
                f"container status for {bare} has no 'info' entry")
        try:
            pid = int(json.loads(info["info"]).get("pid") or 0)
        except (ValueError, AttributeError) as e:
            raise CRIError(f"could not parse container info JSON: {e}") from e
        if pid <= 0:
            raise CRIError(f"container {bare} reports no running pid")
        return pid

    def close(self) -> None:
        self._channel.close()


class ContainerdClient(CRIRuntimeClient):
    runtime = "containerd"

    def __init__(self, socket_path: str = CONTAINERD_SOCKET, **kw):
        super().__init__(socket_path, **kw)


class CrioClient(CRIRuntimeClient):
    runtime = "cri-o"

    def __init__(self, socket_path: str = CRIO_SOCKET, **kw):
        super().__init__(socket_path, **kw)


class CRIResolver:
    """Prefix-dispatching resolver over lazily-constructed per-runtime
    clients (the role of kubernetes.go's runtime switch). Client factories
    are injectable for tests; by default a runtime's client is built on
    first use from whichever well-known socket exists."""

    def __init__(self, factories: dict[str, "callable"] | None = None,
                 socket_probe: "callable" = None,
                 breaker_ttl_s: float = 30.0,
                 socket_path: str | None = None):
        import os

        probe = socket_probe or os.path.exists
        if factories is None:
            if socket_path:
                # The reference's
                # --metadata-container-runtime-socket-path: one
                # operator-chosen socket for whichever runtime answers
                # (kubernetes.go passes the same path to every runtime
                # client it constructs).
                factories = {
                    "docker": lambda: DockerClient(socket_path),
                    "containerd": lambda: ContainerdClient(socket_path),
                    "cri-o": lambda: CrioClient(socket_path),
                }
            else:
                factories = {
                    "docker": lambda: DockerClient(),
                    "containerd": lambda: ContainerdClient(
                        CONTAINERD_SOCKET if probe(CONTAINERD_SOCKET)
                        else CONTAINERD_K3S_SOCKET),
                    "cri-o": lambda: CrioClient(),
                }
        self._factories = factories
        self._clients: dict[str, object] = {}
        # Per-RUNTIME circuit breaker: one hung socket costs one dial
        # timeout per TTL, not one per unresolved container (the caller's
        # per-container negative cache cannot give that bound).
        self._breaker_ttl_s = breaker_ttl_s
        self._broken_until: dict[str, float] = {}

    def pid_from_container_id(self, container_id: str) -> int:
        import time

        runtime, _ = split_runtime_prefix(container_id)
        if runtime not in self._factories:
            raise CRIError(f"unsupported container runtime {runtime!r}")
        if self._broken_until.get(runtime, 0) > time.monotonic():
            raise CRITransportError(
                f"{runtime} runtime circuit open (recent transport "
                "failure); not redialing yet")
        client = self._clients.get(runtime)
        if client is None:
            client = self._clients[runtime] = self._factories[runtime]()
        try:
            return client.pid_from_container_id(container_id)
        except Exception as e:
            if isinstance(e, CRIError) and \
                    not isinstance(e, CRITransportError):
                raise  # routine lookup miss: keep the healthy channel
            # Transport-level failure. Self-heal: a cached client can be
            # pinned to a socket chosen before the runtime was up (e.g.
            # the containerd probe fell through to the k3s path during
            # node boot) — evict so the next resolution re-probes and
            # rebuilds — and open the circuit so a hung socket is only
            # redialed once per TTL.
            self._broken_until[runtime] = (
                time.monotonic() + self._breaker_ttl_s)
            self._clients.pop(runtime, None)
            try:
                client.close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
            raise

    def close(self) -> None:
        for client in self._clients.values():
            try:
                client.close()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
        self._clients.clear()
