"""systemd service discovery.

Role of the reference's pkg/discovery/systemd.go:48-107 (D-Bus
SubscribeUnitsCustom on .service units, reading MainPID, emitting Groups
labeled systemd_unit). No D-Bus client library exists in this image, so
the same facts come from systemctl — injectable as `runner` so tests feed
canned output and hosts without systemd skip cleanly.
"""

from __future__ import annotations

import dataclasses
import subprocess
import threading
from typing import Callable

from parca_agent_tpu.discovery.manager import Group


def _systemctl(args: list[str]) -> str:
    return subprocess.run(
        ["systemctl", *args], capture_output=True, text=True, timeout=10,
    ).stdout


@dataclasses.dataclass
class SystemdDiscoverer:
    units: tuple[str, ...] = ()        # empty = all .service units
    poll_s: float = 5.0
    runner: Callable[[list[str]], str] = _systemctl

    def scrape(self) -> list[Group]:
        names = list(self.units)
        if not names:
            listing = self.runner(
                ["list-units", "--type=service", "--state=running",
                 "--plain", "--no-legend", "--no-pager"]
            )
            names = [ln.split()[0] for ln in listing.splitlines() if ln.split()]
        if not names:
            return []
        # One batched `show` for all units (blank-line-separated blocks in
        # argument order) instead of N+1 execs per scrape.
        out = self.runner(["show", "-p", "MainPID", "--value", *names])
        values = out.split("\n\n") if out else []
        groups = []
        for unit, block in zip(names, values):
            try:
                pid = int(block.strip())
            except ValueError:
                continue
            if pid <= 0:
                continue
            groups.append(Group(
                source=f"systemd/{unit}",
                labels={"systemd_unit": unit},
                pids=[pid],
                entry_pid=pid,
            ))
        return groups

    def run(self, stop: threading.Event,
            up: Callable[[list[Group]], None]) -> None:
        while not stop.is_set():
            try:
                up(self.scrape())
            except (OSError, subprocess.SubprocessError):
                pass  # systemd absent or transient failure; retry next poll
            stop.wait(self.poll_s)
