"""Kubernetes pod discovery (node-filtered), gated on cluster access.

Role of the reference's pkg/discovery/kubernetes.go + kubernetes/
podinformer.go: watch pods scheduled to this node, resolve each running
container's PIDs, and emit one Group per pod with
node/namespace/pod/container/containerid labels (kubernetes.go:76-133).

The kube API client is optional (no `kubernetes` package in this image and
no cluster in CI): construction raises a clear error without it. PID
resolution reuses the cgroup scan (discovery/cgroup.py) instead of talking
CRI sockets — the container ids from the pod status join against the ids
found in /proc/*/cgroup, which works across docker/containerd/cri-o
without per-runtime socket clients (the role of
kubernetes/containerruntimes/*).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable

from parca_agent_tpu.discovery.cgroup import CgroupContainerDiscoverer
from parca_agent_tpu.discovery.manager import Group


@dataclasses.dataclass
class PodDiscoverer:
    node: str
    poll_s: float = 5.0
    cgroups: CgroupContainerDiscoverer = dataclasses.field(
        default_factory=CgroupContainerDiscoverer
    )

    def __post_init__(self):
        try:
            from kubernetes import client, config  # type: ignore

            try:
                config.load_incluster_config()
            except Exception:
                config.load_kube_config()
            self._core = client.CoreV1Api()
        except ImportError as e:
            raise RuntimeError(
                "kubernetes discovery needs the 'kubernetes' client package; "
                "use CgroupContainerDiscoverer for API-free container labels"
            ) from e

    def scrape(self) -> list[Group]:
        pods = self._core.list_pod_for_all_namespaces(
            field_selector=f"spec.nodeName={self.node}"
        )
        # container id -> pids from the local cgroup scan.
        pid_groups = {g.labels.get("containerid"): g.pids
                      for g in self.cgroups.scrape()}
        groups = []
        for pod in pods.items:
            for cs in pod.status.container_statuses or []:
                cid = (cs.container_id or "").rsplit("//", 1)[-1]
                pids = pid_groups.get(cid, [])
                if not pids:
                    continue
                groups.append(Group(
                    source=f"pod/{pod.metadata.namespace}/{pod.metadata.name}"
                           f"/{cs.name}",
                    labels={
                        "node": self.node,
                        "namespace": pod.metadata.namespace,
                        "pod": pod.metadata.name,
                        "container": cs.name,
                        "containerid": cid,
                    },
                    pids=list(pids),
                    entry_pid=min(pids),
                ))
        return groups

    def run(self, stop: threading.Event,
            up: Callable[[list[Group]], None]) -> None:
        while not stop.is_set():
            try:
                up(self.scrape())
            except Exception:
                pass  # API hiccup; retry next poll
            stop.wait(self.poll_s)
