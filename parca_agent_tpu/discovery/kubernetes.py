"""Kubernetes pod discovery: node-filtered pod watch joined to local PIDs.

Role of the reference's pkg/discovery/kubernetes.go:76-133 +
kubernetes/podinformer.go:47-96: watch the pods scheduled to THIS node,
resolve each running container to PIDs, and emit one Group per container
with node/namespace/pod/container/containerid labels.

Two deliberate departures from the reference, both TPU-era-host friendly:

  * PID resolution does not speak CRI sockets (the role of
    kubernetes/containerruntimes/containerruntimes.go:78-81). All runtimes
    embed the 64-hex container id in the cgroup path, so joining pod
    container ids against the /proc/*/cgroup scan (discovery/cgroup.py)
    covers docker/containerd/cri-o with one code path and no socket
    permissions.
  * The API client is a seam, not a dependency. `PodLister` is any
    callable returning plain `PodInfo` rows; production uses
    `InClusterPodLister` (stdlib HTTPS against the service-account
    credentials every in-cluster pod has — no client package needed);
    tests inject a fake (SURVEY.md §4 fs-injection pattern applied to the
    API boundary, which the reference never did — its discoverer is only
    testable against a live cluster).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Callable, Protocol

from parca_agent_tpu.discovery.cgroup import CgroupContainerDiscoverer
from parca_agent_tpu.discovery.manager import Group

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


@dataclasses.dataclass(frozen=True)
class ContainerInfo:
    """One running container of a pod (status.containerStatuses entry)."""

    name: str
    container_id: str  # bare 64-hex id, runtime prefix stripped
    running: bool = True
    raw_id: str = ""   # prefixed form ('containerd://<hex>') for CRI dispatch


@dataclasses.dataclass(frozen=True)
class PodInfo:
    """The slice of a Pod object discovery needs."""

    name: str
    namespace: str
    uid: str
    node: str
    containers: tuple[ContainerInfo, ...] = ()


class PodLister(Protocol):
    def __call__(self, node: str) -> list[PodInfo]: ...


def strip_runtime_prefix(container_id: str) -> str:
    """'containerd://<hex>' / 'docker://<hex>' -> '<hex>'
    (kubernetes.go containerIDFromPodStatus analog)."""
    return container_id.rsplit("//", 1)[-1]


def _field(d: dict, camel: str, snake: str):
    """API JSON uses camelCase; the official client's to_dict() emits
    snake_case. Accept either so both lister paths share this parser."""
    v = d.get(camel)
    return d.get(snake) if v is None else v


def parse_pod_list(doc: dict) -> list[PodInfo]:
    """Plain-data projection of a k8s PodList document."""
    pods = []
    for item in doc.get("items") or []:
        meta = item.get("metadata") or {}
        status = item.get("status") or {}
        containers = []
        for cs in _field(status, "containerStatuses",
                         "container_statuses") or []:
            raw = _field(cs, "containerID", "container_id") or ""
            cid = strip_runtime_prefix(raw)
            if not cid:
                continue  # not started yet
            containers.append(ContainerInfo(
                name=cs.get("name") or "",
                container_id=cid,
                running="running" in {k for k, v in
                                      (cs.get("state") or {}).items() if v},
                raw_id=raw,
            ))
        pods.append(PodInfo(
            name=meta.get("name") or "",
            namespace=meta.get("namespace") or "",
            uid=meta.get("uid") or "",
            node=_field(item.get("spec") or {}, "nodeName", "node_name") or "",
            containers=tuple(containers),
        ))
    return pods


class InClusterPodLister:
    """Node-filtered pod listing over the in-cluster API, stdlib-only.

    Uses the service-account token + CA certificate mounted into every
    pod and the KUBERNETES_SERVICE_{HOST,PORT} env vars — the same
    credentials client-go's rest.InClusterConfig() reads. The HTTP opener
    is injectable so the URL/headers contract is testable offline.
    """

    def __init__(self, sa_dir: str = _SA_DIR,
                 env: dict[str, str] | None = None,
                 opener: Callable[[str, dict[str, str]], bytes] | None = None):
        env = os.environ if env is None else env
        host = env.get("KUBERNETES_SERVICE_HOST")
        port = env.get("KUBERNETES_SERVICE_PORT", "443")
        if not host:
            raise RuntimeError(
                "not running in a cluster (KUBERNETES_SERVICE_HOST unset)")
        self._base = f"https://{host}:{port}"
        self._sa_dir = sa_dir
        self._opener = opener or self._https_get

    def _https_get(self, url: str, headers: dict[str, str]) -> bytes:
        import ssl
        import urllib.request

        ctx = ssl.create_default_context(
            cafile=os.path.join(self._sa_dir, "ca.crt"))
        req = urllib.request.Request(url, headers=headers)
        with urllib.request.urlopen(req, context=ctx, timeout=10) as resp:
            return resp.read()

    def __call__(self, node: str) -> list[PodInfo]:
        with open(os.path.join(self._sa_dir, "token")) as f:
            token = f.read().strip()
        url = (f"{self._base}/api/v1/pods"
               f"?fieldSelector=spec.nodeName%3D{node}")
        raw = self._opener(url, {"Authorization": f"Bearer {token}"})
        return parse_pod_list(json.loads(raw))


def default_pod_lister() -> PodLister:
    """Prefer the official client package when present (kubeconfig
    support for out-of-cluster runs), else the stdlib in-cluster path."""
    try:
        from kubernetes import client, config  # type: ignore

        try:
            config.load_incluster_config()
        except Exception:
            config.load_kube_config()
        core = client.CoreV1Api()

        def lister(node: str) -> list[PodInfo]:
            resp = core.list_pod_for_all_namespaces(
                field_selector=f"spec.nodeName={node}")
            return parse_pod_list(resp.to_dict() if hasattr(resp, "to_dict")
                                  else resp)

        return lister
    except ImportError:
        return InClusterPodLister()


@dataclasses.dataclass
class PodDiscoverer:
    """node=None resolves from KUBERNETES_NODE_NAME (the DaemonSet sets it
    from spec.nodeName) then the hostname; lister=None wires the default
    API client at first scrape so construction never needs a cluster."""

    node: str | None = None
    poll_s: float = 5.0
    lister: PodLister | None = None
    cgroups: CgroupContainerDiscoverer = dataclasses.field(
        default_factory=CgroupContainerDiscoverer
    )
    # Fallback pid resolver for containers the cgroup scan missed — the
    # scan/list race (container started between the two) and transient
    # /proc read failures: anything with pid_from_container_id(raw_id)
    # -> int, normally discovery.cri.CRIResolver. None disables the
    # fallback. The runtime answers with a HOST-namespace pid, so the
    # answer is adopted only after _validate_cri_pid confirms that pid's
    # cgroup names this container in this agent's /proc view (needs
    # hostPID, which deploy/daemonset.yaml mandates; an agent outside
    # the host pid namespace rejects the pid instead of mislabeling a
    # stranger, and a cgroup layout that hides the id entirely stays
    # unresolved by design).
    cri: object | None = None
    # Failed CRI resolutions are not retried for this long: each attempt
    # can block scrape() for the client's dial timeout, and container
    # churn makes "status says running, runtime says gone" routine.
    cri_negative_ttl_s: float = 30.0
    _cri_failed_until: dict = dataclasses.field(default_factory=dict,
                                                repr=False)

    def __post_init__(self):
        if not self.node:
            import socket

            self.node = (os.environ.get("KUBERNETES_NODE_NAME")
                         or socket.gethostname())

    def _validate_cri_pid(self, pid: int, container_id: str) -> bool:
        """The runtime reports the container's pid in the HOST pid
        namespace. Adopt it only if this agent's /proc agrees it is that
        container's process: /proc/<pid>/cgroup must mention the bare
        container id. An agent outside the host pid namespace (or a pid
        raced by reuse) fails this check and the pid is discarded rather
        than profiled under a stranger's labels."""
        try:
            cg = self.cgroups.fs.read_bytes(f"/proc/{pid}/cgroup")
        except OSError:
            return False
        return container_id.encode() in cg

    def _cri_fallback(self, cs: ContainerInfo) -> list[int]:
        """Ask the runtime itself (the reference's only path,
        containerruntimes.go:78-81) when the cgroup scan is blind, with a
        negative cache so a dead/slow runtime socket cannot stall every
        poll."""
        now = time.monotonic()
        if self._cri_failed_until.get(cs.container_id, 0) > now:
            return []
        try:
            pid = self.cri.pid_from_container_id(cs.raw_id)
            if self._validate_cri_pid(pid, cs.container_id):
                return [pid]
        except Exception:  # noqa: BLE001 - runtime may be absent
            pass
        self._cri_failed_until[cs.container_id] = (
            now + self.cri_negative_ttl_s)
        if len(self._cri_failed_until) > 4096:  # bound on churny nodes
            self._cri_failed_until = {
                k: v for k, v in self._cri_failed_until.items() if v > now}
        return []

    def scrape(self) -> list[Group]:
        if self.lister is None:
            self.lister = default_pod_lister()
        pods = self.lister(self.node)
        # container id -> pids from the local cgroup scan.
        pid_groups = {g.labels.get("containerid"): g.pids
                      for g in self.cgroups.scrape()}
        groups = []
        for pod in pods:
            for cs in pod.containers:
                pids = pid_groups.get(cs.container_id, [])
                if not pids and self.cri is not None and cs.running \
                        and cs.raw_id:
                    pids = self._cri_fallback(cs)
                if not pids:
                    continue  # not on this node / already exited
                groups.append(Group(
                    source=f"pod/{pod.namespace}/{pod.name}/{cs.name}",
                    labels={
                        "node": self.node,
                        "namespace": pod.namespace,
                        "pod": pod.name,
                        "container": cs.name,
                        "containerid": cs.container_id,
                        "pod_uid": pod.uid,
                    },
                    pids=list(pids),
                    entry_pid=min(pids),
                ))
        return groups

    def run(self, stop: threading.Event,
            up: Callable[[list[Group]], None]) -> None:
        while not stop.is_set():
            try:
                up(self.scrape())
            except Exception:
                pass  # API hiccup; retry next poll
            stop.wait(self.poll_s)
