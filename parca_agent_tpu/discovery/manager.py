"""Discovery manager: providers push target groups, consumers read a
debounced merged view.

Role of the reference's Prometheus-SD-style pkg/discovery/
discovery_manager.go:86-300: each named provider runs in its own thread
pushing [Group] updates; the manager coalesces updates and publishes the
full map at most once per debounce interval. Instead of Go channels the
published state is a versioned snapshot guarded by a condition variable —
`wait_for_update(version)` is the SyncCh equivalent.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Protocol


@dataclasses.dataclass
class Group:
    """One target group (reference target.go:22-35)."""

    source: str
    labels: dict[str, str] = dataclasses.field(default_factory=dict)
    pids: list[int] = dataclasses.field(default_factory=list)
    entry_pid: int = 0


class Discoverer(Protocol):
    def run(self, stop: threading.Event,
            up: Callable[[list[Group]], None]) -> None: ...


class DiscoveryManager:
    def __init__(self, debounce_s: float = 5.0):
        self._debounce = debounce_s
        self._providers: dict[str, Discoverer] = {}
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._groups: dict[str, dict[str, Group]] = {}  # provider -> source -> group
        self._version = 0
        self._last_publish = 0.0
        self._dirty = False
        self.failed_updates = 0

    def apply_config(self, providers: dict[str, Discoverer]) -> None:
        """Register providers (reference ApplyConfig + provider registry)."""
        self._providers.update(providers)

    def run(self) -> None:
        for name, p in self._providers.items():
            self._spawn(name, p)

    def _spawn(self, name: str, p: Discoverer) -> None:
        t = threading.Thread(
            target=self._run_provider, args=(name, p),
            name=f"discovery-{name}", daemon=True,
        )
        t.start()
        self._threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=2)

    # -- supervision hooks (runtime/supervisor.py probe actor) ---------------

    def alive(self) -> bool:
        """True while every started provider thread is still running (a
        provider that raised died silently before; the supervisor's probe
        surfaces and heals it). Fail-open supervisor probe (palint
        fail-open-hook): an exception here reads as unhealthy, never as
        a dead poll loop."""
        try:
            # Snapshot first: restart_dead (the revive hook) mutates the
            # list, and "list changed size during iteration" out of a
            # health probe would be self-harm.
            return all(t.is_alive() for t in list(self._threads))
        except Exception:  # noqa: BLE001 - probe contract: never raise
            self.failed_updates += 1
            return False

    def restart_dead(self) -> int:
        """Respawn provider threads that died (the supervisor's revive
        hook). Returns how many were restarted. Fail-open: a respawn
        failure (thread limits, a provider constructor raising) is
        counted and retried at the next probe tick."""
        try:
            if self._stop.is_set():
                return 0
            restarted = 0
            for t in [t for t in self._threads if not t.is_alive()]:
                # Per-provider containment: one spawn failure (thread
                # limits) must not abort the remaining respawns or
                # discard the count of those already restarted. Spawn
                # FIRST, drop the dead entry only on success: a failed
                # spawn leaves the corpse in _threads so alive() stays
                # False and the next probe tick retries — removing
                # first would read as healthy with the provider
                # silently gone.
                try:
                    name = t.name.removeprefix("discovery-")
                    p = self._providers.get(name)
                    if p is not None:
                        self._spawn(name, p)
                        restarted += 1
                    self._threads.remove(t)
                except Exception:  # noqa: BLE001 - probe contract
                    self.failed_updates += 1
            return restarted
        except Exception:  # noqa: BLE001 - probe contract: never raise
            self.failed_updates += 1
            return 0

    def _run_provider(self, name: str, p: Discoverer) -> None:
        def up(groups: list[Group]) -> None:
            self._update(name, groups)

        try:
            p.run(self._stop, up)
        except Exception:
            with self._lock:
                self.failed_updates += 1

    def _update(self, provider: str, groups: list[Group]) -> None:
        with self._cond:
            # Each provider update carries its FULL current target set:
            # replacing the provider's map (not merging into it) is what
            # lets dead sources disappear, so exited containers/units stop
            # labeling recycled PIDs and the map stays bounded.
            self._groups[provider] = {g.source: g for g in groups}
            now = time.monotonic()
            self._dirty = True
            # Debounce: publish immediately if quiet, else mark dirty and
            # let the next update (or reader poll) publish.
            if now - self._last_publish >= self._debounce:
                self._publish_locked(now)

    def _publish_locked(self, now: float) -> None:
        self._version += 1
        self._last_publish = now
        self._dirty = False
        self._cond.notify_all()

    def flush(self) -> None:
        """Force-publish pending updates (tests, shutdown)."""
        with self._cond:
            if self._dirty:
                self._publish_locked(time.monotonic())

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    def groups(self) -> list[Group]:
        """Current merged view across providers."""
        with self._lock:
            if self._dirty and \
                    time.monotonic() - self._last_publish >= self._debounce:
                self._publish_locked(time.monotonic())
            return [g for per in self._groups.values() for g in per.values()]

    def wait_for_update(self, seen_version: int, timeout: float | None = None) -> int:
        """Block until the published version advances past seen_version
        (the SyncCh read equivalent). Returns the new version."""
        with self._cond:
            self._cond.wait_for(
                lambda: self._version > seen_version, timeout=timeout
            )
            return self._version
