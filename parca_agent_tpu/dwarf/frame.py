"""`.eh_frame` parser and CFI interpreter.

Role of the reference's vendored delve fork (internal/dwarf/frame/
{parser,entries,table}.go, SURVEY.md section 2.3): parse CIEs/FDEs and
execute the DW_CFA program of each FDE into per-location register-rule rows.
Written from the DWARF v4/v5 spec §6.4 and the LSB eh_frame supplement —
the structures differ from the reference's (arrays of dataclass rows, one
interpreter loop, no lazy frame contexts) but the rule taxonomy matches
(RuleUndefined..RuleCFA, table.go:13-38).

Scope: the x86_64 unwind-table pipeline needs the CFA rule and the rules
for RBP (DWARF reg 6) and RA (reg 16). All standard opcodes are executed;
expression rules are kept as raw DWARF expression bytes for the expression
identifier (unwind/plt.py) to classify, exactly how the reference treats
them (pkg/stack/unwind/dwarf_expression.go:31-57).
"""

from __future__ import annotations

import dataclasses
import enum
import struct

from parca_agent_tpu.utils.poison import PoisonInput

# x86_64 DWARF register numbers (System V ABI).
REG_RBP = 6
REG_RSP = 7
REG_RA = 16


class FrameError(PoisonInput):
    site = "unwind.build"


# Poison caps (docs/robustness.md "ingest containment"): .eh_frame comes
# from arbitrary host binaries; bound what one section may claim before
# the parser materializes it. glibc carries ~25k FDEs; chromium ~600k.
_MAX_CFI_ENTRIES = 2_000_000
_MAX_LEB_SHIFT = 70  # > 64 value bits in a LEB128 is malformed


# -- LEB128 -----------------------------------------------------------------


def uleb128(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        try:
            b = data[pos]
        except IndexError:
            raise FrameError("truncated ULEB128") from None
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > _MAX_LEB_SHIFT:
            raise FrameError("overlong ULEB128")


def sleb128(data: bytes, pos: int) -> tuple[int, int]:
    result = 0
    shift = 0
    while True:
        try:
            b = data[pos]
        except IndexError:
            raise FrameError("truncated SLEB128") from None
        pos += 1
        result |= (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            if b & 0x40:
                result -= 1 << shift
            return result, pos
        if shift > _MAX_LEB_SHIFT:
            raise FrameError("overlong SLEB128")


# -- DW_EH_PE pointer encodings --------------------------------------------

DW_EH_PE_omit = 0xFF
DW_EH_PE_absptr = 0x00
DW_EH_PE_uleb128 = 0x01
DW_EH_PE_udata2 = 0x02
DW_EH_PE_udata4 = 0x03
DW_EH_PE_udata8 = 0x04
DW_EH_PE_sleb128 = 0x09
DW_EH_PE_sdata2 = 0x0A
DW_EH_PE_sdata4 = 0x0B
DW_EH_PE_sdata8 = 0x0C
DW_EH_PE_pcrel = 0x10
DW_EH_PE_textrel = 0x20
DW_EH_PE_datarel = 0x30
DW_EH_PE_funcrel = 0x40
DW_EH_PE_aligned = 0x50
DW_EH_PE_indirect = 0x80


def read_encoded(data: bytes, pos: int, enc: int, section_addr: int) -> tuple[int, int]:
    """Decode one DW_EH_PE-encoded pointer at `pos`; returns (value, new_pos).
    `section_addr` is the virtual address of .eh_frame (for pcrel)."""
    if enc == DW_EH_PE_omit:
        return 0, pos
    fmt = enc & 0x0F
    app = enc & 0x70
    start = pos
    if fmt == DW_EH_PE_absptr:
        v = struct.unpack_from("<Q", data, pos)[0]
        pos += 8
    elif fmt == DW_EH_PE_uleb128:
        v, pos = uleb128(data, pos)
    elif fmt == DW_EH_PE_udata2:
        v = struct.unpack_from("<H", data, pos)[0]
        pos += 2
    elif fmt == DW_EH_PE_udata4:
        v = struct.unpack_from("<I", data, pos)[0]
        pos += 4
    elif fmt == DW_EH_PE_udata8:
        v = struct.unpack_from("<Q", data, pos)[0]
        pos += 8
    elif fmt == DW_EH_PE_sleb128:
        v, pos = sleb128(data, pos)
    elif fmt == DW_EH_PE_sdata2:
        v = struct.unpack_from("<h", data, pos)[0]
        pos += 2
    elif fmt == DW_EH_PE_sdata4:
        v = struct.unpack_from("<i", data, pos)[0]
        pos += 4
    elif fmt == DW_EH_PE_sdata8:
        v = struct.unpack_from("<q", data, pos)[0]
        pos += 8
    else:
        raise FrameError(f"unsupported pointer encoding {enc:#x}")
    if app == DW_EH_PE_pcrel:
        v += section_addr + start
    elif app in (DW_EH_PE_textrel, DW_EH_PE_datarel, DW_EH_PE_funcrel):
        raise FrameError(f"unsupported pointer application {enc:#x}")
    return v % 2**64, pos


# -- rule model -------------------------------------------------------------


class RuleType(enum.IntEnum):
    UNDEFINED = 0
    SAME_VALUE = 1
    OFFSET = 2        # value at CFA + offset
    VAL_OFFSET = 3    # value is CFA + offset
    REGISTER = 4      # value is in another register
    EXPRESSION = 5    # value at eval(expr)
    VAL_EXPRESSION = 6  # value is eval(expr)
    CFA = 7           # CFA rule: register + offset
    CFA_EXPRESSION = 8  # CFA rule: eval(expr)


@dataclasses.dataclass(frozen=True)
class RegRule:
    type: RuleType
    reg: int = 0
    offset: int = 0
    expr: bytes = b""


UNDEFINED = RegRule(RuleType.UNDEFINED)
SAME_VALUE = RegRule(RuleType.SAME_VALUE)


@dataclasses.dataclass
class Row:
    """Register rules taking effect at `loc` (until the next row's loc)."""

    loc: int
    cfa: RegRule
    regs: dict[int, RegRule]

    def rule(self, reg: int) -> RegRule:
        return self.regs.get(reg, UNDEFINED)


@dataclasses.dataclass
class CIE:
    offset: int
    code_align: int
    data_align: int
    ra_reg: int
    initial_instructions: bytes
    fde_pointer_enc: int = DW_EH_PE_absptr
    augmentation: str = ""
    aug_data: bytes = b""


@dataclasses.dataclass
class FDE:
    offset: int
    cie: CIE
    pc_begin: int
    pc_range: int
    instructions: bytes

    @property
    def pc_end(self) -> int:
        return self.pc_begin + self.pc_range


# -- parsing ---------------------------------------------------------------


def parse_eh_frame(data: bytes, section_addr: int = 0) -> list[FDE]:
    """Parse an .eh_frame section into FDEs (with their CIEs resolved).

    `section_addr` is the sh_addr of .eh_frame, needed for pcrel pointer
    encodings (the common case for PIC code).

    Malformed input raises FrameError (a PoisonInput) — including any
    truncation an untrusted binary can produce (struct/index failures are
    converted so nothing but the taxonomy escapes).
    """
    try:
        return _parse_eh_frame(data, section_addr)
    except FrameError:
        raise
    except (IndexError, struct.error, ValueError) as e:
        raise FrameError(f"malformed .eh_frame: {e!r}") from None


def _parse_eh_frame(data: bytes, section_addr: int) -> list[FDE]:
    cies: dict[int, CIE] = {}
    fdes: list[FDE] = []
    pos = 0
    n = len(data)
    while pos + 4 <= n:
        if len(cies) + len(fdes) >= _MAX_CFI_ENTRIES:
            raise FrameError("CFI entry count exceeds cap")
        entry_off = pos
        length = struct.unpack_from("<I", data, pos)[0]
        pos += 4
        if length == 0:  # ZERO terminator
            break
        if length == 0xFFFFFFFF:
            length = struct.unpack_from("<Q", data, pos)[0]
            pos += 8
        end = pos + length
        if end > n:
            raise FrameError("CFI entry overruns section")
        cie_ptr = struct.unpack_from("<I", data, pos)[0]
        body = pos + 4
        if cie_ptr == 0:
            cies[entry_off] = _parse_cie(data, entry_off, body, end)
        else:
            # eh_frame: CIE pointer is a self-relative back-offset from the
            # CIE-pointer field itself (unlike .debug_frame's section offset).
            cie_off = pos - cie_ptr
            cie = cies.get(cie_off)
            if cie is None:
                raise FrameError(f"FDE at {entry_off:#x} references unknown CIE")
            fdes.append(_parse_fde(data, entry_off, body, end, cie, section_addr))
        pos = end
    fdes.sort(key=lambda f: f.pc_begin)
    return fdes


def _parse_cie(data: bytes, entry_off: int, pos: int, end: int) -> CIE:
    if pos >= len(data):
        raise FrameError("truncated CIE")
    version = data[pos]
    pos += 1
    if version not in (1, 3, 4):
        raise FrameError(f"unsupported CIE version {version}")
    aug_end = data.find(b"\x00", pos, end)
    if aug_end < 0:
        raise FrameError("unterminated CIE augmentation string")
    augmentation = data[pos:aug_end].decode(errors="replace")
    pos = aug_end + 1
    if version == 4:
        pos += 2  # address_size, segment_size
    code_align, pos = uleb128(data, pos)
    data_align, pos = sleb128(data, pos)
    if version == 1:
        ra_reg = data[pos]
        pos += 1
    else:
        ra_reg, pos = uleb128(data, pos)
    fde_enc = DW_EH_PE_absptr
    aug_data = b""
    if augmentation.startswith("z"):
        aug_len, pos = uleb128(data, pos)
        aug_data = data[pos: pos + aug_len]
        apos = 0
        for ch in augmentation[1:]:
            if ch == "R":
                fde_enc = aug_data[apos]
                apos += 1
            elif ch == "L":
                apos += 1  # LSDA encoding byte
            elif ch == "P":
                penc = aug_data[apos]
                apos += 1
                _, apos = read_encoded(aug_data, apos, penc & 0x0F, 0)
            elif ch == "S":
                pass  # signal frame marker
        pos += aug_len
    return CIE(entry_off, code_align, data_align, ra_reg,
               data[pos:end], fde_enc, augmentation, aug_data)


def _parse_fde(data: bytes, entry_off: int, pos: int, end: int, cie: CIE,
               section_addr: int) -> FDE:
    pc_begin, pos = read_encoded(data, pos, cie.fde_pointer_enc, section_addr)
    # pc_range is always absolute-format, same size encoding without app bits
    pc_range, pos = read_encoded(data, pos, cie.fde_pointer_enc & 0x0F, 0)
    if cie.augmentation.startswith("z"):
        aug_len, pos = uleb128(data, pos)
        pos += aug_len
    return FDE(entry_off, cie, pc_begin, pc_range, data[pos:end])


# -- interpreter ------------------------------------------------------------

_DW_CFA_advance_loc = 0x40
_DW_CFA_offset = 0x80
_DW_CFA_restore = 0xC0


def execute_fde(fde: FDE) -> list[Row]:
    """Run CIE initial instructions + FDE instructions; one Row per distinct
    starting location (reference table.go ExecuteDwarfProgram). A CFA
    program truncated or corrupted by its producer raises FrameError."""
    cie = fde.cie
    ctx = _Ctx(fde.pc_begin, cie.code_align, cie.data_align)
    try:
        ctx.run(cie.initial_instructions)
        ctx.initial = {k: v for k, v in ctx.regs.items()}
        ctx.initial_cfa = ctx.cfa
        rows = [ctx.snapshot()]

        def on_advance():
            rows.append(ctx.snapshot())

        ctx.on_advance = on_advance
        ctx.run(fde.instructions)
    except FrameError:
        raise
    except (IndexError, struct.error) as e:
        raise FrameError(f"malformed CFA program: {e!r}") from None
    # Rows are emitted on advance with the PREVIOUS state; the final state
    # needs recording too.
    rows.append(ctx.snapshot())
    # Snapshot semantics: snapshot() records state for current loc; advancing
    # emits the new loc row. Collapse duplicate locs keeping the LAST state.
    out: list[Row] = []
    for r in rows:
        if out and out[-1].loc == r.loc:
            out[-1] = r
        else:
            out.append(r)
    return out


class _Ctx:
    def __init__(self, loc: int, code_align: int, data_align: int):
        self.loc = loc
        self.code_align = code_align
        self.data_align = data_align
        self.cfa = UNDEFINED
        self.regs: dict[int, RegRule] = {}
        self.initial: dict[int, RegRule] = {}
        self.initial_cfa = UNDEFINED
        self.stack: list[tuple[RegRule, dict[int, RegRule]]] = []
        self.on_advance = lambda: None

    def snapshot(self) -> Row:
        return Row(self.loc, self.cfa, dict(self.regs))

    def advance(self, delta: int) -> None:
        self.on_advance()
        self.loc += delta * self.code_align

    def run(self, insns: bytes) -> None:  # noqa: C901 - opcode dispatch
        pos = 0
        n = len(insns)
        while pos < n:
            op = insns[pos]
            pos += 1
            high = op & 0xC0
            low = op & 0x3F
            if high == _DW_CFA_advance_loc:
                self.advance(low)
            elif high == _DW_CFA_offset:
                off, pos = uleb128(insns, pos)
                self.regs[low] = RegRule(RuleType.OFFSET,
                                         offset=off * self.data_align)
            elif high == _DW_CFA_restore:
                self._restore(low)
            elif op == 0x00:  # nop
                pass
            elif op == 0x01:  # set_loc
                # Address-encoded per CIE; assume absptr (8 bytes) — matches
                # compilers in practice; pcrel set_loc is unseen in .eh_frame.
                self.on_advance()
                self.loc = struct.unpack_from("<Q", insns, pos)[0]
                pos += 8
            elif op == 0x02:  # advance_loc1
                self.advance(insns[pos])
                pos += 1
            elif op == 0x03:  # advance_loc2
                self.advance(struct.unpack_from("<H", insns, pos)[0])
                pos += 2
            elif op == 0x04:  # advance_loc4
                self.advance(struct.unpack_from("<I", insns, pos)[0])
                pos += 4
            elif op == 0x05:  # offset_extended
                reg, pos = uleb128(insns, pos)
                off, pos = uleb128(insns, pos)
                self.regs[reg] = RegRule(RuleType.OFFSET,
                                         offset=off * self.data_align)
            elif op == 0x06:  # restore_extended
                reg, pos = uleb128(insns, pos)
                self._restore(reg)
            elif op == 0x07:  # undefined
                reg, pos = uleb128(insns, pos)
                self.regs[reg] = UNDEFINED
            elif op == 0x08:  # same_value
                reg, pos = uleb128(insns, pos)
                self.regs[reg] = SAME_VALUE
            elif op == 0x09:  # register
                reg, pos = uleb128(insns, pos)
                src, pos = uleb128(insns, pos)
                self.regs[reg] = RegRule(RuleType.REGISTER, reg=src)
            elif op == 0x0A:  # remember_state
                self.stack.append((self.cfa, dict(self.regs)))
            elif op == 0x0B:  # restore_state
                if self.stack:
                    self.cfa, self.regs = self.stack.pop()
            elif op == 0x0C:  # def_cfa
                reg, pos = uleb128(insns, pos)
                off, pos = uleb128(insns, pos)
                self.cfa = RegRule(RuleType.CFA, reg=reg, offset=off)
            elif op == 0x0D:  # def_cfa_register
                reg, pos = uleb128(insns, pos)
                self.cfa = RegRule(RuleType.CFA, reg=reg, offset=self.cfa.offset)
            elif op == 0x0E:  # def_cfa_offset
                off, pos = uleb128(insns, pos)
                self.cfa = RegRule(RuleType.CFA, reg=self.cfa.reg, offset=off)
            elif op == 0x0F:  # def_cfa_expression
                ln, pos = uleb128(insns, pos)
                self.cfa = RegRule(RuleType.CFA_EXPRESSION,
                                   expr=insns[pos: pos + ln])
                pos += ln
            elif op == 0x10:  # expression
                reg, pos = uleb128(insns, pos)
                ln, pos = uleb128(insns, pos)
                self.regs[reg] = RegRule(RuleType.EXPRESSION,
                                         expr=insns[pos: pos + ln])
                pos += ln
            elif op == 0x11:  # offset_extended_sf
                reg, pos = uleb128(insns, pos)
                off, pos = sleb128(insns, pos)
                self.regs[reg] = RegRule(RuleType.OFFSET,
                                         offset=off * self.data_align)
            elif op == 0x12:  # def_cfa_sf
                reg, pos = uleb128(insns, pos)
                off, pos = sleb128(insns, pos)
                self.cfa = RegRule(RuleType.CFA, reg=reg,
                                   offset=off * self.data_align)
            elif op == 0x13:  # def_cfa_offset_sf
                off, pos = sleb128(insns, pos)
                self.cfa = RegRule(RuleType.CFA, reg=self.cfa.reg,
                                   offset=off * self.data_align)
            elif op == 0x14:  # val_offset
                reg, pos = uleb128(insns, pos)
                off, pos = uleb128(insns, pos)
                self.regs[reg] = RegRule(RuleType.VAL_OFFSET,
                                         offset=off * self.data_align)
            elif op == 0x15:  # val_offset_sf
                reg, pos = uleb128(insns, pos)
                off, pos = sleb128(insns, pos)
                self.regs[reg] = RegRule(RuleType.VAL_OFFSET,
                                         offset=off * self.data_align)
            elif op == 0x16:  # val_expression
                reg, pos = uleb128(insns, pos)
                ln, pos = uleb128(insns, pos)
                self.regs[reg] = RegRule(RuleType.VAL_EXPRESSION,
                                         expr=insns[pos: pos + ln])
                pos += ln
            elif op == 0x2E:  # GNU_args_size — unwind-irrelevant, skip arg
                _, pos = uleb128(insns, pos)
            elif op == 0x2F:  # GNU_negative_offset_extended
                reg, pos = uleb128(insns, pos)
                off, pos = uleb128(insns, pos)
                self.regs[reg] = RegRule(RuleType.OFFSET,
                                         offset=-off * self.data_align)
            else:
                raise FrameError(f"unknown DW_CFA opcode {op:#x}")

    def _restore(self, reg: int) -> None:
        if reg in self.initial:
            self.regs[reg] = self.initial[reg]
        else:
            self.regs.pop(reg, None)
