"""DWARF call-frame information (reference internal/dwarf/frame, layer L3)."""

from parca_agent_tpu.dwarf.frame import (
    CIE,
    FDE,
    FrameError,
    RegRule,
    Row,
    RuleType,
    execute_fde,
    parse_eh_frame,
)

__all__ = [
    "CIE", "FDE", "FrameError", "RegRule", "Row", "RuleType",
    "execute_fde", "parse_eh_frame",
]
