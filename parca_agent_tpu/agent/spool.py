"""Disk spill spool: CRC-framed write-ahead segments for the ship path.

The reference's batch client retries forever with an unbounded in-memory
buffer; an hours-long store outage therefore costs either the host's
profile history or the agent's RSS. Here the batch client spills whole
batches to this spool instead: one segment file per batch, written
tmp-then-rename (crash-atomic), each series CRC32-framed so a torn or
bit-rotted segment is detected at replay rather than shipped corrupt.
The payload per frame is the wire codec's own single-series
WriteRawRequest encoding (gzipped pprof inside — spill is cheap), so
replay needs no second format.

Size cap: when total spool bytes exceed ``max_bytes`` the OLDEST
segments are evicted first (the newest data is the most valuable in a
profiler — history beyond the cap is the sacrifice) and every dropped
sample/byte is counted, never silent.

Segment layout::

    MAGIC "PASPOOL1" | u32 n_samples | frames...
    frame: u32 len | u32 crc32(payload) | payload

Thread contract: read/pop run on the batch client's flush thread, but
append also runs on whatever thread hits the buffer's overflow spill
(the capture thread or the encode pipeline's worker), and the
stats/pending accessors are read from the HTTP metrics thread — all
shared state is lock-guarded, and the read path re-checks the index
after its unlocked file read (a concurrent append's eviction may have
unlinked the segment under it).
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib

from parca_agent_tpu.agent.profilestore import (
    RawSeries,
    decode_write_raw_request,
    encode_write_raw_request,
)
from parca_agent_tpu.runtime import trace as window_trace
from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.log import get_logger
from parca_agent_tpu.utils.vfs import atomic_write_bytes

_log = get_logger("spool")

# palint: persistence-root — the spool dir IS the crash-only pattern's home.

_MAGIC = b"PASPOOL1"
_HEADER = struct.Struct("<I")   # n_samples
_FRAME = struct.Struct("<II")   # len, crc32


class SpoolDir:
    def __init__(self, directory: str, max_bytes: int = 256 << 20,
                 clock=time.monotonic):
        self._dir = directory
        self._max_bytes = max_bytes
        self._clock = clock
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        # seq -> (bytes, samples, appended_at). Crash leftovers are
        # adopted with appended_at = adoption time (their true age is
        # unknowable across the monotonic-clock restart), so replay lag
        # counts from adoption — nonzero the moment a restart inherits a
        # backlog, which is exactly when the lag gauge matters most.
        self._index: dict[int, tuple[int, int, float]] = {}  # guarded-by: _lock
        # Segments whose corruption has already been counted: a retained
        # partially-corrupt segment is re-read every replay attempt, and
        # its loss must be counted once, not once per attempt.
        self._corrupt_counted: set[int] = set()  # guarded-by: _lock
        self.stats = {  # guarded-by: _lock
            "segments_written": 0,
            "bytes_written": 0,
            "segments_replayed": 0,
            "segments_dropped": 0,
            "samples_dropped": 0,
            "bytes_dropped": 0,
            "corrupt_segments": 0,
            "disk_errors": 0,
        }
        self._next_seq = 1
        self._scan()

    # -- startup adoption ----------------------------------------------------

    def _path(self, seq: int) -> str:
        return os.path.join(self._dir, f"{seq:012d}.seg")

    def _scan(self) -> None:
        """Adopt segments a previous process left behind (crash-only
        recovery: whatever survived the rename barrier is replayable).
        Runs at construction only, but takes the (uncontended) lock
        anyway: the index/stats discipline then holds unconditionally
        (palint lock-discipline) instead of relying on "called before
        the object is shared" staying true."""
        with self._lock:
            self._scan_locked()

    def _scan_locked(self) -> None:  # palint: holds=_lock
        for name in sorted(os.listdir(self._dir)):
            path = os.path.join(self._dir, name)
            if name.endswith(".tmp"):
                # A torn write from a crashed predecessor: never valid.
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            if not name.endswith(".seg"):
                continue
            try:
                seq = int(name[:-4])
                size = os.path.getsize(path)
                with open(path, "rb") as f:
                    head = f.read(len(_MAGIC) + _HEADER.size)
                if not head.startswith(_MAGIC):
                    raise ValueError("bad magic")
                (n_samples,) = _HEADER.unpack(
                    head[len(_MAGIC):len(_MAGIC) + _HEADER.size])
            except (ValueError, OSError):
                self.stats["corrupt_segments"] += 1
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            self._index[seq] = (size, n_samples, self._clock())
            self._next_seq = max(self._next_seq, seq + 1)
        if self._index:
            _log.info("adopted spilled segments from a previous run",
                      segments=len(self._index))

    # -- write side ----------------------------------------------------------

    def append(self, series: list[RawSeries]) -> bool:
        """Spill one batch as a new segment; evict oldest segments past
        the byte cap. False (with counted drops) when the disk write
        itself fails — the batch is lost, but the agent lives."""
        n_samples = sum(len(s.samples) for s in series)
        t0 = time.perf_counter()  # spool_spill stage (runtime/trace.py)
        body = bytearray(_MAGIC)
        body += _HEADER.pack(n_samples)
        for s in series:
            payload = encode_write_raw_request([s], normalized=True)
            body += _FRAME.pack(len(payload), zlib.crc32(payload))
            body += payload
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
        # The disk write runs OUTSIDE the lock: a multi-MB spill must not
        # stall the flush thread's replay or the metrics thread for the
        # write's duration. The segment only becomes visible (index
        # insert) after the rename barrier.
        try:
            faults.inject("spool.write")
            atomic_write_bytes(self._path(seq), bytes(body))
        except OSError as e:
            with self._lock:
                self.stats["disk_errors"] += 1
                self.stats["samples_dropped"] += n_samples
                self.stats["bytes_dropped"] += len(body)
            _log.warn("spool write failed; batch dropped",
                      samples=n_samples, error=repr(e))
            # Failed spills are observed too: a slow-then-failing disk
            # is precisely the stall the histogram exists to explain.
            window_trace.observe("spool_spill", time.perf_counter() - t0)
            return False
        with self._lock:
            self._index[seq] = (len(body), n_samples, self._clock())
            self.stats["segments_written"] += 1
            self.stats["bytes_written"] += len(body)
            self._evict_locked()
        # One spill end-to-end (encode + frame + atomic write): the
        # latency a capture-thread overflow pays — exactly what the
        # flight recorder's spool_spill histogram must answer for.
        window_trace.observe("spool_spill", time.perf_counter() - t0)
        return True

    def _evict_locked(self) -> None:  # palint: holds=_lock
        while self._index and self._total_bytes_locked() > self._max_bytes:
            seq = min(self._index)
            size, n_samples, _ = self._index.pop(seq)
            try:
                os.unlink(self._path(seq))
            except OSError:
                pass
            self.stats["segments_dropped"] += 1
            self.stats["samples_dropped"] += n_samples
            self.stats["bytes_dropped"] += size
            _log.warn("spool over byte cap; evicted oldest segment",
                      seq=seq, samples=n_samples)

    def _total_bytes_locked(self) -> int:  # palint: holds=_lock
        return sum(size for size, _, _ in self._index.values())

    # -- replay side ---------------------------------------------------------

    def read_oldest(self) -> tuple[int, list[RawSeries]] | None:
        """Decode the oldest segment (replay is oldest-first so the store
        receives history in order). A CRC/frame failure drops the BAD
        TAIL of the segment (frames before it are intact by construction)
        and counts the corruption; a fully corrupt segment is deleted and
        the next one is tried."""
        while True:
            with self._lock:
                if not self._index:
                    return None
                seq = min(self._index)
                _, n_samples, _ = self._index[seq]
            series, ok = self._read_segment(seq)
            if series:
                if not ok:
                    # Partial salvage: the torn/corrupt tail frames are a
                    # real loss — count the sample shortfall vs the
                    # header's total, not just the corruption event —
                    # ONCE per segment (a retained segment is re-read on
                    # every replay attempt while the store is down).
                    salvaged = sum(len(s.samples) for s in series)
                    with self._lock:
                        if seq in self._index and \
                                seq not in self._corrupt_counted:
                            self._corrupt_counted.add(seq)
                            self.stats["corrupt_segments"] += 1
                            self.stats["samples_dropped"] += max(
                                0, n_samples - salvaged)
                return seq, series
            # Nothing salvageable. Distinguish real corruption from a
            # concurrent eviction (an overflow-spill append on another
            # thread may have unlinked this segment after our index
            # lookup): an evicted segment was already counted as a drop
            # by _evict_locked and must not read as phantom corruption.
            with self._lock:
                meta = self._index.get(seq)
                if meta is None:
                    continue  # evicted under us; try the next oldest
                if seq not in self._corrupt_counted:
                    self._corrupt_counted.add(seq)
                    self.stats["corrupt_segments"] += 1
                    self.stats["samples_dropped"] += meta[1]
                    self.stats["bytes_dropped"] += meta[0]
            self.pop(seq, replayed=False)

    def _read_segment(self, seq: int) -> tuple[list[RawSeries], bool]:
        series: list[RawSeries] = []
        try:
            with open(self._path(seq), "rb") as f:
                data = f.read()
        except OSError:
            return [], False
        if not data.startswith(_MAGIC):
            return [], False
        off = len(_MAGIC) + _HEADER.size
        while off < len(data):
            if off + _FRAME.size > len(data):
                return series, False  # torn tail
            length, crc = _FRAME.unpack_from(data, off)
            off += _FRAME.size
            payload = data[off:off + length]
            off += length
            if len(payload) != length or zlib.crc32(payload) != crc:
                return series, False
            decoded, _ = decode_write_raw_request(payload)
            series.extend(decoded)
        return series, True

    def pop(self, seq: int, replayed: bool = True) -> None:
        """Delete a segment — after successful replay by default;
        ``replayed=False`` for corrupt-segment disposal so replay
        progress is never overstated while data is being lost."""
        with self._lock:
            meta = self._index.pop(seq, None)
            self._corrupt_counted.discard(seq)
            if meta is not None and replayed:
                self.stats["segments_replayed"] += 1
            try:
                os.unlink(self._path(seq))
            except OSError:
                pass

    # -- observability -------------------------------------------------------

    def pending(self) -> tuple[int, int]:
        """(segments, bytes) awaiting replay."""
        with self._lock:
            return len(self._index), self._total_bytes_locked()

    def oldest_age_s(self) -> float:
        """Age of the oldest pending segment (replay lag proxy); 0 when
        empty. Adopted pre-crash segments age from adoption time."""
        with self._lock:
            if not self._index:
                return 0.0
            _, _, at = self._index[min(self._index)]
            return max(0.0, self._clock() - at)
