"""gRPC ProfileStore client.

Role of the reference's grpcConn + profilestore client wiring
(cmd/parca-agent/main.go:595-656): TLS or insecure channel, optional
bearer token attached per-RPC, and the WriteRaw unary call. No generated
stubs: the request is serialized by agent/profilestore.py and sent over a
generic unary_unary handle, so the dependency stays import-gated.
"""

from __future__ import annotations

from parca_agent_tpu.agent.profilestore import RawSeries, encode_write_raw_request

WRITE_RAW_METHOD = "/parca.profilestore.v1alpha1.ProfileStoreService/WriteRaw"
DEBUGINFO_UPLOAD_METHOD = "/parca.debuginfo.v1alpha1.DebuginfoService/Upload"


# Generous message bounds like the reference's MaxCallRecvMsgSize /
# MaxCallSendMsgSize options (main.go:595-656): one batch can carry many
# gzipped profiles plus debuginfo uploads share the channel.
MAX_MSG_BYTES = 64 << 20


class GRPCStoreClient:
    def __init__(self, address: str, insecure: bool = False,
                 bearer_token: str = "", timeout_s: float = 30.0,
                 max_msg_bytes: int = MAX_MSG_BYTES):
        try:
            import grpc
        except ImportError as e:  # pragma: no cover - grpc is in the image
            raise RuntimeError("grpc package unavailable") from e
        self._grpc = grpc
        self._timeout = timeout_s
        options = [
            ("grpc.max_send_message_length", max_msg_bytes),
            ("grpc.max_receive_message_length", max_msg_bytes),
        ]
        if insecure:
            self._channel = grpc.insecure_channel(address, options=options)
        else:
            creds = grpc.ssl_channel_credentials()
            if bearer_token:
                call_creds = grpc.access_token_call_credentials(bearer_token)
                creds = grpc.composite_channel_credentials(creds, call_creds)
            self._channel = grpc.secure_channel(address, creds,
                                                options=options)
        self._bearer = bearer_token if insecure else ""
        # Shared by the debuginfo client (one connection per server, like
        # the reference's single grpcConn, main.go:595-656).
        self.channel = self._channel
        self._write_raw = self._channel.unary_unary(
            WRITE_RAW_METHOD,
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b,
        )

    def write_raw(self, series: list[RawSeries], normalized: bool) -> None:
        metadata = []
        if self._bearer:
            # Insecure channels can't carry call credentials; send the
            # token as plain metadata like the reference's perRequestBearerToken
            # with insecure=true (main.go:620-637).
            metadata.append(("authorization", f"Bearer {self._bearer}"))
        self._write_raw(
            encode_write_raw_request(series, normalized),
            timeout=self._timeout,
            metadata=metadata or None,
        )

    def close(self) -> None:
        self._channel.close()
