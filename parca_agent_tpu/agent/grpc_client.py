"""gRPC ProfileStore client.

Role of the reference's grpcConn + profilestore client wiring
(cmd/parca-agent/main.go:595-656): TLS or insecure channel, optional
bearer token attached per-RPC, and the WriteRaw unary call. No generated
stubs: the request is serialized by agent/profilestore.py and sent over a
generic unary_unary handle, so the dependency stays import-gated.
"""

from __future__ import annotations

from parca_agent_tpu.agent.profilestore import RawSeries, encode_write_raw_request
from parca_agent_tpu.runtime import trace as window_trace
from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.log import get_logger

_log = get_logger("grpc")

WRITE_RAW_METHOD = "/parca.profilestore.v1alpha1.ProfileStoreService/WriteRaw"
DEBUGINFO_UPLOAD_METHOD = "/parca.debuginfo.v1alpha1.DebuginfoService/Upload"


# Generous message bounds like the reference's MaxCallRecvMsgSize /
# MaxCallSendMsgSize options (main.go:595-656): one batch can carry many
# gzipped profiles plus debuginfo uploads share the channel.
MAX_MSG_BYTES = 64 << 20


def _cert_name_cryptography(pem: str) -> str:
    """Subject CN (DNS-SAN fallback) via the `cryptography` package.
    Raises ImportError when the package is absent; any parse failure
    returns "" so the caller can try the stdlib route."""
    from cryptography import x509
    from cryptography.x509.oid import ExtensionOID, NameOID

    try:
        cert = x509.load_pem_x509_certificate(pem.encode())
        cns = cert.subject.get_attributes_for_oid(NameOID.COMMON_NAME)
        if cns:
            return str(cns[0].value)
        san = cert.extensions.get_extension_for_oid(
            ExtensionOID.SUBJECT_ALTERNATIVE_NAME)
        dns = san.value.get_values_for_type(x509.DNSName)
        return str(dns[0]) if dns else ""
    except Exception:  # noqa: BLE001 - best-effort, stdlib fallback next
        return ""


def _cert_name_stdlib(pem: str) -> str:
    """Subject CN (DNS-SAN fallback) via CPython's private
    ssl._ssl._test_decode_cert — the only stdlib route to the subject of
    an unverified certificate. Kept as the fallback: the API is private
    and may vanish, which is why `cryptography` is tried first."""
    import ssl
    import tempfile

    name = ""
    try:
        with tempfile.NamedTemporaryFile("w", suffix=".pem") as f:
            f.write(pem)
            f.flush()
            decoded = ssl._ssl._test_decode_cert(f.name)  # noqa: SLF001
        for rdn in decoded.get("subject", ()):
            for key, value in rdn:
                if key == "commonName":
                    name = value
        if not name:  # SAN-only certs have no CN
            for kind, value in decoded.get("subjectAltName", ()):
                if kind == "DNS":
                    name = value
                    break
    except Exception:  # noqa: BLE001 - override is best-effort
        name = ""
    return name


def _cert_name(pem: str) -> str:
    """Best-effort subject name of a PEM certificate for the
    SNI/hostname override: prefer the supported `cryptography` parser
    when importable, fall back to the private stdlib decoder."""
    try:
        name = _cert_name_cryptography(pem)
        if name:
            return name
    except ImportError:
        pass
    return _cert_name_stdlib(pem)


def _fetch_server_cert(address: str, timeout_s: float = 30.0
                       ) -> tuple[bytes, str]:
    """(PEM cert, subject common name) of the TLS server at address,
    fetched WITHOUT verification (the point: the caller asked to skip
    it). The returned name (subject CN, falling back to the first DNS
    SAN) lets the caller override SNI/hostname checking against the
    pinned cert."""
    import ssl

    host, port = _split_host_port(address)
    # Bounded: this fetch runs under the client's channel lock — an
    # unbounded dial against a black-holed address would hang every
    # writer and debuginfo worker, not just this call.
    pem = ssl.get_server_certificate((host, port), timeout=timeout_s)
    name = _cert_name(pem)
    if not name:
        # Without a derived name the hostname check runs against the
        # dial address; a CN/SAN mismatch then fails the handshake even
        # though the cert is pinned — worth a log line, not a crash.
        _log.warn("could not derive a subject name from the pinned "
                  "server certificate; skipping the hostname override",
                  address=address)
    return pem.encode(), name


def _split_host_port(address: str, default_port: int = 443
                     ) -> tuple[str, int]:
    """host:port / bare-host / [v6]:port / bare-[v6] -> (host, port)."""
    if address.startswith("["):
        host, _, rest = address[1:].partition("]")
        return host, int(rest.lstrip(":") or default_port)
    host, sep, port = address.rpartition(":")
    if not sep:
        return address, default_port
    if port == "":
        return host, default_port  # trailing colon: "host:"
    if not port.isdigit():
        return address, default_port
    return host, int(port)


class GRPCStoreClient:
    def __init__(self, address: str, insecure: bool = False,
                 insecure_skip_verify: bool = False,
                 bearer_token: str = "", timeout_s: float = 30.0,
                 max_msg_bytes: int = MAX_MSG_BYTES,
                 reset_after_unavailable: int = 3):
        try:
            import grpc
        except ImportError as e:  # pragma: no cover - grpc is in the image
            raise RuntimeError("grpc package unavailable") from e
        import threading

        self._grpc = grpc
        self._timeout = timeout_s
        self._address = address
        self._insecure = insecure
        self._skip_verify = insecure_skip_verify
        self._token = bearer_token
        self._options = [
            ("grpc.max_send_message_length", max_msg_bytes),
            ("grpc.max_receive_message_length", max_msg_bytes),
        ]
        self._bearer = bearer_token if insecure else ""
        # Channel construction is LAZY (first RPC): grpc channels are
        # lazy by themselves, but the skip-verify path must dial the
        # server for its certificate — doing that in __init__ would turn
        # a transiently unreachable store into an agent startup crash,
        # where the normal path starts and retries. A failed build is
        # re-attempted on the next RPC (the batch writer's backoff and
        # the debuginfo manager's error handling both absorb the raise).
        self._lock = threading.Lock()
        self._channel_obj = None   # guarded-by: _lock
        self._write_raw_m = None   # guarded-by: _lock
        # Channel-reset policy (ADVICE round 5): skip-verify pins the
        # server certificate at first use, so a server cert rotation
        # makes every internal reconnect fail TLS until the channel is
        # rebuilt — the reference's InsecureSkipVerify accepts any cert
        # on every handshake and never gets stuck. Reset the lazy channel
        # on handshake-class RPC failures, or after N consecutive
        # UNAVAILABLE errors (how grpc-python surfaces a failed TLS
        # handshake on reconnect), so the next RPC re-fetches and re-pins
        # the current certificate.
        self._reset_after_unavailable = max(1, reset_after_unavailable)
        # Failure bookkeeping is mutated from the writer's flush thread
        # AND the debuginfo workers; its own lock (not the channel lock:
        # _note_rpc_failure calls close(), which takes the channel lock —
        # sharing one would deadlock).
        self._stats_lock = threading.Lock()
        self._consec_unavailable = 0            # guarded-by: _stats_lock
        self.stats = {"channel_resets": 0}      # guarded-by: _stats_lock

    def _build_channel(self):
        grpc = self._grpc
        options = list(self._options)
        faults.inject("grpc.handshake")
        if self._insecure:
            return grpc.insecure_channel(self._address, options=options)
        if self._skip_verify:
            # The reference's --remote-store-insecure-skip-verify
            # (InsecureSkipVerify TLS). grpc-python has no direct switch,
            # so implement the same trust model explicitly: fetch the
            # server's certificate over an UNVERIFIED handshake and pin
            # it as the channel's root CA — encrypted transport, no
            # authentication (trust on first use for the channel's
            # lifetime). The certificate's own subject/SAN overrides the
            # hostname check for the same reason. Covers the flag's
            # dominant case (self-signed server certs); a chain from an
            # unknown CA still fails — OpenSSL will not treat a
            # non-self-signed leaf as a trust anchor, and grpc-python
            # exposes no partial-chain switch.
            cert, name = _fetch_server_cert(self._address,
                                            timeout_s=self._timeout)
            if name:
                options.append(("grpc.ssl_target_name_override", name))
            creds = self._grpc.ssl_channel_credentials(
                root_certificates=cert)
        else:
            creds = grpc.ssl_channel_credentials()
        if self._token:
            call_creds = grpc.access_token_call_credentials(self._token)
            creds = grpc.composite_channel_credentials(creds, call_creds)
        return grpc.secure_channel(self._address, creds, options=options)

    @property
    def channel(self):
        """Shared by the debuginfo client (one connection per server,
        like the reference's single grpcConn, main.go:595-656). Built on
        first access; a failed build raises to the caller and is retried
        on the next access."""
        with self._lock:
            if self._channel_obj is None:
                self._channel_obj = self._build_channel()
            return self._channel_obj

    def _write_raw_method(self):
        """The WriteRaw callable, built (with its channel) under the
        channel lock and returned as a LOCAL reference: a concurrent
        close()/reset can null the cached attribute at any time, so
        callers must never read it twice."""
        with self._lock:
            if self._channel_obj is None:
                self._channel_obj = self._build_channel()
            if self._write_raw_m is None:
                self._write_raw_m = self._channel_obj.unary_unary(
                    WRITE_RAW_METHOD,
                    request_serializer=lambda b: b,
                    response_deserializer=lambda b: b,
                )
            return self._write_raw_m

    def write_raw(self, series: list[RawSeries], normalized: bool) -> None:
        method = self._write_raw_method()
        metadata = []
        if self._bearer:
            # Insecure channels can't carry call credentials; send the
            # token as plain metadata like the reference's perRequestBearerToken
            # with insecure=true (main.go:620-637).
            metadata.append(("authorization", f"Bearer {self._bearer}"))
        try:
            # The chaos site sits inside the failure classifier's scope so
            # an injected UNAVAILABLE/handshake drives the same reset
            # bookkeeping a real RPC failure would.
            faults.inject("grpc.write_raw")
            import time as _time

            t0 = _time.perf_counter()
            method(
                encode_write_raw_request(series, normalized),
                timeout=self._timeout,
                metadata=metadata or None,
            )
            # The raw RPC alone (store_ack, one layer up in the batch
            # client, additionally covers serialization + channel build).
            window_trace.observe("store_rpc", _time.perf_counter() - t0)
        except Exception as e:
            self._note_rpc_failure(e)
            raise
        with self._stats_lock:
            self._consec_unavailable = 0

    def _note_rpc_failure(self, e: Exception) -> None:
        """Reset-on-failure bookkeeping (see __init__): a handshake-class
        error, or reset_after_unavailable consecutive UNAVAILABLEs, drops
        the built channel so the next RPC re-dials (and, under
        skip-verify, re-fetches and re-pins the server's current cert).
        Insecure channels have nothing to re-pin and are left alone."""
        if self._insecure:
            return
        detail = ""
        for attr in ("details", "debug_error_string"):
            try:
                detail += " " + str(getattr(e, attr)() or "")
            except Exception:  # noqa: BLE001 - non-grpc exceptions
                pass
        detail = (detail or repr(e)).lower()
        handshake = any(s in detail for s in (
            "handshake", "ssl", "certificate", "authentication"))
        unavailable = False
        try:
            unavailable = e.code() == self._grpc.StatusCode.UNAVAILABLE
        except Exception:  # noqa: BLE001 - non-grpc exceptions
            pass
        # Decide-and-count under the stats lock: writer + debuginfo
        # threads race through here, and an unguarded read-modify-write
        # both loses counts and can double-reset the channel.
        with self._stats_lock:
            if unavailable:
                self._consec_unavailable += 1
            reset = handshake or (unavailable and self._consec_unavailable
                                  >= self._reset_after_unavailable)
            if reset:
                self._consec_unavailable = 0
                self.stats["channel_resets"] += 1
        if reset:
            _log.warn("resetting gRPC channel after RPC failure "
                      "(re-pinning the server certificate on rebuild)",
                      address=self._address,
                      handshake_class=handshake, error=repr(e)[:200])
            self.close()

    def close(self) -> None:
        with self._lock:
            if self._channel_obj is not None:
                self._channel_obj.close()
                self._channel_obj = None
                self._write_raw_m = None
