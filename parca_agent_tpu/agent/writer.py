"""Profile writers: local files and remote (via listener/batcher).

Role of the reference's pkg/profiler/profile_writer.go:32-97:
FileProfileWriter stores each window's profile as a .pb.gz under a
directory (--local-store-directory mode); RemoteProfileWriter gzips the
encoded pprof and hands it to the write path (listener -> batch client).

Thread contract: in fast-encode mode write() is called from the encode
pipeline's worker thread (ship overlaps the next window's capture), and
may be called CONCURRENTLY from the profiler thread on the scalar
fallback path — both writers must (and do) tolerate that:
FileProfileWriter does one self-contained open/write per profile under a
nanosecond-stamped filename, RemoteProfileWriter's gzip is pure and its
downstream batch buffer is lock-protected. `pprof_bytes` may be any bytes-like (the pipeline
ships zero-copy memoryviews into the encoder's template buffer; the gzip
pass here materializes them before the view is recycled).
"""

from __future__ import annotations

import gzip
import os
import time

from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.vfs import atomic_write_bytes

# palint: persistence-root — local profile store writes survive restarts.


def _series_filename(labels: dict[str, str], now_ns: int) -> str:
    parts = [f"{k}={labels[k]}" for k in sorted(labels)
             if not k.startswith("__")]
    safe = "_".join(parts).replace("/", "-") or "profile"
    return f"{safe}.{now_ns}.pb.gz"


class FileProfileWriter:
    def __init__(self, directory: str):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)

    def write_raw(self, labels: dict[str, str], sample: bytes) -> None:
        """`sample` is already a gzipped pprof proto. Written through a
        tmp file + os.replace so a crash (or injected disk-full) mid-write
        never leaves a truncated .pb.gz in the local-store directory —
        readers of the directory only ever see whole profiles."""
        path = os.path.join(self._dir, _series_filename(labels, time.time_ns()))
        faults.inject("writer.write")
        atomic_write_bytes(path, sample)

    def write(self, labels: dict[str, str],
              pprof_bytes: bytes | memoryview) -> None:
        """Profile-writer interface: encode side handles gzip."""
        self.write_raw(labels, gzip.compress(pprof_bytes, 1))


class RemoteProfileWriter:
    """pprof bytes -> gzip -> downstream write_raw sink."""

    def __init__(self, sink):
        self._sink = sink

    def write(self, labels: dict[str, str],
              pprof_bytes: bytes | memoryview) -> None:
        self._sink.write_raw(labels, gzip.compress(pprof_bytes, 1))


class TeeProfileWriter:
    """Fan one profile write to several writers (--local-store-directory
    plus the remote path). Arms are constructed ONCE, here — the old CLI
    closure built a fresh RemoteProfileWriter per write. A failing arm
    aborts the remaining arms, like the single-writer path: the caller's
    per-profile error handling owns the failure either way."""

    def __init__(self, *writers):
        self._writers = writers

    def write(self, labels: dict[str, str],
              pprof_bytes: bytes | memoryview) -> None:
        for w in self._writers:
            w.write(labels, pprof_bytes)
