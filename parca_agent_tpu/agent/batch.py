"""Batching remote-write client.

Role of the reference's pkg/agent/batch_remote_write_client.go: buffer
RawProfileSeries in memory, merging samples into an existing series when
the label sets are equal (:144-184); a loop flushes every interval with
exponential backoff capped at the interval (:88-142). Failures keep the
batch for the next attempt; the capture path never blocks.
"""

from __future__ import annotations

import threading
import time
from typing import Protocol

from parca_agent_tpu.agent.profilestore import RawSeries
from parca_agent_tpu.utils.log import get_logger

_log = get_logger("batch")


class StoreClient(Protocol):
    def write_raw(self, series: list[RawSeries], normalized: bool) -> None: ...


class NoopStoreClient:
    """Default when no remote store is configured (reference agent.go:23-31)."""

    def write_raw(self, series: list[RawSeries], normalized: bool) -> None:
        pass


class BatchWriteClient:
    def __init__(self, client: StoreClient, interval_s: float = 10.0,
                 initial_backoff_s: float = 0.5, clock=time.monotonic,
                 sleep=None):
        self._client = client
        self._interval = interval_s
        self._initial_backoff = initial_backoff_s
        self._clock = clock
        self._stop = threading.Event()
        self._sleep = sleep or (lambda s: self._stop.wait(s))
        self._lock = threading.Lock()
        self._buffer: dict[tuple, RawSeries] = {}
        self.sent_batches = 0
        self.send_errors = 0

    def write_raw(self, labels: dict[str, str], sample: bytes) -> None:
        """Append one gzipped pprof for a label set (merge by label-set
        equality, batch_remote_write_client.go:167-184). Lock-protected:
        the encode pipeline ships from its worker thread while the flush
        loop drains from its own."""
        s = RawSeries(dict(labels), [sample])
        with self._lock:
            existing = self._buffer.get(s.key())
            if existing is not None:
                existing.samples.append(sample)
            else:
                self._buffer[s.key()] = s

    def buffered(self) -> tuple[int, int]:
        """(series, samples) currently awaiting flush — the observable
        depth of the encode→ship boundary now that encoding is
        pipelined ahead of the flush loop."""
        with self._lock:
            return (len(self._buffer),
                    sum(len(s.samples) for s in self._buffer.values()))

    def _swap(self) -> list[RawSeries]:
        with self._lock:
            batch = list(self._buffer.values())
            self._buffer = {}
        return batch

    def _restore(self, batch: list[RawSeries]) -> None:
        """Failed batch goes back first so order survives a retry window."""
        with self._lock:
            merged: dict[tuple, RawSeries] = {s.key(): s for s in batch}
            for s in self._buffer.values():
                ex = merged.get(s.key())
                if ex is not None:
                    ex.samples.extend(s.samples)
                else:
                    merged[s.key()] = s
            self._buffer = merged

    def flush(self) -> bool:
        """One batch attempt with capped exponential backoff; True on
        success or empty batch."""
        batch = self._swap()
        if not batch:
            return True
        backoff = self._initial_backoff
        deadline = self._clock() + self._interval
        while True:
            try:
                self._client.write_raw(batch, normalized=True)
                self.sent_batches += 1
                return True
            except Exception as e:
                self.send_errors += 1
                if self._clock() + backoff >= deadline or self._stop.is_set():
                    self._restore(batch)
                    _log.warn("batch write failed; will retry next interval",
                              series=len(batch), error=repr(e))
                    return False
                self._sleep(backoff)
                backoff = min(backoff * 2, self._interval)

    def run(self) -> None:
        """Flush loop (one actor of the run group, reference main.go:250)."""
        while not self._stop.is_set():
            self._stop.wait(self._interval)
            if self._stop.is_set():
                break
            self.flush()
        self.flush()  # final drain

    def stop(self) -> None:
        self._stop.set()
