"""Batching remote-write client: bounded, spill-backed, crash-only.

Role of the reference's pkg/agent/batch_remote_write_client.go: buffer
RawProfileSeries in memory, merging samples into an existing series when
the label sets are equal (:144-184); a loop flushes every interval
(:88-142). The reference retries forever with an UNBOUNDED in-memory
buffer — an hours-long store outage costs either the host's profile
history or the agent's RSS (the round-5 outage record: 491 dead probes
over 11.1 h). This client deviates deliberately (docs/robustness.md):

  * The buffer has byte/sample caps. On overflow the whole buffered
    batch spills to the disk spool (agent/spool.py) — or, with no spool
    configured, is dropped and counted.
  * Repeated flush failure (``spill_after_failures`` consecutive) also
    spills instead of re-buffering, so RSS stays bounded for the entire
    outage; the spool's own byte cap + oldest-eviction bounds the disk.
  * Retry backoff is full-jitter exponential (AWS-style: sleep ~
    U(0, min(cap, base·2^attempt))) — after a store restart, a fleet of
    agents with synchronized fixed-doubling backoff is a thundering
    herd; jitter decorrelates them. Retries spend a per-interval budget
    SHARED between the live flush and spool replay, so recovery can
    never starve live windows.
  * On the first successful flush after an outage, spilled segments
    replay oldest-first, at most ``replay_per_interval`` segments per
    interval (bounded-rate catch-up).

The capture path still never blocks: write_raw only appends to the
locked buffer (and at worst pays one spool file write on overflow).
"""

from __future__ import annotations

import random
import threading
import time
from typing import Protocol

from parca_agent_tpu.agent.profilestore import RawSeries
from parca_agent_tpu.runtime import trace as window_trace
from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.log import get_logger

_log = get_logger("batch")


class StoreClient(Protocol):
    def write_raw(self, series: list[RawSeries], normalized: bool) -> None: ...


class NoopStoreClient:
    """Default when no remote store is configured (reference agent.go:23-31)."""

    def write_raw(self, series: list[RawSeries], normalized: bool) -> None:
        pass


def _series_bytes(labels: dict[str, str], sample: bytes) -> int:
    """Buffer accounting: payload plus a small label overhead term."""
    return len(sample) + sum(len(k) + len(v) for k, v in labels.items())


class BatchWriteClient:
    def __init__(self, client: StoreClient, interval_s: float = 10.0,
                 initial_backoff_s: float = 0.5, clock=time.monotonic,
                 sleep=None, rng: random.Random | None = None,
                 max_buffer_bytes: int = 64 << 20,
                 max_buffer_samples: int = 100_000,
                 spool=None, spill_after_failures: int = 2,
                 retry_budget: int = 8,
                 replay_per_interval: int = 4):
        self._client = client
        self._interval = interval_s
        self._initial_backoff = initial_backoff_s
        self._clock = clock
        self._stop = threading.Event()
        self._sleep = sleep or (lambda s: self._stop.wait(s))
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self._buffer: dict[tuple, RawSeries] = {}
        self._buffer_bytes = 0
        self._buffer_samples = 0
        self._max_bytes = max_buffer_bytes
        self._max_samples = max_buffer_samples
        self._spool = spool
        self._spill_after = max(1, spill_after_failures)
        self._retry_budget = max(0, retry_budget)
        self._replay_per_interval = max(1, replay_per_interval)
        self._consec_failures = 0
        self.sent_batches = 0
        self.send_errors = 0
        self.stats = {  # guarded-by: _lock
            "samples_dropped": 0,
            "bytes_dropped": 0,
            "overflow_spills": 0,
            "failure_spills": 0,
            "segments_replayed": 0,
            "samples_replayed": 0,
            "replay_errors": 0,
            "retry_budget_exhausted": 0,
        }

    # -- capture-side API ----------------------------------------------------

    def write_raw(self, labels: dict[str, str], sample: bytes) -> None:
        """Append one gzipped pprof for a label set (merge by label-set
        equality, batch_remote_write_client.go:167-184). Lock-protected:
        the encode pipeline ships from its worker thread while the flush
        loop drains from its own. Past the buffer caps the OLD buffer
        spills to disk (or is dropped, counted) so this call never grows
        memory unboundedly and never blocks on the network."""
        s = RawSeries(dict(labels), [sample])
        cost = _series_bytes(labels, sample)
        spill = None
        with self._lock:
            if (self._buffer_bytes + cost > self._max_bytes
                    or self._buffer_samples + 1 > self._max_samples) \
                    and self._buffer:
                spill = list(self._buffer.values())
                spill_bytes = self._buffer_bytes
                self._buffer = {}
                self._buffer_bytes = 0
                self._buffer_samples = 0
            existing = self._buffer.get(s.key())
            if existing is not None:
                existing.samples.append(sample)
            else:
                self._buffer[s.key()] = s
            self._buffer_bytes += cost
            self._buffer_samples += 1
        if spill is not None:
            with self._lock:
                self.stats["overflow_spills"] += 1
            self._spill(spill, spill_bytes, why="buffer overflow")

    def buffered(self) -> tuple[int, int]:
        """(series, samples) currently awaiting flush — the observable
        depth of the encode→ship boundary now that encoding is
        pipelined ahead of the flush loop."""
        with self._lock:
            return (len(self._buffer), self._buffer_samples)

    def buffer_bytes(self) -> int:
        """Approximate bytes held in the in-memory buffer (the RSS-proxy
        gauge; spool bytes are the disk half)."""
        with self._lock:
            return self._buffer_bytes

    # -- internal buffer plumbing --------------------------------------------

    def _swap(self) -> list[RawSeries]:
        with self._lock:
            batch = list(self._buffer.values())
            self._buffer = {}
            self._buffer_bytes = 0
            self._buffer_samples = 0
        return batch

    def _restore(self, batch: list[RawSeries]) -> None:
        """Failed batch goes back first so order survives a retry window."""
        with self._lock:
            merged: dict[tuple, RawSeries] = {s.key(): s for s in batch}
            for s in self._buffer.values():
                ex = merged.get(s.key())
                if ex is not None:
                    ex.samples.extend(s.samples)
                else:
                    merged[s.key()] = s
            self._buffer = merged
            self._buffer_samples = sum(
                len(s.samples) for s in merged.values())
            self._buffer_bytes = sum(
                _series_bytes(s.labels, b)
                for s in merged.values() for b in s.samples)

    def _spill(self, batch: list[RawSeries], batch_bytes: int,
               why: str) -> None:
        """Move a batch out of memory: to the spool when configured (its
        cap/eviction accounting then owns the data), else counted drop.
        Runs on whichever thread overflowed the buffer (capture thread,
        encode worker) as well as the flush thread, so every stats
        read-modify-write here is under the lock."""
        n_samples = sum(len(s.samples) for s in batch)
        if self._spool is not None:
            if self._spool.append(batch):
                _log.warn("batch spilled to disk", reason=why,
                          samples=n_samples)
            # On a failed spool write the spool counted the drop itself
            # (its stats are exported too) — counting it here as well
            # would double every loss number downstream.
            return
        with self._lock:
            self.stats["samples_dropped"] += n_samples
            self.stats["bytes_dropped"] += batch_bytes
        _log.warn("batch dropped", reason=why, samples=n_samples,
                  spool="none")

    # -- flush / retry / replay ----------------------------------------------

    def _jitter(self, attempt: int) -> float:
        """Full-jitter exponential backoff delay ~ U(0, min(interval,
        initial_backoff · 2^attempt)). Decorrelates a fleet of agents
        retrying against a restarting store."""
        cap = min(self._initial_backoff * (2 ** attempt), self._interval)
        return self._rng.uniform(0.0, cap)

    def flush(self, drain: bool = False) -> bool:
        """One batch attempt with budgeted full-jitter retries; True on
        success or empty batch. On success, replays spilled segments
        (bounded) with whatever retry budget the live flush left over.
        ``drain=True`` (final flush on stop) spills to disk on failure
        regardless of the consecutive-failure threshold, so a shutdown
        during an outage loses nothing that a spool could hold."""
        budget = [self._retry_budget]
        batch = self._swap()
        if not batch:
            # An empty interval still replays: with no live traffic the
            # first replay send doubles as the store-recovery probe (an
            # idle agent must not strand its spilled history).
            self._replay(budget)
            return True
        attempt = 0
        # Flight-recorder stages (runtime/trace.py, free when no recorder
        # is installed): batch_flush is the whole attempt loop — retries,
        # backoff, and terminal spill included, the end-to-end latency of
        # getting one batch out of memory — store_ack one successful
        # WriteRaw round trip.
        t_flush0 = time.perf_counter()
        # Retries stop at whichever comes first: the per-interval budget
        # (herd control) or the interval deadline (the reference's cap —
        # a flush never runs past its own interval).
        deadline = self._clock() + self._interval
        while True:
            try:
                # Chaos site for ONE send attempt: an injected error here
                # rides the same retry/spill machinery as a store failure
                # (an actor-killing crash is the actor.flush site's job).
                faults.inject("batch.flush")
                t_ack0 = time.perf_counter()
                self._client.write_raw(batch, normalized=True)
                window_trace.observe("store_ack",
                                     time.perf_counter() - t_ack0)
                self.sent_batches += 1
                self._consec_failures = 0
                window_trace.observe("batch_flush",
                                     time.perf_counter() - t_flush0)
                self._replay(budget)
                return True
            except Exception as e:
                self.send_errors += 1
                # The deadline is checked BEFORE sleeping (like the old
                # fixed-doubling loop): a jittered sleep that would end
                # past the deadline is never taken, so one flush cannot
                # overrun its interval by a backoff.
                delay = self._jitter(attempt)
                if budget[0] <= 0 or self._clock() + delay >= deadline \
                        or self._stop.is_set():
                    if budget[0] <= 0:
                        # Stats RMWs ride the lock everywhere (palint
                        # lock-discipline): the capture/encode threads'
                        # overflow path increments concurrently.
                        with self._lock:
                            self.stats["retry_budget_exhausted"] += 1
                    self._consec_failures += 1
                    if self._spool is not None and \
                            (drain or self._consec_failures
                             >= self._spill_after):
                        batch_bytes = sum(
                            _series_bytes(s.labels, b)
                            for s in batch for b in s.samples)
                        with self._lock:
                            self.stats["failure_spills"] += 1
                        self._spill(batch, batch_bytes,
                                    why="repeated flush failure"
                                    if not drain else "final drain")
                    else:
                        self._restore(batch)
                    _log.warn("batch write failed; will retry next interval",
                              series=len(batch), error=repr(e),
                              consec_failures=self._consec_failures)
                    window_trace.observe("batch_flush",
                                         time.perf_counter() - t_flush0)
                    return False
                budget[0] -= 1
                self._sleep(delay)
                attempt += 1

    def _replay(self, budget: list[int]) -> None:
        """Replay spilled segments oldest-first after a successful live
        flush, bounded per interval AND by the shared retry budget, so
        outage recovery cannot starve live windows of their send slots."""
        if self._spool is None:
            return
        for _ in range(self._replay_per_interval):
            if budget[0] <= 0 or self._stop.is_set():
                return
            t_seg0 = time.perf_counter()
            got = self._spool.read_oldest()
            if got is None:
                return
            seq, series = got
            budget[0] -= 1
            try:
                t_ack0 = time.perf_counter()
                self._client.write_raw(series, normalized=True)
                window_trace.observe("store_ack",
                                     time.perf_counter() - t_ack0)
            except Exception as e:
                # Store flapped again mid-replay: the segment stays for
                # the next interval (replay is at-least-once; the store
                # dedups nothing, so a duplicate costs bytes, not
                # correctness of the history).
                with self._lock:
                    self.stats["replay_errors"] += 1
                _log.warn("spool replay failed; segment retained",
                          seq=seq, error=repr(e))
                return
            self._spool.pop(seq)
            self._consec_failures = 0  # the store took data: recovered
            with self._lock:
                self.stats["segments_replayed"] += 1
                self.stats["samples_replayed"] += sum(
                    len(s.samples) for s in series)
            # One replayed segment end-to-end: decode + send + delete.
            window_trace.observe("spool_replay",
                                 time.perf_counter() - t_seg0)

    def replay_backlog(self) -> tuple[int, int]:
        """(segments, bytes) still spilled on disk (0, 0 without a spool)."""
        if self._spool is None:
            return (0, 0)
        return self._spool.pending()

    def replay_lag_s(self) -> float:
        return self._spool.oldest_age_s() if self._spool is not None else 0.0

    def spool_stats(self) -> dict:
        """The spool's own counters (evictions, disk errors, corruption
        — the disk-side loss accounting); {} without a spool."""
        return dict(self._spool.stats) if self._spool is not None else {}

    # -- actor ---------------------------------------------------------------

    def run(self) -> None:
        """Flush loop (one actor of the run group; supervised in the CLI).
        The ``actor.flush`` fault site lets the chaos layer kill this
        actor to exercise supervisor restarts."""
        while not self._stop.is_set():
            self._stop.wait(self._interval)
            if self._stop.is_set():
                break
            faults.inject("actor.flush")
            self.flush()
        self.flush(drain=True)  # final drain

    def stop(self) -> None:
        self._stop.set()
