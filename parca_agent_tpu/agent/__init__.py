"""Agent transport: batching remote write + live-query tee
(reference pkg/agent)."""

from parca_agent_tpu.agent.profilestore import (
    RawSeries,
    encode_write_raw_request,
    decode_write_raw_request,
)
from parca_agent_tpu.agent.batch import BatchWriteClient
from parca_agent_tpu.agent.listener import MatchingProfileListener
from parca_agent_tpu.agent.writer import FileProfileWriter, RemoteProfileWriter

__all__ = [
    "RawSeries", "encode_write_raw_request", "decode_write_raw_request",
    "BatchWriteClient", "MatchingProfileListener",
    "FileProfileWriter", "RemoteProfileWriter",
]
