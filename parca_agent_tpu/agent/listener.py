"""Matching profile listener: tee in the write path for live queries.

Role of the reference's pkg/agent/matching_profile_listener.go:44-127: the
HTTP /query endpoint registers an observer with Prometheus-style label
matchers and receives the next raw profile whose labels match; regular
writes flow through to the next writer unchanged.
"""

from __future__ import annotations

import threading
from typing import Callable

Matcher = Callable[[dict[str, str]], bool]


def equals_matcher(**want: str) -> Matcher:
    return lambda labels: all(labels.get(k) == v for k, v in want.items())


class _Observer:
    def __init__(self, matcher: Matcher):
        self.matcher = matcher
        self.event = threading.Event()
        self.result: tuple[dict[str, str], bytes] | None = None


class MatchingProfileListener:
    def __init__(self, next_writer=None):
        self._next = next_writer
        self._lock = threading.Lock()
        self._observers: list[_Observer] = []

    def write_raw(self, labels: dict[str, str], sample: bytes) -> None:
        with self._lock:
            remaining = []
            for ob in self._observers:
                if ob.result is None and ob.matcher(labels):
                    ob.result = (dict(labels), sample)
                    ob.event.set()
                else:
                    remaining.append(ob)
            self._observers = remaining
        if self._next is not None:
            self._next.write_raw(labels, sample)

    def next_matching_profile(self, matcher: Matcher,
                              timeout: float | None = None
                              ) -> tuple[dict[str, str], bytes] | None:
        ob = _Observer(matcher)
        with self._lock:
            self._observers.append(ob)
        if not ob.event.wait(timeout):
            with self._lock:
                if ob in self._observers:
                    self._observers.remove(ob)
            return None
        return ob.result
