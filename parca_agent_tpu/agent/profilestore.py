"""Parca profilestore wire format (WriteRawRequest).

Hand-rolled protobuf encode/decode for the gRPC method the agent ships
profiles over (reference: parca profilestore v1alpha1, used by
pkg/agent/batch_remote_write_client.go). Schema subset:

  WriteRawRequest  { string tenant = 1; repeated RawProfileSeries series = 2;
                     bool normalized = 3; }
  RawProfileSeries { LabelSet labels = 1; repeated RawSample samples = 2; }
  LabelSet         { repeated Label labels = 1; }
  Label            { string name = 1; string value = 2; }
  RawSample        { bytes raw_profile = 1; }
"""

from __future__ import annotations

import dataclasses

from parca_agent_tpu.pprof.proto import (
    get_varint,
    iter_fields,
    put_tag_bytes,
    put_tag_varint,
)


@dataclasses.dataclass
class RawSeries:
    labels: dict[str, str]
    samples: list[bytes]  # gzipped pprof protos

    def key(self) -> tuple:
        return tuple(sorted(self.labels.items()))


def _encode_label(name: str, value: str) -> bytes:
    out = bytearray()
    put_tag_bytes(out, 1, name.encode())
    put_tag_bytes(out, 2, value.encode())
    return bytes(out)


def _encode_labelset(labels: dict[str, str]) -> bytes:
    out = bytearray()
    for name in sorted(labels):
        put_tag_bytes(out, 1, _encode_label(name, labels[name]))
    return bytes(out)


def encode_write_raw_request(series: list[RawSeries],
                             normalized: bool = True) -> bytes:
    out = bytearray()
    for s in series:
        body = bytearray()
        put_tag_bytes(body, 1, _encode_labelset(s.labels))
        for sample in s.samples:
            sm = bytearray()
            put_tag_bytes(sm, 1, sample)
            put_tag_bytes(body, 2, bytes(sm))
        put_tag_bytes(out, 2, bytes(body))
    put_tag_varint(out, 3, 1 if normalized else 0)
    return bytes(out)


def decode_write_raw_request(data: bytes) -> tuple[list[RawSeries], bool]:
    """Inverse of encode (tests + the in-memory store fake)."""
    series: list[RawSeries] = []
    normalized = False
    for field, wt, value in iter_fields(data):
        if field == 2 and wt == 2:
            labels: dict[str, str] = {}
            samples: list[bytes] = []
            for f2, w2, v2 in iter_fields(value):
                if f2 == 1 and w2 == 2:  # LabelSet
                    for f3, w3, v3 in iter_fields(v2):
                        if f3 == 1 and w3 == 2:  # Label
                            name = val = ""
                            for f4, w4, v4 in iter_fields(v3):
                                if f4 == 1:
                                    name = v4.decode()
                                elif f4 == 2:
                                    val = v4.decode()
                            labels[name] = val
                elif f2 == 2 and w2 == 2:  # RawSample
                    for f3, w3, v3 in iter_fields(v2):
                        if f3 == 1 and w3 == 2:
                            samples.append(v3)
            series.append(RawSeries(labels, samples))
        elif field == 3 and wt == 0:
            normalized = bool(value)
    return series, normalized


def decode_varint_prefixed(data: bytes) -> tuple[int, int]:
    return get_varint(data, 0)
