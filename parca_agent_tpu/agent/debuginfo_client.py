"""gRPC debuginfo upload client (parca debuginfo v1alpha1 flow).

Implements the DebuginfoManager's client protocol over the server-side
upload conversation the reference drives through generated stubs
(pkg/debuginfo/client.go + parca's debuginfo service):

  exists():  ShouldInitiateUpload(build_id, hash) — server answers whether
             it wants this build id at all;
  upload():  InitiateUpload(build_id, hash, size) -> upload_id, then a
             client-streaming Upload(info, chunks...), then
             MarkUploadFinished(build_id, upload_id).

Wire messages are hand-rolled like the rest of the transport (schema
subset of parca's debuginfo/v1alpha1/debuginfo.proto; the grpc channel is
shared machinery with agent/grpc_client.py).
"""

from __future__ import annotations

from parca_agent_tpu.pprof.proto import iter_fields, put_tag_bytes, put_tag_varint

SVC = "/parca.debuginfo.v1alpha1.DebuginfoService"
SHOULD_INITIATE = f"{SVC}/ShouldInitiateUpload"
INITIATE = f"{SVC}/InitiateUpload"
UPLOAD = f"{SVC}/Upload"
MARK_FINISHED = f"{SVC}/MarkUploadFinished"

_CHUNK = 1 << 20  # 1 MiB per streamed chunk


def _enc_should_initiate(build_id: str, hash_: str) -> bytes:
    out = bytearray()
    put_tag_bytes(out, 1, build_id.encode())
    put_tag_bytes(out, 2, hash_.encode())
    return bytes(out)


def _dec_should_initiate(data: bytes) -> bool:
    for field, wt, value in iter_fields(data):
        if field == 1 and wt == 0:
            return bool(value)
    return False


def _enc_initiate(build_id: str, hash_: str, size: int) -> bytes:
    out = bytearray()
    put_tag_bytes(out, 1, build_id.encode())
    put_tag_varint(out, 2, size)
    put_tag_bytes(out, 3, hash_.encode())
    return bytes(out)


def _dec_initiate_upload_id(data: bytes) -> str:
    # InitiateUploadResponse{ UploadInstructions upload_instructions = 1 }
    # UploadInstructions{ build_id = 1; upload_id = 2; ... }
    for field, wt, value in iter_fields(data):
        if field == 1 and wt == 2:
            for f2, w2, v2 in iter_fields(value):
                if f2 == 2 and w2 == 2:
                    return v2.decode()
    return ""


def _enc_upload_info(build_id: str, upload_id: str) -> bytes:
    info = bytearray()
    put_tag_bytes(info, 1, build_id.encode())
    put_tag_bytes(info, 2, upload_id.encode())
    out = bytearray()
    put_tag_bytes(out, 1, bytes(info))  # oneof data { UploadInfo info = 1; }
    return bytes(out)


def _enc_upload_chunk(chunk: bytes) -> bytes:
    out = bytearray()
    put_tag_bytes(out, 2, chunk)  # oneof data { bytes chunk_data = 2; }
    return bytes(out)


def _enc_mark_finished(build_id: str, upload_id: str) -> bytes:
    out = bytearray()
    put_tag_bytes(out, 1, build_id.encode())
    put_tag_bytes(out, 2, upload_id.encode())
    return bytes(out)


class GRPCDebuginfoClient:
    """DebuginfoManager client over a shared grpc channel.

    `channel` may also be a zero-arg CALLABLE returning the channel:
    stub construction is then deferred to the first RPC, so a channel
    whose own construction dials the server (the store client's
    skip-verify cert fetch) cannot turn agent startup into a crash when
    the store is transiently down — the manager's per-upload error
    handling absorbs the raise and retries after its TTL."""

    def __init__(self, channel, timeout_s: float = 60.0):
        self._timeout = timeout_s
        self._should = None
        if callable(channel):
            self._channel_provider = channel
        else:
            self._channel_provider = lambda: channel
            self._make_stubs(channel)

    def _make_stubs(self, channel) -> None:
        ident = lambda b: b  # noqa: E731 - raw-bytes (de)serializers
        # self._should doubles as the initialized sentinel for the
        # manager's concurrent workers: assign it LAST so no thread can
        # observe a partially-stubbed client.
        self._initiate = channel.unary_unary(
            INITIATE, request_serializer=ident, response_deserializer=ident)
        self._upload = channel.stream_unary(
            UPLOAD, request_serializer=ident, response_deserializer=ident)
        self._mark = channel.unary_unary(
            MARK_FINISHED, request_serializer=ident,
            response_deserializer=ident)
        self._should = channel.unary_unary(
            SHOULD_INITIATE, request_serializer=ident,
            response_deserializer=ident)

    def _ensure_stubs(self) -> None:
        if self._should is None:
            self._make_stubs(self._channel_provider())

    def exists(self, build_id: str, hash_: str) -> bool:
        self._ensure_stubs()
        resp = self._should(_enc_should_initiate(build_id, hash_),
                            timeout=self._timeout)
        return not _dec_should_initiate(resp)

    def upload(self, build_id: str, hash_: str, data: bytes) -> None:
        self._ensure_stubs()
        resp = self._initiate(_enc_initiate(build_id, hash_, len(data)),
                              timeout=self._timeout)
        upload_id = _dec_initiate_upload_id(resp)

        def chunks():
            yield _enc_upload_info(build_id, upload_id)
            for off in range(0, len(data), _CHUNK):
                yield _enc_upload_chunk(data[off: off + _CHUNK])

        self._upload(chunks(), timeout=self._timeout)
        self._mark(_enc_mark_finished(build_id, upload_id),
                   timeout=self._timeout)
