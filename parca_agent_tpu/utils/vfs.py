"""Filesystem injection seam.

Every component that reads procfs or ELF files takes a `VFS` so tests run
against in-memory trees — the role `pkg/testutil/fs.go:30-55`'s
NewFakeFS/NewErrorFS plays in the reference's test strategy (SURVEY.md
section 4). Paths are absolute strings; FakeFS keys are absolute paths.
"""

from __future__ import annotations

import io
import os
from typing import Iterable, Protocol

# palint: persistence-root — atomic_write_bytes is the shared tmp+rename primitive.


class VFS(Protocol):
    def read_bytes(self, path: str) -> bytes: ...
    def exists(self, path: str) -> bool: ...
    def listdir(self, path: str) -> list[str]: ...
    def open(self, path: str) -> io.BufferedIOBase: ...

    def stat_signature(self, path: str) -> tuple:
        """Cheap file-identity tuple for change detection and cross-path
        dedup. Must distinguish files across devices (the agent reads
        through /proc/<pid>/root/, crossing container mounts)."""
        ...


class RealFS:
    """The host filesystem."""

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> list[str]:
        return os.listdir(path)

    def open(self, path: str):
        return open(path, "rb")

    def stat_signature(self, path: str) -> tuple:
        st = os.stat(path)
        # st_dev matters: inode numbers are only unique per device, and
        # /proc/<pid>/root paths cross container filesystems.
        return (st.st_dev, st.st_ino, st.st_size, st.st_mtime_ns)


class FakeFS:
    """In-memory tree: {absolute_path: bytes}."""

    def __init__(self, files: dict[str, bytes] | None = None):
        self.files = dict(files or {})
        self._version = 0

    def put(self, path: str, data: bytes) -> None:
        self.files[path] = data
        self._version += 1

    def read_bytes(self, path: str) -> bytes:
        try:
            return self.files[path]
        except KeyError:
            raise FileNotFoundError(path) from None

    def exists(self, path: str) -> bool:
        if path in self.files:
            return True
        prefix = path.rstrip("/") + "/"
        return any(p.startswith(prefix) for p in self.files)

    def listdir(self, path: str) -> list[str]:
        prefix = path.rstrip("/") + "/"
        names = {p[len(prefix):].split("/", 1)[0]
                 for p in self.files if p.startswith(prefix)}
        if not names and not self.exists(path):
            raise FileNotFoundError(path)
        return sorted(names)

    def open(self, path: str):
        return io.BytesIO(self.read_bytes(path))

    def stat_signature(self, path: str) -> tuple:
        data = self.read_bytes(path)
        # Content hash stands in for (dev, inode): distinct fake files
        # must never collide just by having equal lengths.
        import hashlib

        digest = hashlib.blake2b(data, digest_size=8).hexdigest()
        return (digest, len(data), self._version)


class ErrorFS:
    """Every operation raises `err` — exercises error paths in tests."""

    def __init__(self, err: Exception):
        self.err = err

    def _raise(self, *a, **k):
        raise self.err

    read_bytes = exists = listdir = open = stat_signature = _raise


def atomic_write_bytes(path: str | os.PathLike, data: bytes) -> None:
    """Crash-atomic file write: tmp sibling + os.replace, tmp cleaned on
    failure. Readers of `path` only ever see a whole file (the
    local-store profile writer and the spill spool both depend on this
    — a crash mid-write must never leave a truncated artifact)."""
    tmp = os.fspath(path) + ".tmp"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def fake_procfs(pids: Iterable[int], extra: dict[str, bytes] | None = None) -> FakeFS:
    """A minimal /proc skeleton for the given pids."""
    files = {}
    for pid in pids:
        files[f"/proc/{pid}/comm"] = f"proc{pid}\n".encode()
    files.update(extra or {})
    return FakeFS(files)
