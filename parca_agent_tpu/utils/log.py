"""Leveled, structured (logfmt) logging.

Role of the reference's pkg/logger/logger.go: a go-kit style leveled
logger emitting logfmt lines with timestamp, level, and caller, with the
level chosen by --log-level. Built on stdlib logging so handlers/threads
behave, but the emission format is logfmt — `ts=... level=info
caller=cpu.py:134 msg="..." key=value` — matching the observability
contract SURVEY.md §5.5 records.

Usage:
    from parca_agent_tpu.utils.log import get_logger, setup_logging
    setup_logging("debug")               # once, in the CLI
    log = get_logger("profiler")
    log.info("window closed", pids=412, samples=99840)

Until setup_logging runs, the root agent logger has no handler and
follows logging's lastResort (warnings+ to stderr) — library users who
configure logging themselves are not surprised by double output.
"""

from __future__ import annotations

import logging
import os
import sys
import time

_ROOT = "parca_agent_tpu"

LEVELS = {
    "error": logging.ERROR,
    "warn": logging.WARNING,
    "info": logging.INFO,
    "debug": logging.DEBUG,
}


def _quote(v) -> str:
    s = str(v)
    if s == "" or any(c in s for c in ' "='):
        return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'
    return s


class LogfmtFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        ts = time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(record.created))
        level = {logging.ERROR: "error", logging.WARNING: "warn",
                 logging.INFO: "info", logging.DEBUG: "debug"}.get(
                     record.levelno, record.levelname.lower())
        # Prefer the facade's explicitly captured caller: logging's own
        # findCaller walks `stacklevel` frames, whose accounting differs
        # between 3.10 and 3.11+ — the explicit frame is version-proof.
        caller = getattr(record, "logfmt_caller", None) \
            or f"{record.filename}:{record.lineno}"
        parts = [
            f"ts={ts}.{int(record.msecs):03d}Z",
            f"level={level}",
            f"caller={caller}",
            f"component={record.name.removeprefix(_ROOT + '.') or 'agent'}",
            f"msg={_quote(record.getMessage())}",
        ]
        for k, v in sorted(getattr(record, "logfmt_kv", {}).items()):
            parts.append(f"{k}={_quote(v)}")
        if record.exc_info and record.exc_info[1] is not None:
            parts.append(f"err={_quote(repr(record.exc_info[1]))}")
        return " ".join(parts)


class Logger:
    """Keyword-value logging facade over one stdlib logger."""

    def __init__(self, logger: logging.Logger):
        self._logger = logger

    def _log(self, level: int, msg: str, exc=None, **kv) -> None:
        if self._logger.isEnabledFor(level):
            # Capture the real caller ourselves: frame 0 is this _log,
            # frame 1 the public facade method (info/debug/...), frame 2
            # the call site. stdlib `stacklevel` walks frames with
            # version-dependent accounting (3.10 lands one frame off
            # under pytest's importer), so the explicit frame is the
            # only portable source of caller=file:line.
            try:
                f = sys._getframe(2)
                caller = (f"{os.path.basename(f.f_code.co_filename)}"
                          f":{f.f_lineno}")
            except Exception:
                caller = None
            self._logger._log(
                level, msg, (), exc_info=exc,
                extra={"logfmt_kv": kv, "logfmt_caller": caller})

    def debug(self, msg: str, **kv) -> None:
        self._log(logging.DEBUG, msg, **kv)

    def info(self, msg: str, **kv) -> None:
        self._log(logging.INFO, msg, **kv)

    def warn(self, msg: str, **kv) -> None:
        self._log(logging.WARNING, msg, **kv)

    def error(self, msg: str, exc: BaseException | None = None, **kv) -> None:
        self._log(logging.ERROR, msg, exc=exc, **kv)


def get_logger(component: str = "") -> Logger:
    name = f"{_ROOT}.{component}" if component else _ROOT
    return Logger(logging.getLogger(name))


def setup_logging(level: str = "info", stream=None) -> None:
    """Install the logfmt handler on the agent root logger at `level`
    (--log-level). Idempotent; replaces a prior agent handler."""
    if level not in LEVELS:
        raise ValueError(f"unknown log level {level!r} "
                         f"(want one of {sorted(LEVELS)})")
    root = logging.getLogger(_ROOT)
    for h in list(root.handlers):
        root.removeHandler(h)
    handler = logging.StreamHandler(stream)
    handler.setFormatter(LogfmtFormatter())
    root.addHandler(handler)
    root.setLevel(LEVELS[level])
    root.propagate = False
