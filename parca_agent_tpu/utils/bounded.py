"""The abandonable bounded call: one shared guard for wedge-able work.

A wedged device runtime (or a fleet peer lost mid-collective) blocks
inside a C call no exception ever leaves and no thread can cancel; the
only containment is to run the call where it can be ABANDONED. Used by
the profiler's device watchdog and inline-encode deadline
(profiler/cpu.py), the bounded fleet join, and the fleet collective
guard (parallel/distributed.py) — one implementation, so the subtle
parts (BaseException capture, the done-event ordering that lets callers
gate on "the abandoned call may still be executing") stay in sync.
"""

from __future__ import annotations

import threading


def bounded_call(thunk, timeout_s: float, thread_name: str = "bounded-call"):
    """Run ``thunk`` on an abandonable daemon thread, bounded by
    ``timeout_s``. A daemon thread, NOT a ThreadPoolExecutor: pool
    workers are non-daemon and joined at interpreter exit, so one wedged
    call would block process shutdown forever.

    Returns ``(status, value, done, box)``:

      * ``("ok", result, ...)`` — the call returned in time;
      * ``("err", exception, ...)`` — it raised in time;
      * ``("hang", None, done, box)`` — it blew the deadline and was
        abandoned. It may STILL be executing: ``done`` (a
        threading.Event) fires when it finally returns, and ``box`` then
        holds ``"out"`` or ``"err"`` — callers that share state with the
        thunk must gate on ``done`` before touching it again, and should
        inspect ``box`` for a late error instead of discarding it.

    The box is filled BEFORE the event fires, so ``done.is_set()``
    guarantees the box is complete.
    """
    box: dict = {}
    done = threading.Event()

    def call():
        try:
            box["out"] = thunk()
        except BaseException as e:  # noqa: BLE001 - surfaced to the caller
            box["err"] = e
        finally:
            done.set()

    threading.Thread(target=call, name=thread_name, daemon=True).start()
    if done.wait(timeout_s):
        if "err" in box:
            return "err", box["err"], done, box
        return "ok", box["out"], done, box
    return "hang", None, done, box
