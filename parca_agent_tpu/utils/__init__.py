"""Shared small utilities (fs injection, file hashing, caches)."""
