"""Deterministic fault injection for the ship path.

The always-on agent's hard scenarios — hours-long store outages, cert
rotations, disk-full spool directories, partial actor death — cannot be
waited for; they have to be injected. This module is the single chaos
layer the ship-path components consult at NAMED SITES:

    grpc.write_raw    the WriteRaw RPC (unavailable / handshake / latency)
    grpc.handshake    channel construction (TLS handshake class)
    spool.write       spill-segment write (disk_full)
    writer.write      local-store profile write (disk_full)
    batch.flush       one flush attempt of the batch client
    actor.<name>      a supervised actor's loop tick (crash)
    statics.snapshot  warm statics+registry snapshot write
                      (pprof/statics_store.py; disk_full/error — a
                      failed snapshot is counted and skipped, the
                      window it followed is already shipped)
    trace.record      every flight-recorder entry point (runtime/
                      trace.py begin/add_span/complete/observe) — the
                      tracing path is FAIL-OPEN by contract: an injected
                      fault here is swallowed and counted
                      (record_errors) and must never stall or lose a
                      window (docs/observability.md)
    incident.dump     the slow-window incident writer — an injected
                      fault costs the incident file (incidents_failed),
                      never the window
    hotspot.fold      one window's fold into the hotspot rollup store
                      (runtime/hotspots.py) — fail-open like tracing:
                      an injected fault is counted (fold_errors) and
                      costs query freshness, never the window
    sink.emit         one secondary output-backend's per-window emit
                      (sinks/registry.py) — fail-open by contract: an
                      injected fault is counted (the sink's errors
                      stat) and costs that sink one window, never the
                      pprof ship (docs/sinks.md)
    sink.flush        one AutoFDO profdata file's crash-only rewrite
                      (sinks/autofdo.py; disk_full/error — counted
                      flush_errors, the file stays dirty and is
                      retried at the next flush cadence)
    admission.resolve one pid's cgroup -> tenant resolution
                      (runtime/admission.py) — fail-open by contract:
                      an injected fault is counted (resolve_errors)
                      and lands the pid in the "unknown" tenant,
                      never costing a window
    admission.shed    one overload-governor shed step
                      (runtime/admission.py) — fail-open: an injected
                      fault is counted (shed_errors) and costs this
                      window's shed step only; quotas and windows are
                      untouched
    regression.fold   one window's fold into the regression sentinel's
                      rollup groups (runtime/regression.py) — fail-open
                      like the hotspot fold: an injected fault is
                      counted (fold_errors) and costs that window's
                      judgment, never the window or the pprof ship
    regression.baseline
                      the sentinel's baseline persistence (save on the
                      encode worker, adopt at startup) — counted
                      (baseline_save_errors / baseline_adopt_errors)
                      and skipped: the sentinel relearns cold, the
                      agent is unharmed
    feed.coalesce     the host-side (stack, weight) fold of one feed
                      batch (aggregator/dict.py; docs/perf.md "ingest
                      wall") — fail-open by contract: an injected fault
                      is counted (coalesce_fallbacks) and the batch
                      dispatches UNCOALESCED — identical counts and
                      pprof bytes, never a lost feed or window
    feed.carry        the cross-drain carry-cache match of one feed
                      batch (aggregator/dict.py; docs/perf.md "feed
                      endgame") — fail-open by contract: an injected
                      fault is counted (carry_fallbacks) and the
                      aggregator falls back to per-drain dispatch for
                      the REST of the window (mass already carried
                      still flushes at close) — identical counts and
                      pprof bytes, never a lost feed or window
    device.telemetry  every device flight-recorder entry point
                      (runtime/device_telemetry.py record /
                      record_transfer / note_backend / tick_window) —
                      fail-open like trace.record: an injected fault is
                      swallowed and counted (record_errors) and must
                      never cost a window or change a pprof byte
                      (docs/observability.md "device flight recorder")

and, on the ingest side (docs/robustness.md "ingest containment" — the
``poison`` kind raises an InjectedPoison, which IS a PoisonInput, so an
injected fault rides the same per-pid attribution path as real poison):

    elf.read          ElfFile construction over untrusted bytes
    perfmap.parse     reading + parsing a JIT perf map
    maps.parse        parsing /proc/<pid>/maps
    symbolize.kernel  the batched kallsyms resolve
    unwind.build      building one mapping's unwind table

and, on the device-runtime side (docs/robustness.md "device & fleet
health" — the ``hang`` kind is duration-bearing: the site sleeps ``ms``
milliseconds, default one hour, modeling a wedged C call that no
exception ever leaves; the caller's watchdog/deadline machinery is what
must bound it):

    device.probe      one backend bring-up probe (runtime/device_health.py)
    device.dispatch   the guarded device aggregation call (profiler/cpu.py)
    fleet.join        jax.distributed fleet join (parallel/distributed.py)
    fleet.collective  one fleet merge/re-probe collective round

Sites call :func:`inject` which is a no-op until an injector is installed
(via the CLI's --fault-inject flag, the PARCA_FAULTS env var, or a test):
production pays one module-attribute read per site.

Determinism: every probabilistic draw comes from one seeded
``random.Random`` and every time window from one injectable clock, so a
fixed seed + deterministic call order reproduces the same fault schedule
— the chaos suite and the bench soak phase both rely on this.

Rule spec grammar (CLI/env), semicolon-separated::

    site:kind[:k=v[,k=v...]]

    kinds:  unavailable | handshake | error | latency | disk_full | crash
            | poison | hang
    keys:   p=<prob 0..1>   firing probability (default 1)
            after=<s>       rule arms this many seconds after install
            for=<s>         rule disarms this many seconds after arming
            count=<n>       max total firings
            ms=<millis>     latency/hang kinds: injected delay (hang
                            defaults to 3600000 — "forever" at any
                            realistic watchdog deadline)

Example — a scripted 60 s store outage five seconds in, plus a flaky
spool disk::

    grpc.write_raw:unavailable:after=5,for=60;spool.write:disk_full:p=0.2
"""

from __future__ import annotations

import dataclasses
import errno
import random
import threading
import time

from parca_agent_tpu.utils.log import get_logger
from parca_agent_tpu.utils.poison import PoisonInput

_log = get_logger("faults")


# The machine-readable site registry: the contract between the inject()
# call sites, the chaos-marked tests, and palint's chaos-site checker
# (tools/lint/chaos_sites.py), which enforces that the three agree —
# every call site documented here, every entry injected somewhere, and
# every entry exercised by at least one test under the `chaos` marker.
# The docstring above narrates the same list; THIS is the source of
# truth a checker can read. Wildcard entries ("actor.*") match by
# prefix, mirroring FaultRule.matches.
SITES = {
    "grpc.write_raw": "the WriteRaw RPC (agent/grpc_client.py)",
    "grpc.handshake": "channel construction (agent/grpc_client.py)",
    "spool.write": "spill-segment write (agent/spool.py)",
    "writer.write": "local-store profile write (agent/writer.py)",
    "batch.flush": "one flush attempt (agent/batch.py)",
    "actor.*": "a supervised actor's loop tick (runtime/supervisor.py)",
    "statics.snapshot": "warm statics snapshot (pprof/statics_store.py)",
    "trace.record": "flight-recorder entry points (runtime/trace.py)",
    "incident.dump": "slow-window incident writer (runtime/trace.py)",
    "hotspot.fold": "hotspot rollup fold (runtime/hotspots.py)",
    "sink.emit": "secondary output-backend emit (sinks/registry.py)",
    "sink.flush": "AutoFDO profdata crash-only rewrite (sinks/autofdo.py)",
    "admission.resolve": "pid -> tenant resolution (runtime/admission.py)",
    "admission.shed": "overload-governor shed step (runtime/admission.py)",
    "regression.fold": "regression sentinel fold (runtime/regression.py)",
    "regression.baseline":
        "sentinel baseline save/adopt (runtime/regression.py)",
    "feed.coalesce": "feed-batch (stack, weight) fold (aggregator/dict.py)",
    "feed.carry": "cross-drain carry-cache match (aggregator/dict.py)",
    "elf.read": "ElfFile construction (elf/reader.py)",
    "perfmap.parse": "JIT perf-map read+parse (symbolize/perfmap.py)",
    "maps.parse": "/proc/<pid>/maps parse (process/maps.py)",
    "symbolize.kernel": "batched kallsyms resolve (symbolize/ksym.py)",
    "unwind.build": "one mapping's unwind table (unwind/table.py)",
    "device.probe": "backend bring-up probe (runtime/device_health.py)",
    "device.dispatch": "guarded device aggregation (profiler/cpu.py)",
    "fleet.join": "jax.distributed fleet join (parallel/distributed.py)",
    "fleet.collective": "one fleet merge/re-probe collective round",
    "device.telemetry":
        "device flight-recorder entry points (runtime/device_telemetry.py)",
    "process.identity":
        "per-window pid generation check (process/identity.py)",
    "zoo.scenario":
        "one zoo scenario window build (bench_zoo/scenarios.py)",
    "zoo.path":
        "one zoo streaming-arm feed step (bench_zoo/runner.py) — "
        "fail-open: an injected fault is counted (path_fallbacks) and "
        "the window ships via the one-shot close path instead, same "
        "mass, never a lost window",
    "soak.tick":
        "one soak-loop accounting sample (bench_zoo/soak.py) — "
        "fail-open: an injected fault is counted (tick_errors) and "
        "costs that window's RSS/byte sample only, never the window "
        "or the verdict arithmetic",
}


class InjectedFault(Exception):
    """Base class for every injected failure (tests filter on it)."""


class InjectedPoison(InjectedFault, PoisonInput):
    """An injected malformed-input fault: both an InjectedFault (the
    chaos suite filters on it) and a PoisonInput (the ingest containment
    layer attributes it to a pid like real poison)."""

    def __init__(self, site: str):
        self.site = site
        super().__init__(f"injected poison input at {site}")


class InjectedCrash(InjectedFault):
    """An actor-crash fault: escapes the actor's loop so the supervisor
    sees a real thread death."""


class InjectedRpcError(InjectedFault):
    """Mimics a grpc RpcError closely enough for GRPCStoreClient's
    failure classifier: code() returns the real StatusCode.UNAVAILABLE
    when grpc is importable, and the detail string carries the handshake
    markers for handshake-class rules."""

    def __init__(self, kind: str, site: str):
        self.kind = kind
        detail = (f"injected fault at {site}: Ssl handshake failed"
                  if kind == "handshake"
                  else f"injected fault at {site}: connection refused")
        super().__init__(detail)
        self._detail = detail

    def code(self):
        try:
            import grpc

            return grpc.StatusCode.UNAVAILABLE
        except ImportError:  # pragma: no cover - grpc is in the image
            return "UNAVAILABLE"

    def details(self) -> str:
        return self._detail

    def debug_error_string(self) -> str:
        return self._detail


def injected_disk_full(site: str) -> OSError:
    return OSError(errno.ENOSPC,
                   f"injected fault at {site}: no space left on device")


@dataclasses.dataclass
class FaultRule:
    site: str              # exact name, or prefix wildcard "actor.*"
    kind: str              # unavailable|handshake|error|latency|disk_full|crash
    p: float = 1.0
    after_s: float = 0.0
    for_s: float | None = None
    count: int | None = None
    latency_s: float = 0.0
    fired: int = 0

    def matches(self, site: str) -> bool:
        if self.site.endswith("*"):
            return site.startswith(self.site[:-1])
        return site == self.site


_KINDS = ("unavailable", "handshake", "error", "latency", "disk_full",
          "crash", "poison", "hang")

# A hang with no explicit ms= is "forever" relative to any watchdog.
_HANG_DEFAULT_S = 3600.0


def parse_rules(spec: str) -> list[FaultRule]:
    rules = []
    for part in filter(None, (s.strip() for s in spec.split(";"))):
        fields = part.split(":", 2)
        if len(fields) < 2:
            raise ValueError(f"bad fault rule {part!r} (want site:kind)")
        site, kind = fields[0], fields[1]
        if kind not in _KINDS:
            raise ValueError(f"unknown fault kind {kind!r} "
                             f"(want one of {_KINDS})")
        rule = FaultRule(site=site, kind=kind)
        for kv in filter(None, (fields[2].split(",")
                                if len(fields) == 3 else ())):
            k, _, v = kv.partition("=")
            if k == "p":
                rule.p = float(v)
            elif k == "after":
                rule.after_s = float(v)
            elif k == "for":
                rule.for_s = float(v)
            elif k == "count":
                rule.count = int(v)
            elif k == "ms":
                rule.latency_s = float(v) / 1e3
            else:
                raise ValueError(f"unknown fault rule key {k!r} in {part!r}")
        if rule.kind == "hang" and rule.latency_s == 0.0:
            rule.latency_s = _HANG_DEFAULT_S
        rules.append(rule)
    return rules


class FaultInjector:
    def __init__(self, rules: list[FaultRule], seed: int = 0,
                 clock=time.monotonic, sleep=time.sleep):
        self._rules = list(rules)
        self._rng = random.Random(seed)
        self._clock = clock
        self._sleep = sleep
        self._t0 = clock()
        self._lock = threading.Lock()
        self.fired: dict[str, int] = {}

    @classmethod
    def from_spec(cls, spec: str, seed: int = 0, clock=time.monotonic,
                  sleep=time.sleep) -> "FaultInjector":
        return cls(parse_rules(spec), seed=seed, clock=clock, sleep=sleep)

    def _armed(self, rule: FaultRule, now_s: float) -> bool:
        if now_s < rule.after_s:
            return False
        if rule.for_s is not None and now_s >= rule.after_s + rule.for_s:
            return False
        if rule.count is not None and rule.fired >= rule.count:
            return False
        return True

    def check(self, site: str) -> None:
        """Apply every matching armed rule: latency/hang rules sleep,
        error rules raise (first match wins for raises). Thread-safe;
        draws are serialized so a fixed seed stays reproducible."""
        delay = 0.0
        raise_rule: FaultRule | None = None
        with self._lock:
            now_s = self._clock() - self._t0
            for rule in self._rules:
                if not rule.matches(site) or not self._armed(rule, now_s):
                    continue
                if rule.p < 1.0 and self._rng.random() >= rule.p:
                    continue
                rule.fired += 1
                self.fired[site] = self.fired.get(site, 0) + 1
                if rule.kind in ("latency", "hang"):
                    delay += rule.latency_s
                elif raise_rule is None:
                    raise_rule = rule
        if delay:
            self._sleep(delay)
        if raise_rule is None:
            return
        kind = raise_rule.kind
        _log.debug("injecting fault", site=site, kind=kind)
        if kind in ("unavailable", "handshake"):
            raise InjectedRpcError(kind, site)
        if kind == "disk_full":
            raise injected_disk_full(site)
        if kind == "crash":
            raise InjectedCrash(f"injected crash at {site}")
        if kind == "poison":
            raise InjectedPoison(site)
        raise InjectedFault(f"injected fault at {site}")

    def stats(self) -> dict[str, int]:
        with self._lock:
            return dict(self.fired)


# -- process-global installation ---------------------------------------------

_active: FaultInjector | None = None


def install(injector: FaultInjector | None) -> None:
    """Install (or with None, remove) the process-wide injector. The CLI
    calls this once at startup; tests install/uninstall around cases."""
    global _active
    _active = injector


def get() -> FaultInjector | None:
    return _active


def inject(site: str) -> None:
    """The site hook: free when no injector is installed."""
    if _active is not None:
        _active.check(site)
