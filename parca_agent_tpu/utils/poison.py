"""The poison-input error taxonomy for the ingest path.

Every byte the agent parses on the ingest side — ELF images, perf maps,
`/proc/<pid>/maps`, kallsyms, `.eh_frame` — is produced by an arbitrary,
untrusted host process. A malformed input must never abort a window's
profile build for every pid on the host (docs/robustness.md, "ingest
containment"): the parsers raise subclasses of :class:`PoisonInput` for
anything attributable to the INPUT (truncation, out-of-bounds offsets,
absurd table sizes), so callers can tell "this pid's inputs are poison"
apart from agent bugs and feed the per-pid error budget
(runtime/quarantine.py) instead of failing the window.

The taxonomy lives in utils (the bottom layer) because both the parsers
(elf/, dwarf/, symbolize/, process/) and the containment layer
(runtime/quarantine.py) need it without importing each other.

Each subclass carries a ``site`` matching the fault-injection site of the
parser that raised it (utils/faults.py), so chaos-injected faults and
real poison flow through the same attribution path.
"""

from __future__ import annotations

import os


class PoisonInput(ValueError):
    """Malformed untrusted input detected by an ingest-side parser.

    ``site`` names the parser (and its fault-injection site); callers
    catch PoisonInput, attribute the fault to the pid whose input was
    being parsed, and degrade that pid instead of dropping the window.
    """

    site = "ingest.parse"


class OversizedInput(PoisonInput):
    """Untrusted input larger than its ingest byte cap. Raised by
    read_bounded BEFORE the input is fully materialized — the cap bounds
    the read itself, not just the parse."""

    def __init__(self, path: str, cap: int, site: str):
        self.site = site
        super().__init__(f"{path} exceeds ingest byte cap ({cap})")


# ELF images the ingest path opens are mapped EXECUTABLE files; real
# production binaries reach several hundred MB (chromium ~0.3 GB,
# bundled single-file runtimes ~0.9 GB observed in the wild), so the cap
# sits well above them. A PROT_EXEC-mapped multi-GB-plus sparse file is
# a resource bomb: reading it whole would OOM the agent before any
# parser cap could fire; past the cap the read stops and the pid is
# charged. Note the bound IS the cap — a file at/under it still costs
# that much transient RSS (it must be parsed to be rejected), so
# memory-capped deployments should lower PARCA_ELF_READ_CAP below their
# container limit.
ELF_READ_CAP = int(os.environ.get("PARCA_ELF_READ_CAP", 2 << 30))


def read_bounded(fs, path: str, cap: int, site: str = "ingest.parse"
                 ) -> bytes:
    """Read at most ``cap`` bytes of an untrusted file; a larger file
    raises OversizedInput (a PoisonInput, chargeable to the owning pid)
    having cost at most cap+1 bytes of memory."""
    with fs.open(path) as f:
        data = f.read(cap + 1)
    if len(data) > cap:
        raise OversizedInput(path, cap, site)
    return data


def poison_sites() -> tuple[str, ...]:
    """The named ingest fault sites (mirrors utils/faults.py docs)."""
    return ("elf.read", "perfmap.parse", "maps.parse",
            "symbolize.kernel", "unwind.build")
