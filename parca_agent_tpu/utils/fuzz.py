"""Seeded mutation-fuzz harness over the ingest parsers.

The containment contract (docs/robustness.md "ingest containment") is
that NO byte sequence an untrusted host process can hand the agent —
through an ELF, a perf map, a maps file, a kallsyms snapshot, or an
.eh_frame section — makes a parser raise anything outside the PoisonInput
taxonomy (utils/poison.py). This harness enforces it the only way that
scales: start from a small valid corpus, apply seeded byte-level
mutations (bit flips, truncations, splices, length-field bombs), feed
every mutant to the parser, and flag any escaping non-PoisonInput
exception.

Deterministic by construction — one ``random.Random(seed)`` drives every
draw — so `make fuzz`, the chaos suite, and the bench ``ingest_poison``
phase all reproduce the same mutant stream bit-for-bit.

Usage:

    from parca_agent_tpu.utils.fuzz import PARSERS, fuzz_parser
    report = fuzz_parser("elf", n=500, seed=42)
    assert not report["escapes"], report["escapes"]
"""

from __future__ import annotations

import random
import struct

from parca_agent_tpu.utils.poison import PoisonInput

# -- corpus -------------------------------------------------------------------


def _sample_elf() -> bytes:
    """A small valid ELF64 with the sections the readers exercise: text,
    GNU build-id note, symtab/strtab, eh_frame."""
    from parca_agent_tpu.elf.reader import (
        ET_DYN,
        PF_R,
        PF_X,
        PT_LOAD,
        SHT_NOTE,
        SHT_SYMTAB,
        Section,
        Segment,
    )
    from parca_agent_tpu.elf.writer import SHT_STRTAB, ElfWriter

    def sec(name, typ, *, flags=0, addr=0, link=0, entsize=0, align=1):
        return Section(name, typ, flags, addr, 0, 0, link, 0, align, entsize)

    w = ElfWriter(ET_DYN, 62)  # EM_X86_64
    text = bytes(range(64)) * 4
    w.add_section(sec(".text", 1, flags=6, addr=0x1000, align=16), text)
    note = struct.pack("<III", 4, 20, 3) + b"GNU\x00" + bytes(20)
    w.add_section(sec(".note.gnu.build-id", SHT_NOTE, align=4), note)
    strtab = b"\x00main\x00hot\x00"
    syms = b"\x00" * 24
    for name_off, value in ((1, 0x1000), (6, 0x1040)):
        syms += struct.pack("<IBBHQQ", name_off, 0x12, 0, 1, value, 0x40)
    w.add_section(sec(".symtab", SHT_SYMTAB, link=2, entsize=24, align=8),
                  syms)
    w.add_section(sec(".strtab", SHT_STRTAB), strtab)
    w.add_section(sec(".eh_frame", 1, flags=2, addr=0x2000, align=8),
                  _sample_eh_frame())
    w.add_segment(Segment(PT_LOAD, PF_R | PF_X, 0, 0x1000, 0x1000,
                          len(text), len(text), 0x1000))
    return w.serialize()


def _sample_eh_frame() -> bytes:
    """One CIE + one FDE, hand-assembled: def_cfa(rsp, 8), RA at CFA-8 —
    the canonical x86_64 prologue row."""

    def entry(body: bytes) -> bytes:
        pad = (-len(body)) % 4
        return struct.pack("<I", len(body) + pad) + body + b"\x00" * pad

    cie_body = (
        struct.pack("<I", 0)      # CIE id
        + b"\x01"                 # version 1
        + b"zR\x00"               # augmentation
        + b"\x01"                 # code_align = 1
        + b"\x78"                 # data_align = -8 (sleb)
        + b"\x10"                 # ra reg = 16
        + b"\x01\x04"             # aug len 1, fde_enc = udata8
        + b"\x0c\x07\x08"         # def_cfa rsp+8
        + b"\x90\x01"             # offset r16 @ cfa-8
    )
    cie = entry(cie_body)
    fde_body = (
        struct.pack("<I", len(cie) + 4)   # back-offset to the CIE
        + struct.pack("<Q", 0x2100)       # pc_begin
        + struct.pack("<Q", 0x40)         # pc_range
        + b"\x00"                         # aug len 0
        + b"\x44"                         # advance_loc 4
        + b"\x0e\x10"                     # def_cfa_offset 16
    )
    return cie + entry(fde_body) + struct.pack("<I", 0)


_PERF_MAP = b"".join(
    b"%x %x jit_method_%d with spaces\n" % (0x7f00_0000_0000 + i * 0x100,
                                            0x80, i)
    for i in range(64)
)

_MAPS = b"".join(
    b"%x-%x r-xp %x fd:01 %d /usr/lib/libfoo%d.so\n"
    % (0x5000_0000 + i * 0x10000, 0x5000_8000 + i * 0x10000,
       0x1000 * i, 100 + i, i)
    for i in range(32)
) + b"7ffc0000-7ffd0000 rw-p 00000000 00:00 0 [stack]\n"

_CGROUP = b"".join(
    b"%d:%s:/kubepods/burstable/pod12345678-dead-beef-0000-%012d/%016x\n"
    % (12 - i, ctrl, i, 0xABC0 + i)
    for i, ctrl in enumerate((b"cpu,cpuacct", b"memory", b"pids",
                              b"blkio", b"devices", b"freezer"))
) + b"0::/system.slice/app-workload.service\n"

_KALLSYMS = b"".join(
    b"%016x %c func_%d\n" % (0xffffffff81000000 + i * 0x40,
                             b"tT"[i % 2], i)
    for i in range(64)
) + b"0000000000000000 b bss_sym\n"


def _drive_elf(data: bytes) -> None:
    from parca_agent_tpu.elf.buildid import build_id
    from parca_agent_tpu.elf.reader import ElfFile

    ef = ElfFile(data)
    ef.segments
    ef.sections
    ef.exec_load_segment()
    ef.notes()
    ef.symbols()
    build_id(ef)


def _drive_eh_frame(data: bytes) -> None:
    from parca_agent_tpu.unwind.table import build_compact_table

    build_compact_table(data, section_addr=0x2000)


def _drive_perfmap(data: bytes) -> None:
    from parca_agent_tpu.symbolize.perfmap import parse_perf_map

    parse_perf_map(data)


def _drive_maps(data: bytes) -> None:
    from parca_agent_tpu.process.maps import parse_proc_maps

    parse_proc_maps(data)


def _drive_kallsyms(data: bytes) -> None:
    from parca_agent_tpu.symbolize.ksym import parse_kallsyms

    parse_kallsyms(data)


def _drive_cgroup(data: bytes) -> None:
    from parca_agent_tpu.metadata.providers import parse_cgroup_path
    from parca_agent_tpu.runtime.admission import tenant_from_cgroup

    tenant_from_cgroup(parse_cgroup_path(data))


# parser name -> (corpus thunk, driver). Thunks, not bytes: the ELF
# corpus needs the writer, and import-time work here would tax every
# agent start for a test-only path.
PARSERS: dict = {
    "elf": (_sample_elf, _drive_elf),
    "eh_frame": (_sample_eh_frame, _drive_eh_frame),
    "perfmap": (lambda: _PERF_MAP, _drive_perfmap),
    "maps": (lambda: _MAPS, _drive_maps),
    "kallsyms": (lambda: _KALLSYMS, _drive_kallsyms),
    "cgroup": (lambda: _CGROUP, _drive_cgroup),
}


# -- mutation engine ----------------------------------------------------------


def mutate(rng: random.Random, data: bytes) -> bytes:
    """1-4 seeded byte-level mutations; always returns a new buffer."""
    buf = bytearray(data)
    for _ in range(rng.randint(1, 4)):
        if not buf:
            buf = bytearray(rng.randbytes(rng.randint(1, 64)))
            continue
        op = rng.randrange(7)
        i = rng.randrange(len(buf))
        if op == 0:        # bit flip
            buf[i] ^= 1 << rng.randrange(8)
        elif op == 1:      # byte overwrite
            buf[i] = rng.randrange(256)
        elif op == 2:      # truncate
            del buf[i:]
        elif op == 3:      # delete a slice
            del buf[i: i + rng.randint(1, 32)]
        elif op == 4:      # duplicate a slice in place
            chunk = bytes(buf[i: i + rng.randint(1, 32)])
            buf[i:i] = chunk
        elif op == 5:      # insert random bytes
            buf[i:i] = rng.randbytes(rng.randint(1, 32))
        else:              # length-field bomb: saturate 4 or 8 bytes
            width = rng.choice((4, 8))
            buf[i: i + width] = b"\xff" * width
    return bytes(buf)


def fuzz_parser(name: str, n: int = 500, seed: int = 42) -> dict:
    """Run ``n`` seeded mutants of ``name``'s corpus through its driver.

    Returns ``{"parser", "mutations", "benign", "contained", "escapes"}``
    where escapes lists (repr'd, capped) every exception OUTSIDE the
    PoisonInput taxonomy — the containment bar is ``escapes == []``.
    """
    corpus_thunk, driver = PARSERS[name]
    corpus = corpus_thunk()
    driver(corpus)  # the unmutated corpus must parse cleanly
    rng = random.Random(seed)
    benign = contained = 0
    escapes: list[str] = []
    for i in range(n):
        data = mutate(rng, corpus)
        try:
            driver(data)
            benign += 1
        except PoisonInput:
            contained += 1
        except Exception as e:  # noqa: BLE001 - the escape being hunted
            if len(escapes) < 20:
                escapes.append(f"mutant {i}: {e!r}")
    return {"parser": name, "mutations": n, "benign": benign,
            "contained": contained, "escapes": escapes}


def fuzz_all(n: int = 500, seed: int = 42) -> dict:
    """Every registered parser; the bench ingest_poison phase reports
    this dict, the chaos suite asserts each escapes list is empty."""
    return {name: fuzz_parser(name, n=n, seed=seed) for name in PARSERS}
