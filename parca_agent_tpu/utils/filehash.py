"""Cheap content hashing for cache invalidation.

The role minio/highwayhash plays in the reference (pkg/hash/hash.go:36-58:
hash a file to detect change without parsing it). blake2b is in-stdlib,
keyed, and fast enough for the few-MB files involved (kallsyms, perf maps,
/proc/PID/maps).
"""

from __future__ import annotations

import hashlib

from parca_agent_tpu.utils.vfs import VFS

_KEY = b"parca-agent-tpu-filehash"


def hash_bytes(data: bytes) -> int:
    h = hashlib.blake2b(data, key=_KEY, digest_size=8)
    return int.from_bytes(h.digest(), "little")


def hash_file(fs: VFS, path: str) -> int:
    return hash_bytes(fs.read_bytes(path))
