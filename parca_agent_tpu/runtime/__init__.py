"""Actor runtime: supervised run-groups for the always-on agent."""
