"""Actor runtime: supervised run-groups and per-pid ingest quarantine
for the always-on agent."""
