"""Fleet-global hotspot rollups: mergeable window summaries + top-K query.

The first READ path in the agent. Every other subsystem moves profiles
toward the store; this one answers questions locally: "the top-K hottest
stacks matching this label selector, over this time range, node-local or
fleet-wide" — served at dashboard rates out of pre-merged rollups, never
by touching the capture/close hot path (Atys, PAPERS.md arxiv 2506.15523:
hotspot identification across a large fleet needs hierarchical
aggregation of compact summaries, not raw profile shipping).

The unit is a :class:`WindowSummary`: a count-min sketch over the whole
window's (stack-hash, count) stream (ops/sketch.py — the `ab_sketch`
bench phase holds its error envelope at mean rel. err ~0.002) plus an
exact top-candidates table keyed by the 64-bit content hash
(h1 << 32 | h2, the same identity the fleet merge dedups on), each entry
carrying enough frame/label context to render a human-readable answer.
Summaries are MERGEABLE: count-min merges elementwise (+), candidate
tables merge by key with count addition and prune back to the candidate
bound. That makes the whole hierarchy one operation applied at different
granularities:

  per-window  ->  1-minute buckets  ->  1-hour buckets      (node-local)
  fleet round ->  1-minute buckets  ->  1-hour buckets      (fleet scope)

Each level is a byte-capped ring with oldest-eviction, so an always-on
agent answers multi-hour queries in bounded memory.

Where the work runs: :meth:`HotspotStore.fold_from_aggregator` is called
by the encode pipeline's WORKER thread after each shipped window (the
same clock and thread as the statics snapshot hook) — the capture/close
thread contributes zero cycles. Queries run on HTTP server threads
against sealed summaries under one lock.

Accuracy contract (docs/hotspots.md): candidate-table counts are EXACT
for mass observed while the stack was inside the candidate bound; a
summary's ``cut`` is an upper bound on the count any stack absent from
its table can have, so an answer is exact when cut == 0 and otherwise a
lower bound with the count-min estimate as the matching upper bound.

Fleet scope rides the timeout-bounded, degrade-safe FleetWindowMerger
collectives (parallel/distributed.py): every successful merge round
hands the fleet-deduped (h1, h2, count) stream to
:meth:`fleet_fold`; on CollectiveTimeout the merger notifies
:meth:`fleet_degraded` and queries serve node-local answers flagged
stale — the window loop never blocks on a hung peer.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time

import numpy as np

from parca_agent_tpu.ops.sketch import CountMinSpec, cm_add, cm_query
from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.log import get_logger

_log = get_logger("hotspots")

# Entry slots: [count, pid, frames, labels] (a list so merges mutate the
# count in place; context slots are frozen at first sight of the key).
_COUNT, _PID, _FRAMES, _LABELS = range(4)


@dataclasses.dataclass(frozen=True)
class HotspotSpec:
    """Sizing of one summary: K answers served, candidate entries kept
    (the exactness headroom above K), the count-min backstop, and how
    many frames of context each candidate carries."""

    k: int = 50
    candidates: int = 512
    cm: CountMinSpec = CountMinSpec(depth=4, width=1 << 12)
    frames: int = 8

    def __post_init__(self):
        if self.k < 1:
            raise ValueError("k must be >= 1")
        if self.candidates < self.k:
            raise ValueError("candidates must be >= k")


class WindowSummary:
    """One mergeable hotspot summary (a window, a rollup bucket, or a
    fleet round)."""

    __slots__ = ("t0_ns", "t1_ns", "total", "windows", "nodes", "cm",
                 "entries", "cut")

    def __init__(self, spec: HotspotSpec, t0_ns: int = 0, t1_ns: int = 0):
        self.t0_ns = t0_ns
        self.t1_ns = t1_ns
        self.total = 0
        self.windows = 0
        self.nodes = 1
        self.cm = np.zeros((spec.cm.depth, spec.cm.width), np.int64)
        self.entries: dict[int, list] = {}
        self.cut = 0

    @classmethod
    def build(cls, h1, h2, counts, ctx_for, spec: HotspotSpec,
              time_ns: int, duration_ns: int, nodes: int = 1
              ) -> "WindowSummary":
        """Summarize one (hash, count) stream. ``ctx_for(i)`` returns
        (pid, frames, labels) for stream row i — called only for the
        candidate rows, so context rendering is bounded by the spec, not
        the stream."""
        h1 = np.asarray(h1, np.uint32)
        h2 = np.asarray(h2, np.uint32)
        counts = np.asarray(counts, np.int64)
        s = cls(spec, t0_ns=int(time_ns), t1_ns=int(time_ns + duration_ns))
        s.total = int(counts.sum())
        s.windows = 1
        s.nodes = nodes
        cm_add(s.cm, h1, counts, spec.cm)
        n = len(counts)
        if n > spec.candidates:
            part = np.argpartition(counts, n - spec.candidates)
            keep = part[n - spec.candidates:]
            s.cut = int(counts[part[: n - spec.candidates]].max(initial=0))
        else:
            keep = np.arange(n)
        key64 = ((h1[keep].astype(np.uint64) << np.uint64(32))
                 | h2[keep].astype(np.uint64))
        for j, i in enumerate(keep.tolist()):
            k = int(key64[j])
            e = s.entries.get(k)
            if e is None:
                pid, frames, labels = ctx_for(i)
                s.entries[k] = [int(counts[i]), pid, frames, labels]
            else:
                # 64-bit hash collision inside one stream: merge, the
                # same way the exact fleet merge would.
                e[_COUNT] += int(counts[i])
        return s

    def merge_in(self, other: "WindowSummary",
                 spec: HotspotSpec) -> None:
        """Fold ``other`` into this summary (linear: associative and
        commutative up to candidate pruning)."""
        if self.windows == 0:
            self.t0_ns, self.t1_ns = other.t0_ns, other.t1_ns
        else:
            self.t0_ns = min(self.t0_ns, other.t0_ns)
            self.t1_ns = max(self.t1_ns, other.t1_ns)
        self.cm += other.cm
        self.total += other.total
        self.windows += other.windows
        self.nodes = max(self.nodes, other.nodes)
        self.cut += other.cut
        mine = self.entries
        for k, e in other.entries.items():
            got = mine.get(k)
            if got is None:
                mine[k] = list(e)
            else:
                got[_COUNT] += e[_COUNT]
                if got[_FRAMES] is None and e[_FRAMES] is not None:
                    got[_PID], got[_FRAMES], got[_LABELS] = e[1:]
        if len(mine) > spec.candidates:
            drop = sorted(mine.items(), key=lambda kv: kv[1][_COUNT])
            dropped_max = 0
            for k, e in drop[: len(mine) - spec.candidates]:
                dropped_max = max(dropped_max, e[_COUNT])
                del mine[k]
            # A dropped key's true mass <= its merged count plus what the
            # children's own cuts already hid from it.
            self.cut += dropped_max

    def nbytes(self) -> int:
        """Footprint estimate for the byte-capped rings: the sketch is
        exact; entries are approximated per slot (key + count + context
        strings)."""
        n = self.cm.nbytes
        for e in self.entries.values():
            n += 80
            if e[_FRAMES]:
                n += sum(len(f) for f in e[_FRAMES])
            if e[_LABELS]:
                n += sum(len(k) + len(v) for k, v in e[_LABELS].items())
        return n

    def overlaps(self, t0_ns: int, t1_ns: int) -> bool:
        return self.t1_ns > t0_ns and self.t0_ns < t1_ns


class _Level:
    """One rollup granularity: an open accumulating bucket (span-aligned)
    plus a byte-capped ring of sealed summaries, oldest evicted first.
    span_s None = the per-window level (no bucketing: every fold seals
    immediately)."""

    def __init__(self, name: str, span_s: float | None, max_bytes: int,
                 spec: HotspotSpec):
        self.name = name
        self.span_s = span_s
        self.max_bytes = max_bytes
        self._spec = spec
        self.ring: collections.deque[tuple[WindowSummary, int]] \
            = collections.deque()
        self.bytes = 0
        self.evictions = 0
        self.open: WindowSummary | None = None
        self._open_until_ns = 0

    def _append(self, s: WindowSummary) -> None:
        nb = s.nbytes()
        self.ring.append((s, nb))
        self.bytes += nb
        while self.bytes > self.max_bytes and len(self.ring) > 1:
            _, old_nb = self.ring.popleft()
            self.bytes -= old_nb
            self.evictions += 1

    def add(self, s: WindowSummary) -> WindowSummary | None:
        """Fold one summary in; returns a SEALED bucket when this fold
        closed one (the caller promotes it to the next level)."""
        if self.span_s is None:
            self._append(s)
            return s
        span_ns = int(self.span_s * 1e9)
        sealed = None
        if self.open is not None and s.t0_ns >= self._open_until_ns:
            sealed = self.open
            self._append(sealed)
            self.open = None
        if self.open is None:
            self.open = WindowSummary(self._spec)
            self._open_until_ns = (s.t0_ns // span_ns + 1) * span_ns
        self.open.merge_in(s, self._spec)
        return sealed

    def overlapping(self, t0_ns: int, t1_ns: int) -> list[WindowSummary]:
        out = [s for s, _ in self.ring if s.overlaps(t0_ns, t1_ns)]
        if self.open is not None and self.open.windows \
                and self.open.overlaps(t0_ns, t1_ns):
            out.append(self.open)
        return out

    def span(self) -> tuple[int, int] | None:
        """(t0_ns, t1_ns) of the data this level still holds."""
        lo = hi = None
        if self.ring:
            lo, hi = self.ring[0][0].t0_ns, self.ring[-1][0].t1_ns
        if self.open is not None and self.open.windows:
            lo = self.open.t0_ns if lo is None else min(lo, self.open.t0_ns)
            hi = self.open.t1_ns if hi is None else max(hi, self.open.t1_ns)
        return None if lo is None else (lo, hi)


class RegistryView:
    """Rotation-consistent snapshot of the per-id mirrors a fold reads
    (`_loc_off`/`_loc_flat`/`_id_pid`/`_id_h1`/`_id_h2`/`_pids`),
    captured on the PROFILER thread at window hand-off — the same thread
    that runs cold-stack rotation, so capture and rotation can never
    interleave. Rotation REPLACES these arrays with compacted copies
    (it never mutates the old ones in place), so references captured
    before the next window's first feed stay internally consistent for
    the whole fold, no matter when the encode worker gets to it;
    in-place appends only ever land beyond the published watermark the
    prepared ids were read under. Duck-types the aggregator surface
    ``fold_from_aggregator`` and ``render_frames`` consume."""

    __slots__ = ("_loc_off", "_loc_flat", "_id_pid", "_id_h1", "_id_h2",
                 "_pids", "registry_epoch", "_published")

    def __init__(self, agg):
        self._loc_off = agg._loc_off
        self._loc_flat = agg._loc_flat
        self._id_pid = agg._id_pid
        self._id_h1 = agg._id_h1
        self._id_h2 = agg._id_h2
        self._pids = agg._pids
        self.registry_epoch = getattr(agg, "registry_epoch", 0)
        self._published = getattr(agg, "_published", 0)

    def id_hashes(self, n: int | None = None):
        if n is None:
            n = self._published
        return self._id_h1[:n], self._id_h2[:n]


def render_frames(agg, sid: int, max_frames: int) -> tuple:
    """Human-readable frame context for one stack id, straight from the
    aggregator's per-pid location registry (append-only; reads are safe
    for ids below the published watermark — the window encoder's
    concurrent-reader contract). Frames render as mapping+offset (the
    agent ships unsymbolized, like the reference — function names are
    the server's job; mapping-relative addresses are what its symbolizer
    consumes and what a human can at least attribute to a binary)."""
    lo = int(agg._loc_off[sid])
    hi = int(agg._loc_off[sid + 1])
    loc_ids = agg._loc_flat[lo:hi][:max_frames]
    pid = int(agg._id_pid[sid])
    reg = agg._pids.get(pid)
    frames = []
    for lid in loc_ids.tolist():
        i = int(lid) - 1
        if reg is None or not (0 <= i < len(reg.loc_address)):
            frames.append("?")
            continue
        addr = int(reg.loc_address[i])
        if reg.loc_is_kernel[i]:
            frames.append(f"[kernel] 0x{addr:x}")
            continue
        mid = int(reg.loc_mapping_id[i])
        if 1 <= mid <= len(reg.mappings):
            m = reg.mappings[mid - 1]
            name = m.path or m.build_id or "?"
            frames.append(f"{name}+0x{int(reg.loc_normalized[i]):x}")
        else:
            frames.append(f"0x{addr:x}")
    return tuple(frames)


class HotspotStore:
    """Bounded-memory hierarchical hotspot rollups + the query engine.

    Thread model: fold_from_aggregator runs on the encode pipeline's
    worker; fleet_fold/fleet_degraded on the fleet merge actor; query/
    metrics/snapshot on HTTP threads. One lock guards the level rings
    and counters; summary CONSTRUCTION (sketch build, frame rendering)
    runs outside it.
    """

    def __init__(self, spec: HotspotSpec = HotspotSpec(),
                 window_s: float = 10.0,
                 rollup_spans_s: tuple = (60.0, 3600.0),
                 level_bytes: int = 32 << 20,
                 stale_after_s: float = 60.0,
                 labels_for=None,
                 context_cap: int = 8192,
                 clock=time.monotonic):
        self.spec = spec
        self.window_s = window_s
        self.stale_after_s = stale_after_s
        # Label resolution for candidate entries; the profiler installs
        # its (lock-guarded) labels manager hook. None = pid-only labels.
        self.labels_for = labels_for
        self._clock = clock
        self._lock = threading.Lock()
        for s in rollup_spans_s:
            # A zero span would ZeroDivisionError every bucket
            # alignment on the encode worker — fail at construction,
            # not per-fold.
            if not (float(s) > 0):
                raise ValueError(f"rollup span must be > 0, got {s!r}")
        names = ["window"] + [_span_name(s) for s in rollup_spans_s]
        spans = [None] + [float(s) for s in rollup_spans_s]
        self._levels = [_Level(n, s, level_bytes, spec)
                        for n, s in zip(names, spans)]
        self._fleet_levels = [_Level(n, s, level_bytes, spec)
                              for n, s in zip(names, spans)]
        # key64 -> (pid, frames, labels): locally-learned context joined
        # onto fleet-merged rows (hashes are all that crosses the wire —
        # Atys-style compact summaries). Bounded LRU.
        self._context: collections.OrderedDict = collections.OrderedDict()
        self._context_cap = context_cap
        # Per-sid rendered frames, valid for one registry epoch.
        self._frames_cache: dict[int, tuple] = {}
        self._frames_epoch = -1
        self.fleet_interval_s: float = window_s
        self._fleet_last_at: float | None = None
        self._fleet_degraded = False
        self.last_fleet_error = ""
        self.stats = {  # guarded-by: _lock
            "windows_folded": 0,
            "fold_errors": 0,
            "last_fold_s": 0.0,
            "fleet_rounds_ok": 0,
            "fleet_rounds_degraded": 0,
            "queries_total": 0,
            "query_errors": 0,
            "context_entries": 0,
        }

    # -- fold paths (worker / fleet-actor threads) ---------------------------

    def fold_from_aggregator(self, agg, idx, vals, time_ns: int,
                             duration_ns: int) -> None:
        """Summarize one shipped window straight from the aggregator's
        published per-id mirrors and fold it into the node-local rollups.
        Encode-pipeline worker thread only (the statics-snapshot hook's
        twin) — and off the profiler thread ``agg`` must be a
        :class:`RegistryView` captured at hand-off, never the live
        aggregator: a cold-stack rotation at the next window's first
        feed compacts the live mirrors under the fold. Errors are
        counted here (``fold_errors``, the exported contract) and
        re-raised for the pipeline to contain — a rollup bug can never
        lose a window."""
        try:
            self._fold_from(agg, idx, vals, time_ns, duration_ns)
        except Exception:
            # Under the lock (palint lock-discipline): the HTTP thread's
            # count_query_error and the fleet actor's degrade counter
            # mutate the same dict concurrently.
            with self._lock:
                self.stats["fold_errors"] += 1
            raise

    def _fold_from(self, agg, idx, vals, time_ns: int,
                   duration_ns: int) -> None:
        t0 = time.perf_counter()
        faults.inject("hotspot.fold")
        epoch = getattr(agg, "registry_epoch", 0)
        if epoch != self._frames_epoch:
            # Rotation remapped the id space: every cached render is
            # keyed by a dead sid.
            self._frames_cache.clear()
            self._frames_epoch = epoch
        idx = np.asarray(idx)
        h1, h2 = agg.id_hashes(int(idx.max()) + 1 if len(idx) else 0)
        label_memo: dict[int, dict | None] = {}

        def ctx_for(i: int):
            sid = int(idx[i])
            frames = self._frames_cache.get(sid)
            if frames is None:
                frames = render_frames(agg, sid, self.spec.frames)
                if len(self._frames_cache) < 4 * self.spec.candidates * 8:
                    self._frames_cache[sid] = frames
            pid = int(agg._id_pid[sid])
            if pid in label_memo:
                labels = label_memo[pid]
            else:
                labels = ({"pid": str(pid)} if self.labels_for is None
                          else self.labels_for(pid))
                label_memo[pid] = labels
            return pid, frames, labels

        s = WindowSummary.build(
            h1[idx], h2[idx], np.asarray(vals, np.int64), ctx_for,
            self.spec, time_ns, duration_ns)
        self.fold(s)
        with self._lock:
            self.stats["last_fold_s"] = time.perf_counter() - t0

    def fold(self, s: WindowSummary) -> None:
        """Fold one node-local window summary into the level hierarchy
        (public so the bench can drive synthetic streams)."""
        with self._lock:
            for k, e in s.entries.items():
                if e[_FRAMES] is not None:
                    self._context[k] = (e[_PID], e[_FRAMES], e[_LABELS])
                    self._context.move_to_end(k)
            while len(self._context) > self._context_cap:
                self._context.popitem(last=False)
            self.stats["context_entries"] = len(self._context)
            self._fold_levels(self._levels, s)
            self.stats["windows_folded"] += 1

    @staticmethod
    def _fold_levels(levels: list[_Level], s: WindowSummary) -> None:
        promote = s
        for lvl in levels:
            sealed = lvl.add(promote)
            if sealed is None:
                break
            promote = sealed

    def fleet_fold(self, h1, h2, counts, time_ns: int | None = None
                   ) -> None:
        """Ingest one successful fleet merge round's deduplicated
        (h1, h2, count) stream (FleetWindowMerger's collective output).
        Context joins back from locally-learned entries; stacks only
        other nodes have seen render as opaque hashes — the wire carries
        sketches and hashes, never frame payloads."""
        counts = np.asarray(counts, np.int64)
        if time_ns is None:
            time_ns = time.time_ns() - int(self.fleet_interval_s * 1e9)
        h1 = np.asarray(h1, np.uint32)
        h2 = np.asarray(h2, np.uint32)
        key64 = ((h1.astype(np.uint64) << np.uint64(32))
                 | h2.astype(np.uint64))

        def ctx_for(i: int):
            k = int(key64[i])
            with self._lock:  # the fold thread mutates the LRU
                got = self._context.get(k)
            if got is not None:
                return got
            return None, (f"stack:0x{k:016x}",), None

        s = WindowSummary.build(
            h1, h2, counts, ctx_for, self.spec, time_ns,
            # Floor the span: a zero-duration summary could never
            # overlap any range (sub-second merge cadences exist only
            # in tests, but the invariant is cheap to keep).
            max(int(self.fleet_interval_s * 1e9), 1))
        with self._lock:
            self._fold_levels(self._fleet_levels, s)
            self.stats["fleet_rounds_ok"] += 1
            self._fleet_last_at = self._clock()
            self._fleet_degraded = False

    def count_query_error(self) -> None:
        """Bad-parameter accounting for the HTTP layer's handler
        threads — same lock discipline as every other stats counter (a
        bare `stats[...] += 1` across ThreadingHTTPServer threads would
        lose increments)."""
        with self._lock:
            self.stats["query_errors"] += 1

    def fleet_degraded(self, error: str = "") -> None:
        """FleetWindowMerger's degrade notification (CollectiveTimeout
        or any collective failure): fleet answers turn stale-flagged
        node-local until a round completes again."""
        with self._lock:
            self.stats["fleet_rounds_degraded"] += 1
            self._fleet_degraded = True
            self.last_fleet_error = error[:200]

    # -- query path (HTTP threads) -------------------------------------------

    def _fleet_stale(self) -> bool:
        if self._fleet_degraded:
            return True
        if self._fleet_last_at is None:
            return True
        return (self._clock() - self._fleet_last_at
                > max(self.stale_after_s, 2 * self.fleet_interval_s))

    def _pick_levels(self, levels, t0_ns, t1_ns):
        """Granularity choice: the coarsest level whose bucket span fits
        the range at least twice (a dashboard asking for 6 h should read
        ~6 hour-buckets, not 2160 windows), falling COARSER first when
        the chosen ring has evicted the range (older data survives
        longest at the top), then finer."""
        range_s = max((t1_ns - t0_ns) / 1e9, 0.0)
        pick = 0
        for i, lvl in enumerate(levels):
            if lvl.span_s is not None and 2 * lvl.span_s <= range_s:
                pick = i
        order = list(range(pick, len(levels))) + \
            list(range(pick - 1, -1, -1))
        for i in order:
            got = levels[i].overlapping(t0_ns, t1_ns)
            if got:
                return levels[i], got
        return levels[pick], []

    def query(self, k: int | None = None, t0_s: float | None = None,
              t1_s: float | None = None, selector: dict | None = None,
              scope: str = "local") -> dict:
        """Top-K hottest stacks matching ``selector`` over [t0_s, t1_s]
        (unix seconds; None = the stored data's own bounds). Always
        answers: fleet scope with no fleet data degrades to node-local,
        flagged. Counts are candidate-exact lower bounds with the
        count-min estimate alongside (equal when ``exact``)."""
        if scope not in ("local", "fleet"):
            raise ValueError("scope must be 'local' or 'fleet'")
        t0 = time.perf_counter()
        with self._lock:
            self.stats["queries_total"] += 1
            k = self.spec.k if k is None else max(1, min(
                int(k), self.spec.candidates))
            fallback = None
            stale = False
            levels = self._levels
            if scope == "fleet":
                stale = self._fleet_stale()
                has_fleet = any(lv.span() for lv in self._fleet_levels)
                if has_fleet:
                    levels = self._fleet_levels
                else:
                    fallback = "local"
                    stale = True
            # Data bounds default the range.
            spans = [sp for sp in (lv.span() for lv in levels) if sp]
            data_lo = min((sp[0] for sp in spans), default=0)
            data_hi = max((sp[1] for sp in spans), default=0)
            t0_ns = int(t0_s * 1e9) if t0_s is not None else data_lo
            t1_ns = int(t1_s * 1e9) if t1_s is not None else data_hi
            if t1_ns < t0_ns:
                raise ValueError("empty time range (t1 < t0)")
            lvl, sums = self._pick_levels(levels, t0_ns, t1_ns)
            merged = WindowSummary(self.spec)
            sealed = []
            for s in sums:
                # Only the OPEN bucket keeps accumulating under later
                # folds, so only it must merge while locked. Sealed
                # summaries are immutable once ringed (folds build fresh
                # ones; promotion only reads them), and they are the
                # bulk of a long range — merging them after release
                # keeps a query burst from stalling the encode worker's
                # fold into backpressure-dropped rollups.
                if s is lvl.open:
                    merged.merge_in(s, self.spec)
                else:
                    sealed.append(s)
        for s in sealed:
            # Eviction may pop these refs from the ring concurrently;
            # the objects themselves never mutate, so the merge stays
            # consistent with the pick-time snapshot.
            merged.merge_in(s, self.spec)
        # Ranking + rendering outside the lock too: `merged` is private.
        want = dict(selector or {})

        def match(e) -> bool:
            if not want:
                return True
            labels = e[_LABELS]
            if labels is None:
                return False
            return all(labels.get(kk) == vv for kk, vv in want.items())

        ranked = sorted(
            ((key, e) for key, e in merged.entries.items() if match(e)),
            key=lambda kv: kv[1][_COUNT], reverse=True)[:k]
        ests = {}
        if ranked:
            keys = np.array([key for key, _ in ranked], np.uint64)
            h1 = (keys >> np.uint64(32)).astype(np.uint32)
            est = cm_query(merged.cm, h1, self.spec.cm)
            ests = {int(key): int(v) for key, v in zip(keys.tolist(),
                                                       est.tolist())}
        covered = sum(
            max(0, min(s.t1_ns, t1_ns) - max(s.t0_ns, t0_ns))
            for s in sums)
        span = max(t1_ns - t0_ns, 1)
        out = {
            "scope": scope,
            "k": k,
            "level": lvl.name,
            "summaries_merged": len(sums),
            "t0_s": round(t0_ns / 1e9, 3),
            "t1_s": round(t1_ns / 1e9, 3),
            "cover": round(min(1.0, covered / span), 4),
            "total_samples": merged.total,
            "windows": merged.windows,
            "unique_tracked": len(merged.entries),
            "cut": merged.cut,
            "exact": merged.cut == 0,
            "stale": stale,
            "query_s": 0.0,
            "entries": [
                {
                    "stack": f"0x{key:016x}",
                    "count": e[_COUNT],
                    "estimate": max(ests.get(key, e[_COUNT]), e[_COUNT]),
                    "exact": merged.cut == 0,
                    "pid": e[_PID],
                    "frames": list(e[_FRAMES] or ()),
                    "labels": e[_LABELS],
                }
                for key, e in ranked
            ],
        }
        if fallback:
            out["fallback"] = fallback
        if scope == "fleet":
            out["degraded"] = self._fleet_degraded
            if self.last_fleet_error:
                out["fleet_error"] = self.last_fleet_error
        out["query_s"] = round(time.perf_counter() - t0, 6)
        return out

    # -- observability -------------------------------------------------------

    def metrics(self) -> dict:
        """Flat gauges for /metrics (web.py renders the grouped
        families)."""
        with self._lock:
            levels = []
            for scope, lvls in (("local", self._levels),
                                ("fleet", self._fleet_levels)):
                for lv in lvls:
                    levels.append({
                        "name": lv.name, "scope": scope,
                        "summaries": len(lv.ring)
                        + (1 if lv.open is not None and lv.open.windows
                           else 0),
                        "bytes": lv.bytes
                        + (lv.open.nbytes()
                           if lv.open is not None and lv.open.windows
                           else 0),
                        "evictions": lv.evictions,
                    })
            out = {
                "levels": levels,
                "stale": self._fleet_stale(),
                **{k: v for k, v in self.stats.items()},
            }
            if self._fleet_last_at is not None:
                out["fleet_age_s"] = round(
                    self._clock() - self._fleet_last_at, 3)
            return out

    def snapshot(self) -> dict:
        """/healthz section. Informational only by contract: rollup
        state never turns readiness red — a degraded fleet or an evicted
        ring means coarser/staler ANSWERS, not an unhealthy agent."""
        m = self.metrics()
        return {
            "windows_folded": m["windows_folded"],
            "fold_errors": m["fold_errors"],
            "levels": {
                f"{lv['scope']}/{lv['name']}": {
                    "summaries": lv["summaries"],
                    "bytes": lv["bytes"],
                    "evictions": lv["evictions"],
                } for lv in m["levels"]
            },
            "fleet": {
                "rounds_ok": m["fleet_rounds_ok"],
                "rounds_degraded": m["fleet_rounds_degraded"],
                "stale": m["stale"],
                "age_s": m.get("fleet_age_s"),
                "last_error": self.last_fleet_error,
            },
        }


def _span_name(span_s: float) -> str:
    span_s = float(span_s)
    if span_s % 3600 == 0:
        return f"{int(span_s // 3600)}h"
    if span_s % 60 == 0:
        return f"{int(span_s // 60)}m"
    return f"{int(span_s)}s"
