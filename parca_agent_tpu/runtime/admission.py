"""Multi-tenant admission control: fair overload shedding on the pid axis.

A whole-machine profiler under fleet traffic faces hundreds of thousands
of short-lived pids (kube pods, CI sandboxes, serverless), and nothing in
PRs 3-5 stopped ONE noisy tenant from evicting everyone else's registry
state or blowing the close-latency budget — the quarantine registry
contains *poisonous* pids, not *greedy* ones. This module is the
fairness twin (docs/robustness.md "multi-tenant admission"; Atys,
arxiv 2506.15523, makes the same per-service-fairness argument one
layer up):

  * :class:`TenantResolver` maps pid -> tenant identity from the
    `/proc/<pid>/cgroup` path (the parse lives in
    metadata/providers.py:parse_cgroup_path, bounded and PoisonInput-
    disciplined like every other /proc reader). Resolution is FAIL-OPEN:
    anything going wrong lands the pid in the "unknown" tenant, counted,
    never costing a window.
  * :class:`AdmissionController` accounts per-tenant sample/pid usage
    against token buckets refilled on the WINDOW clock and, when a
    tenant runs dry, rides its pids down the existing QuarantineRegistry
    degradation ladder (full -> addresses-only -> scalar,
    runtime/quarantine.py) — fidelity is shed, samples NEVER are, and
    in-quota tenants are untouched by construction (their level is
    simply never raised).
  * A global overload governor watches close latency, registry size,
    and encode-pipeline backlog; when the whole agent is over budget
    for `shed_after` consecutive windows it sheds proportionally from
    the HEAVIEST tenants first (largest last-window sample mass, enough
    of them to cover about half the window), one ladder step per shed
    window, and releases the sheds stepwise once the agent has been
    back in budget for `recover_after` windows.
  * :meth:`AdmissionController.shard_of` keys pid -> shard routing for
    the mesh-sharded dict aggregator (aggregator/sharded.py:route_h2)
    by tenant, so one tenant's registry growth concentrates on its home
    shard instead of polluting every sub-table.

Enforcement scope, by write path (the same shape the quarantine ladder
has had since PR 4): on the scalar/symbolized path, ``apply_ladder``
and the symbolizer enforce every rung (addresses-only strip, scalar
collapse). Under ``--fast-encode`` the agent already ships
unsymbolized, addresses-only profiles for EVERY pid by design (the
reference's server-side-symbolization wire contract), so the ladder's
level-1 fidelity is the fast path's baseline and the scalar rung is
not applied there — admission still accounts, routes shards by
tenant, scopes quarantine eviction, drives the governor, and exports
per-tenant state; what it does not do on that path is further reduce
already-addresses-only output. The CLI logs this scope at startup.

Chaos sites (utils/faults.py): ``admission.resolve`` (one pid's tenant
resolution) and ``admission.shed`` (one governor shed step) — both
fail-open by contract: an injected fault is counted and costs at most
tenant attribution ("unknown") or one shed step, never a window.

Thread contract: account_window/tick_window/level_for run on the
profiler thread; metrics/snapshot on the HTTP thread; shard_of on
whatever thread feeds the aggregator. All shared state is behind one
lock.
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time
import zlib

import numpy as np

from parca_agent_tpu.metadata.providers import (
    CGROUP_MAX_BYTES,
    parse_cgroup_path,
)
from parca_agent_tpu.runtime.quarantine import (
    LEVEL_ADDRESSES,
    LEVEL_FULL,
    LEVEL_SCALAR,
)
from parca_agent_tpu.runtime.window_clock import (
    REFERENCE_WINDOW_S,
    check_window_s,
    per_window,
    windows_for,
)
from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.log import get_logger
from parca_agent_tpu.utils.poison import read_bounded
from parca_agent_tpu.utils.vfs import RealFS

_log = get_logger("admission")

# The label key the TenantProvider attaches and the /query + /hotspots
# `tenant=` selector shorthand expands to: ONE identity from cgroup to
# quota to read path (metadata/providers.py keeps the literal in sync).
TENANT_LABEL = "tenant"

# Tenant ids are derived from cgroup paths but travel as metric labels
# and HTTP selector values; the validator is the shared gate.
_TENANT_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._:@/-]{0,127}$")

UNKNOWN_TENANT = "unknown"


def validate_tenant(value: str) -> str:
    """A well-formed tenant selector value, or ValueError (the HTTP
    handlers turn it into a 400)."""
    if not isinstance(value, str) or not _TENANT_RE.match(value):
        raise ValueError(f"malformed tenant value {str(value)[:64]!r}")
    return value


_POD_RE = re.compile(r"pod([0-9a-fA-F][0-9a-fA-F_-]{7,63})")
_CTR_RE = re.compile(r"(?:docker|cri-containerd|crio)[/:-]([0-9a-f]{12,64})")
_USER_RE = re.compile(r"/user\.slice/user-(\d+)\.slice")


def tenant_from_cgroup(path: str | None) -> str:
    """Tenant identity out of a primary cgroup path. Recognized shapes,
    most specific first: kube pod uid, container id, user slice, systemd
    unit, else the first path component; root/empty is "system". The
    result always passes validate_tenant (hostile path bytes collapse
    to the unknown tenant rather than poisoning a metric label)."""
    if not path or path == "/":
        return "system"
    m = _POD_RE.search(path)
    if m:
        tenant = "pod:" + m.group(1).replace("_", "-").lower()
    else:
        m = _CTR_RE.search(path)
        if m:
            tenant = "ctr:" + m.group(1)[:12]
        else:
            m = _USER_RE.search(path)
            if m:
                tenant = "user:" + m.group(1)
            else:
                unit = None
                for comp in path.split("/"):
                    if comp:
                        unit = comp
                        if comp != "system.slice":
                            break
                if unit is None:
                    return "system"
                tenant = ("svc:" + unit if unit.endswith(
                    (".service", ".scope", ".slice")) else "grp:" + unit)
    try:
        return validate_tenant(tenant)
    except ValueError:
        return UNKNOWN_TENANT


class TenantResolver:
    """pid -> tenant, from `/proc/<pid>/cgroup`, LRU-cached and
    fail-open. The cache is bounded (pid churn must not grow it without
    limit) and entries carry a TTL: pid REUSE would otherwise hand a
    recycled pid its dead predecessor's tenant forever (an actively
    profiled pid is a cache hit every window, so pure recency never
    ages it out) — past ``ttl_s`` a hit re-resolves, bounding any
    reuse mis-attribution to one TTL. Sized for a few hundred thousand
    live pids (~100 B/entry); past the cap the oldest entries recycle,
    which with a cyclic 500k+ pid scan degrades to one bounded cgroup
    read per pid per window — correct, observable via
    ``cache_hits_total`` flatlining, and the TTL re-read cost's upper
    bound anyway."""

    _MAX_CACHED = 1 << 18

    def __init__(self, fs=None, ttl_s: float = 300.0,
                 clock=time.monotonic):
        self._fs = fs if fs is not None else RealFS()
        self._ttl = float(ttl_s)
        self._clock = clock
        self._lock = threading.Lock()
        # pid -> (tenant, resolved_at); dict order = recency.
        self._cache: dict[int, tuple[str, float]] = {}  # guarded-by: _lock
        self.stats = {  # guarded-by: _lock
            "resolves_total": 0,
            "cache_hits_total": 0,
            "cache_expired_total": 0,
            "resolve_errors_total": 0,
        }

    def resolve(self, pid: int) -> str:
        pid = int(pid)
        now = self._clock()
        with self._lock:
            got = self._cache.pop(pid, None)
            if got is not None:
                if now - got[1] <= self._ttl:
                    self._cache[pid] = got  # re-insert: recency order
                    self.stats["cache_hits_total"] += 1
                    return got[0]
                self.stats["cache_expired_total"] += 1
        tenant = self._resolve_uncached(pid)
        with self._lock:
            self.stats["resolves_total"] += 1
            if len(self._cache) >= self._MAX_CACHED:
                self._cache.pop(next(iter(self._cache)))  # oldest
            self._cache[pid] = (tenant, now)
        return tenant

    # palint: fail-open
    def _resolve_uncached(self, pid: int) -> str:
        """One bounded cgroup read + parse. Fail-open by contract: a
        missing file (pid exited), poison (row/byte bomb), or an
        injected fault is counted and lands the pid in the unknown
        tenant — admission is a fairness layer, never a window risk."""
        try:
            faults.inject("admission.resolve")
            data = read_bounded(self._fs, f"/proc/{pid}/cgroup",
                                CGROUP_MAX_BYTES, site="admission.resolve")
            return tenant_from_cgroup(parse_cgroup_path(data))
        except Exception as e:  # noqa: BLE001 - counted, fail-open
            with self._lock:
                self.stats["resolve_errors_total"] += 1
            _log.debug("tenant resolution failed; pid joins the unknown "
                       "tenant", pid=pid, error=repr(e)[:120])
            return UNKNOWN_TENANT

    def forget(self, pid: int) -> None:
        with self._lock:
            self._cache.pop(int(pid), None)

    def shard_of(self, pid: int, n_shards: int) -> int:
        """Stable tenant -> shard placement for the sharded aggregator's
        pid routing (aggregator/sharded.py:route_h2): every pid of a
        tenant lands on one home shard, so registry growth parallelizes
        across tenants instead of spraying every sub-table."""
        tenant = self.resolve(pid)
        return zlib.crc32(tenant.encode()) % max(1, int(n_shards))


@dataclasses.dataclass
class OverloadPolicy:
    """Global overload budget for the governor. A signal with a zero
    threshold is disabled; the agent is "over budget" in a window when
    ANY enabled signal exceeds its threshold."""

    close_latency_s: float = 0.0   # window close slower than this
    registry_rows: int = 0         # dict-registry unique stacks above this
    backlog: int = 0               # encode backpressure fallbacks/window
    shed_after: int = 3            # consecutive over-budget windows to shed
    recover_after: int = 6         # consecutive in-budget windows to release

    def enabled(self) -> bool:
        return (self.close_latency_s > 0 or self.registry_rows > 0
                or self.backlog > 0)


@dataclasses.dataclass
class _TenantState:
    tokens_samples: float = 0.0
    tokens_pids: float = 0.0
    level: int = LEVEL_FULL        # quota ladder level
    shed_level: int = LEVEL_FULL   # governor overlay (max of both applies)
    over_windows: int = 0
    clean_windows: int = 0
    idle_windows: int = 0
    samples_window: int = 0        # usage accumulating THIS window
    pids_window: int = 0
    samples_last: int = 0          # previous window (governor ranking)
    pids_last: int = 0
    samples_total: int = 0
    over_quota_windows_total: int = 0


class AdmissionController:
    """Per-tenant token-bucket quotas + the global overload governor.

    Quota semantics, on the window clock (tick_window is called by the
    profiler once per iteration, like the quarantine registry's):

      * each tenant's buckets refill by `quota` per window, capped at
        `burst_windows * quota` (a quiet tenant banks a short burst);
      * a window whose usage drains a bucket below zero is OVER QUOTA:
        after `degrade_after` consecutive over windows the tenant's
        pids ride the ladder at addresses-only, after `escalate_after`
        more at scalar — samples always travel (scalar_profile keeps
        the mass exact), fidelity is what's shed;
      * `recover_windows` consecutive in-quota windows step the level
        back DOWN one rung, so recovery is full -> addresses -> full
        fidelity, mirroring how it was lost.

    In-quota tenants are untouched by construction: nothing in the
    quota path ever raises another tenant's level, and the governor's
    shed order (heaviest first) can only reach a light tenant after
    every heavier one is already shed.
    """

    _MAX_TENANTS = 4096
    _IDLE_FORGET_WINDOWS = 60
    # Bound on the fork-storm seen-pid set; past it the set restarts
    # from the current window (a long-lived fleet cycling through the
    # pid space must not hold every pid ever observed).
    _MAX_SEEN_PIDS = 1 << 20

    def __init__(self, resolver: TenantResolver,
                 quota_samples: int = 0, quota_pids: int = 0,
                 burst_windows: int = 3, degrade_after: int = 2,
                 escalate_after: int = 3, recover_windows: int = 3,
                 overload: OverloadPolicy | None = None,
                 top_n: int = 10, storm_new_pids: int = 0,
                 window_s: float = REFERENCE_WINDOW_S):
        if quota_samples < 0 or quota_pids < 0:
            raise ValueError("tenant quotas must be >= 0")
        self.resolver = resolver
        # Every knob is expressed at the reference 10 s window and
        # converted here (runtime/window_clock.py): quotas are
        # per-window REFILLS (same samples/second at any cadence),
        # window-count knobs are wall-time commitments (same seconds of
        # patience at any cadence). At the reference cadence both
        # conversions are exact identities.
        self._window_s = check_window_s(window_s)
        self._quota_samples = per_window(quota_samples, window_s)
        self._quota_pids = per_window(quota_pids, window_s)
        self._burst = windows_for(burst_windows, window_s)
        self._degrade_after = windows_for(degrade_after, window_s)
        self._escalate_after = windows_for(escalate_after, window_s)
        self._recover = windows_for(recover_windows, window_s)
        self._idle_forget = windows_for(self._IDLE_FORGET_WINDOWS,
                                        window_s)
        self._overload = overload or OverloadPolicy()
        self._shed_after = windows_for(self._overload.shed_after, window_s)
        self._recover_after = windows_for(self._overload.recover_after,
                                          window_s)
        self._top_n = max(1, int(top_n))
        # Fork/exec-storm detection: a window introducing more than
        # `storm_new_pids` never-seen pids (0 = off) degrades via the
        # governor's shed step — discovery cost (maps parses, unwind
        # builds, registry inserts) is per NEW pid, paid before any
        # quota sees a sample.
        self._storm_threshold = per_window(
            max(0.0, float(storm_new_pids)), window_s)
        self._seen_pids: set[int] = set()   # guarded-by: _lock
        self._storm_new_window = 0          # guarded-by: _lock
        self._lock = threading.Lock()
        self._tenants: dict[str, _TenantState] = {}  # guarded-by: _lock
        self._over_streak = 0       # guarded-by: _lock
        self._calm_streak = 0       # guarded-by: _lock
        self._last_backlog = 0      # guarded-by: _lock (cumulative diff)
        self.stats = {  # guarded-by: _lock
            "windows_total": 0,
            "tenants_tracked": 0,
            "tenants_degraded": 0,
            "tenants_evicted_total": 0,
            "over_quota_windows_total": 0,
            "overload_windows_total": 0,
            "shed_steps_total": 0,
            "shed_releases_total": 0,
            "shed_errors_total": 0,
            "samples_degraded_total": 0,
            "account_errors_total": 0,
            "fork_storm_windows_total": 0,
            "fork_storm_sheds_total": 0,
        }

    # -- per-window accounting (profiler thread) -----------------------------

    # palint: fail-open
    def account_window(self, pids, counts) -> None:
        """Charge one window's snapshot usage to its tenants. Fail-open:
        an accounting failure is counted and the window proceeds
        unadmitted — fairness enforcement degrades, profiles never do."""
        try:
            pids = np.asarray(pids, np.int64)
            if len(pids) == 0:
                return
            counts = np.asarray(counts, np.int64)
            upids, inverse = np.unique(pids, return_inverse=True)
            sums = np.bincount(inverse, weights=counts).astype(np.int64)
            per_tenant: dict[str, list[int]] = {}
            for i, pid in enumerate(upids.tolist()):
                agg = per_tenant.setdefault(
                    self.resolver.resolve(pid), [0, 0])
                agg[0] += int(sums[i])
                agg[1] += 1
            with self._lock:
                for tenant, (samples, n_pids) in per_tenant.items():
                    st = self._state_locked(tenant)
                    st.samples_window += samples
                    st.pids_window += n_pids
                    st.samples_total += samples
                    st.idle_windows = 0
                if self._storm_threshold > 0:
                    pid_list = upids.tolist()
                    self._storm_new_window += sum(
                        1 for p in pid_list if p not in self._seen_pids)
                    self._seen_pids.update(pid_list)
                    if len(self._seen_pids) > self._MAX_SEEN_PIDS:
                        self._seen_pids = set(pid_list)
        except Exception as e:  # noqa: BLE001 - counted, fail-open
            with self._lock:
                self.stats["account_errors_total"] += 1
            _log.warn("admission accounting failed for this window",
                      error=repr(e)[:200])

    def _state_locked(self, tenant: str) -> _TenantState:  # palint: holds=_lock
        st = self._tenants.get(tenant)
        if st is None:
            if len(self._tenants) >= self._MAX_TENANTS:
                self._evict_tenant_locked()
            st = _TenantState(
                tokens_samples=float(self._quota_samples * self._burst),
                tokens_pids=float(self._quota_pids * self._burst))
            self._tenants[tenant] = st
        return st

    def _evict_tenant_locked(self) -> None:  # palint: holds=_lock
        """Room at the tenant cap: drop the idlest fully-recovered
        tenant (an over-quota or shed tenant's state is containment
        history and survives, mirroring the quarantine registry's
        eviction discipline)."""
        victim, victim_key = None, None
        for name, st in self._tenants.items():
            if st.level != LEVEL_FULL or st.shed_level != LEVEL_FULL:
                continue
            key = (-st.idle_windows, st.samples_total)
            if victim is None or key < victim_key:
                victim, victim_key = name, key
        if victim is None:  # every tenant degraded: drop the idlest anyway
            for name, st in self._tenants.items():
                if victim is None \
                        or st.idle_windows > victim_key:
                    victim, victim_key = name, st.idle_windows
        del self._tenants[victim]
        self.stats["tenants_evicted_total"] += 1

    # -- the window boundary (profiler thread) -------------------------------

    # palint: fail-open
    def tick_window(self, close_latency_s: float = 0.0,
                    registry_rows: int = 0, backlog: int = 0) -> None:
        """Advance every tenant's bucket + ladder by one window, then run
        the governor over this window's overload signals (`backlog` is
        the encode pipeline's CUMULATIVE backpressure counter; the diff
        is taken here). Fail-open like account_window: a tick failure is
        counted, never raised into the profiler loop."""
        try:
            with self._lock:
                self.stats["windows_total"] += 1
                drop = []
                for tenant, st in self._tenants.items():
                    self._tick_tenant_locked(tenant, st)
                    if st.idle_windows >= self._idle_forget \
                            and st.level == LEVEL_FULL \
                            and st.shed_level == LEVEL_FULL:
                        drop.append(tenant)
                for tenant in drop:
                    del self._tenants[tenant]
                self._govern_locked(close_latency_s, registry_rows,
                                    backlog)
                self._storm_tick_locked()
                self.stats["tenants_tracked"] = len(self._tenants)
                self.stats["tenants_degraded"] = sum(
                    1 for st in self._tenants.values()
                    if max(st.level, st.shed_level) > LEVEL_FULL)
        except Exception as e:  # noqa: BLE001 - counted, fail-open
            with self._lock:
                self.stats["account_errors_total"] += 1
            _log.warn("admission tick failed for this window",
                      error=repr(e)[:200])

    def _tick_tenant_locked(self, tenant: str,
                            st: _TenantState) -> None:  # palint: holds=_lock
        over = False
        if self._quota_samples > 0:
            st.tokens_samples = min(
                st.tokens_samples + self._quota_samples,
                float(self._quota_samples * self._burst))
            st.tokens_samples -= st.samples_window
            if st.tokens_samples < 0:
                over = True
                st.tokens_samples = 0.0  # no debt past the window
        if self._quota_pids > 0:
            st.tokens_pids = min(
                st.tokens_pids + self._quota_pids,
                float(self._quota_pids * self._burst))
            st.tokens_pids -= st.pids_window
            if st.tokens_pids < 0:
                over = True
                st.tokens_pids = 0.0
        if over:
            st.over_windows += 1
            st.clean_windows = 0
            st.over_quota_windows_total += 1
            self.stats["over_quota_windows_total"] += 1
            if st.over_windows >= self._degrade_after + self._escalate_after:
                new = LEVEL_SCALAR
            elif st.over_windows >= self._degrade_after:
                new = LEVEL_ADDRESSES
            else:
                new = st.level
            if new > st.level:
                st.level = new
                _log.warn("tenant over quota; degrading its pids",
                          tenant=tenant, ladder=st.level,
                          over_windows=st.over_windows)
        else:
            st.clean_windows += 1
            st.over_windows = 0
            if st.level > LEVEL_FULL \
                    and st.clean_windows >= self._recover:
                st.level -= 1  # one rung at a time: scalar->addresses->full
                st.clean_windows = 0
                _log.info("tenant back in quota; easing its ladder level",
                          tenant=tenant, ladder=st.level)
        if st.samples_window == 0 and st.pids_window == 0:
            st.idle_windows += 1
        st.samples_last = st.samples_window
        st.pids_last = st.pids_window
        st.samples_window = 0
        st.pids_window = 0

    # -- the global overload governor ----------------------------------------

    def _govern_locked(self, close_latency_s: float, registry_rows: int,
                       backlog: int) -> None:  # palint: holds=_lock
        if not self._overload.enabled():
            return
        backlog_delta = max(0, int(backlog) - self._last_backlog)
        self._last_backlog = int(backlog)
        over = (
            (self._overload.close_latency_s > 0
             and close_latency_s > self._overload.close_latency_s)
            or (self._overload.registry_rows > 0
                and registry_rows > self._overload.registry_rows)
            or (self._overload.backlog > 0
                and backlog_delta >= self._overload.backlog))
        if over:
            self.stats["overload_windows_total"] += 1
            self._over_streak += 1
            self._calm_streak = 0
            if self._over_streak >= self._shed_after:
                self._shed_locked()
        else:
            self._over_streak = 0
            self._calm_streak += 1
            if self._calm_streak >= self._recover_after:
                self._calm_streak = 0
                self._release_locked()

    def _storm_tick_locked(self) -> None:  # palint: holds=_lock
        """Fork/exec-storm admission: when one window introduced more
        never-seen pids than the threshold (container churn, serverless
        cold-start bursts), degrade via the EXISTING governor shed step
        — heaviest tenants ride the ladder one rung, samples still
        travel — instead of letting per-new-pid discovery work blow the
        window. Recovery rides the governor's normal calm-streak
        release; a quiet fleet pays nothing (threshold 0 = off)."""
        if self._storm_threshold <= 0:
            return
        n_new = self._storm_new_window
        self._storm_new_window = 0
        if n_new <= self._storm_threshold:
            return
        self.stats["fork_storm_windows_total"] += 1
        self._shed_locked()
        self.stats["fork_storm_sheds_total"] += 1
        _log.warn("fork storm: shedding one ladder rung",
                  new_pids=n_new, threshold=self._storm_threshold)

    def _shed_locked(self) -> None:  # palint: holds=_lock
        """One shed step: degrade the heaviest SHEDDABLE tenants (by
        last-window sample mass, descending) one ladder rung each,
        taking tenants until ~half the sheddable mass is covered —
        proportional shedding that reaches a light tenant only after
        every heavier one is already at the ladder's floor. Tenants
        already at LEVEL_SCALAR are excluded from both the target and
        the coverage (counting them would make later shed steps no-ops
        once the head of the distribution is fully shed, and starve
        the mid-weight tenants the step exists to reach). Fail-open:
        an injected/real fault here is counted and costs this window's
        shed step, nothing else."""
        try:
            faults.inject("admission.shed")
            ranked = []
            total = 0
            for tenant, st in self._tenants.items():
                if st.shed_level < LEVEL_SCALAR and st.samples_last > 0:
                    ranked.append((tenant, st))
                    total += st.samples_last
            ranked.sort(key=lambda kv: kv[1].samples_last, reverse=True)
            target = total / 2
            covered = 0
            for tenant, st in ranked:
                if covered >= target:
                    break
                covered += st.samples_last
                st.shed_level += 1
                self.stats["shed_steps_total"] += 1
                _log.warn("overload governor shedding tenant",
                          tenant=tenant, shed_level=st.shed_level,
                          window_samples=st.samples_last)
        except Exception as e:  # noqa: BLE001 - counted, fail-open
            self.stats["shed_errors_total"] += 1
            _log.warn("overload shed step failed; skipped",
                      error=repr(e)[:200])

    def _release_locked(self) -> None:  # palint: holds=_lock
        for tenant, st in self._tenants.items():
            if st.shed_level > LEVEL_FULL:
                st.shed_level -= 1
                self.stats["shed_releases_total"] += 1
                _log.info("overload cleared; releasing shed tenant",
                          tenant=tenant, shed_level=st.shed_level)

    # -- queries -------------------------------------------------------------

    def level_for(self, pid: int) -> int:
        """The pid's admission ladder level (max of its tenant's quota
        level and the governor's shed overlay); FULL for anything
        unresolvable — degradation must be a positive decision."""
        try:
            tenant = self.resolver.resolve(pid)
            with self._lock:
                st = self._tenants.get(tenant)
                if st is None:
                    return LEVEL_FULL
                return max(st.level, st.shed_level)
        except Exception:  # noqa: BLE001 - never degrade by accident
            return LEVEL_FULL

    def tenant_level(self, tenant: str) -> int:
        with self._lock:
            st = self._tenants.get(tenant)
            return max(st.level, st.shed_level) if st is not None \
                else LEVEL_FULL

    def count_degraded(self, samples: int) -> None:
        """Sample mass that rode the ladder because of ADMISSION (the
        quarantine registry counts its own); fed by apply_ladder."""
        with self._lock:
            self.stats["samples_degraded_total"] += int(samples)

    def shard_of(self, pid: int, n_shards: int) -> int:
        return self.resolver.shard_of(pid, n_shards)

    # -- observability (HTTP thread) -----------------------------------------

    def metrics(self) -> dict:
        """Bounded-cardinality view for /metrics: the top-N tenants by
        last-window mass, every DEGRADED tenant (the ones an operator is
        debugging), and one "other" rollup for the rest — a 100k-tenant
        host must not emit 100k label sets."""
        with self._lock:
            ranked = sorted(self._tenants.items(),
                            key=lambda kv: kv[1].samples_last,
                            reverse=True)
            rows = []
            other = {"tenant": "other", "samples": 0, "window_samples": 0,
                     "pids": 0, "level": 0, "over_quota": 0, "tenants": 0}
            for i, (tenant, st) in enumerate(ranked):
                lvl = max(st.level, st.shed_level)
                if i < self._top_n or lvl > LEVEL_FULL:
                    rows.append({
                        "tenant": tenant,
                        "samples": st.samples_total,
                        "window_samples": st.samples_last,
                        "pids": st.pids_last,
                        "level": lvl,
                        "over_quota": int(st.over_windows > 0),
                    })
                else:
                    other["samples"] += st.samples_total
                    other["window_samples"] += st.samples_last
                    other["pids"] += st.pids_last
                    other["tenants"] += 1
            if other["tenants"]:
                rows.append(other)
            return {"tenants": rows, "stats": dict(self.stats),
                    "resolver": dict(self.resolver.stats)}

    def snapshot(self, limit: int = 50) -> dict:
        """JSON view for /healthz (bounded like the quarantine one).
        By contract this section NEVER turns readiness red: shedding is
        the agent doing its job under load, not failing at it."""
        with self._lock:
            tenants = {}
            ranked = sorted(self._tenants.items(),
                            key=lambda kv: kv[1].samples_last,
                            reverse=True)
            for tenant, st in ranked[:limit]:
                tenants[tenant] = {
                    "level": max(st.level, st.shed_level),
                    "quota_level": st.level,
                    "shed_level": st.shed_level,
                    "over_windows": st.over_windows,
                    "window_samples": st.samples_last,
                    "window_pids": st.pids_last,
                    "samples_total": st.samples_total,
                }
            return {
                "quota_samples": self._quota_samples,
                "quota_pids": self._quota_pids,
                "over_streak": self._over_streak,
                "tenants": tenants,
                "stats": dict(self.stats),
                "resolver": dict(self.resolver.stats),
            }
