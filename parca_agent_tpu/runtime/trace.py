"""Window flight recorder: per-window lifecycle traces, streaming stage
histograms, and slow-window auto-capture.

The agent is a profiler that could not explain its own tail latency:
`/metrics` exposed only last-value gauges, so the 140 ms median close
headline hid the distribution, and a stalled window (the two >420 s
device hangs on record, the 930-2230 ms statics rebuilds) had to be
reconstructed from logs after the fact. This module is the always-on
instrumentation substrate (docs/observability.md):

  * ``WindowTrace`` — one trace per window, trace id = window seq,
    carrying per-stage spans (drain, close, feed, fetch, prepare,
    statics, encode, ship, symbolize, total) recorded by the profiler
    loop, the encode pipeline's worker, and the encoder.
  * ``FlightRecorder`` — a bounded ring of completed traces (the flight
    recorder `/debug/windows` serves as wide-event JSON) plus one
    streaming log-bucket histogram per stage (p50/p90/p99/max), exported
    in real Prometheus histogram format from `/metrics`. Transport
    stages that are not per-window (batch_flush, store_ack, store_rpc,
    spool_spill, spool_replay) feed the same histograms through
    :func:`observe`.
  * A slow-window detector: a span whose duration exceeds
    ``slow_multiple`` x the stage's RUNNING p99 (with a sample-count
    gate and an absolute floor) auto-captures an incident — the
    offending trace, a self-pprof (profiler/selfprofile.py), and the
    current supervisor/device/quarantine state — into a crash-only
    tmp+rename JSON file, rate-limited and counted.

Tracing is FAIL-OPEN by contract: every recorder entry point swallows
its own errors (counted in ``stats["record_errors"]``), so a broken or
chaos-injected tracing path can never stall or lose a window. The chaos
sites ``trace.record`` and ``incident.dump`` (utils/faults.py) exist to
prove exactly that.

Like ``utils/faults.py``, a process-global recorder can be installed so
deep components (batch client, spool, gRPC client, encoder) observe
stage durations without plumbing: production pays one module-attribute
read per site when tracing is off.
"""

from __future__ import annotations

import base64
import collections
import json
import os
import threading
import time

from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.log import get_logger
from parca_agent_tpu.utils.vfs import atomic_write_bytes

_log = get_logger("trace")

# palint: persistence-root — incident files are read by operators post-crash.

# Log-spaced bucket upper bounds in seconds: 10 us doubling to ~671 s.
# 27 finite buckets + the implicit +Inf bucket cover everything from a
# sub-ms host-side stage to the >420 s device hangs on record.
BUCKET_BOUNDS = tuple(1e-5 * (2.0 ** i) for i in range(27))

# The spans every complete fast-path (dict aggregator + fast encode)
# window trace carries; `make trace-smoke` and the integration tests
# assert these. Scalar-path traces replace prepare/encode with
# symbolize-less builder work and still carry drain/close/ship.
MANDATORY_SPANS = ("drain", "close", "prepare", "encode", "ship")


class StageHistogram:
    """One streaming log-bucket histogram: fixed bounds, cumulative-free
    per-bucket counts (cumulated at export), running sum/count/max.
    Mutation is serialized by the owning recorder's lock."""

    __slots__ = ("counts", "count", "sum_s", "max_s")

    def __init__(self):
        self.counts = [0] * (len(BUCKET_BOUNDS) + 1)
        self.count = 0
        self.sum_s = 0.0
        self.max_s = 0.0

    def observe(self, dur_s: float) -> None:
        dur_s = max(0.0, float(dur_s))
        lo, hi = 0, len(BUCKET_BOUNDS)
        while lo < hi:  # first bound >= dur_s (inlined bisect: no import)
            mid = (lo + hi) // 2
            if BUCKET_BOUNDS[mid] < dur_s:
                lo = mid + 1
            else:
                hi = mid
        self.counts[lo] += 1
        self.count += 1
        self.sum_s += dur_s
        if dur_s > self.max_s:
            self.max_s = dur_s

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile estimate. With log-spaced
        buckets the true value is within one bucket ratio (2x) of the
        estimate — good enough for budgets and dashboards, and the max
        is tracked exactly alongside."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank and c:
                if i >= len(BUCKET_BOUNDS):
                    return self.max_s
                lo = BUCKET_BOUNDS[i - 1] if i else 0.0
                # Cap at the exact max (all-zero stages report 0, not
                # half a bucket bound); observations in bucket i are
                # strictly above lo, so max(hi, lo) only guards the
                # zero-bucket case.
                hi = min(BUCKET_BOUNDS[i], self.max_s)
                frac = (rank - (seen - c)) / c
                return lo + (max(hi, lo) - lo) * frac
        return self.max_s

    def export(self) -> dict:
        """Cumulative buckets + summary stats (the /metrics shape)."""
        cum, acc = [], 0
        for i, c in enumerate(self.counts[:-1]):
            acc += c
            cum.append((BUCKET_BOUNDS[i], acc))
        return {
            "buckets": cum,             # [(le_seconds, cumulative_count)]
            "count": self.count,        # == the +Inf cumulative bucket
            "sum_s": self.sum_s,
            "max_s": self.max_s,
            "p50_s": self.quantile(0.50),
            "p90_s": self.quantile(0.90),
            "p99_s": self.quantile(0.99),
        }


class _SpanCtx:
    """Context manager for one timed span. Always measures (the gauges
    that must stay in lockstep with the histograms read .duration_s even
    when tracing is disabled); recording is the trace's problem and is
    fail-open there. User exceptions are recorded and re-raised."""

    __slots__ = ("_trace", "_stage", "_t0", "duration_s")

    def __init__(self, trace, stage: str):
        self._trace = trace
        self._stage = stage
        self.duration_s = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, et, ev, tb):
        self.duration_s = time.perf_counter() - self._t0
        self._trace.add_span(
            self._stage, self.duration_s,
            error=(repr(ev)[:200] if ev is not None else None))
        return False


class _NullTrace:
    """The do-nothing trace: call sites never branch on whether tracing
    is enabled. Spans still measure (see _SpanCtx) but record nowhere."""

    seq = 0
    completed = True
    detached = False

    def span(self, stage: str) -> _SpanCtx:
        return _SpanCtx(self, stage)

    def add_span(self, stage, duration_s, error=None,
                 histogram=True) -> None:
        pass

    def annotate(self, **kv) -> None:
        pass

    def detach(self) -> None:
        pass

    def finish(self, error: str | None = None) -> None:
        pass

    def complete(self, error: str | None = None) -> None:
        pass

    def discard(self) -> None:
        pass


NULL_TRACE = _NullTrace()


class WindowTrace:
    """One window's lifecycle. Created by FlightRecorder.begin on the
    profiler thread; ownership may transfer to the encode pipeline's
    worker (detach) — the hand-off lock gives the happens-before edge,
    so spans never need their own lock. complete() is idempotent and
    routes through the recorder (ring + histograms + slow detector)."""

    __slots__ = ("seq", "time_ns", "t0_s", "spans", "meta", "error",
                 "completed", "detached", "_rec")

    def __init__(self, rec, seq: int, time_ns: int):
        self._rec = rec
        self.seq = seq
        self.time_ns = time_ns
        self.t0_s = time.perf_counter()
        self.spans: list[dict] = []
        self.meta: dict = {}
        self.error: str | None = None
        self.completed = False
        self.detached = False

    def span(self, stage: str) -> _SpanCtx:
        return _SpanCtx(self, stage)

    # palint: fail-open
    def add_span(self, stage: str, duration_s: float,
                 error: str | None = None,
                 histogram: bool = True) -> None:
        """Record one span; fail-open (a tracing fault must never cost
        the window — the trace.record chaos site injects exactly here).
        ``histogram=False`` keeps the span out of the stage histograms
        at completion: for stages whose histogram is fed elsewhere
        (the encoder observes each statics build per call; the worker's
        per-window statics span would double-count it)."""
        try:
            faults.inject("trace.record")
            now = time.perf_counter()
            self.spans.append({
                "stage": stage,
                "start_s": round(max(0.0, now - duration_s - self.t0_s), 6),
                "duration_s": round(float(duration_s), 6),
                "thread": threading.current_thread().name,
                **({} if histogram else {"nohist": True}),
                **({"error": error} if error else {}),
            })
        except Exception as e:  # noqa: BLE001 - tracing is fail-open
            self._rec._record_error(e)

    # palint: fail-open
    def annotate(self, **kv) -> None:
        try:
            # Rebind, don't mutate: a detached trace may already be in
            # the ring (the worker completed it) while the profiler
            # thread annotates a late iteration error — a concurrent
            # /debug/windows json.dumps must see the old dict or the
            # new one, never one resizing mid-iteration. The recorder
            # lock serializes against complete()'s slow_stage rebind —
            # two unlocked rebinds would lose one writer's keys.
            with self._rec._lock:
                self.meta = {**self.meta, **kv}
        except Exception as e:  # noqa: BLE001 - tracing is fail-open
            self._rec._record_error(e)

    def detach(self) -> None:
        """Ownership moved to another thread (the encode worker): the
        profiler loop's end-of-iteration complete() becomes a no-op."""
        self.detached = True

    def finish(self, error: str | None = None) -> None:
        """The profiler loop's end-of-iteration completion. Detached
        traces are NEVER completed from here — the encode worker owns
        them (completing one early would race the worker's span writes
        and drop its encode/ship samples from the histograms); an
        iteration error that co-occurs with a successful hand-off (e.g.
        a debuginfo upload failure) is annotated instead, so it still
        shows on /debug/windows without stealing the completion."""
        if self.detached:
            if error is not None:
                self.annotate(iteration_error=error)
            return
        self._rec.complete(self, error=error)

    def complete(self, error: str | None = None) -> None:
        self._rec.complete(self, error=error)

    def discard(self) -> None:
        self._rec.discard(self)

    def to_dict(self) -> dict:
        total = next((s["duration_s"] for s in self.spans
                      if s["stage"] == "total"), None)
        d = {
            "seq": self.seq,
            "time_ns": self.time_ns,
            "complete": self.completed,
            "duration_s": total if total is not None else round(
                sum(s["duration_s"] for s in self.spans), 6),
            "spans": list(self.spans),
        }
        if self.meta:
            d["meta"] = dict(self.meta)
        if self.error:
            d["error"] = self.error
        return d


class FlightRecorder:
    """The per-process window flight recorder (module docs above).

    ``context`` is a zero-arg callable returning a JSON-able dict of
    runtime state for incident files (the CLI wires supervisor/device/
    quarantine snapshots via set_context after those exist);
    ``self_profile`` a zero-arg callable returning gzipped pprof bytes
    (defaults to a 1 s profiler/selfprofile.py wall-clock sample).
    ``incident_dir`` empty disables incident files (slow windows are
    still detected and counted)."""

    def __init__(self, ring: int = 512, slow_multiple: float = 5.0,
                 min_count: int = 8, min_duration_s: float = 0.05,
                 incident_dir: str = "", incident_interval_s: float = 300.0,
                 max_incidents: int = 64, self_profile_s: float = 1.0,
                 context=None, self_profile=None, clock=time.monotonic):
        self._lock = threading.Lock()
        # guarded-by: _lock (the next three + stats below): profiler
        # thread, encode worker, batch/flush threads, and the HTTP read
        # side all meet here — the PR 7 review round's two-writer
        # lost-update is exactly what the annotation now machine-checks.
        self._ring: collections.deque = collections.deque(  # guarded-by: _lock
            maxlen=max(1, ring))
        self._hists: dict[str, StageHistogram] = {}  # guarded-by: _lock
        self._seq = 0  # guarded-by: _lock
        self._slow_multiple = slow_multiple
        self._min_count = max(1, min_count)
        self._min_duration = min_duration_s
        self._incident_dir = incident_dir
        self._incident_interval = incident_interval_s
        self._max_incidents = max(1, max_incidents)
        self._last_incident_at: float | None = None
        self._dumping = False
        self._clock = clock
        self._context = context
        self._self_profile = self_profile
        self._self_profile_s = self_profile_s
        if incident_dir:
            os.makedirs(incident_dir, exist_ok=True)
        self.stats = {  # guarded-by: _lock
            "traces_started": 0,
            "traces_completed": 0,
            "traces_discarded": 0,
            "record_errors": 0,
            "slow_spans_total": 0,
            "incidents_written": 0,
            "incidents_suppressed": 0,
            "incidents_failed": 0,
        }

    # -- configuration -------------------------------------------------------

    def set_context(self, context) -> None:
        """Late-bind the incident context provider (the CLI builds the
        recorder before the supervisor exists)."""
        self._context = context

    # -- trace lifecycle -----------------------------------------------------

    # palint: fail-open
    def begin(self, time_ns: int | None = None):
        """Start the next window's trace. Fail-open: any internal error
        returns the NULL trace so the window proceeds untraced."""
        try:
            faults.inject("trace.record")
            with self._lock:
                self._seq += 1
                seq = self._seq
                self.stats["traces_started"] += 1
            return WindowTrace(self, seq,
                               time_ns if time_ns is not None
                               else time.time_ns())
        except Exception as e:  # noqa: BLE001 - tracing is fail-open
            self._record_error(e)
            return NULL_TRACE

    # palint: fail-open
    def complete(self, trace: WindowTrace, error: str | None = None) -> None:
        """Finish a trace: total span, ring append, histogram feed, slow
        detection. Idempotent; fail-open."""
        try:
            faults.inject("trace.record")
            with self._lock:
                if trace.completed:
                    return
                trace.completed = True
            if error:
                trace.error = error
            total_s = time.perf_counter() - trace.t0_s
            trace.spans.append({
                "stage": "total",
                "start_s": 0.0,
                "duration_s": round(total_s, 6),
                "thread": threading.current_thread().name,
            })
            worst = None  # (ratio, stage, duration, budget)
            with self._lock:
                for s in trace.spans:
                    stage, dur = s["stage"], s["duration_s"]
                    if s.pop("nohist", False):
                        # This stage's histogram AND slow detection are
                        # fed per-call elsewhere (encoder statics via
                        # observe()); the per-window aggregate span is
                        # display-only — a churn window summing N fast
                        # builds must not trip a budget derived from
                        # per-call samples.
                        continue
                    budget = self._budget_locked(stage)
                    if budget is not None and dur > budget:
                        self.stats["slow_spans_total"] += 1
                        s["slow"] = True
                        if worst is None or dur / budget > worst[0]:
                            worst = (dur / budget, stage, dur, budget)
                    self._hists.setdefault(
                        stage, StageHistogram()).observe(dur)
                if worst is not None:
                    # Rebind, don't mutate: the trace is already visible
                    # to /debug/windows serialization (see annotate(),
                    # which shares this lock so neither rebind is lost).
                    trace.meta = {**trace.meta, "slow_stage": worst[1]}
                self._ring.append(trace)
                self.stats["traces_completed"] += 1
            if worst is not None:
                self._capture_incident(trace, worst)
        except Exception as e:  # noqa: BLE001 - tracing is fail-open
            self._record_error(e)

    # palint: fail-open
    def discard(self, trace) -> None:
        """Drop a trace that never became a window (source exhausted):
        not ringed, not histogrammed."""
        try:
            with self._lock:
                if not getattr(trace, "completed", True):
                    trace.completed = True
                    self.stats["traces_discarded"] += 1
        except Exception as e:  # noqa: BLE001 - tracing is fail-open
            self._record_error(e)

    # palint: fail-open
    def observe(self, stage: str, duration_s: float) -> None:
        """Feed one non-per-window stage observation (batch flush, store
        ack, spool spill/replay) into its histogram + the slow detector.
        Fail-open."""
        try:
            faults.inject("trace.record")
            slow = None
            with self._lock:
                budget = self._budget_locked(stage)
                if budget is not None and duration_s > budget:
                    self.stats["slow_spans_total"] += 1
                    slow = (duration_s / budget, stage, duration_s, budget)
                self._hists.setdefault(
                    stage, StageHistogram()).observe(duration_s)
            if slow is not None:
                self._capture_incident(None, slow)
        except Exception as e:  # noqa: BLE001 - tracing is fail-open
            self._record_error(e)

    def _record_error(self, e: Exception) -> None:
        try:
            with self._lock:
                self.stats["record_errors"] += 1
            _log.debug("trace recording failed (fail-open)", error=repr(e))
        except Exception:  # noqa: BLE001 - never escalate from here
            pass

    # -- slow-window detection / incidents -----------------------------------

    def _budget_locked(self, stage: str) -> float | None:  # palint: holds=_lock
        """Stage budget = slow_multiple x running p99, floored at
        min_duration_s; None until min_count samples exist (a budget
        computed from two observations is noise, not a contract)."""
        h = self._hists.get(stage)
        if h is None or h.count < self._min_count:
            return None
        return max(self._slow_multiple * h.quantile(0.99),
                   self._min_duration)

    def _capture_incident(self, trace, worst) -> None:
        """Rate-limited, single-flight incident capture on a daemon
        thread (the self-profile samples for self_profile_s seconds —
        never on the window path)."""
        _ratio, stage, dur, budget = worst
        with self._lock:
            now = self._clock()
            if self._dumping or (
                    self._last_incident_at is not None
                    and now - self._last_incident_at
                    < self._incident_interval):
                self.stats["incidents_suppressed"] += 1
                return
            self._last_incident_at = now
            if not self._incident_dir:
                self.stats["incidents_suppressed"] += 1
                return
            self._dumping = True
        _log.warn("slow window detected; capturing incident",
                  stage=stage, duration_s=round(dur, 3),
                  budget_s=round(budget, 3),
                  seq=getattr(trace, "seq", None))
        threading.Thread(
            target=self._dump_incident, args=(trace, stage, dur, budget),
            name="trace-incident", daemon=True).start()

    def capture_event(self, kind: str, stage: str, detail: dict) -> bool:
        """External incident capture — the device flight recorder routes
        recompile storms here (runtime/device_telemetry.py). Same rate
        limiter, single-flight daemon thread, context/self-profile
        bundle, and pruning as slow-window capture; the incident file
        carries the caller's ``kind`` and ``detail`` payload. Returns
        False when suppressed (rate limit, capture in flight, no
        incident dir)."""
        with self._lock:
            now = self._clock()
            if self._dumping or (
                    self._last_incident_at is not None
                    and now - self._last_incident_at
                    < self._incident_interval):
                self.stats["incidents_suppressed"] += 1
                return False
            self._last_incident_at = now
            if not self._incident_dir:
                self.stats["incidents_suppressed"] += 1
                return False
            self._dumping = True
        _log.warn("external incident; capturing", kind=kind, stage=stage)
        threading.Thread(
            target=self._dump_incident, args=(None, stage, 0.0, 0.0),
            kwargs={"kind": kind, "detail": detail},
            name="trace-incident", daemon=True).start()
        return True

    def _dump_incident(self, trace, stage: str, dur: float,
                       budget: float, kind: str = "slow_window",
                       detail: dict | None = None) -> None:
        try:
            faults.inject("incident.dump")
            body = {
                "kind": kind,
                "stage": stage,
                "duration_s": round(dur, 6),
                "budget_s": round(budget, 6),
                "slow_multiple": self._slow_multiple,
                "captured_at_ns": time.time_ns(),
                "trace": trace.to_dict() if trace is not None else None,
                "stage_percentiles": self.percentiles(),
            }
            if detail is not None:
                body["detail"] = detail
            if self._context is not None:
                try:
                    body["context"] = self._context()
                except Exception as e:  # noqa: BLE001 - partial > none
                    body["context_error"] = repr(e)[:200]
            try:
                prof = self._self_profile_bytes()
                body["self_profile_pprof_gz_b64"] = \
                    base64.b64encode(prof).decode()
            except Exception as e:  # noqa: BLE001 - partial > none
                body["self_profile_error"] = repr(e)[:200]
            seq = getattr(trace, "seq", 0) or 0
            path = os.path.join(
                self._incident_dir,
                f"incident-{time.strftime('%Y%m%dT%H%M%S')}"
                f"-w{seq:06d}-{stage}.json")
            atomic_write_bytes(
                path, json.dumps(body, indent=1).encode())
            self._prune_incidents()
            with self._lock:
                self.stats["incidents_written"] += 1
            _log.warn("incident captured", path=path)
        except Exception as e:  # noqa: BLE001 - incidents are best-effort
            with self._lock:
                self.stats["incidents_failed"] += 1
            _log.warn("incident capture failed", error=repr(e))
        finally:
            with self._lock:
                self._dumping = False

    def _self_profile_bytes(self) -> bytes:
        if self._self_profile is not None:
            return self._self_profile()
        from parca_agent_tpu.profiler.selfprofile import profile_self

        return profile_self(self._self_profile_s)

    def _prune_incidents(self) -> None:
        """Keep the newest max_incidents files: an agent stuck slow must
        not fill the disk with its own forensics."""
        try:
            names = sorted(n for n in os.listdir(self._incident_dir)
                           if n.startswith("incident-")
                           and n.endswith(".json"))
            for n in names[:-self._max_incidents]:
                os.unlink(os.path.join(self._incident_dir, n))
        except OSError:  # pragma: no cover - prune is best-effort
            pass

    # -- read side (HTTP thread) ---------------------------------------------

    def traces(self, limit: int | None = None) -> list[dict]:
        """The ring, oldest first, as wide-event dicts (/debug/windows)."""
        with self._lock:
            out = [t.to_dict() for t in self._ring]
        return out[-limit:] if limit else out

    def trace(self, seq: int) -> dict | None:
        with self._lock:
            for t in self._ring:
                if t.seq == seq:
                    return t.to_dict()
        return None

    def export_histograms(self) -> dict[str, dict]:
        """{stage: StageHistogram.export()} for /metrics rendering."""
        with self._lock:
            return {stage: h.export()
                    for stage, h in sorted(self._hists.items())}

    def percentiles(self) -> dict[str, dict]:
        """{stage: {p50_ms, p90_ms, p99_ms, max_ms, count}} — the compact
        distribution stamp (bench JSON, incident files)."""
        with self._lock:
            return {
                stage: {
                    "p50_ms": round(h.quantile(0.50) * 1e3, 3),
                    "p90_ms": round(h.quantile(0.90) * 1e3, 3),
                    "p99_ms": round(h.quantile(0.99) * 1e3, 3),
                    "max_ms": round(h.max_s * 1e3, 3),
                    "count": h.count,
                }
                for stage, h in sorted(self._hists.items())
            }


# -- process-global installation (the faults.py pattern) ----------------------

_active: FlightRecorder | None = None


def install(recorder: FlightRecorder | None) -> None:
    """Install (or with None, remove) the process-wide recorder. The CLI
    calls this once at startup; tests install/uninstall around cases."""
    global _active
    _active = recorder


def get() -> FlightRecorder | None:
    return _active


def observe(stage: str, duration_s: float) -> None:
    """The deep-component hook (batch client, spool, gRPC client,
    encoder): free when no recorder is installed."""
    if _active is not None:
        _active.observe(stage, duration_s)
