"""Device-side flight recorder: kernel, compile, and transfer truth.

PR 7's window flight recorder (runtime/trace.py) explains the agent's
host-side tail — but the hardware arc (ROADMAP item 1) is blind exactly
where its truth lives: kernel dispatch cost is folded into whatever
stage span happens to contain it, the first call's XLA compile (seconds)
is indistinguishable from steady-state execution (microseconds), a
Pallas->lax fallback latches silently behind a one-shot log line, and
nothing accounts the H2D/D2H bytes each kernel moves. This module is
the device-side twin: a process-global :class:`DeviceTelemetry`
registry that every kernel dispatch site reports into —

  * per-kernel streaming latency histograms discriminating
    ``event=compile|execute`` via a shape-signature first-call latch
    (the first observation of a new signature on a kernel IS the call
    that paid tracing+compilation; JAX caches by shape, so a signature
    seen before executes from cache);
  * a recompile-storm detector: a NEW signature on a previously-latched
    kernel increments a counter and routes a rate-limited incident
    through PR 7's incident machinery (``FlightRecorder.capture_event``)
    — a workload whose shapes churn recompiles forever, and that must
    be an incident, not a vibe;
  * H2D/D2H transfer-byte accounting per kernel, derived from the
    packed buffer sizes the sites already compute — no extra syncs;
  * a latched backend-identity record (platform, device_kind, jax /
    jaxlib versions, per-kernel pallas/lax resolution and interpret
    flag) exported once as info-style gauges so a node that silently
    fell back to lax is visible from /metrics, not just logs;
  * a window-SLO layer rolling capture-thread busy time plus off-thread
    kernel seconds into a per-window budget-used ratio and a
    windows-over-budget burn counter keyed to the configured period —
    the instrument the sub-second-window work is measured against.

Reporting sites (aggregator/{dict,tpu,sharded}.py) call the module-level
hooks (:func:`record`, :func:`transfer`, :func:`note_backend`,
:func:`tick_window`) — the faults.py pattern: one module-attribute read
when telemetry is off. Several sites sit on the CAPTURE PATH (palint's
host-sync walk reaches them), so every hook is observation-only: wall
clocks and byte counts already on the host, never a device sync.

Fail-open discipline mirrors trace.py exactly: every entry point is
annotated ``# palint: fail-open``, swallows its own errors into
``stats["record_errors"]``, and carries the ``device.telemetry`` chaos
site — telemetry must never cost a window or change a pprof byte
(docs/observability.md "device flight recorder").
"""

from __future__ import annotations

import threading
import time
from collections import deque

from parca_agent_tpu.runtime import trace as trace_mod
from parca_agent_tpu.runtime.trace import StageHistogram
from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.log import get_logger

_log = get_logger("device_telemetry")

# The kernel names the dispatch sites report under (the registry is
# dynamic — these are documentation, not a closed set):
#   feed_probe   dict feed probe dispatch (aggregator/dict.py)
#   miss_settle  vectorized miss plan-then-commit (aggregator/dict.py)
#   close_pack   full close pack dispatch (aggregator/dict.py)
#   close_delta  delta close pack dispatch (aggregator/dict.py)
#   close_fetch  the packed close D2H collect (aggregator/dict.py)
#   loc_dedup    batched window kernel + loc-table dedup (aggregator/tpu.py)
#   shard_put    per-device sharded feed puts (aggregator/sharded.py)
EVENTS = ("compile", "execute")


def _collect_identity() -> dict:
    """The latched backend-identity record: platform, device kind,
    versions, pallas availability. Any probe failure degrades a field
    to its unknown default — identity must never cost startup."""
    import socket

    ident = {
        "platform": "unknown",
        "device_kind": "unknown",
        "device_count": 0,
        "jax_version": "unknown",
        "jaxlib_version": "unknown",
        "pallas_available": False,
        "interpret_default": True,
        "hostname": socket.gethostname(),
    }
    try:
        import jax

        ident["jax_version"] = str(getattr(jax, "__version__", "unknown"))
        ident["platform"] = str(jax.default_backend())
        devs = jax.devices()
        ident["device_count"] = len(devs)
        if devs:
            ident["device_kind"] = str(
                getattr(devs[0], "device_kind", "unknown"))
    except Exception:  # noqa: BLE001 - identity is best-effort
        pass
    try:
        import jaxlib

        ident["jaxlib_version"] = str(
            getattr(jaxlib, "__version__", "unknown"))
    except Exception:  # noqa: BLE001 - identity is best-effort
        pass
    try:
        from parca_agent_tpu.aggregator import pallas_probe

        ident["pallas_available"] = bool(pallas_probe.pallas_available())
        ident["interpret_default"] = bool(pallas_probe.default_interpret())
    except Exception:  # noqa: BLE001 - identity is best-effort
        pass
    return ident


class DeviceTelemetry:
    """Process-global device flight recorder (one per agent, installed
    via :func:`install`). Thread-safe; every write path is fail-open."""

    def __init__(self, period_s: float = 0.0, ring: int = 256,
                 incident_interval_s: float = 300.0,
                 clock=time.monotonic):
        self._lock = threading.Lock()
        self._clock = clock
        self._t0 = clock()
        self.period_s = float(period_s)
        self._incident_interval = incident_interval_s
        self._hists: dict[tuple[str, str], StageHistogram] = {}  # guarded-by: _lock
        self._shapes: dict[str, set] = {}  # guarded-by: _lock
        self._transfers: dict[tuple[str, str], list[int]] = {}  # guarded-by: _lock
        self._backends: dict[str, dict] = {}  # guarded-by: _lock
        self._identity: dict | None = None  # guarded-by: _lock
        self._budget_hist = StageHistogram()  # guarded-by: _lock
        self._events = deque(maxlen=max(16, ring))  # guarded-by: _lock
        self._windows = deque(maxlen=max(16, ring))  # guarded-by: _lock
        self._win_kernel_s: dict[int, float] = {}  # guarded-by: _lock
        self._last_recompile_at: float | None = None  # guarded-by: _lock
        self.stats = {  # guarded-by: _lock
            "record_errors": 0,
            "events_total": 0,
            "compiles_total": 0,
            "recompiles_total": 0,
            "recompile_incidents": 0,
            "recompile_incidents_suppressed": 0,
        }
        self.window_stats = {  # guarded-by: _lock
            "windows_total": 0,
            "windows_over_budget_total": 0,
            "budget_used_last": 0.0,
        }

    # -- write side (dispatch sites; capture path) ---------------------------

    # palint: fail-open
    def record(self, kernel: str, duration_s: float, shape=None,
               h2d_bytes: int = 0, d2h_bytes: int = 0) -> None:
        """Record one kernel observation: latency histogram keyed
        (kernel, event), shape-signature compile latch, transfer bytes,
        per-window kernel-seconds, and the bounded event timeline.
        ``shape`` is the site's compiled-program signature (its jit
        cache key, or the padded shape class for eager dispatches);
        None records an execute event with no latch. Fail-open."""
        try:
            faults.inject("device.telemetry")
            storm = None
            with self._lock:
                event = "execute"
                if shape is not None:
                    seen = self._shapes.get(kernel)
                    if seen is None:
                        seen = self._shapes[kernel] = set()
                    if shape not in seen:
                        event = "compile"
                        self.stats["compiles_total"] += 1
                        if seen:
                            self.stats["recompiles_total"] += 1
                            storm = (kernel, shape, len(seen) + 1)
                        seen.add(shape)
                self._hists.setdefault(
                    (kernel, event), StageHistogram()).observe(duration_s)
                self.stats["events_total"] += 1
                if h2d_bytes:
                    t = self._transfers.setdefault((kernel, "h2d"), [0, 0])
                    t[0] += int(h2d_bytes)
                    t[1] += 1
                if d2h_bytes:
                    t = self._transfers.setdefault((kernel, "d2h"), [0, 0])
                    t[0] += int(d2h_bytes)
                    t[1] += 1
                tid = threading.get_ident()
                self._win_kernel_s[tid] = \
                    self._win_kernel_s.get(tid, 0.0) + duration_s
                self._events.append({
                    "t_s": round(self._clock() - self._t0, 6),
                    "kernel": kernel,
                    "event": event,
                    "duration_s": round(duration_s, 6),
                    "h2d_bytes": int(h2d_bytes),
                    "d2h_bytes": int(d2h_bytes),
                    "shape": repr(shape) if shape is not None else None,
                })
            if storm is not None:
                self._recompile_incident(*storm)
        except Exception as e:  # noqa: BLE001 - telemetry is fail-open
            self._record_error(e)

    # palint: fail-open
    def record_transfer(self, kernel: str, direction: str,
                        nbytes: int) -> None:
        """Account a transfer with no latency observation (eager device
        writes whose dispatch rides another kernel's clock). Fail-open."""
        try:
            faults.inject("device.telemetry")
            with self._lock:
                t = self._transfers.setdefault((kernel, direction), [0, 0])
                t[0] += int(nbytes)
                t[1] += 1
        except Exception as e:  # noqa: BLE001 - telemetry is fail-open
            self._record_error(e)

    # palint: fail-open
    def note_backend(self, kernel: str, requested: str | None = None,
                     resolved: str | None = None,
                     interpret: bool | None = None,
                     fallback: bool | None = None) -> None:
        """Latch one kernel's backend resolution (requested vs resolved
        pallas/lax, interpret-mode flag, fallback one-hot). Fields are
        sticky per call — last write wins, None leaves a field alone.
        Fail-open."""
        try:
            faults.inject("device.telemetry")
            with self._lock:
                rec = self._backends.setdefault(kernel, {
                    "requested": None, "resolved": None,
                    "interpret": None, "fallback": False})
                if requested is not None:
                    rec["requested"] = requested
                if resolved is not None:
                    rec["resolved"] = resolved
                if interpret is not None:
                    rec["interpret"] = bool(interpret)
                if fallback is not None:
                    rec["fallback"] = bool(fallback)
        except Exception as e:  # noqa: BLE001 - telemetry is fail-open
            self._record_error(e)

    # palint: fail-open
    def tick_window(self, used_s: float) -> None:
        """Roll one window into the SLO layer. ``used_s`` is the capture
        thread's busy wall for the window; kernel seconds recorded from
        OTHER threads this window (streaming feed tees, encode-side
        fetches) are added on top — same-thread kernel time is already
        inside ``used_s``. Judged against the configured period; a
        period of 0 (tests, bench micro-phases) counts windows without
        a budget. Fail-open."""
        try:
            faults.inject("device.telemetry")
            with self._lock:
                me = threading.get_ident()
                other = sum(s for tid, s in self._win_kernel_s.items()
                            if tid != me)
                kernel_s = sum(self._win_kernel_s.values())
                self._win_kernel_s.clear()
                used = float(used_s) + other
                self.window_stats["windows_total"] += 1
                entry = {
                    "seq": self.window_stats["windows_total"],
                    "used_s": round(used, 6),
                    "kernel_s": round(kernel_s, 6),
                    "period_s": self.period_s,
                }
                if self.period_s > 0:
                    ratio = used / self.period_s
                    self.window_stats["budget_used_last"] = ratio
                    self._budget_hist.observe(ratio)
                    over = ratio > 1.0
                    if over:
                        self.window_stats["windows_over_budget_total"] += 1
                    entry["ratio"] = round(ratio, 6)
                    entry["over"] = over
                self._windows.append(entry)
        except Exception as e:  # noqa: BLE001 - telemetry is fail-open
            self._record_error(e)

    # palint: fail-open
    def ensure_identity(self) -> dict:
        """Latch (once) and return the backend-identity record. Safe off
        the capture path only — the first call may initialize the jax
        backend. Fail-open: an empty dict on error."""
        try:
            with self._lock:
                if self._identity is not None:
                    return dict(self._identity)
            ident = _collect_identity()
            with self._lock:
                if self._identity is None:
                    self._identity = ident
                return dict(self._identity)
        except Exception as e:  # noqa: BLE001 - telemetry is fail-open
            self._record_error(e)
            return {}

    def _recompile_incident(self, kernel: str, shape, n_shapes: int) -> None:
        """Rate-limited recompile-storm incident routed through the
        window flight recorder's machinery (called inside record()'s
        fail-open guard — its own errors are counted there)."""
        with self._lock:
            now = self._clock()
            if (self._last_recompile_at is not None
                    and now - self._last_recompile_at
                    < self._incident_interval):
                self.stats["recompile_incidents_suppressed"] += 1
                return
            self._last_recompile_at = now
            recompiles = self.stats["recompiles_total"]
        rec = trace_mod.get()
        captured = rec is not None and rec.capture_event(
            "recompile_storm", stage="recompile",
            detail={
                "kernel": kernel,
                "shape": repr(shape),
                "shapes_latched": n_shapes,
                "recompiles_total": recompiles,
                "kernel_percentiles": self.percentiles(),
                "backends": self.backends(),
            })
        with self._lock:
            if captured:
                self.stats["recompile_incidents"] += 1
            else:
                self.stats["recompile_incidents_suppressed"] += 1
        _log.warn("kernel recompile detected", kernel=kernel,
                  shape=repr(shape)[:120], shapes_latched=n_shapes,
                  incident=captured)

    def _record_error(self, e: Exception) -> None:
        try:
            with self._lock:
                self.stats["record_errors"] += 1
            _log.debug("device telemetry recording failed (fail-open)",
                       error=repr(e))
        except Exception:  # noqa: BLE001 - never escalate from here
            pass

    # -- read side (HTTP thread, bench, incident bundles) --------------------

    def export_kernel_histograms(self) -> list[tuple[str, str, dict]]:
        """[(kernel, event, StageHistogram.export())] for /metrics."""
        with self._lock:
            return [(k, e, h.export())
                    for (k, e), h in sorted(self._hists.items())]

    def transfers(self) -> list[tuple[str, str, int, int]]:
        """[(kernel, direction, bytes_total, ops_total)] for /metrics."""
        with self._lock:
            return [(k, d, t[0], t[1])
                    for (k, d), t in sorted(self._transfers.items())]

    def backends(self) -> dict[str, dict]:
        """{kernel: {requested, resolved, interpret, fallback}}."""
        with self._lock:
            return {k: dict(v) for k, v in sorted(self._backends.items())}

    def percentiles(self) -> dict[str, dict]:
        """{kernel: {event: {p50_ms, p99_ms, max_ms, count}}} — the
        compact per-kernel stamp (bench JSON, incident files)."""
        out: dict[str, dict] = {}
        with self._lock:
            for (kernel, event), h in sorted(self._hists.items()):
                out.setdefault(kernel, {})[event] = {
                    "p50_ms": round(h.quantile(0.50) * 1e3, 4),
                    "p99_ms": round(h.quantile(0.99) * 1e3, 4),
                    "max_ms": round(h.max_s * 1e3, 4),
                    "count": h.count,
                }
        return out

    def shape_counts(self) -> dict[str, int]:
        """{kernel: latched shape signatures} (recompiles = count - 1)."""
        with self._lock:
            return {k: len(v) for k, v in sorted(self._shapes.items())}

    def budget_export(self) -> dict:
        """The window-SLO block: ratio histogram + burn counters."""
        with self._lock:
            return {
                "period_s": self.period_s,
                "hist": self._budget_hist.export(),
                **dict(self.window_stats),
            }

    def snapshot(self) -> dict:
        """The full JSON-able telemetry stamp (bench artifacts,
        /debug/device): identity, per-kernel events/percentiles/shape
        latches, backends, transfers, window budget, self-accounting."""
        ident = self.ensure_identity()
        shapes = self.shape_counts()
        kernels: dict[str, dict] = {}
        for kernel, events in self.percentiles().items():
            kernels[kernel] = {
                "events": events,
                "compiles": events.get("compile", {}).get("count", 0),
                "executes": events.get("execute", {}).get("count", 0),
                "shapes_latched": shapes.get(kernel, 0),
                "recompiles": max(0, shapes.get(kernel, 0) - 1),
            }
        transfers: dict[str, dict] = {}
        for kernel, direction, nbytes, ops in self.transfers():
            transfers.setdefault(kernel, {})[direction] = {
                "bytes": nbytes, "ops": ops}
        with self._lock:
            stats = dict(self.stats)
        return {
            "identity": ident,
            "kernels": kernels,
            "backends": self.backends(),
            "transfers": transfers,
            "window_budget": self.budget_export(),
            "stats": stats,
        }

    def timeline(self, limit: int | None = None) -> dict:
        """The bounded rings for /debug/device: recent kernel events and
        per-window SLO entries, oldest first."""
        with self._lock:
            events = list(self._events)
            windows = list(self._windows)
        if limit:
            events = events[-limit:]
            windows = windows[-limit:]
        return {"events": events, "windows": windows}


# -- process-global installation (the faults.py pattern) ----------------------

_active: DeviceTelemetry | None = None


def install(telemetry: DeviceTelemetry | None) -> None:
    """Install (or with None, remove) the process-wide device telemetry.
    The CLI calls this once at startup; tests install/uninstall around
    cases."""
    global _active
    _active = telemetry


def get() -> DeviceTelemetry | None:
    return _active


def record(kernel: str, duration_s: float, shape=None,
           h2d_bytes: int = 0, d2h_bytes: int = 0) -> None:
    """Dispatch-site hook: free when no telemetry is installed."""
    if _active is not None:
        _active.record(kernel, duration_s, shape, h2d_bytes, d2h_bytes)


def transfer(kernel: str, direction: str, nbytes: int) -> None:
    """Transfer-only site hook (eager device writes)."""
    if _active is not None:
        _active.record_transfer(kernel, direction, nbytes)


def note_backend(kernel: str, **fields) -> None:
    """Backend-resolution latch hook (pallas/lax/interpret/fallback)."""
    if _active is not None:
        _active.note_backend(kernel, **fields)


def tick_window(used_s: float) -> None:
    """Window-SLO hook, called once per profiler iteration."""
    if _active is not None:
        _active.tick_window(used_s)
