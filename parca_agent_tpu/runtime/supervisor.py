"""Actor supervision: a run-group that restarts crashed actors.

Role of the reference's oklog/run group (cmd/parca-agent/main.go:505-592)
— but where the reference tears the whole process down when any actor
exits, an always-on profiler must NOT die because one component crashed:
the profiler is the last thing allowed to take a node down. So this
run-group restarts a crashed actor with capped exponential backoff, marks
it dead after ``max_restarts`` crashes (crash-looping forever would just
hide the bug), and surfaces per-actor state for ``/healthz``:

    healthy   running, no recent crash
    degraded  restarted within the last ``healthy_after_s`` seconds
    dead      crash budget exhausted (a critical dead actor turns the
              whole /healthz red)
    exited    returned cleanly (e.g. a replay source ran dry)

Two supervision styles:

  * ``add_actor(name, run, stop)`` — a thread-backed long-running actor
    (the batch flush loop, the profiler loop, the config reloader). The
    supervisor owns the thread and restarts it on an escaped exception.
  * ``add_probe(name, check, revive)`` — a component that owns its own
    thread/lifecycle (the encode pipeline's worker, the discovery
    manager's provider threads). The supervisor's tick polls ``check()``
    and calls ``revive()`` on failure, with the same crash budget.

Actors may call ``faults.inject("actor.<name>")`` at their loop tick so
the chaos layer can kill them at a named site.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from parca_agent_tpu.utils.log import get_logger

_log = get_logger("supervisor")


@dataclasses.dataclass
class _Actor:
    name: str
    run: object = None            # callable | None (probe actors)
    stop_fn: object = None
    check: object = None          # probe: () -> bool healthy
    revive: object = None         # probe: () -> None
    critical: bool = True
    restarts: int = 0             # cumulative (the /metrics counter)
    strikes: int = 0              # consecutive-ish crashes (the budget);
    #                               reset after a sustained healthy run
    last_crash_at: float | None = None
    last_error: BaseException | None = None
    dead: bool = False
    finished: bool = False        # clean return
    thread: threading.Thread | None = None


class Supervisor:
    def __init__(self, max_restarts: int = 5,
                 backoff_initial_s: float = 0.5,
                 backoff_max_s: float = 30.0,
                 healthy_after_s: float = 30.0,
                 probe_tick_s: float = 1.0,
                 clock=time.monotonic, sleep=None):
        self._max_restarts = max_restarts
        self._backoff_initial = backoff_initial_s
        self._backoff_max = backoff_max_s
        self._healthy_after = healthy_after_s
        self._tick = probe_tick_s
        self._clock = clock
        self._stop = threading.Event()
        self._sleep = sleep or (lambda s: self._stop.wait(s))
        self._lock = threading.Lock()
        self._actors: dict[str, _Actor] = {}
        self._probe_thread: threading.Thread | None = None
        self._started = False

    # -- registration --------------------------------------------------------

    def add_actor(self, name: str, run, stop=None,
                  critical: bool = True) -> None:
        if name in self._actors:
            raise ValueError(f"duplicate actor {name!r}")
        self._actors[name] = _Actor(name=name, run=run, stop_fn=stop,
                                    critical=critical)
        if self._started:
            self._start_actor(self._actors[name])

    def add_probe(self, name: str, check, revive=None,
                  critical: bool = True) -> None:
        if name in self._actors:
            raise ValueError(f"duplicate actor {name!r}")
        self._actors[name] = _Actor(name=name, check=check, revive=revive,
                                    critical=critical)

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._started = True
        for a in self._actors.values():
            if a.run is not None:
                self._start_actor(a)
        if any(a.check is not None for a in self._actors.values()):
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="supervisor-probe",
                daemon=True)
            self._probe_thread.start()

    def stop(self, timeout_s: float = 5.0) -> None:
        """Tear down in REVERSE registration order, joining each actor
        before stopping the next: upstream actors (registered last, e.g.
        the profiler) finish draining into downstream ones (registered
        first, e.g. the batch flush loop) before those run their final
        drain. ``timeout_s`` is PER ACTOR — a slow profiler join must
        not leave the flush actor's final drain with a zero budget (the
        drain of exactly the windows the profiler just handed over)."""
        self._stop.set()
        for a in reversed(list(self._actors.values())):
            if a.stop_fn is not None:
                try:
                    a.stop_fn()
                except Exception as e:  # noqa: BLE001 - teardown continues
                    _log.warn("actor stop hook failed", actor=a.name,
                              error=repr(e))
            t = a.thread
            if t is not None and t.is_alive():
                t.join(timeout_s)
                if t.is_alive():
                    _log.warn("actor did not stop within its budget",
                              actor=a.name, timeout_s=timeout_s)
        if self._probe_thread is not None:
            self._probe_thread.join(timeout_s)

    # -- thread actors -------------------------------------------------------

    def _start_actor(self, a: _Actor) -> None:
        a.thread = threading.Thread(target=self._run_actor, args=(a,),
                                    name=f"actor-{a.name}", daemon=True)
        a.thread.start()

    def _run_actor(self, a: _Actor) -> None:
        while not self._stop.is_set():
            try:
                a.run()
                a.finished = True
                return
            except Exception as e:  # noqa: BLE001 - the point of supervision
                if self._stop.is_set():
                    return
                self._note_crash(a, e)
                if a.dead:
                    return
                backoff = min(
                    self._backoff_initial * (2 ** (a.strikes - 1)),
                    self._backoff_max)
                _log.warn("actor crashed; restarting after backoff",
                          actor=a.name, restarts=a.restarts,
                          backoff_s=round(backoff, 3), error=repr(e))
                self._sleep(backoff)
            except BaseException as e:  # noqa: BLE001 - terminal, never
                # restarted (SystemExit and friends are not crashes to
                # supervise through) — but the death must be VISIBLE:
                # before supervision, thread death was caught by the
                # CLI's is_alive() check; mark the actor dead so
                # finished()/health() report it instead of an eternally
                # "healthy" corpse.
                with self._lock:
                    a.last_error = e
                    a.last_crash_at = self._clock()
                    a.dead = True
                _log.error("actor raised a terminal BaseException; "
                           "marking dead", actor=a.name, exc=e)
                return

    def _note_crash(self, a: _Actor, e: BaseException) -> None:
        with self._lock:
            now = self._clock()
            if a.last_crash_at is not None and \
                    now - a.last_crash_at >= self._healthy_after:
                # A sustained healthy run refreshes the crash budget: an
                # always-on agent must only die for crash LOOPS, not for
                # max_restarts transient crashes spread over weeks of
                # uptime. `restarts` stays cumulative for the metric.
                a.strikes = 0
            a.restarts += 1
            a.strikes += 1
            a.last_crash_at = now
            a.last_error = e
            if a.strikes > self._max_restarts:
                a.dead = True
                _log.error("actor exhausted its crash budget; marking dead",
                           actor=a.name, restarts=a.restarts, exc=e)

    # -- probe actors --------------------------------------------------------

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            self.poll_probes()
            self._stop.wait(self._tick)

    def poll_probes(self) -> None:
        """One probe pass over every check-style actor (the tick thread
        calls this; tests and simulated-time harnesses call it directly)."""
        for a in self._actors.values():
            if a.check is None or a.dead:
                continue
            try:
                healthy = bool(a.check())
            except Exception as e:  # noqa: BLE001 - a broken probe = unhealthy
                healthy = False
                a.last_error = e
            if healthy:
                continue
            self._note_crash(a, a.last_error
                             or RuntimeError(f"probe {a.name} unhealthy"))
            if a.dead or a.revive is None:
                continue
            try:
                a.revive()
                _log.warn("probe actor revived", actor=a.name,
                          restarts=a.restarts)
            except Exception as e:  # noqa: BLE001 - next tick retries
                a.last_error = e
                _log.warn("probe actor revive failed", actor=a.name,
                          error=repr(e))

    # -- observability -------------------------------------------------------

    def _state(self, a: _Actor) -> str:
        if a.dead:
            return "dead"
        if a.finished:
            return "exited"
        if a.last_crash_at is not None and \
                self._clock() - a.last_crash_at < self._healthy_after:
            return "degraded"
        return "healthy"

    def health(self) -> dict[str, dict]:
        with self._lock:
            out = {}
            for a in self._actors.values():
                alive = (a.thread.is_alive() if a.thread is not None
                         else a.check is not None and not a.dead)
                out[a.name] = {
                    "state": self._state(a),
                    "restarts": a.restarts,
                    "alive": bool(alive and not a.finished),
                    "critical": a.critical,
                    "last_error": (repr(a.last_error)[:200]
                                   if a.last_error else ""),
                }
            return out

    def overall(self) -> str:
        """healthy | degraded | dead for the /healthz headline. Only
        critical actors can turn it dead; any degraded actor (critical
        or not) turns it degraded."""
        worst = "healthy"
        for name, h in self.health().items():
            if h["state"] == "dead" and h["critical"]:
                return "dead"
            if h["state"] in ("dead", "degraded"):
                worst = "degraded"
        return worst

    def finished(self, name: str) -> bool:
        a = self._actors.get(name)
        return a is not None and (a.finished or a.dead)

    def actor_restarts(self) -> dict[str, int]:
        with self._lock:
            return {a.name: a.restarts for a in self._actors.values()}
