"""Cadence-invariant window-clock conversions.

Every window-clocked state machine in runtime/ (admission token refill,
quarantine cooldown/strike decay, device-health cooldowns) was tuned at
the reference 10-second window (PAPER.md: "every profiling duration").
ROADMAP item 1 makes the window length a product axis — at
``--profiling-duration 1.0`` the same knob values would mean 10x less
wall-clock patience and 10x smaller wall-clock budgets, silently
changing the robustness contract.

The fix is one conversion discipline, applied at construction time by
every registry that takes a ``window_s``:

  * **window-count knobs** (cooldowns, streaks, decay horizons) are
    WALL-TIME commitments expressed in reference windows; convert with
    :func:`windows_for` so "3 windows of cooldown" stays ~30 seconds at
    any cadence.
  * **per-window rate knobs** (token-bucket quotas, storm thresholds)
    are PER-REFERENCE-WINDOW budgets; convert with :func:`per_window`
    so "1000 samples per window" stays 100 samples/second at any
    cadence. Burst CAPS stay absolute (refill x converted burst
    windows), so the bankable burst is the same wall-clock budget too.

At ``window_s == REFERENCE_WINDOW_S`` both conversions are exact
identities (``round`` of an integer), so the default construction is
bit-for-bit the pre-conversion behavior — tests/test_window_clock.py
pins the invariance over {10.0, 1.0, 0.5}.
"""

from __future__ import annotations

# The cadence every window-count and per-window-rate knob in the repo
# was tuned at: the reference agent's 10-second profiling duration.
REFERENCE_WINDOW_S = 10.0


def check_window_s(window_s: float) -> float:
    """A usable window length, or ValueError (constructors call this
    once; cli.py raises the readable SystemExit before any registry is
    built)."""
    w = float(window_s)
    if not w > 0.0:
        raise ValueError(f"window_s must be > 0, got {window_s!r}")
    return w


def windows_for(n, window_s: float) -> int:
    """A reference-window count ``n`` as a count of ``window_s``-long
    windows covering the same wall time, never below one window.
    Accepts floats so a caller can express sub-reference commitments
    (``windows_for(0.3, 1.0) == 3``); the identity case
    ``windows_for(n, 10.0) == n`` is exact for integer ``n``."""
    w = check_window_s(window_s)
    return max(1, round(float(n) * REFERENCE_WINDOW_S / w))


def per_window(rate, window_s: float) -> float:
    """A per-reference-window budget ``rate`` as a per-``window_s``
    budget (same per-second rate). Exact identity at the reference
    cadence."""
    w = check_window_s(window_s)
    return float(rate) * w / REFERENCE_WINDOW_S
