"""Per-pid error budget, quarantine registry, and the degradation ladder.

PR 3 made the *output* side crash-only; this is the *ingest* twin: the
unit of failure must be ONE PID, not one window. Every ingest-side
consumer of untrusted per-process input — the mapping-table builder, the
unwind-table builder, the symbolizer, the streaming feeder — reports
per-pid faults here, and routes a faulty pid's samples down a
degradation ladder instead of dropping them:

    level 0  FULL        normal processing (symbolization, unwind, maps)
    level 1  ADDRESSES   addresses-only profile: no local symbolization,
                         no unwind-table build, but normalized address +
                         build id still travel (the reference's
                         server-side-symbolization contract,
                         symbol.go:55-139 — the profile stays useful)
    level 2  SCALAR      one scalar count sample; the pid still shows up
                         in aggregate CPU accounting, nothing else

Budget semantics mirror the supervisor's crash budget
(runtime/supervisor.py): a pid accumulating more than ``max_strikes``
input faults (or per-pid processing-deadline overruns) within its budget
window is QUARANTINED for a capped-exponential number of windows
(doubling per trip, like actor restart backoff), then enters PROBATION:
full processing resumes, but one more fault re-trips immediately with a
longer cooldown and — past ``escalate_after`` trips — a deeper ladder
level. ``probation_windows`` clean windows recover the pid fully, and a
sustained healthy run decays accumulated strikes (the supervisor's
healthy_after refresh), so an always-on agent only degrades pids that
are ACTIVELY feeding it poison.

All mutation is lock-protected: errors are recorded from the profiler
thread, the streaming feeder's tee, and (metrics reads) the HTTP thread.
"""

from __future__ import annotations

import dataclasses
import threading
import time

import numpy as np

from parca_agent_tpu.runtime.window_clock import (
    REFERENCE_WINDOW_S,
    check_window_s,
    windows_for,
)
from parca_agent_tpu.utils.log import get_logger

_log = get_logger("quarantine")

LEVEL_FULL = 0
LEVEL_ADDRESSES = 1
LEVEL_SCALAR = 2

_LEVEL_NAMES = {LEVEL_FULL: "full", LEVEL_ADDRESSES: "addresses",
                LEVEL_SCALAR: "scalar"}


@dataclasses.dataclass
class _PidState:
    strikes: int = 0            # faults within the current budget window
    trips: int = 0              # times quarantined (escalation + backoff)
    state: str = "healthy"      # healthy | quarantined | probation
    level: int = LEVEL_FULL
    cooldown: int = 0           # quarantine windows left
    probation_left: int = 0     # clean windows needed for full recovery
    ok_windows: int = 0         # consecutive clean windows (strike decay)
    errored_this_window: bool = False
    last_error: str = ""
    last_site: str = ""
    tenant: str = ""            # resolved once at insert (tenant_of set)


class QuarantineRegistry:
    """The shared per-pid fault-containment state machine.

    trip → quarantined (ladder level ≥ 1, cooldown windows)
         → probation (full processing, watched)
         → recovered (clean) | re-tripped (instant, doubled cooldown)
    """

    def __init__(self, max_strikes: int = 3,
                 quarantine_windows: int = 3,
                 max_quarantine_windows: int = 60,
                 probation_windows: int = 2,
                 escalate_after: int = 2,
                 healthy_after_windows: int = 6,
                 deadline_s: float | None = None,
                 clock=time.perf_counter,
                 window_s: float = REFERENCE_WINDOW_S):
        self._max_strikes = max_strikes
        # Window-count knobs are wall-time commitments expressed at the
        # reference 10 s cadence (runtime/window_clock.py): a 3-window
        # cooldown means ~30 s of quarantine whatever the window length.
        # Strike counts (max_strikes, escalate_after) are per-FAULT, not
        # per-window, and stay unconverted. At the reference cadence the
        # conversion is an exact identity.
        check_window_s(window_s)
        self._base_cooldown = windows_for(quarantine_windows, window_s)
        self._max_cooldown = max(self._base_cooldown, windows_for(
            max_quarantine_windows, window_s))
        self._probation = windows_for(probation_windows, window_s)
        # 0 = straight to scalar on the first trip; N = N trips ride the
        # addresses-only level first.
        self._escalate_after = max(0, escalate_after)
        self._healthy_after = windows_for(healthy_after_windows, window_s)
        self.deadline_s = deadline_s
        self._clock = clock
        # Optional pid -> tenant hook (runtime/admission.py's resolver):
        # with it set, tracked-pid-cap eviction is scoped PER TENANT, so
        # a pid-churn storm from one tenant can only flush that tenant's
        # own quarantine history, never another's. Set once at wiring
        # time (cli.py), before any recording.
        self.tenant_of = None
        self._lock = threading.Lock()
        self._pids: dict[int, _PidState] = {}  # guarded-by: _lock
        self.stats = {  # guarded-by: _lock
            "errors_total": 0,
            "deadline_trips_total": 0,
            "trips_total": 0,
            "recoveries_total": 0,
            "samples_degraded_total": 0,
            "windows_salvaged_total": 0,
            "pids_forgotten_total": 0,
        }

    def forget_pid(self, pid: int) -> None:
        """Generation-stamped identity invalidation (process/identity.py):
        the pid was RECYCLED, so its tracked strikes/trips/ladder state
        belongs to a dead predecessor — a fresh innocent process must
        start with a clean budget, and a fresh hostile one must re-earn
        its quarantine (the tick_window docstring has always named this
        exact hazard). Dropping under the lock is the whole operation;
        unknown pids are a no-op."""
        with self._lock:
            if self._pids.pop(int(pid), None) is not None:
                self.stats["pids_forgotten_total"] += 1

    # -- fault reporting -----------------------------------------------------

    # Hard bound on tracked pids: a hostile workload spawning erroring
    # short-lived processes must not grow the registry without limit
    # (oldest healthy entries are evicted first; quarantined ones never).
    _MAX_TRACKED = 65536

    def record_error(self, pid: int, site: str, exc: BaseException) -> int:
        """One attributable input fault for ``pid``; returns the pid's
        ladder level after recording."""
        tenant = self._tenant_for(pid)
        with self._lock:
            if int(pid) not in self._pids \
                    and len(self._pids) >= self._MAX_TRACKED \
                    and not self._evict_one_locked(tenant):
                # Every tracked entry is quarantined: refuse the insert
                # rather than exceed the bound (or flush containment
                # state); the fault is still counted.
                self.stats["errors_total"] += 1
                return LEVEL_FULL
            st = self._pids.setdefault(int(pid), _PidState(tenant=tenant))
            self.stats["errors_total"] += 1
            st.errored_this_window = True
            st.ok_windows = 0
            st.last_error = repr(exc)[:200]
            st.last_site = site
            if st.state == "quarantined":
                # Inputs should not be parsed while quarantined; a stray
                # report just refreshes the record.
                return st.level
            if st.state == "probation":
                # Still poisonous: re-trip immediately, doubled cooldown.
                self._trip(st, pid)
                return st.level
            st.strikes += 1
            if st.strikes > self._max_strikes:
                self._trip(st, pid)
            return st.level

    def record_deadline(self, pid: int, elapsed_s: float) -> int:
        """Per-pid processing-deadline overrun — a fault like any other
        (a pathological input that parses *slowly* poisons the window as
        surely as one that throws)."""
        level = self.record_error(
            pid, "deadline",
            TimeoutError(f"pid processing exceeded deadline "
                         f"({elapsed_s:.3f}s > {self.deadline_s}s)"))
        with self._lock:
            self.stats["deadline_trips_total"] += 1
        return level

    def _tenant_for(self, pid: int) -> str:
        """Tenant of a pid about to be tracked; "" without a resolver or
        on a resolver failure (eviction then falls back to the global
        rule — the resolver is itself fail-open, this is belt-and-
        braces). Called OUTSIDE the registry lock: the resolver takes
        its own lock and may touch /proc."""
        if self.tenant_of is None:
            return ""
        try:
            return str(self.tenant_of(int(pid)))
        except Exception:  # noqa: BLE001 - eviction scoping is best-effort
            return ""

    def _evict_one_locked(self, tenant: str = "") -> bool:  # palint: holds=_lock
        """Make room at the tracked-pid cap: evict the least-incriminated
        non-quarantined entry (fewest trips, then strikes, oldest first),
        so a churn of one-error pids can never flush a persistently
        poisonous pid's accumulated state. With a tenant resolved for the
        INCOMING pid, the victim is drawn from that pid's OWN tenant
        first — a pid-churn storm from one tenant then recycles its own
        slots and other tenants' quarantine history survives; only a
        tenant with nothing evictable falls back to the global scan.
        False when every candidate entry is quarantined (nothing
        evictable)."""
        scopes = ([lambda st: st.tenant == tenant, lambda st: True]
                  if tenant else [lambda st: True])
        for in_scope in scopes:
            victim = None
            victim_key = None
            for old, st in self._pids.items():
                if st.state == "quarantined" or not in_scope(st):
                    continue
                key = (st.trips, st.strikes)
                if victim is None or key < victim_key:
                    victim, victim_key = old, key
                    if key == (0, 0):
                        break  # nothing beats a clean watched entry
            if victim is not None:
                del self._pids[victim]
                return True
        return False

    def check_deadline(self, pid: int, t0: float) -> None:
        """Caller-timed deadline check: ``t0`` from ``registry.clock()``."""
        if self.deadline_s is None:
            return
        elapsed = self._clock() - t0
        if elapsed > self.deadline_s:
            self.record_deadline(pid, elapsed)

    def clock(self) -> float:
        return self._clock()

    # There is deliberately NO record_ok/ship-receipt API: clean-window
    # credit is granted by tick_window to every watched pid that did not
    # error, so strikes decay (and exited pids are forgotten) even on
    # paths that never report successes — an error-free window is the
    # absence of faults, not a ship receipt.

    # -- queries (lock-free reads of immutable snapshots are fine; these
    #    take the lock because dict mutation can race resize) ---------------

    def level(self, pid: int) -> int:
        with self._lock:
            st = self._pids.get(int(pid))
            return st.level if st is not None else LEVEL_FULL

    def is_quarantined(self, pid: int) -> bool:
        with self._lock:
            st = self._pids.get(int(pid))
            return st is not None and st.state == "quarantined"

    def quarantined_pids(self) -> list[int]:
        with self._lock:
            return sorted(p for p, st in self._pids.items()
                          if st.state == "quarantined")

    # -- window boundary -----------------------------------------------------

    def tick_window(self) -> None:
        """Advance every pid's state machine by one window; the profiler
        calls this once per iteration (quarantine time is WINDOW time —
        a stalled agent must not silently serve out cooldowns)."""
        with self._lock:
            salvaged = False
            drop = []
            for pid, st in self._pids.items():
                if st.state == "quarantined":
                    salvaged = True
                    st.cooldown -= 1
                    if st.cooldown <= 0:
                        st.state = "probation"
                        st.probation_left = self._probation
                        st.level = LEVEL_FULL  # probation = full, watched
                        _log.info("pid entering probation", pid=pid,
                                  trips=st.trips)
                elif st.state == "probation":
                    if not st.errored_this_window:
                        st.probation_left -= 1
                        if st.probation_left <= 0:
                            st.state = "healthy"
                            st.strikes = 0
                            st.ok_windows = 0
                            self.stats["recoveries_total"] += 1
                            _log.info("pid recovered from quarantine",
                                      pid=pid, trips=st.trips)
                else:  # healthy, but watched
                    if not st.errored_this_window:
                        # Clean-window credit is granted HERE, not via
                        # record_ok: a pid that exited (or a fast-encode
                        # run that never reports ship successes) must
                        # still decay its strikes and eventually be
                        # forgotten, or pid reuse hands an innocent new
                        # process a stale budget.
                        st.ok_windows += 1
                        if st.ok_windows >= self._healthy_after:
                            if st.strikes or st.trips:
                                # Sustained clean run refreshes the
                                # budget (supervisor healthy_after
                                # semantics).
                                st.strikes = 0
                                st.trips = 0
                                st.ok_windows = 0
                            else:
                                drop.append(pid)  # nothing to remember
                st.errored_this_window = False
            for pid in drop:
                del self._pids[pid]
            if salvaged:
                self.stats["windows_salvaged_total"] += 1

    def _trip(self, st: _PidState, pid: int) -> None:  # palint: holds=_lock
        # Lock held by caller.
        st.trips += 1
        st.state = "quarantined"
        st.level = (LEVEL_ADDRESSES if st.trips <= self._escalate_after
                    else LEVEL_SCALAR)
        st.cooldown = min(self._base_cooldown * (2 ** (st.trips - 1)),
                          self._max_cooldown)
        st.strikes = 0
        self.stats["trips_total"] += 1
        _log.warn("pid quarantined", pid=pid, trips=st.trips,
                  ladder=_LEVEL_NAMES[st.level],
                  cooldown_windows=st.cooldown,
                  site=st.last_site, error=st.last_error)

    # -- observability -------------------------------------------------------

    def counts(self) -> dict[str, int]:
        with self._lock:
            return self._counts_locked()

    def _counts_locked(self) -> dict[str, int]:  # palint: holds=_lock
        out = {"quarantined": 0, "probation": 0, "watched": 0,
               "level_addresses": 0, "level_scalar": 0}
        for st in self._pids.values():
            if st.state == "quarantined":
                out["quarantined"] += 1
                key = ("level_addresses"
                       if st.level == LEVEL_ADDRESSES
                       else "level_scalar")
                out[key] += 1
            elif st.state == "probation":
                out["probation"] += 1
            else:
                out["watched"] += 1
        return out

    def snapshot(self, limit: int = 100) -> dict:
        """JSON-shaped view for /healthz (bounded: a poisoned fleet must
        not turn the health endpoint into a megabyte dump)."""
        with self._lock:
            pids = {}
            for pid, st in sorted(self._pids.items())[:limit]:
                pids[str(pid)] = {
                    "state": st.state,
                    "level": _LEVEL_NAMES[st.level],
                    "strikes": st.strikes,
                    "trips": st.trips,
                    "cooldown_windows": st.cooldown,
                    "last_site": st.last_site,
                    "last_error": st.last_error,
                }
            return {"counts": self._counts_locked(),
                    "stats": dict(self.stats), "pids": pids}


# -- the degradation ladder over aggregated profiles -------------------------


def scalar_profile(prof):
    """Collapse one PidProfile to its scalar count: one depth-1 sample at
    (unmapped, unsymbolized) address 0 carrying the pid's total. The
    window's aggregate CPU accounting stays exact; everything else about
    the pid is withheld."""
    from parca_agent_tpu.aggregator.base import PidProfile

    return PidProfile(
        pid=prof.pid,
        stack_loc_ids=np.array([[1]], np.int32),
        stack_depths=np.array([1], np.int32),
        values=np.array([prof.total()], np.int64),
        loc_address=np.zeros(1, np.uint64),
        loc_normalized=np.zeros(1, np.uint64),
        loc_mapping_id=np.zeros(1, np.int32),
        loc_is_kernel=np.zeros(1, bool),
        mappings=[],
        period_ns=prof.period_ns,
        time_ns=prof.time_ns,
        duration_ns=prof.duration_ns,
    )


def apply_ladder(profiles, registry: QuarantineRegistry | None,
                 admission=None):
    """Route each profile down its pid's ladder level — the max of the
    quarantine registry's (poison containment) and the admission
    controller's (quota/overload fairness, runtime/admission.py) when
    both are wired. Level 0 passes through untouched; level 1 strips
    local symbolization artifacts (normalized addresses + build ids
    still travel — byte-identical to an unsymbolized profile through
    the pprof builder); level 2 becomes the scalar count. Never drops a
    profile, and degraded mass is charged to whichever layer demanded
    the deeper level."""
    if registry is None and admission is None:
        return list(profiles)
    out = []
    degraded_samples = 0
    admission_samples = 0
    for prof in profiles:
        q_lvl = registry.level(prof.pid) if registry is not None \
            else LEVEL_FULL
        a_lvl = admission.level_for(prof.pid) if admission is not None \
            else LEVEL_FULL
        lvl = max(q_lvl, a_lvl)
        if lvl == LEVEL_FULL:
            out.append(prof)
            continue
        if a_lvl > q_lvl:
            admission_samples += prof.total()
        else:
            degraded_samples += prof.total()
        if lvl == LEVEL_ADDRESSES:
            prof.functions = []
            prof.loc_lines = None
            out.append(prof)
        else:
            out.append(scalar_profile(prof))
    if degraded_samples:
        with registry._lock:
            registry.stats["samples_degraded_total"] += degraded_samples
    if admission_samples:
        admission.count_degraded(admission_samples)
    return out
