"""Device-runtime health: bounded bring-up and demote/promote supervision.

``runtime/quarantine.py`` owns per-pid trust; this module owns the
ACCELERATOR BACKEND's lifecycle. The failure mode it exists for is the
one the bench trajectory recorded twice (BENCH_r05: "device probe:
attempt hung >420s"): a wedged device runtime blocks *inside a C call*
— backend init, a dispatch, a fetch — that no exception ever leaves and
no thread can cancel. An always-on profiler must therefore (a) never
touch the backend from its capture loop without an abandonable guard,
and (b) never pay an unbounded backend *init*: bring-up probes run in a
THROWAWAY SUBPROCESS with a hard deadline and a kill, so a wedged init
costs one dead child, not a hung agent.

State machine (all transitions on the profiler's window clock — a
stalled agent must not silently serve out cooldowns):

    probing ──probe ok──────────────► healthy
       │ probe fail/hang                 │ dispatch hang, or
       ▼                                 │ failure_strikes consecutive
    degraded (CPU fallback) ◄────────────┘ dispatch errors
       │ cooldown windows (doubles per trip, capped), then
       │ k consecutive healthy probes (--device-promote-after), then
       │ ONE shadow window: device + CPU fallback both aggregate and
       │ the results must MATCH (the aggregator A/B gate — a device
       │ that answers promptly but wrongly stays demoted)
       ├──shadow match──────────────► healthy   (promotion)
       ├──shadow mismatch/hang──────► degraded  (doubled cooldown)
       └──trips > dead_after_trips──► dead      (fallback forever;
                                                 0 = keep re-probing)

While degraded every window ships via the CPU fallback: windows are
COUNTED (``fallback_windows_total``), never dropped. The profiler's
per-window hang watchdog (`profiler/cpu.py:_guarded`) reports into this
registry, so wedge accounting, cooldowns, and metrics live in one place;
`/metrics` and `/healthz` render :meth:`snapshot`.

Chaos sites: ``device.probe`` fires inside the probe thread,
``device.dispatch`` inside the profiler's guarded device call — both
accept the duration-bearing ``hang`` kind (utils/faults.py).
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time

from parca_agent_tpu.runtime import device_telemetry as dtel
from parca_agent_tpu.runtime.window_clock import (
    REFERENCE_WINDOW_S,
    check_window_s,
    windows_for,
)
from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.log import get_logger

_log = get_logger("device-health")

STATE_PROBING = "probing"
STATE_HEALTHY = "healthy"
STATE_DEGRADED = "degraded"
STATE_DEAD = "dead"

STATES = (STATE_PROBING, STATE_HEALTHY, STATE_DEGRADED, STATE_DEAD)

# One tiny device round trip: backend init + put + jit + fetch — the same
# aha-moment op bench.py's liveness probe runs. Printing "1" proves the
# whole path, not just that the import survived.
_PROBE_CODE = (
    "import numpy as np, jax\n"
    "x = jax.device_put(np.zeros(8, np.int32))\n"
    "print(int(np.asarray(jax.jit(lambda a: a + 1)(x))[0]))\n"
)


def subprocess_probe(timeout_s: float, code: str = _PROBE_CODE
                     ) -> tuple[bool, str]:
    """One backend bring-up probe in a throwaway subprocess, killed at
    ``timeout_s``. A wedged backend init cannot be cancelled from a
    thread (it hangs inside a C call), but a child process CAN be
    killed — this is the only hang-proof shape for the probe. Returns
    (ok, detail)."""
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, f"probe hung >{timeout_s:.0f}s (child killed)"
    except OSError as e:  # pragma: no cover - spawn failure is exotic
        return False, f"probe spawn failed: {e!r}"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()
        last = tail[-1][-200:] if tail else "no output"
        return False, f"probe rc={r.returncode}: {last}"
    if (r.stdout or "").strip().splitlines()[-1:] != ["1"]:
        return False, f"probe wrong output: {(r.stdout or '')[:80]!r}"
    return True, "ok"


class DeviceHealthRegistry:
    """The device-backend trust state machine (module docs above).

    ``probe`` is a zero-arg callable returning ``(ok, detail)`` — the
    CLI passes :func:`subprocess_probe`; ``None`` disables the probe
    phase entirely (cooldown expiry goes straight to the shadow window,
    the pre-registry retry semantics the profiler's embedded default
    keeps). Probes run on a daemon thread so the window loop never waits
    on one; a probe that outlives ``probe_deadline_s`` is counted as a
    hang and its eventual (stale) result ignored.

    All mutation is lock-protected: the profiler thread reports faults
    and ticks windows, probe threads deliver results, the HTTP thread
    reads snapshots.
    """

    def __init__(self, probe=None, probe_timeout_s: float = 60.0,
                 probe_deadline_s: float | None = None,
                 promote_after: int = 2,
                 cooldown_windows: int = 3,
                 max_cooldown_windows: int = 240,
                 failure_strikes: int = 3,
                 dead_after_trips: int = 0,
                 start_state: str = STATE_PROBING,
                 clock=time.monotonic,
                 window_s: float = REFERENCE_WINDOW_S):
        self._probe = probe
        self._probe_timeout = probe_timeout_s
        # Grace over the probe's own (subprocess) timeout: the in-process
        # deadline only exists for probes wedged BEFORE their own bound
        # can fire (a hung spawn, an injected hang at the site).
        self._probe_deadline = (probe_deadline_s
                                if probe_deadline_s is not None
                                else probe_timeout_s + 5.0)
        self._promote_after = max(0, promote_after)
        # Cooldowns are wall-time commitments expressed at the reference
        # 10 s window (runtime/window_clock.py): "3 windows before the
        # first re-probe" means ~30 s of CPU-fallback patience whatever
        # the cadence. Probe counts (promote_after) and failure strikes
        # are per-event and stay unconverted; probe deadlines are
        # already seconds. Exact identity at the reference cadence.
        check_window_s(window_s)
        self._base_cooldown = windows_for(cooldown_windows, window_s)
        self._max_cooldown = max(self._base_cooldown, windows_for(
            max_cooldown_windows, window_s))
        self._failure_strikes = max(1, failure_strikes)
        self._dead_after = max(0, dead_after_trips)
        self._clock = clock
        self._lock = threading.Lock()

        if start_state not in STATES:
            raise ValueError(f"unknown start state {start_state!r}")
        # The state machine below is mutated from the profiler thread
        # (window clock), the probe-result callback thread, and read
        # from the HTTP thread — everything rides _lock (palint
        # lock-discipline; the _locked-suffix helpers are annotated
        # holds=_lock).
        self.state = start_state            # guarded-by: _lock
        self.windows = 0                    # guarded-by: _lock
        self.trips = 0                      # guarded-by: _lock
        self.cooldown_left = 0              # guarded-by: _lock
        self.consecutive_ok_probes = 0      # guarded-by: _lock
        self.shadow_pending = False         # guarded-by: _lock
        self.wedged_at: int | None = None   # window of the last hang
        self.last_demote_window: int | None = None
        self.last_promote_window: int | None = None
        self.last_error: str = ""
        self._consec_failures = 0                    # guarded-by: _lock
        self._probe_gen = 0                          # guarded-by: _lock
        self._probe_started_at: float | None = None  # guarded-by: _lock
        self.stats = {  # guarded-by: _lock
            "probes_total": 0,
            "probes_ok": 0,
            "probes_failed": 0,   # == probes_total - probes_ok (invariant)
            "probes_hung": 0,     # the probes_failed that were deadline
            #                       overruns (BENCH_r05's failure mode)
            "hangs_total": 0,
            "dispatch_errors_total": 0,
            "demotions_total": 0,
            "promotions_total": 0,
            "shadow_windows_total": 0,
            "shadow_mismatches_total": 0,
            "fallback_windows_total": 0,
        }

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Kick off the bounded bring-up. With no probe configured the
        registry trusts the backend optimistically (the first guarded
        dispatch is itself watchdogged); with one, the agent captures on
        the CPU fallback until the probe child proves the backend out —
        a wedged init costs a killed child, never a hung agent."""
        with self._lock:
            if self.state != STATE_PROBING:
                return
            if self._probe is None:
                self.state = STATE_HEALTHY
                return
            self._launch_probe_locked()

    # -- profiler-facing decisions -------------------------------------------

    def window_mode(self) -> str:
        """What this window's aggregation should do: ``device`` (normal),
        ``shadow`` (run device AND fallback, compare, report via
        :meth:`record_shadow`), or ``fallback``. The caller additionally
        gates device/shadow on its own abandoned-call state — an
        abandoned dispatch may still be executing inside the
        aggregator."""
        with self._lock:
            if self.state == STATE_HEALTHY:
                return "device"
            if self.state == STATE_DEGRADED and self.shadow_pending:
                return "shadow"
            return "fallback"

    def record_dispatch_ok(self) -> None:
        with self._lock:
            self._consec_failures = 0

    def record_dispatch_error(self, exc: BaseException) -> None:
        """A device call that FAILED (raised) — one strike; repeated
        consecutive failures demote (a flapping backend is as useless as
        a wedged one, just cheaper to discover)."""
        with self._lock:
            self.stats["dispatch_errors_total"] += 1
            self.last_error = repr(exc)[:200]
            self._consec_failures += 1
            if self.state == STATE_HEALTHY \
                    and self._consec_failures >= self._failure_strikes:
                self._demote_locked("dispatch failures")

    def record_hang(self) -> None:
        """The guarded device call blew its watchdog and was abandoned.
        Demotes immediately — a hang is never a strike to accumulate
        (the next one would stall another window's deadline)."""
        with self._lock:
            self.stats["hangs_total"] += 1
            self.wedged_at = self.windows
            self.last_error = "device call hung (abandoned)"
            self.shadow_pending = False  # a shadow that hung failed too
            self._demote_locked("dispatch hang")

    def record_fallback_window(self) -> None:
        with self._lock:
            self.stats["fallback_windows_total"] += 1

    def record_shadow(self, matched: bool, error: str = "") -> None:
        """Outcome of the promotion gate's A/B window."""
        with self._lock:
            self.stats["shadow_windows_total"] += 1
            self.shadow_pending = False
            if matched:
                trips_survived = self.trips
                self.state = STATE_HEALTHY
                self.trips = 0
                self.cooldown_left = 0
                self.consecutive_ok_probes = 0
                self._consec_failures = 0
                self.wedged_at = None
                self.last_promote_window = self.windows
                self.stats["promotions_total"] += 1
                dtel.note_backend("device", resolved="device",
                                  fallback=False)
                _log.info("device promoted: shadow window matched the "
                          "CPU fallback", window=self.windows,
                          trips_survived=trips_survived)
                return
            self.stats["shadow_mismatches_total"] += 1
            self.last_error = error or "shadow window mismatched the CPU " \
                                       "fallback"
            _log.warn("device promotion refused: shadow window did not "
                      "match the CPU fallback; re-demoting",
                      error=self.last_error)
            self._demote_locked("shadow mismatch")

    # -- the window clock ----------------------------------------------------

    def tick_window(self) -> None:
        """Advance cooldowns and drive re-probes; the profiler calls this
        once per iteration (window time, like the quarantine registry)."""
        probe_needed = False
        with self._lock:
            self.windows += 1
            self._check_probe_deadline_locked()
            if self.state != STATE_DEGRADED or self.shadow_pending:
                return
            if self.cooldown_left > 0:
                self.cooldown_left -= 1
                if self.cooldown_left > 0:
                    return
            if self._probe is None \
                    or self.consecutive_ok_probes >= self._promote_after:
                # Promotion gate's last hurdle: the next window runs the
                # device in the fallback's shadow.
                self.shadow_pending = True
                return
            if self._probe_started_at is None:
                probe_needed = True
                window = self.windows  # captured under the lock: the
                #                        log below runs after release
                self._launch_probe_locked()
        if probe_needed:
            _log.debug("device re-probe launched", window=window)

    # -- probes --------------------------------------------------------------

    def _launch_probe_locked(self) -> None:  # palint: holds=_lock
        self._probe_gen += 1
        self._probe_started_at = self._clock()
        self.stats["probes_total"] += 1
        threading.Thread(target=self._run_probe, args=(self._probe_gen,),
                         name="device-probe", daemon=True).start()

    def _run_probe(self, gen: int) -> None:
        try:
            faults.inject("device.probe")
            ok, detail = self._probe()
        except BaseException as e:  # noqa: BLE001 - a broken probe = failed
            ok, detail = False, repr(e)[:200]
        self._on_probe_result(gen, bool(ok), str(detail))

    def _check_probe_deadline_locked(self) -> None:  # palint: holds=_lock
        """A probe that outlived its deadline is a HANG: count it failed
        now and ignore its eventual result (generation bump). The probe
        subprocess bounds itself; this catches wedged spawns and
        injected in-process hangs."""
        if self._probe_started_at is None:
            return
        if self._clock() - self._probe_started_at <= self._probe_deadline:
            return
        self._probe_gen += 1  # stale result will be dropped
        self._probe_started_at = None
        self.stats["probes_failed"] += 1
        self.stats["probes_hung"] += 1
        self._note_probe_failed_locked(
            f"probe overran its deadline ({self._probe_deadline:.0f}s)")

    def _on_probe_result(self, gen: int, ok: bool, detail: str) -> None:
        with self._lock:
            if gen != self._probe_gen or self.state == STATE_DEAD:
                return  # stale (deadline already charged it) or moot
            self._probe_started_at = None
            if ok:
                self.stats["probes_ok"] += 1
                self.consecutive_ok_probes += 1
                if self.state == STATE_PROBING:
                    # Bring-up: the backend proved out; no shadow needed,
                    # there is nothing demoted to distrust yet.
                    self.state = STATE_HEALTHY
                    _log.info("device backend probe ok; starting on the "
                              "device", detail=detail)
                elif self.state == STATE_DEGRADED \
                        and self.consecutive_ok_probes < self._promote_after:
                    # More consecutive probes wanted: next window's tick
                    # launches the next one.
                    self.cooldown_left = 0
                return
            self.stats["probes_failed"] += 1
            self._note_probe_failed_locked(detail)

    def _note_probe_failed_locked(self, detail: str) -> None:  # palint: holds=_lock
        self.consecutive_ok_probes = 0
        self.last_error = detail[:200]
        _log.warn("device probe failed", error=self.last_error,
                  trips=self.trips)
        self._demote_locked("probe failure")

    # -- transitions ---------------------------------------------------------

    def _demote_locked(self, reason: str) -> None:  # palint: holds=_lock
        """One more trip: enter (or stay in) degraded with a doubled,
        capped cooldown; past the trip budget, dead."""
        self.trips += 1
        self.consecutive_ok_probes = 0
        self.shadow_pending = False
        self.cooldown_left = min(
            self._base_cooldown * (2 ** (self.trips - 1)),
            self._max_cooldown)
        if self.state != STATE_DEGRADED:
            self.last_demote_window = self.windows
            self.stats["demotions_total"] += 1
        if self._dead_after and self.trips > self._dead_after:
            self.state = STATE_DEAD
            _log.error("device re-probe budget exhausted; backend marked "
                       "dead (CPU fallback is permanent)",
                       trips=self.trips, reason=reason,
                       error=self.last_error)
            return
        prev = self.state
        self.state = STATE_DEGRADED
        # Latch the demotion into the device flight recorder's backend
        # gauges: a node running its windows on the CPU fallback must be
        # visible from /metrics next to the per-kernel pallas/lax state.
        dtel.note_backend("device", resolved="cpu_fallback", fallback=True)
        if prev != STATE_DEGRADED:
            _log.warn("device demoted to the CPU fallback", reason=reason,
                      window=self.windows, cooldown_windows=self.cooldown_left,
                      trips=self.trips)

    # -- observability -------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-shaped view for /healthz and the bench artifact."""
        with self._lock:
            return {
                "state": self.state,
                "windows": self.windows,
                "trips": self.trips,
                "cooldown_windows_left": self.cooldown_left,
                "consecutive_ok_probes": self.consecutive_ok_probes,
                "shadow_pending": self.shadow_pending,
                "probe_in_flight": self._probe_started_at is not None,
                "wedged_at_window": self.wedged_at,
                "last_demote_window": self.last_demote_window,
                "last_promote_window": self.last_promote_window,
                "last_error": self.last_error,
                "stats": dict(self.stats),
            }
