"""Regression sentinel: continuous baseline-diff drift detection.

The agent aggregates (encode pipeline), indexes (hotspot store), and
exports (sinks, incl. AutoFDO profdata) profiles — this module is the
first subsystem that COMPARES them across time. A deploy that doubles a
function's cost, or drift that silently invalidates an emitted PGO
profile, should be a verdict on `/diff` and a marker beside the
profdata file, not a human staring at dashboards ("From Profiling to
Optimization", arxiv 2507.16649: stale profiles actively hurt PGO
builds; Atys, arxiv 2506.15523: fleet-scale hotspot analysis must ride
hierarchical aggregates, not raw profiles).

The unit of judgment is a 1-minute ROLLUP per (build-id, tenant) group:
every shipped window's rows are attributed by leaf binary (the same
build-id keying the AutoFDO sink uses, so staleness verdicts address
the same profdata files) and tenant label, then folded into the group's
open rollup — an exact bounded top-key table plus a count-min sketch
backstop, the hotspot store's candidate/cut design one level down. When
a rollup seals it is diffed against the group's BASELINE:

  * the baseline is a frozen merge of the group's first
    ``baseline_rollups`` sealed rollups, content-addressed (its id is a
    digest of its own bytes) and persisted with the statics_store
    crash-only tmp+rename discipline, adopted at startup;
  * the diff is sketch subtraction (ops/sketch.cm_sub) with the
    propagated two-sided count-min error bound
    ``eps * (total_cur + total_base)`` plus EXACT deltas on the tracked
    top keys;
  * a per-key noise floor is learned from historical rollup-to-rollup
    variance (EWMA of |delta|); unlearned keys default to a Poisson-ish
    ``sqrt(base)`` floor;
  * a verdict (``new_hotspot`` / ``regressed`` / ``improved``) fires
    only when the shift clears BOTH the noise floor (times ``k_sigma``)
    and the sketch error bound, plus an absolute ``min_count`` and a
    relative ``min_ratio`` — four gates, so 30 clean windows produce
    zero verdicts (the bench bar) while a genuine 2x shift clears all
    four within two rollup intervals;
  * a group whose normalized distribution distance vs its baseline
    exceeds ``drift_threshold`` (EWMA-smoothed, edge-triggered) emits a
    ``drifted`` verdict and calls the staleness hook — the AutoFDO sink
    marks that binary's profdata stale so downstream PGO refreshes.

Where the work runs: :meth:`fold_from_prepared` is the encode-pipeline
WORKER's rider, beside the hotspot rollup and statics snapshot hooks —
fail-open by contract (``regression.fold`` chaos site): an injected or
real failure is counted (``fold_errors``) and costs judgment freshness,
never a window, and can never delay the pprof ship (the fold runs after
it). Persistence rides the same worker (``regression.baseline`` site):
a failed save/adopt is counted and the sentinel stays warm-less, agent
unharmed. Queries (/diff) run on HTTP threads under one lock.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import json
import math
import struct
import threading
import time

import numpy as np

from parca_agent_tpu.ops.sketch import CountMinSpec, cm_add, cm_query, cm_sub
from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.log import get_logger
from parca_agent_tpu.utils.vfs import atomic_write_bytes

# palint: persistence-root — frozen baselines are adopted at startup.

_log = get_logger("regression")

VERDICT_KINDS = ("new_hotspot", "regressed", "improved", "drifted")

_MAGIC = b"PAREGR1"
_FMARK = b"PRRC"                # per-frame marker (resync anchor)
_FRAME = struct.Struct("<II")   # payload len, crc32(payload)
_U32 = struct.Struct("<I")


@dataclasses.dataclass(frozen=True)
class RegressionSpec:
    """Sizing and sensitivity of the sentinel. The defaults detect a 2x
    shift on a hot binary within two rollup intervals while holding 30+
    clean windows verdict-free (the bench-regress acceptance bars)."""

    interval_s: float = 60.0        # rollup bucket span
    baseline_rollups: int = 5       # sealed rollups frozen into a baseline
    k_sigma: float = 4.0            # noise-floor multiplier
    min_count: int = 16             # absolute per-verdict count floor
    min_ratio: float = 1.5          # relative shift a verdict must clear
    drift_threshold: float = 0.5    # EWMA distribution distance -> stale
    max_groups: int = 256           # (build, tenant) groups tracked
    max_keys: int = 4096            # exact keys tracked per group
    fold_rows: int = 8192           # rows attributed per window (top by count)
    max_verdicts_per_rollup: int = 16
    repeat_every: int = 10          # rollups before re-emitting a held verdict
    verdict_ring: int = 1024
    save_every: int = 5             # baseline saves at most every N seals
    cm: CountMinSpec = CountMinSpec(depth=4, width=1 << 10)

    def __post_init__(self):
        if not (self.interval_s > 0):
            raise ValueError("interval_s must be > 0")
        if self.baseline_rollups < 1:
            raise ValueError("baseline_rollups must be >= 1")
        if self.k_sigma <= 0 or self.min_ratio < 1.0:
            raise ValueError("k_sigma must be > 0 and min_ratio >= 1.0")
        if not (0 < self.drift_threshold <= 1.0):
            raise ValueError("drift_threshold must be in (0, 1]")
        if self.max_groups < 1 or self.max_keys < 16:
            raise ValueError("max_groups >= 1 and max_keys >= 16 required")


class _Baseline:
    """A frozen merge of the group's first rollups: exact per-key totals
    plus the merged count-min table. Content-addressed: ``ident`` is a
    digest of the serialized content, so two agents that froze the same
    traffic agree on the id and a corrupted record can never adopt."""

    __slots__ = ("counts", "cm", "total", "rollups", "created_ns", "ident")

    def __init__(self, counts, cm_table, total, rollups, created_ns):
        self.counts: dict[int, int] = counts
        self.cm = cm_table
        self.total = int(total)
        self.rollups = int(rollups)
        self.created_ns = int(created_ns)
        self.ident = self._digest()

    def _digest(self) -> str:
        h = hashlib.sha256()
        h.update(struct.pack("<QQ", self.total, self.rollups))
        for k in sorted(self.counts):
            h.update(struct.pack("<Qq", k, self.counts[k]))
        h.update(np.ascontiguousarray(self.cm).tobytes())
        return h.hexdigest()[:16]

    def rate(self, key: int) -> float:
        """Per-rollup baseline rate for one stack key (exact for tracked
        keys, 0 for untracked — the cm upper bound rides separately)."""
        return self.counts.get(key, 0) / self.rollups


class _Group:
    """One (build-id, tenant) judgment stream: the open rollup, the
    learned noise floors, the frozen baseline, and the drift latch."""

    __slots__ = ("build", "tenant", "synthetic",
                 "open_counts", "open_cm", "open_total", "open_windows",
                 "open_t0_ns", "open_until_ns",
                 "pending_counts", "pending_cm", "pending_total",
                 "pending_rollups", "baseline",
                 "floor", "last_counts", "last_total",
                 "drift", "stale_marked", "rollups_sealed", "active")

    def __init__(self, build: str, tenant: str, spec: RegressionSpec):
        self.build = build
        self.tenant = tenant
        # Kernel/unmapped leaves are judged like any binary but have no
        # profdata file to mark stale.
        self.synthetic = build in ("kernel", "unmapped")
        self.open_counts: dict[int, int] = {}
        self.open_cm = np.zeros((spec.cm.depth, spec.cm.width), np.int64)
        self.open_total = 0
        self.open_windows = 0
        self.open_t0_ns = 0
        self.open_until_ns = 0
        self.pending_counts: dict[int, int] = {}
        self.pending_cm = np.zeros((spec.cm.depth, spec.cm.width), np.int64)
        self.pending_total = 0
        self.pending_rollups = 0
        self.baseline: _Baseline | None = None
        self.floor: dict[int, float] = {}      # key -> EWMA |rollup delta|
        self.last_counts: dict[int, int] | None = None
        self.last_total = 0
        self.drift = 0.0
        self.stale_marked = False
        self.rollups_sealed = 0
        self.active: dict[int, tuple[str, int]] = {}  # key -> (kind, seal#)

    def reset_open(self, t0_ns: int, span_ns: int) -> None:
        self.open_counts = {}
        self.open_cm.fill(0)
        self.open_total = 0
        self.open_windows = 0
        self.open_t0_ns = t0_ns
        self.open_until_ns = (t0_ns // span_ns + 1) * span_ns


def _top_keys(counts: dict[int, int], k: int) -> list[int]:
    if len(counts) <= k:
        return list(counts)
    return sorted(counts, key=counts.__getitem__, reverse=True)[:k]


class RegressionSentinel:
    """Continuous baseline-diff over the per-(build, tenant) rollup
    stream.

    Thread model: fold_from_prepared runs on the encode pipeline's
    worker (the rollup/snapshot hooks' twin); verdicts()/diff_ranges()/
    metrics()/snapshot() on HTTP threads; drain_alerts() on whichever
    thread the alerts sink emits from (worker for pipelined windows,
    profiler for inline fallbacks). One lock guards groups, counters,
    and the verdict/alert rings; per-window attribution (the numpy/loop
    work) runs outside it.
    """

    def __init__(self, spec: RegressionSpec = RegressionSpec(),
                 path: str | None = None, labels_for=None,
                 clock=time.time, adopt: bool = True):
        self.spec = spec
        self.path = path
        # pid -> label dict hook (the profiler installs its lock-guarded
        # labels manager, exactly like the hotspot store); the "tenant"
        # label is the group axis. None = single "default" tenant.
        self.labels_for = labels_for
        self._clock = clock
        self._lock = threading.Lock()
        self._groups: dict[tuple[str, str], _Group] = {}
        self._verdicts: collections.deque = collections.deque(
            maxlen=spec.verdict_ring)
        self._alerts: collections.deque = collections.deque(maxlen=4096)
        self._mark_stale = None     # AutoFDO staleness hook (bind_staleness)
        self._stale_pending: list[str] = []  # guarded-by: _lock
        self._seals_unsaved = 0
        self._tenant_memo: dict[int, str] = {}
        self.stats = {  # guarded-by: _lock
            "windows_folded": 0,
            "windows_skipped": 0,    # no registry view: rows unreadable
            "fold_errors": 0,
            "rollups_sealed": 0,
            "groups_dropped": 0,
            "keys_overflow": 0,
            "rows_dropped": 0,
            "verdicts_suppressed": 0,
            "alerts_dropped": 0,
            "baselines_frozen": 0,
            "baseline_saves": 0,
            "baseline_save_errors": 0,
            "baselines_adopted": 0,
            "baseline_adopt_errors": 0,
            "stale_marks": 0,
            "stale_mark_errors": 0,
            "queries": 0,
            "query_errors": 0,
            "last_fold_s": 0.0,
        }
        self._verdict_counts = {k: 0 for k in VERDICT_KINDS}  # guarded-by: _lock
        if adopt and path:
            self._adopt()

    # -- wiring --------------------------------------------------------------

    def bind_staleness(self, hook) -> None:
        """Install the AutoFDO staleness hook: ``hook(build_key)`` is
        called (fail-open, counted) when a group's drift crosses the
        threshold — sinks/autofdo.py marks that binary's profdata stale."""
        self._mark_stale = hook

    # -- fold path (encode-pipeline worker) ----------------------------------

    # palint: fail-open
    def fold_from_prepared(self, view, prep) -> None:
        """Attribute one shipped window's rows by (leaf build-id, tenant)
        and fold them into the group rollups, sealing and judging any
        bucket the window clock closed. The encode worker's rider, after
        the ship: fail-open by contract — an injected
        (``regression.fold``) or real failure is counted and costs this
        window's judgment, never the window or the pprof bytes."""
        try:
            t0 = time.perf_counter()
            faults.inject("regression.fold")
            if view is None:
                with self._lock:
                    self.stats["windows_skipped"] += 1
                return
            self._fold(view, prep)
            self._flush_stale_marks()
            with self._lock:
                self.stats["windows_folded"] += 1
                self.stats["last_fold_s"] = time.perf_counter() - t0
            if self.path and self._seals_unsaved >= self.spec.save_every:
                self.save()
        except Exception as e:  # noqa: BLE001 - fail-open contract
            with self._lock:
                self.stats["fold_errors"] += 1
            _log.warn("regression fold failed; window unjudged",
                      error=repr(e))

    def _fold(self, view, prep) -> None:
        spec = self.spec
        idx = np.asarray(prep.idx)
        n = len(idx)
        span_ns = int(spec.interval_s * 1e9)
        now_ns = int(prep.time_ns)
        if n:
            vals = np.asarray(prep.vals, np.int64)
            h1, h2 = view.id_hashes(int(idx.max()) + 1)
            rh1 = h1[idx]
            key64 = ((rh1.astype(np.uint64) << np.uint64(32))
                     | h2[idx].astype(np.uint64))
            leaf = view._loc_flat[view._loc_off[idx]]
            pids = np.asarray(prep.pids_live)
            rows = np.arange(n)
            if n > spec.fold_rows:
                # Bounded attribution: the hottest rows carry the
                # regression signal; the tail is counted, not judged.
                part = np.argpartition(vals, n - spec.fold_rows)
                rows = part[n - spec.fold_rows:]
                with self._lock:
                    self.stats["rows_dropped"] += n - spec.fold_rows
            batches: dict[tuple[str, str], list] = {}
            caps = prep.caps
            for i in rows.tolist():
                pid = int(pids[i])
                build = self._build_for(caps.get(pid), int(leaf[i]))
                tenant = self._tenant_for(pid)
                b = batches.get((build, tenant))
                if b is None:
                    b = batches[(build, tenant)] = [[], [], []]
                b[0].append(int(key64[i]))
                b[1].append(int(rh1[i]))
                b[2].append(int(vals[i]))
        else:
            batches = {}
        with self._lock:
            # Seal every group the window clock has passed — including
            # untouched ones: a binary that vanished from the profile
            # (a deploy) must still be judged against its baseline.
            for g in self._groups.values():
                if g.open_until_ns and now_ns >= g.open_until_ns:
                    self._seal(g, span_ns, now_ns)
            for (build, tenant), (keys, h1s, counts) in batches.items():
                g = self._groups.get((build, tenant))
                if g is None:
                    if len(self._groups) >= spec.max_groups:
                        self.stats["groups_dropped"] += 1
                        continue
                    g = _Group(build, tenant, spec)
                    g.reset_open(now_ns, span_ns)
                    self._groups[(build, tenant)] = g
                if not g.open_until_ns:
                    g.reset_open(now_ns, span_ns)
                oc = g.open_counts
                for k, v in zip(keys, counts):
                    if k in oc:
                        oc[k] += v
                    elif len(oc) < spec.max_keys:
                        oc[k] = v
                    else:
                        # Past the exact-key cap the sketch still holds
                        # the mass — the diff falls back to cm bounds.
                        self.stats["keys_overflow"] += 1
                cm_add(g.open_cm, np.asarray(h1s, np.uint32),
                       np.asarray(counts, np.int64), spec.cm)
                g.open_total += int(sum(counts))
                g.open_windows += 1

    def _build_for(self, cap, leaf_loc: int) -> str:
        """Leaf binary key for one row, through the per-pid registry cap
        (the AutoFDO sink's attribution, sharing its keying so staleness
        verdicts address the same profdata files)."""
        from parca_agent_tpu.sinks.autofdo import binary_key

        j = leaf_loc - 1  # registry loc ids are 1-based
        if cap is None or not (0 <= j < cap[2]):
            return "unmapped"
        reg = cap[0]
        if reg.loc_is_kernel[j]:
            return "kernel"
        mid = int(reg.loc_mapping_id[j])
        if not (1 <= mid <= cap[1]):
            return "unmapped"
        return binary_key(reg.mappings[mid - 1])

    def _tenant_for(self, pid: int) -> str:
        tenant = self._tenant_memo.get(pid)
        if tenant is not None:
            return tenant
        tenant = "default"
        if self.labels_for is not None:
            labels = self.labels_for(pid)
            if labels:
                tenant = str(labels.get("tenant") or "default")
        if len(self._tenant_memo) > 8192:
            self._tenant_memo.clear()
        self._tenant_memo[pid] = tenant
        return tenant

    # -- sealing + judgment (worker thread, under _lock) ---------------------

    # palint: holds=_lock
    def _seal(self, g: _Group, span_ns: int, now_ns: int) -> None:
        counts = g.open_counts
        total = g.open_total
        cm_table = g.open_cm.copy()
        t1_ns = g.open_until_ns
        g.rollups_sealed += 1
        self.stats["rollups_sealed"] += 1
        spec = self.spec
        if g.baseline is None:
            for k, v in counts.items():
                if k in g.pending_counts:
                    g.pending_counts[k] += v
                elif len(g.pending_counts) < spec.max_keys:
                    g.pending_counts[k] = v
            g.pending_cm += cm_table
            g.pending_total += total
            g.pending_rollups += 1
            if g.pending_rollups >= spec.baseline_rollups:
                g.baseline = _Baseline(
                    g.pending_counts, g.pending_cm.copy(),
                    g.pending_total, g.pending_rollups, t1_ns)
                g.pending_counts = {}
                g.pending_cm.fill(0)
                g.pending_total = 0
                self.stats["baselines_frozen"] += 1
                self._seals_unsaved = spec.save_every  # save at next fold
        else:
            self._judge(g, counts, cm_table, total, t1_ns)
            self._seals_unsaved += 1
        self._learn_floor(g, counts)
        g.last_counts = counts
        g.last_total = total
        # Re-open, aligned to the bucket grid the window clock sits in
        # (reset_open replaces the counts dict, so the `counts`
        # reference kept as last_counts above stays intact, and zeroes
        # the cm in place — cm_table was copied at the top).
        g.reset_open(max(now_ns, t1_ns), span_ns)

    # palint: holds=_lock
    def _learn_floor(self, g: _Group, counts: dict[int, int]) -> None:
        """Per-key noise floor: EWMA of |rollup-to-rollup delta| over the
        union of the previous and current top keys — the historical
        window-to-window variance a verdict must clear."""
        if g.last_counts is None:
            return
        spec = self.spec
        keys = set(_top_keys(counts, spec.max_verdicts_per_rollup * 4))
        keys.update(_top_keys(g.last_counts,
                              spec.max_verdicts_per_rollup * 4))
        floor = g.floor
        for k in keys:
            d = abs(counts.get(k, 0) - g.last_counts.get(k, 0))
            prev = floor.get(k)
            floor[k] = d if prev is None else 0.7 * prev + 0.3 * d
        while len(floor) > spec.max_keys:
            floor.pop(next(iter(floor)))

    # palint: holds=_lock
    def _judge(self, g: _Group, counts: dict[int, int], cm_table,
               total: int, t1_ns: int) -> None:
        spec = self.spec
        base = g.baseline
        base_rate_total = base.total / base.rollups
        # Propagated two-sided sketch bound for keys either side only
        # estimates (ops/sketch.cm_sub contract).
        err_bound = spec.cm.epsilon * (total + base_rate_total)
        diff_cm = cm_sub(cm_table, base.cm / base.rollups)
        cand = set(_top_keys(counts, spec.max_verdicts_per_rollup * 4))
        cand.update(_top_keys(base.counts,
                              spec.max_verdicts_per_rollup * 4))
        found = []
        for k in cand:
            cur = counts.get(k)
            cur_exact = cur is not None
            if cur is None:
                cur = 0 if total == 0 else max(0, int(cm_query(
                    cm_table, np.asarray([k >> 32], np.uint32),
                    spec.cm)[0]))
            base_rate = base.rate(k)
            base_exact = k in base.counts or base.total == 0
            delta = cur - base_rate
            # The learned floor can dip below a Poisson stream's true
            # variance on an unlucky EWMA run; sqrt(base) is the
            # physical lower bound for counting noise, so it backstops
            # the learned value. The sketch bound then ADDS to the
            # noise gate rather than competing with it — a shift must
            # clear both stacked, which is what holds 30+ clean Poisson
            # windows at zero verdicts while a 2x shift (delta ~= base)
            # still clears in one rollup.
            floor = max(g.floor.get(k, 0.0),
                        math.sqrt(max(base_rate, 1.0)))
            threshold = err_bound + max(spec.k_sigma * floor,
                                        float(spec.min_count))
            kind = None
            if delta > threshold and cur >= base_rate * spec.min_ratio:
                kind = ("new_hotspot"
                        if base_rate <= max(err_bound, 1.0) else "regressed")
            elif -delta > threshold and cur <= base_rate / spec.min_ratio:
                kind = "improved"
            if kind is None:
                g.active.pop(k, None)  # shift subsided: latch clears
                continue
            held = g.active.get(k)
            if held is not None and held[0] == kind \
                    and g.rollups_sealed - held[1] < spec.repeat_every:
                self.stats["verdicts_suppressed"] += 1
                continue
            g.active[k] = (kind, g.rollups_sealed)
            found.append({
                "kind": kind,
                "stack": f"0x{k:016x}",
                "current": int(cur),
                "baseline": round(base_rate, 2),
                "delta": round(delta, 2),
                "threshold": round(threshold, 2),
                "noise_floor": round(floor, 2),
                "error_bound": round(err_bound, 2),
                "exact": bool(cur_exact and base_exact),
            })
        found.sort(key=lambda v: abs(v["delta"]), reverse=True)
        if len(found) > spec.max_verdicts_per_rollup:
            self.stats["verdicts_suppressed"] += \
                len(found) - spec.max_verdicts_per_rollup
            found = found[: spec.max_verdicts_per_rollup]
        for v in found:
            self._emit(g, t1_ns, v)
        self._judge_drift(g, counts, total, t1_ns, diff_cm)

    # palint: holds=_lock
    def _judge_drift(self, g: _Group, counts: dict[int, int], total: int,
                     t1_ns: int, diff_cm) -> None:
        """Distribution-level drift: normalized L1 distance between the
        rollup's and the baseline's per-key mass over the tracked keys.
        EWMA-smoothed and edge-triggered — one ``drifted`` verdict (and
        one staleness mark) per excursion, re-armed only after the score
        falls back below half the threshold."""
        spec = self.spec
        base = g.baseline
        if total == 0 and base.total == 0:
            d = 0.0
        elif total == 0 or base.total == 0:
            d = 1.0
        else:
            keys = set(counts) | set(base.counts)
            d = 0.5 * sum(
                abs(counts.get(k, 0) / total
                    - base.counts.get(k, 0) / base.total)
                for k in keys)
        g.drift = 0.7 * g.drift + 0.3 * min(d, 1.0)
        if g.drift > spec.drift_threshold and not g.stale_marked:
            g.stale_marked = True
            self._emit(g, t1_ns, {
                "kind": "drifted",
                "stack": None,
                "current": int(total),
                "baseline": round(base.total / base.rollups, 2),
                "delta": None,
                "threshold": spec.drift_threshold,
                "noise_floor": None,
                "error_bound": round(float(np.abs(diff_cm).max()), 2),
                "exact": False,
                "drift": round(g.drift, 4),
            })
            if self._mark_stale is not None and not g.synthetic:
                # The hook is a DISK write (autofdo .stale marker):
                # queued here and flushed by fold_from_prepared after
                # the lock drops, so a hung filesystem can never freeze
                # /metrics //healthz //diff behind this lock.
                self._stale_pending.append(g.build)
        elif g.stale_marked and g.drift < spec.drift_threshold / 2:
            g.stale_marked = False

    # palint: holds=_lock
    def _emit(self, g: _Group, t1_ns: int, verdict: dict) -> None:
        rec = {
            "t_s": round(t1_ns / 1e9, 3),
            "tenant": g.tenant,
            "build": g.build,
            "baseline_id": g.baseline.ident if g.baseline else None,
            **verdict,
        }
        self._verdict_counts[rec["kind"]] += 1
        self._verdicts.append(rec)
        if len(self._alerts) == self._alerts.maxlen:
            self.stats["alerts_dropped"] += 1
        self._alerts.append(rec)

    def _flush_stale_marks(self) -> None:
        """Run the queued AutoFDO staleness marks OUTSIDE the lock (the
        hook writes a marker file; a hung disk must stall only this
        worker's judgment, never an HTTP scrape). Worker thread only."""
        with self._lock:
            pending, self._stale_pending = self._stale_pending, []
        for build in pending:
            try:
                self._mark_stale(build)
                with self._lock:
                    self.stats["stale_marks"] += 1
            except Exception as e:  # noqa: BLE001 - hook is best-effort
                with self._lock:
                    self.stats["stale_mark_errors"] += 1
                _log.warn("autofdo staleness mark failed",
                          build=build, error=repr(e))

    # -- alert drain (sinks/alerts.py) ---------------------------------------

    def drain_alerts(self) -> list[dict]:
        """Pop every pending verdict record for the alerts sink (bounded
        by the ring; a sink outage costs the oldest alerts, counted)."""
        with self._lock:
            out = list(self._alerts)
            self._alerts.clear()
        return out

    def requeue_alerts(self, records: list[dict]) -> None:
        """Put drained-but-unwritten records back at the FRONT of the
        ring (the alerts sink's append failed): they retry at the next
        window's drain, oldest-first order preserved. Past the ring
        bound the oldest are dropped, counted — a long disk outage
        costs the oldest alerts, never the newest."""
        with self._lock:
            room = self._alerts.maxlen - len(self._alerts)
            if len(records) > room:
                self.stats["alerts_dropped"] += len(records) - room
                records = records[len(records) - room:]
            self._alerts.extendleft(reversed(records))

    # -- query path (HTTP threads) -------------------------------------------

    def count_query_error(self) -> None:
        """Bad-parameter accounting for /diff handler threads (the
        hotspot store's count_query_error twin)."""
        with self._lock:
            self.stats["query_errors"] += 1

    def verdicts(self, tenant: str | None = None, build: str | None = None,
                 kind: str | None = None, since_s: float | None = None,
                 limit: int = 100) -> dict:
        """Recent verdicts (newest first) plus per-group judgment state."""
        if kind is not None and kind not in VERDICT_KINDS:
            raise ValueError(f"kind must be one of {VERDICT_KINDS}")
        limit = max(1, min(int(limit), self.spec.verdict_ring))
        with self._lock:
            self.stats["queries"] += 1
            out = []
            for rec in reversed(self._verdicts):
                if tenant is not None and rec["tenant"] != tenant:
                    continue
                if build is not None and rec["build"] != build:
                    continue
                if kind is not None and rec["kind"] != kind:
                    continue
                if since_s is not None and rec["t_s"] < since_s:
                    continue
                out.append(rec)
                if len(out) >= limit:
                    break
            groups = [{
                "build": g.build,
                "tenant": g.tenant,
                "baseline_id": g.baseline.ident if g.baseline else None,
                "baseline_rollups": g.baseline.rollups if g.baseline else 0,
                "baseline_total": g.baseline.total if g.baseline else 0,
                "rollups_sealed": g.rollups_sealed,
                "tracked_keys": len(g.open_counts),
                "last_total": g.last_total,
                "drift": round(g.drift, 4),
                "stale_marked": g.stale_marked,
            } for g in self._groups.values()
                if tenant is None or g.tenant == tenant]
            counts = dict(self._verdict_counts)
        return {"verdicts": out, "groups": groups,
                "verdict_counts": counts,
                "interval_s": self.spec.interval_s}

    def diff_ranges(self, store, a0_s: float, a1_s: float, b0_s: float,
                    b1_s: float, k: int | None = None,
                    selector: dict | None = None,
                    scope: str = "local") -> dict:
        """On-demand diff of two time ranges over the hotspot store's
        rollup hierarchy (range A minus range B), every entry carrying
        exact/estimate bounds: ``delta`` is the candidate-exact
        difference, ``delta_min``/``delta_max`` bracket the true shift
        using each side's count-min estimate and cut (the upper bound on
        any key absent from a candidate table)."""
        qa = store.query(k=k, t0_s=a0_s, t1_s=a1_s, selector=selector,
                         scope=scope)
        qb = store.query(k=k, t0_s=b0_s, t1_s=b1_s, selector=selector,
                         scope=scope)
        ea = {e["stack"]: e for e in qa["entries"]}
        eb = {e["stack"]: e for e in qb["entries"]}
        entries = []
        for stack in set(ea) | set(eb):
            a, b = ea.get(stack), eb.get(stack)
            count_a = a["count"] if a else 0
            est_a = a["estimate"] if a else qa["cut"]
            count_b = b["count"] if b else 0
            est_b = b["estimate"] if b else qb["cut"]
            src = a or b
            entries.append({
                "stack": stack,
                "count_a": count_a, "estimate_a": est_a,
                "count_b": count_b, "estimate_b": est_b,
                "delta": count_a - count_b,
                "delta_min": count_a - est_b,
                "delta_max": est_a - count_b,
                "exact": bool(qa["exact"] and qb["exact"]),
                "frames": src.get("frames"),
                "labels": src.get("labels"),
            })
        entries.sort(key=lambda e: abs(e["delta"]), reverse=True)
        with self._lock:
            self.stats["queries"] += 1
        return {
            "mode": "range",
            "scope": scope,
            "exact": bool(qa["exact"] and qb["exact"]),
            "a": {kk: qa[kk] for kk in ("t0_s", "t1_s", "total_samples",
                                        "windows", "level", "cut",
                                        "stale")},
            "b": {kk: qb[kk] for kk in ("t0_s", "t1_s", "total_samples",
                                        "windows", "level", "cut",
                                        "stale")},
            "entries": entries,
        }

    # -- crash-only persistence (regression.baseline site) -------------------

    def save(self) -> bool:
        """Persist every frozen baseline via tmp+rename (the
        statics_store discipline: whole file or no file, every record
        CRC-framed and digest-checked at adoption). Runs on the encode
        worker after seals; fail-open — a failed save is counted and the
        next seal retries."""
        try:
            faults.inject("regression.baseline")
            with self._lock:
                body = bytearray(_MAGIC)
                self._frame(body, json.dumps({
                    "version": 1,
                    "created_at_unix": self._clock(),
                    "interval_s": self.spec.interval_s,
                    "cm_depth": self.spec.cm.depth,
                    "cm_width": self.spec.cm.width,
                }).encode())
                n = 0
                for g in self._groups.values():
                    if g.baseline is None:
                        continue
                    self._frame(body, self._pack_baseline(g))
                    n += 1
            atomic_write_bytes(self.path, bytes(body))
            # Reset the dirty counter only AFTER the write landed: a
            # failed write must retry at the very next seal, not after
            # another save_every of exposure.
            self._seals_unsaved = 0
            with self._lock:
                self.stats["baseline_saves"] += 1
            _log.debug("regression baselines saved", baselines=n)
            return True
        except Exception as e:  # noqa: BLE001 - persistence is best-effort
            with self._lock:
                self.stats["baseline_save_errors"] += 1
            _log.warn("regression baseline save failed; retrying at the "
                      "next seal", error=repr(e))
            return False

    @staticmethod
    def _frame(body: bytearray, payload: bytes) -> None:
        import zlib

        body.extend(_FMARK)
        body.extend(_FRAME.pack(len(payload), zlib.crc32(payload)))
        body.extend(payload)

    @staticmethod
    def _pack_baseline(g: _Group) -> bytes:
        base = g.baseline
        keys = np.fromiter(base.counts.keys(), np.uint64,
                           len(base.counts))
        counts = np.fromiter(base.counts.values(), np.int64,
                             len(base.counts))
        meta = json.dumps({
            "build": g.build, "tenant": g.tenant, "n": len(base.counts),
            "total": base.total, "rollups": base.rollups,
            "created_ns": base.created_ns, "ident": base.ident,
        }).encode()
        return b"".join((_U32.pack(len(meta)), meta, keys.tobytes(),
                         counts.tobytes(),
                         np.ascontiguousarray(base.cm).tobytes()))

    # palint: holds=_lock — called from __init__ only, before the
    # object is shared with any other thread (the same construction
    # exemption the checker grants __init__ itself).
    def _adopt(self) -> None:
        """Adopt the previous run's frozen baselines at startup (from
        __init__, before the sentinel is shared with any thread). Per
        record crash-only: a corrupt frame, undecodable record, spec
        mismatch, or content-digest mismatch is counted and skipped —
        that group just relearns its baseline cold."""
        import zlib

        try:
            faults.inject("regression.baseline")
            with open(self.path, "rb") as f:
                data = f.read(64 << 20)
        except OSError:
            return
        except Exception as e:  # noqa: BLE001 - injected chaos included
            self.stats["baseline_adopt_errors"] += 1
            _log.warn("regression baseline adoption failed; cold start",
                      error=repr(e))
            return
        if not data.startswith(_MAGIC):
            self.stats["baseline_adopt_errors"] += 1
            return
        off = len(_MAGIC)
        head_len = len(_FMARK) + _FRAME.size
        frames = []
        while 0 <= off < len(data):
            if data[off: off + len(_FMARK)] != _FMARK \
                    or off + head_len > len(data):
                self.stats["baseline_adopt_errors"] += 1
                nxt = data.find(_FMARK, off + 1)
                if nxt < 0:
                    break
                off = nxt
                continue
            length, crc = _FRAME.unpack_from(data, off + len(_FMARK))
            start = off + head_len
            payload = data[start: start + length]
            if len(payload) != length or zlib.crc32(payload) != crc:
                self.stats["baseline_adopt_errors"] += 1
                nxt = data.find(_FMARK, off + 1)
                if nxt < 0:
                    break
                off = nxt
                continue
            frames.append(payload)
            off = start + length
        if not frames:
            return
        try:
            header = json.loads(frames[0])
            if header.get("cm_depth") != self.spec.cm.depth \
                    or header.get("cm_width") != self.spec.cm.width \
                    or float(header.get("interval_s", 0)) \
                    != self.spec.interval_s:
                # Spec changed across the restart: rates and sketch
                # shapes are incomparable; relearn everything.
                self.stats["baseline_adopt_errors"] += 1
                return
        except (ValueError, TypeError):
            self.stats["baseline_adopt_errors"] += 1
            return
        for payload in frames[1:]:
            try:
                self._adopt_record(payload)
            except (ValueError, KeyError, struct.error,
                    UnicodeDecodeError):
                self.stats["baseline_adopt_errors"] += 1
        _log.info("regression baselines adopted",
                  adopted=self.stats["baselines_adopted"],
                  errors=self.stats["baseline_adopt_errors"])

    # palint: holds=_lock
    def _adopt_record(self, payload: bytes) -> None:
        spec = self.spec
        (meta_len,) = _U32.unpack_from(payload, 0)
        off = _U32.size
        meta = json.loads(payload[off: off + meta_len])
        off += meta_len
        n = int(meta["n"])
        cm_bytes = spec.cm.depth * spec.cm.width * 8
        want = off + 16 * n + cm_bytes
        if want != len(payload):
            raise ValueError("baseline record length mismatch")
        keys = np.frombuffer(payload, np.uint64, n, off)
        counts = np.frombuffer(payload, np.int64, n, off + 8 * n)
        cm_table = np.frombuffer(
            payload, np.int64, spec.cm.depth * spec.cm.width,
            off + 16 * n).reshape(spec.cm.depth, spec.cm.width).copy()
        base = _Baseline(
            dict(zip(keys.tolist(), counts.tolist())), cm_table,
            int(meta["total"]), int(meta["rollups"]),
            int(meta["created_ns"]))
        if base.ident != meta.get("ident"):
            # Content-addressing is the adoption gate: a record that
            # frames correctly but decodes to different content (or was
            # written by different code) must not seed judgment.
            raise ValueError("baseline content digest mismatch")
        key = (str(meta["build"]), str(meta["tenant"]))
        if key in self._groups or len(self._groups) >= spec.max_groups:
            raise ValueError("baseline group conflict")
        g = _Group(key[0], key[1], spec)
        g.baseline = base
        self._groups[key] = g
        self.stats["baselines_adopted"] += 1

    # -- observability -------------------------------------------------------

    def metrics(self) -> dict:
        """Flat gauges for /metrics (web.py renders the
        parca_agent_regression_* families)."""
        with self._lock:
            out = dict(self.stats)
            out["groups"] = len(self._groups)
            out["baselines"] = sum(
                1 for g in self._groups.values() if g.baseline is not None)
            out["alerts_pending"] = len(self._alerts)
            out["drift_max"] = round(max(
                (g.drift for g in self._groups.values()), default=0.0), 4)
            out["verdicts"] = dict(self._verdict_counts)
        return out

    def snapshot(self) -> dict:
        """/healthz section. Informational only by contract: verdicts,
        drift, or persistence trouble degrade JUDGMENT, never readiness
        — this section can never turn the agent red."""
        m = self.metrics()
        return {
            "windows_folded": m["windows_folded"],
            "fold_errors": m["fold_errors"],
            "rollups_sealed": m["rollups_sealed"],
            "groups": m["groups"],
            "baselines": m["baselines"],
            "verdicts": m["verdicts"],
            "drift_max": m["drift_max"],
            "stale_marks": m["stale_marks"],
            "baseline_saves": m["baseline_saves"],
            "baseline_save_errors": m["baseline_save_errors"],
            "alerts_pending": m["alerts_pending"],
        }
