"""Build/version metadata surfaced at startup and on the status page.

Role of the reference's pkg/buildinfo (used at cmd/parca-agent/main.go:
194-207): it reads Go's embedded runtime/debug build info — version, VCS
revision, commit time, dirty flag. Python embeds nothing, so the analog
collects from the best available source, in order:

  1. a git checkout (running from source): `git rev-parse` / `git log`
     on the package's repository, with a dirty-tree probe;
  2. baked environment (container images set PARCA_AGENT_VCS_REVISION /
     PARCA_AGENT_VCS_TIME at build time — the Dockerfile analog of
     Go's -ldflags stamping);
  3. bare package version only.

Collection runs once (cached) and never raises: metadata must not be
able to break agent startup.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import subprocess
import sys

from parca_agent_tpu import __version__


@dataclasses.dataclass(frozen=True)
class BuildInfo:
    version: str
    vcs_revision: str = ""
    vcs_time: str = ""
    vcs_modified: bool = False
    python: str = ""

    def display(self) -> str:
        """One-line form for logs and the status page header."""
        out = self.version
        if self.vcs_revision:
            out += f" ({self.vcs_revision[:12]}"
            if self.vcs_modified:
                out += "-dirty"
            out += ")"
        return out

    def as_metrics(self) -> dict:
        """Flat labels for the /metrics info pseudo-gauge."""
        return {
            "version": self.version,
            "revision": self.vcs_revision,
            "vcs_time": self.vcs_time,
            "modified": str(self.vcs_modified).lower(),
        }


def _git(args: list[str], cwd: str) -> str:
    r = subprocess.run(["git", *args], cwd=cwd, capture_output=True,
                       text=True, timeout=5)
    return r.stdout.strip() if r.returncode == 0 else ""


@functools.lru_cache(maxsize=1)
def collect() -> BuildInfo:
    py = f"{sys.version_info.major}.{sys.version_info.minor}.{sys.version_info.micro}"
    rev = os.environ.get("PARCA_AGENT_VCS_REVISION", "")
    vtime = os.environ.get("PARCA_AGENT_VCS_TIME", "")
    modified = False
    if not rev:
        try:
            pkg_dir = os.path.dirname(os.path.abspath(__file__))
            # Only trust git when the package actually lives at the top of
            # the repository git resolves (a pip-installed package under a
            # user's unrelated checkout — dotfiles, an infra monorepo
            # holding the venv — must NOT report that repo's HEAD as this
            # agent's build).
            top = _git(["rev-parse", "--show-toplevel"], pkg_dir)
            ours = (top and os.path.realpath(top)
                    == os.path.realpath(os.path.dirname(pkg_dir)))
            rev = _git(["rev-parse", "HEAD"], pkg_dir) if ours else ""
            if rev:
                vtime = _git(["log", "-1", "--format=%cI"], pkg_dir)
                modified = bool(_git(["status", "--porcelain",
                                      "--untracked-files=no"], pkg_dir))
        except Exception:  # noqa: BLE001 - metadata must never break startup
            rev = ""
    return BuildInfo(version=__version__, vcs_revision=rev,
                     vcs_time=vtime, vcs_modified=modified, python=py)
