"""host-sync: no blocking device fetches on the capture path.

PR 8's whole point was that the capture thread pays DISPATCH only: the
feed kernel's miss check is deferred to the next drain, the close is
split into dispatch/collect, and the one sync point left
(``_settle_misses``) is a documented boundary where the kernel has
already completed. A host sync creeping back into this path (an
``np.asarray`` over a device array, a ``float()`` on a traced scalar,
``.block_until_ready()``) silently re-serializes capture against the
device and undoes the overlap — the bench would catch the regression
eventually; this checker catches the diff.

Seeds are annotated at the def::

    def feed(self, ...):  # palint: capture-path

The checker walks the project call graph from every seed (``self.m()``
resolves within the class, bare names within the module, ``x.m()``
within the file) and flags, in every reachable function:

  * ``jax.device_get(...)``, ``.block_until_ready()``, ``.item()``;
  * ``np.asarray`` / ``np.array`` / ``float()`` / ``int()`` whose
    argument mentions *device state* — an attribute or name listed in
    the module's ``# palint: device-state: _acc, _touch, ...``
    annotation, or a local assigned from a ``jnp.*`` call.

A function that must sync by design (a deferred settle, a collect)
carries ``# palint: sync-ok -- <why>`` on its def line: the walk stops
there and its body is exempt — the annotation is the documentation.
``jnp.asarray`` (host->device upload) is free and never flagged.
"""

from __future__ import annotations

import ast

from parca_agent_tpu.tools.lint.core import (
    _DEVICE_STATE_RE,
    Finding,
    Project,
    SourceFile,
)

ID = "host-sync"

_NP_NAMES = ("np", "numpy", "onp")
_NP_SYNCS = ("asarray", "array")


def _device_names(src: SourceFile, fn) -> set[str]:
    """Names/attrs in ``fn`` holding device-resident values: the
    module's declared device-state attributes plus locals assigned from
    ``jnp.*`` calls or from other device values (flow-insensitive
    fixpoint — two passes cover realistic chains)."""
    declared = src.device_state_attrs()
    names = set(declared)
    for _ in range(2):
        grew = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if not _mentions_device(node.value, names):
                continue
            for tgt in node.targets:
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name) and sub.id not in names:
                        names.add(sub.id)
                        grew = True
        if not grew:
            break
    return names


def _mentions_device(expr, names: set[str]) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr in names:
            return True
        if isinstance(node, ast.Name) and node.id in names:
            return True
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "jnp":
            return True
    return False


def _sync_reason(node: ast.Call, device: set[str]) -> str | None:
    f = node.func
    if isinstance(f, ast.Attribute):
        if f.attr == "block_until_ready":
            return ".block_until_ready() blocks on the device"
        if f.attr == "item" and not node.args and not node.keywords:
            return ".item() is a blocking device fetch"
        if f.attr == "device_get":
            return "jax.device_get is a blocking device fetch"
        if f.attr in _NP_SYNCS and isinstance(f.value, ast.Name) \
                and f.value.id in _NP_NAMES \
                and node.args and _mentions_device(node.args[0], device):
            return (f"np.{f.attr}() over device state materializes on "
                    f"the host (blocking fetch)")
    if isinstance(f, ast.Name) and f.id in ("float", "int") \
            and node.args and _mentions_device(node.args[0], device):
        return f"{f.id}() over device state is a blocking device fetch"
    return None


class _Graph:
    def __init__(self, project: Project):
        self.project = project
        # (file-rel, qualname) -> (src, fn)
        self.nodes: dict[tuple[str, str], tuple[SourceFile, ast.AST]] = {}
        for src in project.files:
            for node in ast.walk(src.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self.nodes[(src.rel, src.qualname(node))] = (src, node)

    def callees(self, src: SourceFile, fn):
        """Resolve calls made by ``fn`` to project defs, same-file
        scope: self.m() -> the class's m, bare m() -> module-level m,
        x.m() -> any def named m in this file (loose, and good enough
        for the package's intra-module helper idiom)."""
        cls = src.enclosing_class(fn)
        out = []
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute):
                name = f.attr
                prefer_cls = (cls if isinstance(f.value, ast.Name)
                              and f.value.id == "self" else None)
            elif isinstance(f, ast.Name):
                name = f.id
                prefer_cls = None
            else:
                continue
            for (rel, qual), (dsrc, dfn) in self.nodes.items():
                if rel != src.rel or dfn.name != name:
                    continue
                dcls = dsrc.enclosing_class(dfn)
                if prefer_cls is not None and dcls is not prefer_cls:
                    continue
                if prefer_cls is None and isinstance(f, ast.Name) \
                        and dcls is not None:
                    continue  # bare name cannot be a method
                out.append((dsrc, dfn))
        return out


class HostSyncChecker:
    id = ID

    def check(self, project: Project):
        # A device-state marker that parses to nothing — or whose list
        # was wrapped onto a comment continuation line (the grammar
        # deliberately does not parse those, so the tail attrs would be
        # silently dropped) — is a defanged invariant: flag it rather
        # than lint green with a truncated attr set.
        for src in project.files:
            for ln, text in sorted(src.comments.items()):
                if "palint" not in text or "device-state" not in text:
                    continue
                m = _DEVICE_STATE_RE.search(text)
                if m is None or m.group(1).rstrip().endswith(","):
                    yield Finding(
                        checker=self.id, file=src.rel, line=ln, col=0,
                        message=("device-state annotation parses to no "
                                 "(or a truncated) attribute list — "
                                 "keep the whole list on one comment "
                                 "line"),
                        symbol="<device-state>")
        graph = _Graph(project)
        seeds = [(src, fn) for (rel, q), (src, fn) in graph.nodes.items()
                 if src.def_marker(fn, "capture-path")]
        seen: set[tuple[str, str]] = set()
        queue = [(src, fn, src.qualname(fn)) for src, fn in seeds]
        while queue:
            src, fn, seed = queue.pop()
            key = (src.rel, src.qualname(fn))
            if key in seen:
                continue
            seen.add(key)
            if src.def_marker(fn, "sync-ok"):
                continue  # documented deliberate sync boundary
            yield from self._check_fn(src, fn, seed)
            for dsrc, dfn in graph.callees(src, fn):
                queue.append((dsrc, dfn, seed))

    def _check_fn(self, src: SourceFile, fn, seed: str):
        device = _device_names(src, fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                reason = _sync_reason(node, device)
                if reason is not None:
                    yield Finding(
                        checker=self.id, file=src.rel, line=node.lineno,
                        col=node.col_offset,
                        message=(f"{reason} — on the capture path "
                                 f"(reachable from seed {seed})"),
                        symbol=src.qualname(fn))
