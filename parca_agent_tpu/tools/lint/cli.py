"""palint CLI: run the checkers, apply the baseline, gate on growth.

Exit codes: 0 clean (everything found is baselined or suppressed),
1 non-baselined findings, 2 usage errors. ``--json`` emits one machine-
readable object on stdout for CI/bench consumption; the human format is
``file:line:col: [checker-id] message (symbol)``.

Stale baseline entries (fixed findings still listed in baseline.json)
are always REPORTED — the baseline must shrink with the fixes, not
fossilize — but do not fail the run: use ``--write-baseline`` to
refresh it after fixing, then commit the smaller file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from parca_agent_tpu.tools.lint.bounded_call_check import BoundedCallChecker
from parca_agent_tpu.tools.lint.chaos_sites import ChaosSiteChecker
from parca_agent_tpu.tools.lint.core import (
    Project,
    apply_baseline,
    load_baseline,
    run_checkers,
    write_baseline,
)
from parca_agent_tpu.tools.lint.crash_only_io import CrashOnlyIOChecker
from parca_agent_tpu.tools.lint.fail_open import FailOpenChecker
from parca_agent_tpu.tools.lint.host_sync import HostSyncChecker
from parca_agent_tpu.tools.lint.lock_discipline import LockDisciplineChecker

ALL_CHECKERS = (
    LockDisciplineChecker,
    FailOpenChecker,
    CrashOnlyIOChecker,
    ChaosSiteChecker,
    HostSyncChecker,
    BoundedCallChecker,
)

CHECKER_IDS = tuple(c.id for c in ALL_CHECKERS)

_DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__),
                                 "baseline.json")


def build_checkers(only: list[str] | None = None):
    ids = set(only) if only else None
    out = []
    for cls in ALL_CHECKERS:
        if ids is None or cls.id in ids:
            out.append(cls())
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="palint",
        description="AST-based invariant checker for the agent's "
                    "concurrency, fail-open, and crash-only contracts "
                    "(docs/static-analysis.md)")
    ap.add_argument("--root", default=".",
                    help="repository root (default: cwd)")
    ap.add_argument("--package", default="parca_agent_tpu",
                    help="package directory under root to lint")
    ap.add_argument("--tests", default="tests",
                    help="test directory under root (chaos-site "
                         "coverage only; tests are never linted)")
    ap.add_argument("--checker", action="append", choices=CHECKER_IDS,
                    help="run only this checker (repeatable)")
    ap.add_argument("--baseline", default=_DEFAULT_BASELINE,
                    help="baseline file (default: tools/lint/"
                         "baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report everything, ignore the baseline")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from the current "
                         "findings and exit 0")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable JSON on stdout")
    args = ap.parse_args(argv)

    t0 = time.perf_counter()
    project = Project.load(args.root, package=args.package,
                           tests=args.tests)
    if not project.files:
        print(f"palint: nothing to lint under "
              f"{os.path.join(args.root, args.package)}", file=sys.stderr)
        return 2
    checkers = build_checkers(args.checker)
    active_ids = {c.id for c in checkers}
    findings, suppressed = run_checkers(project, checkers)

    if args.write_baseline:
        keep = []
        if args.checker and os.path.exists(args.baseline):
            # Partial run: entries belonging to checkers that did NOT
            # run are preserved verbatim, not silently deleted.
            try:
                with open(args.baseline, encoding="utf-8") as fp:
                    keep = [e for e in json.load(fp).get("findings", [])
                            if isinstance(e, dict)
                            and e.get("checker") not in active_ids]
            except (ValueError, OSError) as e:
                print(f"palint: bad baseline {args.baseline}: {e}",
                      file=sys.stderr)
                return 2
        write_baseline(args.baseline, findings, keep=keep)
        print(f"palint: wrote {len(findings)} finding(s) "
              f"(+{len(keep)} preserved) to {args.baseline}",
              file=sys.stderr)
        return 0

    baseline = {}
    if not args.no_baseline and os.path.exists(args.baseline):
        try:
            baseline = load_baseline(args.baseline)
        except (ValueError, KeyError, TypeError, OSError) as e:
            print(f"palint: bad baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        # A --checker run only sees that checker's findings; the other
        # checkers' baseline entries are neither spendable nor stale.
        baseline = {k: n for k, n in baseline.items()
                    if k.split("::", 1)[0] in active_ids}
    new, baselined, stale = apply_baseline(findings, baseline)

    dur_s = time.perf_counter() - t0
    if args.as_json:
        print(json.dumps({
            "findings": [f.to_dict() for f in new],
            "baselined": baselined,
            "suppressed": suppressed,
            "stale_baseline": stale,
            "files": len(project.files),
            "checkers": [c.id for c in checkers],
            "duration_s": round(dur_s, 3),
        }, indent=2, sort_keys=True))
    else:
        for f in new:
            print(f.render())
        for key in stale:
            print(f"palint: stale baseline entry (fix landed — remove "
                  f"it): {key}", file=sys.stderr)
        print(f"palint: {len(new)} finding(s), {baselined} baselined, "
              f"{suppressed} suppressed, {len(stale)} stale baseline "
              f"entr{'y' if len(stale) == 1 else 'ies'}, "
              f"{len(project.files)} files in {dur_s:.2f}s",
              file=sys.stderr)
    return 1 if new else 0


if __name__ == "__main__":  # pragma: no cover - __main__.py is the entry
    sys.exit(main())
