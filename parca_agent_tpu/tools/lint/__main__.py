"""``python -m parca_agent_tpu.tools.lint`` — see cli.py."""

import sys

from parca_agent_tpu.tools.lint.cli import main

sys.exit(main())
