"""lock-discipline: guarded attributes only under their guarding lock.

The defect class this kills: a stats field mutated from two threads
where one path grew a ``with self._lock`` and the other didn't (the
GRPCStoreClient consec-unavailable counter, the trace recorder's
two-writer lost-update, the batch client's flush-thread stats). The
contract is declared next to the data, not in the reviewer's head:

    self._consec_unavailable = 0   # guarded-by: _stats_lock

or, for classes with many guarded fields, a class-level map::

    _GUARDED = {"_consec_unavailable": "_stats_lock",
                "stats": "_stats_lock"}

Every ``self.<attr>`` read/write of a guarded attribute must then sit
lexically inside ``with self.<lock>`` in that class. ``__init__`` (and
``__new__``) are exempt — construction happens before the object is
shared. A helper documented to run with the lock already held is
annotated ``# palint: holds=<lock>`` on its def line; palint trusts the
annotation for the body and leaves the call-sites to the with-block
rule.
"""

from __future__ import annotations

import ast

from parca_agent_tpu.tools.lint.core import Finding, Project, SourceFile

ID = "lock-discipline"

# Construction/destruction scopes where the object is not yet (or no
# longer) shared between threads.
_EXEMPT_METHODS = {"__init__", "__new__", "__del__"}


def _guarded_map(src: SourceFile, cls: ast.ClassDef) -> dict[str, str]:
    """attr -> lock-attr for one class, from ``# guarded-by:`` comments
    on ``self.x = ...`` lines and the optional ``_GUARDED`` class map."""
    guarded: dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and src.enclosing_class(node) is cls:
            # _GUARDED = {"attr": "_lock", ...} in the class body
            if (len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "_GUARDED"
                    and isinstance(node.value, ast.Dict)):
                for k, v in zip(node.value.keys, node.value.values):
                    if (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)):
                        guarded[k.value] = v.value
                continue
            for tgt in node.targets:
                _note_guarded_target(src, tgt, node, guarded)
        elif isinstance(node, ast.AnnAssign) \
                and src.enclosing_class(node) is cls:
            _note_guarded_target(src, node.target, node, guarded)
    return guarded


def _note_guarded_target(src: SourceFile, tgt: ast.AST, stmt: ast.stmt,
                         guarded: dict[str, str]) -> None:
    if not (isinstance(tgt, ast.Attribute)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id == "self"):
        return
    for ln in range(stmt.lineno, (stmt.end_lineno or stmt.lineno) + 1):
        lock = src.guarded_by(ln)
        if lock:
            guarded[tgt.attr] = lock
            return


def _with_locks(node: ast.With) -> set[str]:
    """Lock attribute names acquired by one ``with`` statement:
    ``with self._lock:`` / ``with self._cond:`` (Condition's context
    manager IS its lock)."""
    out = set()
    for item in node.items:
        e = item.context_expr
        if (isinstance(e, ast.Attribute) and isinstance(e.value, ast.Name)
                and e.value.id == "self"):
            out.add(e.attr)
    return out


class LockDisciplineChecker:
    id = ID

    def check(self, project: Project):
        for src in project.files:
            for node in ast.walk(src.tree):
                if isinstance(node, ast.ClassDef):
                    yield from self._check_class(src, node)

    def _check_class(self, src: SourceFile, cls: ast.ClassDef):
        guarded = _guarded_map(src, cls)
        if not guarded:
            return
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            if meth.name in _EXEMPT_METHODS:
                continue
            held = set(src.def_holds(meth))
            yield from self._walk(src, cls, meth, meth.body,
                                  guarded, held)

    def _walk(self, src: SourceFile, cls: ast.ClassDef, meth, stmts,
              guarded: dict[str, str], held: set[str]):
        """Statement walk threading the set of currently-held locks;
        lexical containment is the model (a closure defined under the
        lock but called later is out of scope — and out of idiom)."""
        for stmt in stmts:
            if isinstance(stmt, ast.With):
                inner = held | _with_locks(stmt)
                for item in stmt.items:
                    yield from self._scan_expr(src, cls, meth,
                                               item.context_expr,
                                               guarded, held)
                yield from self._walk(src, cls, meth, stmt.body,
                                      guarded, inner)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Nested def: annotations restart; its body is checked
                # as running without the enclosing locks (it usually
                # does — worker targets, deferred callbacks).
                nested = set(src.def_holds(stmt))
                yield from self._walk(src, cls, meth, stmt.body,
                                      guarded, nested)
                continue
            for field, value in ast.iter_fields(stmt):
                if field in ("body", "orelse", "finalbody", "handlers"):
                    blocks = value if isinstance(value, list) else [value]
                    for b in blocks:
                        if isinstance(b, ast.excepthandler):
                            yield from self._walk(src, cls, meth, b.body,
                                                  guarded, held)
                        elif isinstance(b, ast.stmt):
                            yield from self._walk(src, cls, meth, [b],
                                                  guarded, held)
                        elif isinstance(b, list):
                            yield from self._walk(src, cls, meth, b,
                                                  guarded, held)
                        elif isinstance(b, ast.expr):
                            yield from self._scan_expr(src, cls, meth, b,
                                                       guarded, held)
                elif isinstance(value, list):
                    for v in value:
                        if isinstance(v, ast.stmt):
                            yield from self._walk(src, cls, meth, [v],
                                                  guarded, held)
                        elif isinstance(v, ast.expr):
                            yield from self._scan_expr(src, cls, meth, v,
                                                       guarded, held)
                elif isinstance(value, ast.stmt):
                    yield from self._walk(src, cls, meth, [value],
                                          guarded, held)
                elif isinstance(value, ast.expr):
                    yield from self._scan_expr(src, cls, meth, value,
                                               guarded, held)

    def _scan_expr(self, src: SourceFile, cls: ast.ClassDef, meth, expr,
                   guarded: dict[str, str], held: set[str]):
        stack = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                # Deferred execution: the lambda runs later, without the
                # lexically-enclosing locks. Its body is checked as
                # lock-free.
                yield from self._scan_expr(src, cls, meth, node.body,
                                           guarded, set())
                continue
            stack.extend(ast.iter_child_nodes(node))
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr in guarded
                    and guarded[node.attr] not in held):
                yield Finding(
                    checker=self.id, file=src.rel, line=node.lineno,
                    col=node.col_offset,
                    message=(f"self.{node.attr} is guarded-by "
                             f"self.{guarded[node.attr]} but accessed "
                             f"outside it"),
                    symbol=f"{cls.name}.{meth.name}:{node.attr}")
