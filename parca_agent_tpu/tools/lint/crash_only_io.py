"""crash-only-io: persistent writes are tmp + ``os.replace`` or nothing.

Crash-only persistence (agent/spool.py set the pattern; the statics
snapshot, incident dumps, and the local profile writer all inherited
it): a write that can be interrupted mid-stream must land in a tmp
sibling and be renamed into place, so the reader side never sees a
torn file — the recovery path then only has to distinguish "present"
from "absent", never "half".

Modules that hold a persistence root declare it once::

    # palint: persistence-root

In such modules, every write-mode ``open()`` (``w``/``wb``/``a``/``x``
and friends) and every ``Path.write_bytes``/``write_text`` call must
sit in a function that also calls ``os.replace`` (or ``os.rename``) —
i.e. the tmp+rename dance is local and auditable, or (better) the
write goes through ``utils/vfs.py:atomic_write_bytes``. Read-mode
opens are free. Append mode is *not* exempt: a torn append corrupts
the tail, which is why the spool frames records with CRCs and still
rewrites via tmp.
"""

from __future__ import annotations

import ast

from parca_agent_tpu.tools.lint.core import Finding, Project, SourceFile

ID = "crash-only-io"

_WRITE_METHODS = ("write_bytes", "write_text")


def _write_mode(call: ast.Call) -> str | None:
    """The mode string of an ``open()`` call when it is write-ish."""
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    if isinstance(mode, str) and any(c in mode for c in "wax+"):
        return mode
    return None


def _calls_replace(fn) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("replace", "rename") \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "os":
            return True
    return False


class CrashOnlyIOChecker:
    id = ID

    def check(self, project: Project):
        for src in project.files:
            if not src.module_marker("persistence-root"):
                continue
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                mode = None
                what = None
                if isinstance(node.func, ast.Name) \
                        and node.func.id == "open":
                    mode = _write_mode(node)
                    what = f"open(..., {mode!r})" if mode else None
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _WRITE_METHODS:
                    what = f".{node.func.attr}(...)"
                if what is None:
                    continue
                fn = src.enclosing_function(node)
                if fn is not None and _calls_replace(fn):
                    continue  # the tmp+rename dance is local: fine
                scope = src.qualname(fn) if fn is not None else "<module>"
                yield Finding(
                    checker=self.id, file=src.rel, line=node.lineno,
                    col=node.col_offset,
                    message=(f"{what} in a persistence-root module "
                             f"without os.replace in the same function: "
                             f"use utils/vfs.py:atomic_write_bytes or "
                             f"the tmp+rename pattern"),
                    symbol=scope)
