"""fail-open-hook: registered hooks must swallow-and-count, never raise.

The agent's "degrade, never die" contract hangs callbacks off its hot
loops: encode-pipeline snapshot/rollup hooks run on the worker that
ships every window, supervisor probes run on the poll loop that keeps
crashed actors restarting, flight-recorder entry points run inside the
capture iteration itself. An exception escaping any of them turns a
bookkeeping bug into a lost window (or a dead supervisor). The shape
the contract requires — and this checker enforces — is the counted
try/except:

    def hook(...):
        '''...'''
        try:
            ...the whole body...
        except Exception:
            self.stats["hook_errors"] += 1   # counted, and
            ...                              # nothing re-raises

Checked functions are found two ways:

  * annotation: ``# palint: fail-open`` on the def line declares the
    contract explicitly (the flight-recorder entry points);
  * registration: callables passed as ``snapshot=`` / ``rollup=`` /
    ``rollup_capture=`` to an ``EncodePipeline(...)`` call, or as
    ``check=`` / ``revive=`` to ``add_probe(...)``. References resolve
    by name (``self._hook`` -> the enclosing class's method, ``x.save``
    -> every project def named ``save``); a lambda passes only when its
    body contains no calls (attribute reads cannot realistically raise)
    or is a single call to a function that itself passes.

Shape rules: after the docstring and simple constant/local assignments,
the body must be a single ``try`` whose handler set includes a broad
catch (``Exception``/``BaseException``/bare), contains no ``raise``,
and does *something* observable (an ``x += 1`` style count or a call —
a silent ``pass`` hides the failure instead of containing it). ``else:``
blocks are rejected — they run outside the handler's protection. A
trailing ``return`` of a local/constant is allowed.
"""

from __future__ import annotations

import ast

from parca_agent_tpu.tools.lint.core import Finding, Project, SourceFile

ID = "fail-open-hook"

# call-name -> kwargs that register fail-open hooks. add_probe also
# accepts check/revive positionally (args[1], args[2] after the name).
_REGISTRATIONS = {
    "EncodePipeline": ("snapshot", "rollup", "rollup_capture"),
    "add_probe": ("check", "revive"),
}


def _call_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        if isinstance(n, ast.Name) and n.id in ("Exception",
                                                "BaseException"):
            return True
        if isinstance(n, ast.Attribute) and n.attr in ("Exception",
                                                       "BaseException"):
            return True
    return False


def _contains_raise(stmts) -> bool:
    stack = list(stmts)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue  # a nested def's raise fires on ITS caller
        if isinstance(node, ast.Raise):
            return True
        stack.extend(ast.iter_child_nodes(node))
    return False


def _counts_something(stmts) -> bool:
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.AugAssign, ast.Call)):
                return True
            if isinstance(node, ast.Assign):
                return True
    return False


def _is_simple_setup(stmt: ast.stmt) -> bool:
    """Pre-try statements that cannot realistically raise: docstrings,
    assignments of constants/names/attribute reads, and imports of
    core dependencies (a missing core dep fails the first window, not
    just the hook — fail-open cannot help there)."""
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
        return True
    if isinstance(stmt, (ast.Import, ast.ImportFrom)):
        return True
    if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
        value = stmt.value
        return value is None or not any(
            isinstance(n, ast.Call) for n in ast.walk(value))
    return False


def _is_simple_return(stmt: ast.stmt) -> bool:
    if not isinstance(stmt, ast.Return):
        return False
    v = stmt.value
    return v is None or not any(isinstance(n, ast.Call)
                                for n in ast.walk(v))


def check_shape(fn) -> str | None:
    """None when the function satisfies the fail-open shape, else a
    human-readable reason."""
    body = list(fn.body)
    while body and _is_simple_setup(body[0]):
        body.pop(0)
    while body and _is_simple_return(body[-1]):
        body.pop()
    if len(body) != 1 or not isinstance(body[0], ast.Try):
        return ("body is not a single counted try/except "
                "(statements outside the try can raise out of the hook)")
    tr = body[0]
    if tr.orelse:
        return "try has an else: block, which runs unprotected"
    if tr.finalbody:
        return ("try has a finally: block, which runs unprotected (a "
                "raising cleanup escapes the hook)")
    if not any(_broad_handler(h) for h in tr.handlers):
        return ("no broad except handler (Exception/BaseException): "
                "unlisted exception classes escape")
    for h in tr.handlers:
        if _contains_raise(h.body):
            return "except handler re-raises"
        if _broad_handler(h) and not _counts_something(h.body):
            return ("broad handler swallows silently: count or log the "
                    "failure")
    return None


class _Resolver:
    """Name-based callable resolution across the project. Deliberately
    loose: a project this size has essentially unique method names, and
    the golden tests in tests/test_lint.py pin the semantics."""

    def __init__(self, project: Project):
        self.project = project
        self._defs: dict[str, list[tuple[SourceFile, ast.AST]]] = {}
        for src in project.files:
            for node in ast.walk(src.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self._defs.setdefault(node.name, []).append(
                        (src, node))

    def by_name(self, name: str, prefer_class: ast.ClassDef | None = None,
                src: SourceFile | None = None):
        """Candidates for a reference, narrowest scope that matches:
        the preferred class (``self.m``), then the same file, then the
        whole project — but a project-wide fan-out over a common name
        audits unrelated defs, so it is capped: past 4 candidates the
        reference is treated as unresolvable."""
        cands = self._defs.get(name, [])
        if prefer_class is not None and src is not None:
            scoped = [(s, n) for s, n in cands
                      if s is src and s.enclosing_class(n) is prefer_class]
            if scoped:
                return scoped
        if src is not None:
            local = [(s, n) for s, n in cands if s is src]
            if local:
                return local
        return cands if len(cands) <= 4 else []


class FailOpenChecker:
    id = ID

    def check(self, project: Project):
        resolver = _Resolver(project)
        seen: set[int] = set()
        # 1) explicitly annotated functions
        for src in project.files:
            for node in ast.walk(src.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and src.def_marker(node, "fail-open"):
                    yield from self._check_def(src, node, seen,
                                               "annotated fail-open")
        # 2) hook registrations
        for src in project.files:
            for node in ast.walk(src.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = _call_name(node)
                kwargs = _REGISTRATIONS.get(name or "")
                if not kwargs:
                    continue
                for kw in node.keywords:
                    if kw.arg in kwargs:
                        yield from self._check_ref(
                            src, node, kw.value, resolver, seen,
                            f"registered via {name}({kw.arg}=...)")
                if name == "add_probe":
                    for pos in node.args[1:3]:
                        yield from self._check_ref(
                            src, node, pos, resolver, seen,
                            "registered via add_probe(...)")

    # -- helpers -------------------------------------------------------------

    def _check_def(self, src: SourceFile, fn, seen: set[int], why: str):
        if id(fn) in seen:
            return
        seen.add(id(fn))
        if src.def_marker_value(fn, "fail-open") == "caller":
            # Documented disposition: containment lives at the
            # registered invocation site (the pipeline's counted hook
            # guard, the supervisor's probe guard) and the hook's own
            # raise is part of its metrics contract — e.g. the hotspot
            # fold counts fold_errors and re-raises for the worker to
            # count rollup_errors. The annotation is the audit trail.
            return
        reason = check_shape(fn)
        if reason is not None:
            yield Finding(
                checker=self.id, file=src.rel, line=fn.lineno,
                col=fn.col_offset,
                message=f"{fn.name} must be fail-open ({why}): {reason}",
                symbol=src.qualname(fn))

    def _check_ref(self, src: SourceFile, call: ast.Call, ref,
                   resolver: _Resolver, seen: set[int], why: str,
                   depth: int = 0):
        if depth > 3:
            return
        # Conditional registrations: X if cond else None
        if isinstance(ref, ast.IfExp):
            for branch in (ref.body, ref.orelse):
                yield from self._check_ref(src, call, branch, resolver,
                                           seen, why, depth + 1)
            return
        if isinstance(ref, ast.Constant) and ref.value is None:
            return
        if isinstance(ref, ast.Lambda):
            calls = [n for n in ast.walk(ref.body)
                     if isinstance(n, ast.Call)]
            if not calls:
                return  # attribute/comparison lambdas cannot raise
            if len(calls) == 1 and calls[0] is ref.body:
                yield from self._check_ref(src, call, ref.body.func,
                                           resolver, seen, why, depth + 1)
                return
            yield Finding(
                checker=self.id, file=src.rel, line=ref.lineno,
                col=ref.col_offset,
                message=(f"lambda {why} makes calls and cannot contain "
                         f"a try/except: register a fail-open def "
                         f"instead"),
                symbol=(src.qualname(src.enclosing_function(call))
                        if src.enclosing_function(call) else "<module>")
                + ":lambda")
            return
        if isinstance(ref, ast.Name):
            # A plain name: prefer the local binding in the registering
            # function (the ``snapshot = lambda ...`` idiom), else a
            # module-level def in this file. A bare name never resolves
            # project-wide — that would audit unrelated same-named defs.
            local = self._local_binding(src, call, ref.id)
            if local is not None:
                yield from self._check_ref(src, call, local, resolver,
                                           seen, why, depth + 1)
                return
            for dsrc, dfn in resolver.by_name(ref.id, None, src):
                if dsrc is src and dsrc.enclosing_class(dfn) is None:
                    yield from self._check_def(dsrc, dfn, seen, why)
            return
        if isinstance(ref, ast.Attribute):
            prefer = None
            if isinstance(ref.value, ast.Name) and ref.value.id == "self":
                prefer = src.enclosing_class(call)
            for dsrc, dfn in resolver.by_name(ref.attr, prefer, src):
                yield from self._check_def(dsrc, dfn, seen, why)

    @staticmethod
    def _local_binding(src: SourceFile, call: ast.Call, name: str):
        """The value last assigned to ``name`` in the function that
        makes the registration call, textually before the call."""
        fn = src.enclosing_function(call)
        if fn is None:
            return None
        best = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and node.lineno < call.lineno \
                    and any(isinstance(t, ast.Name) and t.id == name
                            for t in node.targets):
                if best is None or node.lineno > best.lineno:
                    best = node
        return best.value if best is not None else None
