"""chaos-site: the fault registry, the call sites, and the chaos tests
agree.

utils/faults.py is the single chaos layer; its ``SITES`` registry is
the contract between three parties that historically drifted apart:

  * the ``faults.inject("<site>")`` call sites in the package,
  * the registry itself (a documented site nobody injects is dead
    weight that reads as coverage),
  * the ``chaos``-marked tests that actually exercise each site.

Three rules, one checker id:

  * every ``inject()`` call site's literal must match a registry entry
    (wildcard entries like ``actor.*`` match by prefix); a non-literal
    argument cannot be audited and is flagged outright;
  * every registry entry must have at least one call site (no dead
    entries);
  * every registry entry must appear in at least one chaos-marked test
    module — matched as a substring over the module's string constants,
    which covers both ``inject("x.y")`` calls and spec-grammar strings
    like ``"x.y:error:after=5"``.

Test modules count as chaos-marked when they contain a
``@pytest.mark.chaos`` function or a module-level ``pytestmark``
mentioning ``chaos``.
"""

from __future__ import annotations

import ast

from parca_agent_tpu.tools.lint.core import Finding, Project, SourceFile

ID = "chaos-site"

_FAULTS_REL = "utils/faults.py"


def _registry(project: Project) -> tuple[SourceFile | None, dict[str, int]]:
    """SITES keys -> declaration line, from the faults module."""
    faults_src = None
    for src in project.files:
        if src.rel.replace("\\", "/").endswith(_FAULTS_REL):
            faults_src = src
            break
    if faults_src is None:
        return None, {}
    sites: dict[str, int] = {}
    for node in ast.walk(faults_src.tree):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "SITES"
                        for t in node.targets) \
                and isinstance(node.value, ast.Dict):
            for k in node.value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    sites[k.value] = k.lineno
    return faults_src, sites


def _matches(site: str, registry: dict[str, int]) -> bool:
    if site in registry:
        return True
    return any(entry.endswith("*") and site.startswith(entry[:-1])
               for entry in registry)


def _inject_sites(project: Project):
    """(src, call, site-literal-or-None) for every faults.inject() call
    in the package (the faults module itself and this lint package are
    not call sites)."""
    for src in project.files:
        rel = src.rel.replace("\\", "/")
        if rel.endswith(_FAULTS_REL) or "/tools/lint/" in rel:
            continue
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            name = f.attr if isinstance(f, ast.Attribute) else (
                f.id if isinstance(f, ast.Name) else None)
            if name != "inject":
                continue
            site = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                site = node.args[0].value
            yield src, node, site


def _chaos_strings(project: Project) -> set[str]:
    """String constants from chaos-marked test modules that can
    actually DRIVE an injection: arguments, assignments, spec strings.
    Docstrings and other bare-expression strings are excluded — a site
    merely narrated in a test's prose must not count as exercised
    (that is exactly the drift this checker exists to catch)."""
    out: set[str] = set()
    for src in project.test_files:
        if not _is_chaos_module(src):
            continue
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value,
                                                            str):
                parent = src.parent(node)
                if isinstance(parent, ast.Expr):
                    continue  # docstring / no-op string statement
                out.add(node.value)
    return out


def _is_chaos_module(src: SourceFile) -> bool:
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                for sub in ast.walk(dec):
                    if isinstance(sub, ast.Attribute) \
                            and sub.attr == "chaos":
                        return True
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name) and t.id == "pytestmark"
                        for t in node.targets):
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Attribute) and sub.attr == "chaos":
                    return True
    return False


class ChaosSiteChecker:
    id = ID

    def check(self, project: Project):
        faults_src, registry = _registry(project)
        if faults_src is None:
            return  # no faults module in this tree (golden known-good)
        if not registry:
            yield Finding(
                checker=self.id, file=faults_src.rel, line=1, col=0,
                message=("utils/faults.py has no SITES registry: the "
                         "documented site list must be machine-readable"),
                symbol="SITES")
            return
        used: set[str] = set()
        for src, call, site in _inject_sites(project):
            fn = src.enclosing_function(call)
            scope = src.qualname(fn) if fn is not None else "<module>"
            if site is None:
                yield Finding(
                    checker=self.id, file=src.rel, line=call.lineno,
                    col=call.col_offset,
                    message=("inject() with a non-literal site cannot be "
                             "audited against the SITES registry"),
                    symbol=scope)
                continue
            used.add(site)
            if not _matches(site, registry):
                yield Finding(
                    checker=self.id, file=src.rel, line=call.lineno,
                    col=call.col_offset,
                    message=(f"inject({site!r}) is not documented in "
                             f"utils/faults.py SITES"),
                    symbol=site)
        strings = _chaos_strings(project)
        for entry, lineno in sorted(registry.items()):
            probe = entry[:-1] if entry.endswith("*") else entry
            # Liveness: prefix matching belongs to wildcard entries
            # only — a non-wildcard entry must be injected EXACTLY
            # (inject("device.probe2") must not keep "device.probe"
            # looking alive).
            if entry.endswith("*"):
                live = any(u.startswith(probe) for u in used)
            else:
                live = entry in used
            if not live:
                yield Finding(
                    checker=self.id, file=faults_src.rel, line=lineno,
                    col=0,
                    message=(f"SITES entry {entry!r} has no inject() "
                             f"call site: dead registry entry"),
                    symbol=entry)
            if not any(probe in s for s in strings):
                yield Finding(
                    checker=self.id, file=faults_src.rel, line=lineno,
                    col=0,
                    message=(f"SITES entry {entry!r} is not exercised by "
                             f"any chaos-marked test"),
                    symbol=entry)
