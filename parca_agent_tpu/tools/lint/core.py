"""palint core: source model, annotation grammar, suppressions, baseline.

The model is deliberately plain: one :class:`SourceFile` per parsed file
(AST + the comment table ``ast`` drops, recovered via ``tokenize``), one
:class:`Project` over the package (plus the test tree, which only the
chaos-site checker reads), and a :class:`Finding` stream the runner
filters through inline suppressions and the committed baseline.

Annotation grammar (docs/static-analysis.md):

    # guarded-by: _lock            this attribute is owned by self._lock
    # palint: holds=_lock          this function is documented to be
                                   called with self._lock already held
    # palint: fail-open            this function promises the counted
                                   try/except fail-open shape
    # palint: capture-path         host-sync seed: this function runs on
                                   the capture thread's dispatch path
    # palint: sync-ok -- <why>     documented deliberate sync boundary;
                                   the host-sync walk stops here
    # palint: persistence-root     module marker: write-mode opens here
                                   must be tmp+os.replace atomic
    # palint: device-state: _a,_b  module marker: attributes holding
                                   device-resident arrays (host-sync)
    # palint: disable=<id>[,<id>] -- <why>
                                   suppress findings on this line
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import os
import re
import tokenize

_DISABLE_RE = re.compile(r"palint:\s*disable=([\w\-]+(?:\s*,\s*[\w\-]+)*)")
_GUARDED_RE = re.compile(r"guarded-by:\s*([\w.]+)")
_HOLDS_RE = re.compile(r"palint:\s*holds=([\w.]+)")
_MARKER_RE = re.compile(r"palint:\s*([\w\-]+)(?:=([\w.\-]+))?")
_DEVICE_STATE_RE = re.compile(r"palint:\s*device-state:\s*([\w, ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    checker: str
    file: str          # project-relative path
    line: int
    col: int
    message: str
    symbol: str        # stable scope key for baseline matching

    def key(self) -> str:
        """Baseline identity: line numbers churn with every edit, the
        (checker, file, symbol) scope does not — so a baselined finding
        stays baselined across unrelated diffs but a NEW finding in the
        same file still gates."""
        return f"{self.checker}::{self.file}::{self.symbol}"

    def render(self) -> str:
        return (f"{self.file}:{self.line}:{self.col}: "
                f"[{self.checker}] {self.message} ({self.symbol})")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class SourceFile:
    def __init__(self, path: str, rel: str, text: str):
        self.path = path
        self.rel = rel
        self.text = text
        self.tree = ast.parse(text, filename=rel)
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:  # pragma: no cover - parse succeeded
            pass
        self._parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent

    # -- comment annotations -------------------------------------------------

    def disables(self, line: int) -> set[str]:
        m = _DISABLE_RE.search(self.comments.get(line, ""))
        if not m:
            return set()
        return {s.strip() for s in m.group(1).split(",") if s.strip()}

    def guarded_by(self, line: int) -> str | None:
        m = _GUARDED_RE.search(self.comments.get(line, ""))
        return m.group(1) if m else None

    def _def_comment_lines(self, node: ast.AST) -> list[int]:
        """Lines where a def-level annotation may sit: the def line(s)
        through the first body statement's predecessor, plus one
        comment-only line above the def/decorators."""
        first = getattr(node, "body", None)
        end = first[0].lineno - 1 if first else node.lineno
        start = node.lineno
        for dec in getattr(node, "decorator_list", ()):
            start = min(start, dec.lineno)
        lines = list(range(start, max(end, node.lineno) + 1))
        # ... plus the contiguous comment block directly above the def —
        # multi-line annotations put the marker on their first line.
        ln = start - 1
        while ln in self.comments:
            lines.append(ln)
            ln -= 1
        return lines

    def def_marker(self, node: ast.AST, name: str) -> bool:
        for ln in self._def_comment_lines(node):
            for m in _MARKER_RE.finditer(self.comments.get(ln, "")):
                if m.group(1) == name:
                    return True
        return False

    def def_marker_value(self, node: ast.AST, name: str) -> str | None:
        """The ``=value`` of a def-line marker (``# palint:
        fail-open=caller`` -> ``"caller"``); empty string for a bare
        marker, None when absent."""
        for ln in self._def_comment_lines(node):
            for m in _MARKER_RE.finditer(self.comments.get(ln, "")):
                if m.group(1) == name:
                    return m.group(2) or ""
        return None

    def def_holds(self, node: ast.AST) -> set[str]:
        held: set[str] = set()
        for ln in self._def_comment_lines(node):
            m = _HOLDS_RE.search(self.comments.get(ln, ""))
            if m:
                held.add(m.group(1))
        return held

    def module_marker(self, name: str) -> bool:
        for text in self.comments.values():
            for m in _MARKER_RE.finditer(text):
                if m.group(1) == name:
                    return True
        return False

    def device_state_attrs(self) -> set[str]:
        attrs: set[str] = set()
        for text in self.comments.values():
            m = _DEVICE_STATE_RE.search(text)
            if m:
                attrs |= {s.strip() for s in m.group(1).split(",")
                          if s.strip()}
        return attrs

    # -- tree helpers --------------------------------------------------------

    def parent(self, node: ast.AST) -> ast.AST | None:
        return self._parents.get(node)

    def enclosing_function(self, node: ast.AST):
        n = self.parent(node)
        while n is not None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return n
            n = self.parent(n)
        return None

    def enclosing_class(self, node: ast.AST) -> ast.ClassDef | None:
        n = self.parent(node)
        while n is not None:
            if isinstance(n, ast.ClassDef):
                return n
            n = self.parent(n)
        return None

    def qualname(self, node: ast.AST) -> str:
        """Dotted scope path for a def/class node (symbol keys)."""
        parts = []
        n = node
        while n is not None and not isinstance(n, ast.Module):
            name = getattr(n, "name", None)
            if name is not None:
                parts.append(name)
            n = self.parent(n)
        return ".".join(reversed(parts)) or "<module>"


class Project:
    """The linted tree: every parsable .py under the package dir, plus
    the test tree (consulted only for chaos-marker coverage — tests are
    never themselves linted)."""

    def __init__(self, files: list[SourceFile],
                 test_files: list[SourceFile] | None = None):
        self.files = files
        self.test_files = test_files or []
        self.by_rel = {f.rel: f for f in files}

    @classmethod
    def load(cls, root: str, package: str = "parca_agent_tpu",
             tests: str = "tests") -> "Project":
        files = cls._scan(root, os.path.join(root, package))
        test_dir = os.path.join(root, tests)
        test_files = (cls._scan(root, test_dir)
                      if os.path.isdir(test_dir) else [])
        return cls(files, test_files)

    @staticmethod
    def _scan(root: str, top: str) -> list[SourceFile]:
        out = []
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in ("__pycache__",))
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root)
                try:
                    with open(path, encoding="utf-8") as f:
                        text = f.read()
                    out.append(SourceFile(path, rel, text))
                except (OSError, SyntaxError, ValueError) as e:
                    # A file the checker cannot parse is a finding-shaped
                    # problem in itself, but the tier-1 suite owns syntax;
                    # palint just skips it loudly via stderr in the CLI.
                    import sys

                    print(f"palint: skipping unparsable {rel}: {e}",
                          file=sys.stderr)
        return out


# -- runner ------------------------------------------------------------------

def run_checkers(project: Project, checkers) -> tuple[list[Finding], int]:
    """Run every checker; returns (findings, suppressed_count) with
    inline ``# palint: disable=`` suppressions already applied. A
    suppression comment may sit on any line the finding's statement
    spans (multi-line calls put the comment where black/PEP8 leaves
    room)."""
    findings: list[Finding] = []
    suppressed = 0
    for checker in checkers:
        for f in checker.check(project):
            src = project.by_rel.get(f.file)
            if src is not None and _is_suppressed(src, f):
                suppressed += 1
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.file, f.line, f.checker))
    return findings, suppressed


def _is_suppressed(src: SourceFile, f: Finding) -> bool:
    lines = {f.line, f.line - 1}
    lines.update(_statement_span(src, f.line))
    for ln in lines:
        ids = src.disables(ln)
        if f.checker in ids or "all" in ids:
            return True
    return False


def _statement_span(src: SourceFile, line: int) -> range:
    """Physical lines of the innermost statement covering ``line`` — a
    multi-line call anchors its finding at the first line while the
    only room for a comment may be the last. Compound statements
    (def/if/with/try...) count only as far as their HEADER: a disable
    deep inside a body must not suppress a finding anchored at the
    header, but the closing line of a multi-line ``with open(...)``
    must."""
    best = None
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.stmt):
            continue
        body = getattr(node, "body", None)
        if isinstance(body, list) and body:
            end = body[0].lineno - 1  # header only
        else:
            end = node.end_lineno or node.lineno
        if node.lineno <= line <= end:
            if best is None or (node.lineno, -end) > (best[0], -best[1]):
                best = (node.lineno, end)
    if best is None:
        return range(line, line + 1)
    return range(best[0], best[1] + 1)


# -- baseline ----------------------------------------------------------------

def load_baseline(path: str) -> dict[str, int]:
    """baseline.json: ``{"findings": [{"checker","file","symbol",
    "count","why"}]}``. Counts gate on growth: N baselined findings in a
    scope allow N, the N+1st gates."""
    with open(path, encoding="utf-8") as fp:
        data = json.load(fp)
    out: dict[str, int] = {}
    for e in data.get("findings", []):
        key = f"{e['checker']}::{e['file']}::{e['symbol']}"
        out[key] = out.get(key, 0) + int(e.get("count", 1))
    return out


def apply_baseline(findings: list[Finding], baseline: dict[str, int]
                   ) -> tuple[list[Finding], int, list[str]]:
    """Split findings into (new, baselined_count, stale_keys). Stale =
    a baseline entry whose findings no longer exist (or exist fewer
    times than baselined): reported so the baseline shrinks with the
    fixes instead of silently fossilizing."""
    budget = dict(baseline)
    new: list[Finding] = []
    baselined = 0
    for f in findings:
        k = f.key()
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            baselined += 1
        else:
            new.append(f)
    stale = sorted(k for k, n in budget.items() if n > 0)
    return new, baselined, stale


def write_baseline(path: str, findings: list[Finding],
                   keep: list[dict] | None = None) -> None:
    """Rewrite the baseline from the current findings; ``keep`` carries
    entries to preserve verbatim (a partial ``--checker`` run must not
    delete the other checkers' deliberate baselines)."""
    from parca_agent_tpu.utils.vfs import atomic_write_bytes

    entries = list(keep or []) + [
        {"checker": f.checker, "file": f.file, "symbol": f.symbol,
         "count": 1, "why": "TODO: justify or fix"}
        for f in findings
    ]
    entries.sort(key=lambda e: (e.get("checker", ""), e.get("file", ""),
                                e.get("symbol", "")))
    body = json.dumps({"findings": entries}, indent=2, sort_keys=True)
    atomic_write_bytes(path, (body + "\n").encode())
