"""palint: the agent's AST-based invariant checker.

Every review round since PR 2 has hand-caught the same defect classes:
fields read outside their guarding lock, hooks that must be fail-open
but let an exception escape, persistent writes missing the tmp+rename
discipline, chaos sites drifting out of the registry, host syncs creeping
back onto the capture path, and hand-rolled copies of the abandonable
bounded-call guard. The paper's always-on contract ("degrade, never
die") depends on these invariants holding as the codebase grows — so
they are machine-checked here, the way parca-agent leans on Go's race
detector and `go vet` where Python gives us neither.

Six project-specific checkers (docs/static-analysis.md):

    lock-discipline   attributes annotated ``# guarded-by: _lock`` (or
                      listed in a per-class ``_GUARDED`` map) may only be
                      touched inside ``with self._lock`` in that class
    fail-open-hook    functions registered as encode-pipeline
                      snapshot/rollup hooks, supervisor probes, or
                      annotated ``# palint: fail-open`` must wrap their
                      body in a counted try/except that cannot re-raise
    crash-only-io     write-mode opens in ``# palint: persistence-root``
                      modules must flow through tmp + ``os.replace``
    chaos-site        every ``inject("<site>")`` call site must match
                      ``utils/faults.py``'s SITES registry and be
                      exercised by a ``chaos``-marked test, and vice
                      versa (no dead registry entries)
    host-sync         functions reachable from a ``# palint:
                      capture-path`` seed may not call blocking device
                      fetches (``jax.device_get``, ``.block_until_
                      ready()``, ``np.asarray``/``float``/``int`` over
                      device state)
    bounded-call      spawn-a-thread-then-``join(timeout)`` reimplements
                      utils/bounded.py:bounded_call — use it instead

Run via ``make lint`` or ``python -m parca_agent_tpu.tools.lint``
(``--json`` for machine-readable output). Inline suppressions use
``# palint: disable=<id>`` with a justification; pre-existing findings
live in ``tools/lint/baseline.json`` so the gate fires on growth, not
history (stale baseline entries are reported, never silently kept).
"""

from parca_agent_tpu.tools.lint.core import (  # noqa: F401
    Finding,
    Project,
    SourceFile,
    run_checkers,
)
