"""bounded-call: don't hand-roll the abandonable daemon-thread guard.

utils/bounded.py:bounded_call exists because the
spawn-thread/join-with-timeout/abandon pattern has three subtle parts
that drifted apart every time it was re-implemented (the device
watchdog, the inline-encode deadline, and the fleet join each had a
copy before PR 5 unified them): BaseException capture in the worker,
the box-before-event ordering that makes ``done.is_set()`` imply the
result is complete, and daemon-ness (a pool worker would block
interpreter exit behind a wedged C call forever).

The checker flags any function that BOTH constructs a
``threading.Thread(target=...)`` AND bounds it with ``.join(<timeout>)``
or an ``<event>.wait(<timeout>)`` — that is the guard, re-implemented.
Plain lifecycle joins (a thread created in ``start()`` and joined in
``stop()``) live in different functions and never match. utils/
bounded.py itself is the one legitimate implementation and is skipped
by path.
"""

from __future__ import annotations

import ast
import os

from parca_agent_tpu.tools.lint.core import Finding, Project, SourceFile

ID = "bounded-call"

_IMPL = os.path.join("utils", "bounded.py")


def _creates_thread(fn) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None)
        if name == "Thread" and any(kw.arg == "target"
                                    for kw in node.keywords):
            return True
    return False


def _bounded_wait(fn):
    """First ``x.join(timeout)`` / ``x.wait(timeout)`` call with an
    actual timeout argument, or None."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute):
            continue
        if node.func.attr not in ("join", "wait"):
            continue
        timed = bool(node.args) or any(
            kw.arg in ("timeout", "timeout_s") for kw in node.keywords)
        if timed:
            return node
    return None


class BoundedCallChecker:
    id = ID

    def check(self, project: Project):
        for src in project.files:
            if src.rel.endswith(_IMPL):
                continue  # the one legitimate implementation
            for node in ast.walk(src.tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if not _creates_thread(node):
                    continue
                wait = _bounded_wait(node)
                if wait is None:
                    continue
                yield Finding(
                    checker=self.id, file=src.rel, line=wait.lineno,
                    col=wait.col_offset,
                    message=("spawn-thread + timed join/wait "
                             "re-implements the abandonable guard: use "
                             "utils/bounded.py:bounded_call"),
                    symbol=src.qualname(node))
