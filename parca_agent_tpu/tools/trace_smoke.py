"""`make trace-smoke`: the window flight recorder's end-to-end drill.

Runs a short traced session through the real profiler loop (synthetic
capture, dict aggregator, fast encode, encode pipeline, HTTP surface)
and asserts the observability contract (docs/observability.md):

  1. `/debug/windows` returns >= 3 COMPLETE traces, each carrying every
     mandatory span (drain, close, prepare, encode, ship).
  2. `/metrics` parses and serves the stage-duration histogram for >= 6
     stages.
  3. One injected slow window (a `device.dispatch` hang well past the
     primed p99 budget) produces EXACTLY ONE incident file containing
     the offending trace and a self-profile — and zero windows are
     lost.
  4. The device flight recorder (docs/observability.md "device flight
     recorder") latched >= 1 compile per exercised kernel during the
     primed session, with zero recompiles on the pinned geometry, and
     `/metrics` serves the kernel/transfer/window-budget families with
     compile and execute separable.
  5. `/debug/device` returns the telemetry snapshot + timeline.
  6. One injected shape change (a window at a different row count — a
     new feed signature on a latched kernel) produces EXACTLY ONE
     `recompile_storm` incident file — and still zero windows lost.

Exit 0 on success; raises (exit 1) with a readable assertion otherwise.
Host-side only: the Make target pins JAX_PLATFORMS=cpu.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import urllib.request


def main() -> int:
    # Like tests/conftest.py: the ambient sitecustomize may have forced
    # a device platform; the smoke is host-side by design.
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    from parca_agent_tpu.aggregator.cpu import CPUAggregator
    from parca_agent_tpu.aggregator.dict import DictAggregator
    from parca_agent_tpu.capture.synthetic import SyntheticSpec, generate
    from parca_agent_tpu.profiler.cpu import CPUProfiler
    from parca_agent_tpu.runtime.trace import (
        MANDATORY_SPANS,
        FlightRecorder,
    )
    from parca_agent_tpu.runtime import device_telemetry as dtel_mod
    from parca_agent_tpu.runtime import trace as trace_mod
    from parca_agent_tpu.utils import faults
    from parca_agent_tpu.web import AgentHTTPServer

    n_prime = int(os.environ.get("PARCA_TRACE_SMOKE_WINDOWS", "8"))
    tmp = tempfile.mkdtemp(prefix="parca-trace-smoke-")
    incident_dir = os.path.join(tmp, "incidents")

    snaps = [generate(SyntheticSpec(
        n_pids=6, n_unique_stacks=256, n_rows=256, total_samples=1024,
        mean_depth=8, seed=i)) for i in range(n_prime + 1)]

    class Src:
        def __init__(self):
            self.snaps = list(snaps)

        def poll(self):
            return self.snaps.pop(0) if self.snaps else None

    shipped = []

    class Sink:
        def write(self, labels, blob):
            shipped.append(len(blob))

    # Pre-warm the aggregation programs OUTSIDE the traced session: the
    # first window's XLA compile (seconds) would otherwise dominate the
    # close histogram's p99 and hide the injected stall behind an
    # inflated budget — a production agent is past compile within its
    # first window too.
    agg = DictAggregator(capacity=1 << 12)
    agg.window_counts(generate(SyntheticSpec(
        n_pids=6, n_unique_stacks=256, n_rows=256, total_samples=1024,
        mean_depth=8, seed=99)))

    recorder = FlightRecorder(
        ring=64, min_count=4, min_duration_s=0.05, slow_multiple=5.0,
        incident_dir=incident_dir,
        # Short enough that the recompile drill's capture (6 below) is
        # not rate-suppressed by the slow-window incident (3) before it.
        incident_interval_s=0.5,
        # A fast self-profile keeps the smoke quick; the incident still
        # carries a REAL gzipped pprof of the agent's threads.
        self_profile=None, self_profile_s=0.3,
        context=lambda: {"smoke": True})
    trace_mod.install(recorder)

    # The device flight recorder rides the whole primed session: install
    # AFTER the pre-warm above (whose one-shot geometry would latch a
    # second signature) so the primed loop's pinned geometry latches
    # exactly one signature per kernel. Its own incident pre-filter is
    # effectively off (one per hour) — the shape-change drill below must
    # surface exactly its FIRST recompile.
    dtel = dtel_mod.DeviceTelemetry(
        period_s=1.0, ring=256, incident_interval_s=3600.0)
    dtel_mod.install(dtel)

    src = Src()
    prof = CPUProfiler(
        source=src, aggregator=agg,
        fallback_aggregator=CPUAggregator(), profile_writer=Sink(),
        duration_s=0.0, fast_encode=True, encode_pipeline=True,
        trace_recorder=recorder)

    http = AgentHTTPServer(port=0, profilers=[prof], recorder=recorder,
                           device_telemetry=dtel)
    http.start()
    base = f"http://127.0.0.1:{http.port}"

    def fetch(path):
        with urllib.request.urlopen(base + path, timeout=10) as r:
            return r.read().decode()

    try:
        # -- prime: n_prime clean windows ------------------------------------
        for _ in range(n_prime):
            assert prof.run_iteration()
        assert prof._pipeline.flush(30)

        body = json.loads(fetch("/debug/windows"))
        complete = [t for t in body["traces"]
                    if t["complete"] and "error" not in t]
        assert len(complete) >= 3, f"only {len(complete)} complete traces"
        for t in complete:
            stages = {s["stage"] for s in t["spans"]}
            missing = set(MANDATORY_SPANS) - stages
            assert not missing, f"trace {t['seq']} missing spans {missing}"
        print(f"trace-smoke: {len(complete)} complete traces, "
              f"all mandatory spans present")

        metrics = fetch("/metrics")
        stages_in_metrics = {
            line.split('stage="', 1)[1].split('"', 1)[0]
            for line in metrics.splitlines()
            if line.startswith(
                "parca_agent_window_stage_duration_seconds_bucket")}
        assert len(stages_in_metrics) >= 6, \
            f"only {len(stages_in_metrics)} stages in /metrics: " \
            f"{sorted(stages_in_metrics)}"
        assert "# TYPE parca_agent_window_stage_duration_seconds " \
            "histogram" in metrics
        print(f"trace-smoke: /metrics histograms for "
              f"{len(stages_in_metrics)} stages: "
              f"{sorted(stages_in_metrics)}")

        # -- device flight recorder: primed-session truth --------------------
        snap_t = dtel.snapshot()
        kernels = snap_t["kernels"]
        assert kernels, "device telemetry saw no kernel dispatches"
        assert "feed_probe" in kernels, f"no feed_probe in {sorted(kernels)}"
        latched = {n for n, i in kernels.items() if i["shapes_latched"]}
        assert "feed_probe" in latched
        for name in latched:
            assert kernels[name]["compiles"] >= 1, \
                f"kernel {name} latched no compile: {kernels[name]}"
        assert snap_t["stats"]["recompiles_total"] == 0, \
            f"pinned geometry recompiled: {snap_t['stats']}"
        assert snap_t["stats"]["record_errors"] == 0
        assert snap_t["window_budget"]["windows_total"] >= n_prime
        assert any(d.get("h2d") or d.get("d2h")
                   for d in snap_t["transfers"].values()), \
            f"no transfer bytes accounted: {snap_t['transfers']}"
        for family in ("parca_agent_kernel_duration_seconds",
                       "parca_agent_kernel_compiles_total",
                       "parca_agent_transfer_bytes_total",
                       "parca_agent_window_budget_windows_total",
                       "parca_agent_device_info"):
            assert f"# TYPE {family} " in metrics, \
                f"family {family} missing from /metrics"
        kernel_events = {
            (line.split('kernel="', 1)[1].split('"', 1)[0],
             line.split('event="', 1)[1].split('"', 1)[0])
            for line in metrics.splitlines()
            if line.startswith(
                "parca_agent_kernel_duration_seconds_count")}
        assert any(e == "compile" for _, e in kernel_events) \
            and any(e == "execute" for _, e in kernel_events), \
            f"compile/execute not separable in /metrics: {kernel_events}"
        device = json.loads(fetch("/debug/device"))
        assert device["identity"]["platform"]
        assert device["kernels"] and device["timeline"]["events"]
        print(f"trace-smoke: device telemetry latched "
              f"{sorted(kernels)} ({sum(i['compiles'] for i in kernels.values())}"
              f" compiles, 0 recompiles), "
              f"{len(device['timeline']['events'])} timeline events")

        # -- injected slow window --------------------------------------------
        # An 8 s device.dispatch hang: the primed close p99 is
        # compile-inflated (the first loop windows pay real XLA compiles
        # for the delta/feed programs, and a loaded CI host has pushed
        # that tail past 400 ms), so the 5x budget can reach ~2 s — the
        # hang must clear it decisively while staying well under the
        # 60 s watchdog. The window still ships, the detector fires,
        # exactly one incident lands.
        faults.install(faults.FaultInjector.from_spec(
            "device.dispatch:hang:ms=8000,count=1"))
        try:
            assert prof.run_iteration()
            assert prof._pipeline.flush(30)
        finally:
            faults.install(None)

        deadline = time.monotonic() + 15
        files = []
        while time.monotonic() < deadline:
            files = (sorted(os.listdir(incident_dir))
                     if os.path.isdir(incident_dir) else [])
            if files and not recorder._dumping:
                break
            time.sleep(0.05)
        assert len(files) == 1, f"expected exactly 1 incident, got {files}"
        incident = json.loads(
            open(os.path.join(incident_dir, files[0])).read())
        assert incident["kind"] == "slow_window"
        assert incident["trace"] is not None
        assert incident["trace"]["seq"] == n_prime + 1
        assert incident["self_profile_pprof_gz_b64"], "no self-profile"
        assert incident["context"] == {"smoke": True}
        slow_stages = [s["stage"] for s in incident["trace"]["spans"]
                       if s.get("slow")]
        assert slow_stages, "no span marked slow in the incident trace"

        # -- nothing lost ----------------------------------------------------
        assert prof.crashed is None and prof.last_error is None
        assert prof._pipeline.stats["windows_lost"] == 0
        assert prof.metrics.attempts_total == n_prime + 1
        done = recorder.stats["traces_completed"]
        assert done == n_prime + 1, \
            f"{done} of {n_prime + 1} traces completed"
        one = json.loads(fetch(f"/debug/trace/{n_prime + 1}"))
        assert one["meta"].get("slow_stage") in ("close", "total")
        print(f"trace-smoke: slow window produced exactly 1 incident "
              f"({files[0]}), slow stage "
              f"{one['meta']['slow_stage']!r}, windows_lost=0")

        # -- injected shape change -> one recompile incident -----------------
        # A window at twice the row count is a NEW feed signature on the
        # latched feed_probe kernel: the detector must count it and land
        # exactly one recompile_storm incident (the telemetry pre-filter
        # admits only its first recompile; the recorder's 0.5 s interval
        # has passed since the slow-window capture above).
        time.sleep(0.6)
        src.snaps.append(generate(SyntheticSpec(
            n_pids=6, n_unique_stacks=512, n_rows=512,
            total_samples=2048, mean_depth=8, seed=500)))
        assert prof.run_iteration()
        assert prof._pipeline.flush(30)
        assert dtel.stats["recompiles_total"] >= 1, \
            f"shape change latched no recompile: {dtel.stats}"

        deadline = time.monotonic() + 15
        storms = []
        while time.monotonic() < deadline:
            names = (sorted(os.listdir(incident_dir))
                     if os.path.isdir(incident_dir) else [])
            storms = []
            for name in names:
                with open(os.path.join(incident_dir, name)) as f:
                    body = json.load(f)
                if body["kind"] == "recompile_storm":
                    storms.append((name, body))
            if storms and not recorder._dumping:
                break
            time.sleep(0.05)
        assert len(storms) == 1, \
            f"expected exactly 1 recompile incident, got " \
            f"{[n for n, _ in storms]}"
        storm = storms[0][1]
        assert storm["detail"]["kernel"] == "feed_probe", storm["detail"]
        assert storm["detail"]["shapes_latched"] >= 2
        assert prof._pipeline.stats["windows_lost"] == 0
        assert prof.metrics.attempts_total == n_prime + 2
        print(f"trace-smoke: shape change produced exactly 1 recompile "
              f"incident ({storms[0][0]}, kernel "
              f"{storm['detail']['kernel']!r}), windows_lost=0")
        print("trace-smoke: PASS")
        return 0
    finally:
        http.stop()
        trace_mod.install(None)
        dtel_mod.install(None)


if __name__ == "__main__":
    sys.exit(main())
