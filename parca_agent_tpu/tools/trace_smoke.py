"""`make trace-smoke`: the window flight recorder's end-to-end drill.

Runs a short traced session through the real profiler loop (synthetic
capture, dict aggregator, fast encode, encode pipeline, HTTP surface)
and asserts the observability contract (docs/observability.md):

  1. `/debug/windows` returns >= 3 COMPLETE traces, each carrying every
     mandatory span (drain, close, prepare, encode, ship).
  2. `/metrics` parses and serves the stage-duration histogram for >= 6
     stages.
  3. One injected slow window (a `device.dispatch` hang well past the
     primed p99 budget) produces EXACTLY ONE incident file containing
     the offending trace and a self-profile — and zero windows are
     lost.

Exit 0 on success; raises (exit 1) with a readable assertion otherwise.
Host-side only: the Make target pins JAX_PLATFORMS=cpu.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import urllib.request


def main() -> int:
    # Like tests/conftest.py: the ambient sitecustomize may have forced
    # a device platform; the smoke is host-side by design.
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    from parca_agent_tpu.aggregator.cpu import CPUAggregator
    from parca_agent_tpu.aggregator.dict import DictAggregator
    from parca_agent_tpu.capture.synthetic import SyntheticSpec, generate
    from parca_agent_tpu.profiler.cpu import CPUProfiler
    from parca_agent_tpu.runtime.trace import (
        MANDATORY_SPANS,
        FlightRecorder,
    )
    from parca_agent_tpu.runtime import trace as trace_mod
    from parca_agent_tpu.utils import faults
    from parca_agent_tpu.web import AgentHTTPServer

    n_prime = int(os.environ.get("PARCA_TRACE_SMOKE_WINDOWS", "8"))
    tmp = tempfile.mkdtemp(prefix="parca-trace-smoke-")
    incident_dir = os.path.join(tmp, "incidents")

    snaps = [generate(SyntheticSpec(
        n_pids=6, n_unique_stacks=256, n_rows=256, total_samples=1024,
        mean_depth=8, seed=i)) for i in range(n_prime + 1)]

    class Src:
        def __init__(self):
            self.snaps = list(snaps)

        def poll(self):
            return self.snaps.pop(0) if self.snaps else None

    shipped = []

    class Sink:
        def write(self, labels, blob):
            shipped.append(len(blob))

    # Pre-warm the aggregation programs OUTSIDE the traced session: the
    # first window's XLA compile (seconds) would otherwise dominate the
    # close histogram's p99 and hide the injected stall behind an
    # inflated budget — a production agent is past compile within its
    # first window too.
    agg = DictAggregator(capacity=1 << 12)
    agg.window_counts(generate(SyntheticSpec(
        n_pids=6, n_unique_stacks=256, n_rows=256, total_samples=1024,
        mean_depth=8, seed=99)))

    recorder = FlightRecorder(
        ring=64, min_count=4, min_duration_s=0.05, slow_multiple=5.0,
        incident_dir=incident_dir,
        # A fast self-profile keeps the smoke quick; the incident still
        # carries a REAL gzipped pprof of the agent's threads.
        self_profile=None, self_profile_s=0.3,
        context=lambda: {"smoke": True})
    trace_mod.install(recorder)

    prof = CPUProfiler(
        source=Src(), aggregator=agg,
        fallback_aggregator=CPUAggregator(), profile_writer=Sink(),
        duration_s=0.0, fast_encode=True, encode_pipeline=True,
        trace_recorder=recorder)

    http = AgentHTTPServer(port=0, profilers=[prof], recorder=recorder)
    http.start()
    base = f"http://127.0.0.1:{http.port}"

    def fetch(path):
        with urllib.request.urlopen(base + path, timeout=10) as r:
            return r.read().decode()

    try:
        # -- prime: n_prime clean windows ------------------------------------
        for _ in range(n_prime):
            assert prof.run_iteration()
        assert prof._pipeline.flush(30)

        body = json.loads(fetch("/debug/windows"))
        complete = [t for t in body["traces"]
                    if t["complete"] and "error" not in t]
        assert len(complete) >= 3, f"only {len(complete)} complete traces"
        for t in complete:
            stages = {s["stage"] for s in t["spans"]}
            missing = set(MANDATORY_SPANS) - stages
            assert not missing, f"trace {t['seq']} missing spans {missing}"
        print(f"trace-smoke: {len(complete)} complete traces, "
              f"all mandatory spans present")

        metrics = fetch("/metrics")
        stages_in_metrics = {
            line.split('stage="', 1)[1].split('"', 1)[0]
            for line in metrics.splitlines()
            if line.startswith(
                "parca_agent_window_stage_duration_seconds_bucket")}
        assert len(stages_in_metrics) >= 6, \
            f"only {len(stages_in_metrics)} stages in /metrics: " \
            f"{sorted(stages_in_metrics)}"
        assert "# TYPE parca_agent_window_stage_duration_seconds " \
            "histogram" in metrics
        print(f"trace-smoke: /metrics histograms for "
              f"{len(stages_in_metrics)} stages: "
              f"{sorted(stages_in_metrics)}")

        # -- injected slow window --------------------------------------------
        # A 400 ms device.dispatch hang: ~2 orders of magnitude over the
        # primed close p99, well under the 60 s watchdog — the window
        # still ships, the detector fires, exactly one incident lands.
        faults.install(faults.FaultInjector.from_spec(
            "device.dispatch:hang:ms=400,count=1"))
        try:
            assert prof.run_iteration()
            assert prof._pipeline.flush(30)
        finally:
            faults.install(None)

        deadline = time.monotonic() + 15
        files = []
        while time.monotonic() < deadline:
            files = (sorted(os.listdir(incident_dir))
                     if os.path.isdir(incident_dir) else [])
            if files and not recorder._dumping:
                break
            time.sleep(0.05)
        assert len(files) == 1, f"expected exactly 1 incident, got {files}"
        incident = json.loads(
            open(os.path.join(incident_dir, files[0])).read())
        assert incident["kind"] == "slow_window"
        assert incident["trace"] is not None
        assert incident["trace"]["seq"] == n_prime + 1
        assert incident["self_profile_pprof_gz_b64"], "no self-profile"
        assert incident["context"] == {"smoke": True}
        slow_stages = [s["stage"] for s in incident["trace"]["spans"]
                       if s.get("slow")]
        assert slow_stages, "no span marked slow in the incident trace"

        # -- nothing lost ----------------------------------------------------
        assert prof.crashed is None and prof.last_error is None
        assert prof._pipeline.stats["windows_lost"] == 0
        assert prof.metrics.attempts_total == n_prime + 1
        done = recorder.stats["traces_completed"]
        assert done == n_prime + 1, \
            f"{done} of {n_prime + 1} traces completed"
        one = json.loads(fetch(f"/debug/trace/{n_prime + 1}"))
        assert one["meta"].get("slow_stage") in ("close", "total")
        print(f"trace-smoke: slow window produced exactly 1 incident "
              f"({files[0]}), slow stage "
              f"{one['meta']['slow_stage']!r}, windows_lost=0")
        print("trace-smoke: PASS")
        return 0
    finally:
        http.stop()
        trace_mod.install(None)


if __name__ == "__main__":
    sys.exit(main())
