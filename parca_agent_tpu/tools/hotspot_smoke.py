"""`make hotspot-smoke`: the hotspot rollup service's end-to-end drill.

Runs a short real profiler session (synthetic capture, dict aggregator,
fast encode, encode pipeline, hotspot store, HTTP surface) and asserts
the read-path contract (docs/hotspots.md):

  1. Every shipped window folds on the encode worker
     (windows_folded == windows shipped, zero fold errors).
  2. `/hotspots` serves top-K answers with human-readable frame context
     and candidate-exact counts; the label selector filters.
  3. Bad parameters (non-numeric k, negative range, unknown scope) are
     400s, never 500s.
  4. `scope=fleet` with no fleet attached degrades to a node-local
     answer flagged stale (fallback=local) — the endpoint always
     answers.
  5. `/metrics` exposes the rollup gauges in the strict grouped-family
     format and `/healthz` carries a `hotspots` section WITHOUT turning
     readiness red.

Exit 0 on success; raises (exit 1) with a readable assertion otherwise.
Host-side only: the Make target pins JAX_PLATFORMS=cpu.
"""

from __future__ import annotations

import json
import os
import sys
import urllib.error
import urllib.request


def main() -> int:
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    from parca_agent_tpu.aggregator.cpu import CPUAggregator
    from parca_agent_tpu.aggregator.dict import DictAggregator
    from parca_agent_tpu.capture.synthetic import SyntheticSpec, generate
    from parca_agent_tpu.ops.sketch import CountMinSpec
    from parca_agent_tpu.profiler.cpu import CPUProfiler
    from parca_agent_tpu.runtime.hotspots import HotspotSpec, HotspotStore
    from parca_agent_tpu.web import AgentHTTPServer

    n_windows = int(os.environ.get("PARCA_HOTSPOT_SMOKE_WINDOWS", "6"))
    snaps = [generate(SyntheticSpec(
        n_pids=6, n_unique_stacks=256, n_rows=256, total_samples=1024,
        mean_depth=8, seed=i)) for i in range(n_windows)]

    class Src:
        def __init__(self):
            self.snaps = list(snaps)

        def poll(self):
            return self.snaps.pop(0) if self.snaps else None

    class Sink:
        def write(self, labels, blob):
            pass

    store = HotspotStore(
        spec=HotspotSpec(k=10, candidates=128,
                         cm=CountMinSpec(depth=4, width=1 << 10)),
        window_s=10.0)
    prof = CPUProfiler(
        source=Src(), aggregator=DictAggregator(capacity=1 << 12),
        fallback_aggregator=CPUAggregator(), profile_writer=Sink(),
        duration_s=0.0, fast_encode=True, encode_pipeline=True,
        hotspot_store=store)

    http = AgentHTTPServer(port=0, profilers=[prof], hotspots=store)
    http.start()
    base = f"http://127.0.0.1:{http.port}"

    def fetch(path):
        with urllib.request.urlopen(base + path, timeout=10) as r:
            return r.read().decode()

    def status_of(path) -> int:
        try:
            with urllib.request.urlopen(base + path, timeout=10) as r:
                return r.status
        except urllib.error.HTTPError as e:
            return e.code

    try:
        for _ in range(n_windows):
            assert prof.run_iteration()
            # Per-window flush: the smoke drives windows back-to-back;
            # a backpressure fallback would (correctly) skip a fold.
            assert prof._pipeline.flush(30)
        assert prof._pipeline.quiesce(30)

        # -- every window folded on the worker -------------------------------
        pipe = prof._pipeline.stats
        assert pipe["windows_rolled"] == n_windows, pipe
        assert pipe["rollup_errors"] == 0, pipe
        assert pipe["windows_lost"] == 0, pipe
        assert store.stats["windows_folded"] == n_windows
        print(f"hotspot-smoke: {n_windows} windows folded on the encode "
              f"worker (last fold {store.stats['last_fold_s'] * 1e3:.2f} ms)")

        # -- the query API ---------------------------------------------------
        ans = json.loads(fetch("/hotspots?k=10"))
        assert ans["scope"] == "local" and ans["entries"], ans
        assert ans["total_samples"] == n_windows * 1024
        top = ans["entries"][0]
        assert top["count"] >= ans["entries"][-1]["count"]
        assert top["frames"], "top entry has no frame context"
        assert top["labels"] and "pid" in top["labels"]
        print(f"hotspot-smoke: /hotspots top-{ans['k']} served from "
              f"level={ans['level']} (top count {top['count']}, "
              f"frame[0]={top['frames'][0]!r})")

        # Label selector: the top pid's share only.
        pid = top["labels"]["pid"]
        sel = json.loads(fetch(f"/hotspots?k=10&pid={pid}"))
        assert sel["entries"], sel
        assert all(e["labels"]["pid"] == pid for e in sel["entries"])
        none = json.loads(fetch("/hotspots?k=10&pid=no-such-pid"))
        assert none["entries"] == []
        print(f"hotspot-smoke: label selector pid={pid} -> "
              f"{len(sel['entries'])} entries, bogus selector -> 0")

        # -- parameter hygiene -----------------------------------------------
        for bad in ("/hotspots?k=abc", "/hotspots?range=-5",
                    "/hotspots?scope=galaxy", "/hotspots?t0=9&t1=1",
                    "/hotspots?range=nan"):
            code = status_of(bad)
            assert code == 400, f"{bad} -> {code}, want 400"
        print("hotspot-smoke: bad parameters all 400")

        # -- fleet scope degrades, never refuses -----------------------------
        fleet = json.loads(fetch("/hotspots?scope=fleet"))
        assert fleet["fallback"] == "local" and fleet["stale"], fleet
        assert fleet["entries"], "fleet fallback served no entries"
        print("hotspot-smoke: fleet scope with no fleet -> node-local "
              "answer flagged stale")

        # -- observability ---------------------------------------------------
        metrics = fetch("/metrics")
        assert "# TYPE parca_agent_hotspot_level_bytes gauge" in metrics
        assert 'parca_agent_hotspot_level_summaries{level="window"' \
            in metrics
        assert "parca_agent_hotspot_windows_folded_total" in metrics
        healthz = json.loads(fetch("/healthz"))
        assert "hotspots" in healthz, healthz
        assert healthz["hotspots"]["windows_folded"] == n_windows
        assert status_of("/healthz") == 200
        print("hotspot-smoke: /metrics gauges present, /healthz hotspots "
              "section reported, readiness untouched")

        assert prof.crashed is None and prof.last_error is None
        print("hotspot-smoke: PASS")
        return 0
    finally:
        http.stop()
        if prof._pipeline is not None:
            prof._pipeline.close(10)


if __name__ == "__main__":
    sys.exit(main())
