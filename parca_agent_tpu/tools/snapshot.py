"""Inspect a saved window snapshot (the replayable map-dump format,
SURVEY.md §4 / BASELINE config #2).

Dev tool in the spirit of the reference's cmd/eh-frame: makes the capture
artifact a thing you can look at. Prints window stats (incl. depth
min/median/max), per-pid totals, and the top stacks by count.

Run: python -m parca_agent_tpu.tools.snapshot FILE [--top N] [--pids N]
"""

from __future__ import annotations

import argparse

import numpy as np

from parca_agent_tpu.capture.formats import WindowSnapshot, load_snapshot


def format_summary(snap: WindowSnapshot, top: int = 10,
                   pids: int = 10) -> str:
    n = len(snap)
    total = int(snap.counts.sum())
    uniq_pids = np.unique(snap.pids)
    depth = snap.user_len.astype(np.int64) + snap.kernel_len.astype(np.int64)
    lines = [
        f"rows: {n}",
        f"samples: {total}",
        f"pids: {len(uniq_pids)}",
        f"period_ns: {snap.period_ns}  window_ns: {snap.window_ns}",
        f"depth: min {int(depth.min()) if n else 0} "
        f"median {int(np.median(depth)) if n else 0} "
        f"max {int(depth.max()) if n else 0}",
        f"kernel frames: {int(snap.kernel_len.sum())} "
        f"user frames: {int(snap.user_len.sum())}",
        f"mappings: {len(snap.mappings.starts)} rows, "
        f"{len(snap.mappings.obj_paths)} objects",
    ]
    if n:
        upids, inv = np.unique(snap.pids, return_inverse=True)
        pid_totals = np.bincount(inv, weights=snap.counts.astype(np.float64))
        lines.append(f"top pids by samples (of {len(upids)}):")
        for j in np.argsort(-pid_totals)[:pids].tolist():
            lines.append(
                f"  pid {int(upids[j]):>7}  {int(pid_totals[j]):>10} samples")
        order = np.argsort(-snap.counts)[:top]
        lines.append("top stacks by count:")
        for i in order.tolist():
            d = int(depth[i])  # user frames then kernel frames
            frames = " ".join(f"{a:#x}" for a in snap.stacks[i, :min(d, 4)])
            more = f" …(+{d - 4})" if d > 4 else ""
            lines.append(
                f"  pid {int(snap.pids[i]):>7} x{int(snap.counts[i]):<8} "
                f"{frames}{more}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="print stats for a saved window snapshot")
    ap.add_argument("file")
    ap.add_argument("--top", type=int, default=10,
                    help="top stacks to list")
    ap.add_argument("--pids", type=int, default=10,
                    help="top pids to list")
    args = ap.parse_args(argv)
    snap = load_snapshot(args.file)
    print(format_summary(snap, top=args.top, pids=args.pids))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
