"""`make regress-smoke`: the regression sentinel's end-to-end drill.

Runs a short real profiler session (synthetic capture, dict aggregator,
fast encode, encode pipeline, hotspot store, regression sentinel,
alerts sink, HTTP surface) over a controlled window stream — a
stationary baseline phase, a clean control phase, then a 10x shift on
exactly ONE stack of one binary — and asserts the judgment contract
(docs/regression.md):

  1. Every shipped window folds into the sentinel on the encode worker
     (zero fold errors, zero windows lost, pprof ship untouched).
  2. The clean control windows produce ZERO verdicts (the noise floor,
     min-count, min-ratio, and sketch-bound gates all hold).
  3. The injected shift produces EXACTLY ONE `regressed` verdict,
     attributed to the right build-id, served on `/diff`.
  4. The alerts sink lands that verdict as a JSONL record on disk.
  5. `/diff` range mode answers over the hotspot store's levels with
     exact/estimate bounds; bad parameters are 400s, never 500s.
  6. `/metrics` exposes the parca_agent_regression_* families and
     `/healthz` carries a `regression` section WITHOUT turning
     readiness red.

Exit 0 on success; raises (exit 1) with a readable assertion otherwise.
Host-side only: the Make target pins JAX_PLATFORMS=cpu.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import tempfile
import urllib.error
import urllib.request

import numpy as np


def main() -> int:
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    from parca_agent_tpu.aggregator.cpu import CPUAggregator
    from parca_agent_tpu.aggregator.dict import DictAggregator
    from parca_agent_tpu.capture.synthetic import SyntheticSpec, generate
    from parca_agent_tpu.ops.sketch import CountMinSpec
    from parca_agent_tpu.profiler.cpu import CPUProfiler
    from parca_agent_tpu.runtime.hotspots import HotspotSpec, HotspotStore
    from parca_agent_tpu.runtime.regression import (
        RegressionSentinel,
        RegressionSpec,
    )
    from parca_agent_tpu.sinks import AlertsSink, PprofSink, SinkRegistry
    from parca_agent_tpu.web import AgentHTTPServer

    baseline_n = 3
    control_n = 4
    shifted_n = 2
    window_s = 10.0
    base = generate(SyntheticSpec(
        n_pids=6, n_unique_stacks=256, n_rows=256, total_samples=4096,
        mean_depth=8, seed=4))
    t0_ns = base.time_ns

    # The victim: the hottest row whose leaf lives in shared object 1
    # (synthetic build id f"{2:040x}" — see capture/synthetic.py).
    lo, hi = 0x0000_7F00_0000_0000, 0x0000_7F00_0000_0000 + (1 << 24)
    leaf = base.stacks[:, 0]
    in_obj = np.flatnonzero((leaf >= lo) & (leaf < hi))
    victim = int(in_obj[np.argmax(base.counts[in_obj])])
    victim_build = f"{2:040x}"

    def window(w: int, shifted: bool):
        counts = base.counts.copy()
        if shifted:
            counts[victim] *= 10
        return dataclasses.replace(
            base, counts=counts, time_ns=t0_ns + int(w * window_s * 1e9))

    snaps = [window(w, False) for w in range(baseline_n + control_n)]
    snaps += [window(baseline_n + control_n + i, True)
              for i in range(shifted_n)]
    # One trailing clean window seals the last shifted rollup.
    snaps.append(window(baseline_n + control_n + shifted_n, False))
    n_windows = len(snaps)

    class Src:
        def __init__(self):
            self.snaps = list(snaps)

        def poll(self):
            return self.snaps.pop(0) if self.snaps else None

    class Sink:
        def write(self, labels, blob):
            pass

    store = HotspotStore(
        spec=HotspotSpec(k=10, candidates=128,
                         cm=CountMinSpec(depth=4, width=1 << 10)),
        window_s=window_s)
    sent = RegressionSentinel(spec=RegressionSpec(
        interval_s=window_s, baseline_rollups=baseline_n, min_count=4,
        cm=CountMinSpec(depth=4, width=1 << 10)))
    alerts_path = os.path.join(tempfile.mkdtemp(prefix="regress-smoke-"),
                               "alerts.jsonl")
    sinks = SinkRegistry([PprofSink(),
                          AlertsSink(alerts_path, sentinel=sent)])
    prof = CPUProfiler(
        source=Src(), aggregator=DictAggregator(capacity=1 << 13),
        fallback_aggregator=CPUAggregator(), profile_writer=Sink(),
        duration_s=0.0, fast_encode=True, encode_pipeline=True,
        hotspot_store=store, regression=sent, sinks=sinks)

    http = AgentHTTPServer(port=0, profilers=[prof], hotspots=store,
                           regression=sent, sinks=sinks)
    http.start()
    base_url = f"http://127.0.0.1:{http.port}"

    def fetch(path):
        with urllib.request.urlopen(base_url + path, timeout=10) as r:
            return r.read().decode()

    def status_of(path) -> int:
        try:
            with urllib.request.urlopen(base_url + path, timeout=10) as r:
                return r.status
        except urllib.error.HTTPError as e:
            return e.code

    try:
        for w in range(n_windows):
            assert prof.run_iteration()
            assert prof._pipeline.flush(30)
            if w == baseline_n + control_n - 1:
                # End of the clean control: baseline frozen, judgment
                # live, and NOT ONE verdict fired.
                clean = json.loads(fetch("/diff"))
                assert clean["verdicts"] == [], clean["verdicts"]
                assert any(g["baseline_id"] for g in clean["groups"])
                print(f"regress-smoke: {control_n - baseline_n + 1} "
                      "judged clean rollups, zero verdicts (control "
                      "holds)")
        assert prof._pipeline.quiesce(30)

        # -- the fold contract ----------------------------------------------
        pipe = prof._pipeline.stats
        assert pipe["windows_lost"] == 0, pipe
        assert pipe["rollup_errors"] == 0, pipe
        assert sent.stats["fold_errors"] == 0
        assert sent.stats["windows_folded"] == n_windows
        print(f"regress-smoke: {n_windows} windows folded on the encode "
              f"worker (last fold "
              f"{sent.stats['last_fold_s'] * 1e3:.2f} ms)")

        # -- exactly one regressed verdict, right build ----------------------
        body = json.loads(fetch("/diff"))
        verdicts = body["verdicts"]
        assert len(verdicts) == 1, verdicts
        v = verdicts[0]
        assert v["kind"] == "regressed", v
        assert v["build"] == victim_build, v
        assert v["current"] > v["baseline"] * 1.5
        assert v["delta"] > v["threshold"]
        print(f"regress-smoke: the 10x shift -> exactly one regressed "
              f"verdict on build {v['build'][:8]}… "
              f"(baseline {v['baseline']}, current {v['current']}, "
              f"threshold {v['threshold']})")

        # -- the alerts sink landed it as JSONL ------------------------------
        with open(alerts_path) as f:
            records = [json.loads(ln) for ln in f]
        assert len(records) == 1 and records[0]["kind"] == "regressed"
        assert records[0]["build"] == victim_build
        print(f"regress-smoke: verdict on disk as JSONL "
              f"({alerts_path})")

        # -- range mode over the hotspot levels ------------------------------
        a0 = (t0_ns / 1e9) + (baseline_n + control_n) * window_s
        a1 = a0 + shifted_n * window_s
        b0, b1 = t0_ns / 1e9, a0
        rng_body = json.loads(fetch(
            f"/diff?a0={a0}&a1={a1}&b0={b0}&b1={b1}&k=5"))
        assert rng_body["mode"] == "range" and rng_body["entries"]
        top = rng_body["entries"][0]
        assert top["delta"] > 0
        assert top["delta_min"] <= top["delta"] <= top["delta_max"]
        print(f"regress-smoke: /diff range mode served "
              f"{len(rng_body['entries'])} bounded deltas from "
              f"level-backed answers (top delta {top['delta']})")

        # -- parameter hygiene -----------------------------------------------
        for bad in ("/diff?kind=bogus", "/diff?limit=0",
                    "/diff?a0=1&a1=2", "/diff?a0=1&a1=2&b0=3&b1=nan",
                    "/diff?since=inf", "/diff?tenant=%00bad"):
            code = status_of(bad)
            assert code == 400, f"{bad} -> {code}, want 400"
        print("regress-smoke: bad parameters all 400")

        # -- observability ---------------------------------------------------
        metrics = fetch("/metrics")
        assert "# TYPE parca_agent_regression_windows_folded_total " \
               "counter" in metrics
        assert 'parca_agent_regression_verdicts_total{kind="regressed"}'\
            " 1" in metrics
        assert "parca_agent_regression_baselines " in metrics
        healthz = json.loads(fetch("/healthz"))
        assert "regression" in healthz, healthz
        assert healthz["regression"]["fold_errors"] == 0
        assert healthz["regression"]["verdicts"]["regressed"] == 1
        assert status_of("/healthz") == 200
        print("regress-smoke: /metrics families present, /healthz "
              "regression section reported, readiness untouched")

        assert prof.crashed is None and prof.last_error is None
        print("regress-smoke: PASS")
        return 0
    finally:
        http.stop()
        if prof._pipeline is not None:
            prof._pipeline.close(10)


if __name__ == "__main__":
    sys.exit(main())
