"""Developer CLI tools (reference cmd/eh-frame)."""
