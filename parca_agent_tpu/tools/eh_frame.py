"""Dump the computed compact unwind table for a binary.

Role of the reference's dev tool cmd/eh-frame/main.go:33-52 (printing via
unwind_table.go:185-233): `python -m parca_agent_tpu.tools.eh_frame BIN`.
"""

from __future__ import annotations

import argparse
import sys

from parca_agent_tpu.elf.reader import ElfFile
from parca_agent_tpu.unwind.table import (
    CFA_TYPE_END_OF_FDE,
    CFA_TYPE_EXPRESSION,
    CFA_TYPE_RBP,
    CFA_TYPE_RSP,
    RBP_TYPE_OFFSET,
    RBP_TYPE_REGISTER,
    build_compact_table,
)

_CFA_NAMES = {CFA_TYPE_RSP: "rsp", CFA_TYPE_RBP: "rbp"}


def format_table(table) -> str:
    lines = []
    for row in table:
        pc = int(row["pc"])
        ct = int(row["cfa_type"])
        if ct == CFA_TYPE_END_OF_FDE:
            lines.append(f"\tpc: {pc:x} .... end of FDE / unsupported")
            continue
        if ct == CFA_TYPE_EXPRESSION:
            cfa = f"exp (plt {int(row['cfa_off'])})"
        else:
            cfa = f"{_CFA_NAMES[ct]}+{int(row['cfa_off'])}"
        rt = int(row["rbp_type"])
        if rt == RBP_TYPE_OFFSET:
            rbp = f"cfa{int(row['rbp_off']):+d}"
        elif rt == RBP_TYPE_REGISTER:
            rbp = f"reg {int(row['rbp_off'])}"
        else:
            rbp = "u"
        lines.append(f"\tpc: {pc:x} cfa: {cfa} rbp: {rbp} ra: cfa-8")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="print the compact DWARF unwind table for an ELF binary"
    )
    ap.add_argument("binary")
    args = ap.parse_args(argv)
    with open(args.binary, "rb") as f:
        ef = ElfFile(f.read())
    sec = ef.section(".eh_frame")
    if sec is None:
        print("no .eh_frame section", file=sys.stderr)
        return 1
    table = build_compact_table(ef.section_data(sec), sec.addr)
    print(f"{len(table)} rows")
    print(format_table(table))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
