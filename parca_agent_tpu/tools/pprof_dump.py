"""Inspect a pprof profile the agent wrote (.pb or .pb.gz).

Dev tool in the spirit of tools/snapshot.py: makes the OUTPUT artifact a
thing you can look at without a Parca server — header metadata, totals,
and the top stacks by self count, decoded through the same parser the
tests trust (pprof/builder.parse_pprof).

Run: python -m parca_agent_tpu.tools.pprof_dump FILE [--top N]
"""

from __future__ import annotations

import argparse
import gzip

from parca_agent_tpu.pprof.builder import ParsedProfile, parse_pprof


def format_profile(p: ParsedProfile, top: int = 10) -> str:
    total = sum(v[0] for _, v, _ in p.samples)
    lines = [
        f"sample_types: {p.sample_types}",
        f"period: {p.period} {p.period_type[1]} ({p.period_type[0]})",
        f"time_nanos: {p.time_nanos}  duration_nanos: {p.duration_nanos}",
        f"samples: {len(p.samples)} rows, {total} total",
        f"locations: {len(p.locations)}  mappings: {len(p.mappings)}  "
        f"functions: {len(p.functions)}  strings: {len(p.strings)}",
    ]
    if p.mappings:
        shown = sorted(p.mappings)[:8]
        more = (f" (+{len(p.mappings) - len(shown)} more)"
                if len(p.mappings) > len(shown) else "")
        lines.append(f"mappings:{more}")
        for mid in shown:
            m = p.mappings[mid]
            lines.append(
                f"  #{mid} {m['start']:#x}-{m['limit']:#x} "
                f"off={m['offset']:#x} {m['filename'] or '?'} "
                f"build_id={m['build_id'][:16] or '-'}")
    ranked = sorted(p.samples, key=lambda s: -s[1][0])[:top]
    lines.append(f"top {len(ranked)} stacks:")
    for loc_ids, vals, labels in ranked:
        frames = []
        for lid in loc_ids[:6]:
            loc = p.locations.get(lid)
            if loc is None:
                frames.append("?")
                continue
            if loc["lines"]:
                fid = loc["lines"][0][0]
                fn = p.functions.get(fid, {}).get("name", "")
                frames.append(fn or f"{loc['address']:#x}")
            else:
                frames.append(f"{loc['address']:#x}")
        more = f" ... +{len(loc_ids) - 6}" if len(loc_ids) > 6 else ""
        lab = f"  {labels}" if labels else ""
        lines.append(f"  {vals[0]:>8}  {' ; '.join(frames)}{more}{lab}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="pprof-dump", description=__doc__.splitlines()[0])
    ap.add_argument("file")
    ap.add_argument("--top", type=int, default=10)
    args = ap.parse_args(argv)
    with open(args.file, "rb") as f:
        data = f.read()
    # parse_pprof sniffs one gzip layer itself; peel any extras here
    # (files written before the double-gzip fix carry two layers).
    while data[:2] == b"\x1f\x8b":
        data = gzip.decompress(data)
    try:
        print(format_profile(parse_pprof(data), top=args.top))
    except BrokenPipeError:
        pass  # piped into head; normal CLI etiquette
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
