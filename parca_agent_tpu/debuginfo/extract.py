"""Strip a binary to only what symbolization needs.

Role of the reference's pkg/debuginfo/extract.go:46-123: keep DWARF debug
sections, symbol tables, notes, and the Go symbol tables; drop text, data
and relocation payload. Implemented on the filtering ELF writer
(parca_agent_tpu/elf/writer.py).
"""

from __future__ import annotations

from parca_agent_tpu.elf.reader import Section
from parca_agent_tpu.elf.writer import filter_elf

# Prefixes/names kept, matching extract.go's isDWARF/isSymbolTable/isNote
# predicates.
KEEP_SECTIONS = (
    ".debug_", ".zdebug_", ".gdb_index",
    ".symtab", ".strtab", ".dynsym", ".dynstr",
    ".note.",
    ".gosymtab", ".gopclntab", ".go.buildinfo",
    ".gnu_debuglink",
)


def _keep(sec: Section) -> bool:
    return sec.name.startswith(KEEP_SECTIONS)


def extract_debuginfo(data: bytes) -> bytes:
    """Return a minimal valid ELF with only symbolization sections."""
    return filter_elf(data, _keep)
