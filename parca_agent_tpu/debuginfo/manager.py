"""Debuginfo upload manager: dedup, extract, ship.

Role of the reference's pkg/debuginfo/manager.go: called once per profiler
iteration with the window's object files; work happens asynchronously so
the capture loop never blocks on uploads (manager.go:130-155, errgroup
limit 4 -> ThreadPoolExecutor(4) here). Per-build-id dedup via three
caches: `uploading` (in-flight singleflight), `exists` (server-confirmed),
`failed` (don't retry hopeless binaries every window) —
manager.go:116-127,226-248.

Flow per new build id (manager.go:157-270): prefer a separate debug file
found on disk (Finder), else extract/strip the mapped binary; validate the
result parses as ELF; ask the server Exists(build_id, hash) first; upload
only on miss.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Protocol

from parca_agent_tpu.debuginfo.extract import extract_debuginfo
from parca_agent_tpu.debuginfo.find import Finder
from parca_agent_tpu.elf.reader import ElfFile
from parca_agent_tpu.process.maps import host_path
from parca_agent_tpu.utils import poison
from parca_agent_tpu.utils.poison import PoisonInput, read_bounded
from parca_agent_tpu.utils.log import get_logger

_log = get_logger("debuginfo")
from parca_agent_tpu.utils.vfs import VFS, RealFS


class DebuginfoClient(Protocol):
    """Server interface (reference client.go:22-38)."""

    def exists(self, build_id: str, hash_: str) -> bool: ...
    def upload(self, build_id: str, hash_: str, data: bytes) -> None: ...


class NoopClient:
    """Default when no remote store is configured (client.go:27-38)."""

    def exists(self, build_id: str, hash_: str) -> bool:
        return True  # pretend present: nothing to do

    def upload(self, build_id: str, hash_: str, data: bytes) -> None:
        pass


@dataclasses.dataclass
class UploadStats:
    uploaded: int = 0
    already_present: int = 0
    extracted: int = 0
    found_separate: int = 0
    errors: int = 0


class DebuginfoManager:
    def __init__(self, client: DebuginfoClient | None = None,
                 fs: VFS | None = None, finder: Finder | None = None,
                 workers: int = 4, failed_ttl_s: float = 600.0,
                 exists_ttl_s: float = 300.0, strip: bool = True,
                 clock=None):
        import time as _time

        self._client = client or NoopClient()
        self._fs = fs or RealFS()
        self._finder = finder or Finder(fs=self._fs)
        self._pool = ThreadPoolExecutor(max_workers=workers,
                                        thread_name_prefix="debuginfo")
        self._lock = threading.Lock()
        self._uploading: dict[str, object] = {}   # build_id -> Future
        # Server-confirmed build ids, cached with a TTL (the reference's
        # --debuginfo-upload-cache-duration, default 5m): servers can
        # garbage-collect, so "exists" is a lease, not a fact.
        self._exists: dict[str, float] = {}       # build_id -> confirmed_at
        self._exists_ttl = exists_ttl_s
        # strip=False uploads the exact binary unmodified (the
        # reference's --debuginfo-strip=false).
        self._strip = strip
        # Failures expire so a transient store outage doesn't blacklist a
        # binary for the agent's lifetime (the reference's caches are
        # TTL-based for the same reason).
        self._failed: dict[str, float] = {}       # build_id -> failed_at
        self._failed_ttl = failed_ttl_s
        self._clock = clock or _time.monotonic
        self.stats = UploadStats()

    def ensure_uploaded(self, objfiles: list[tuple[int, str, str]]) -> None:
        """objfiles: (pid, path, build_id). Fire-and-forget per iteration
        (manager.go:130-155); call drain() to wait (tests, shutdown)."""
        for pid, path, build_id in objfiles:
            if not build_id:
                continue
            with self._lock:
                failed_at = self._failed.get(build_id)
                if failed_at is not None:
                    if self._clock() - failed_at < self._failed_ttl:
                        continue
                    del self._failed[build_id]
                confirmed_at = self._exists.get(build_id)
                if confirmed_at is not None:
                    if self._clock() - confirmed_at < self._exists_ttl:
                        continue
                    del self._exists[build_id]
                if build_id in self._uploading:
                    continue
                fut = self._pool.submit(self._process, pid, path, build_id)
                self._uploading[build_id] = fut
                fut.add_done_callback(
                    lambda _f, b=build_id: self._uploading.pop(b, None)
                )

    def drain(self) -> None:
        while True:
            with self._lock:
                futs = list(self._uploading.values())
            if not futs:
                return
            for f in futs:
                f.result()

    def close(self) -> None:
        self.drain()
        self._pool.shutdown(wait=True)

    # -- internals ----------------------------------------------------------

    def _process(self, pid: int, path: str, build_id: str) -> None:
        try:
            data = self._debug_payload(pid, path, build_id)
            if data is None:
                with self._lock:
                    self._failed[build_id] = self._clock()
                    self.stats.errors += 1
                return
            h = hashlib.sha256(data).hexdigest()
            if self._client.exists(build_id, h):
                with self._lock:
                    self._exists[build_id] = self._clock()
                    self.stats.already_present += 1
                return
            self._client.upload(build_id, h, data)
            with self._lock:
                self._exists[build_id] = self._clock()
                self.stats.uploaded += 1
        except Exception as e:
            with self._lock:
                self._failed[build_id] = self._clock()
                self.stats.errors += 1
            _log.warn("debuginfo upload failed", build_id=build_id,
                      error=repr(e))

    def _debug_payload(self, pid: int, path: str, build_id: str) -> bytes | None:
        try:
            # Bounded: the path comes from the target's mount namespace —
            # a staged multi-GB sparse "binary" must not OOM the agent.
            raw = read_bounded(self._fs, host_path(pid, path),
                               poison.ELF_READ_CAP)
        except (OSError, PoisonInput):
            return None
        sep = self._finder.find(pid, path, data=raw, build_id=build_id)
        if sep is not None:
            try:
                payload = read_bounded(self._fs, sep, poison.ELF_READ_CAP)
                ElfFile(payload)  # validate
                with self._lock:
                    self.stats.found_separate += 1
                return payload
            except (OSError, PoisonInput):
                pass
        if not self._strip:
            # --debuginfo-strip=false: ship the exact binary unmodified
            # (reference main.go flag semantics).
            try:
                ElfFile(raw)
            except ElfError:
                return None
            return raw
        try:
            payload = extract_debuginfo(raw)
            ElfFile(payload)  # validate round-trips
        except (ElfError, Exception):
            return None
        with self._lock:
            self.stats.extracted += 1
        return payload
