"""Debuginfo extract/find/upload (reference pkg/debuginfo, layer L4)."""

from parca_agent_tpu.debuginfo.find import Finder
from parca_agent_tpu.debuginfo.extract import extract_debuginfo, KEEP_SECTIONS
from parca_agent_tpu.debuginfo.manager import (
    DebuginfoClient,
    DebuginfoManager,
    NoopClient,
)

__all__ = [
    "Finder", "extract_debuginfo", "KEEP_SECTIONS",
    "DebuginfoClient", "DebuginfoManager", "NoopClient",
]
