"""Locate separate debuginfo files for a binary.

Role of the reference's pkg/debuginfo/find.go:61-229. Search order:

  1. build-id path:   <debug_dir>/.build-id/<xx>/<rest>.debug
  2. .gnu_debuglink:  the linked filename, searched in the binary's
     directory, its .debug/ subdir, and <debug_dir>/<binary dir>/ — with
     the section's CRC32 checked against the candidate (find.go:150-229)
  3. canonical:       <debug_dir><binary path>.debug

All lookups go through the target's mount namespace (/proc/PID/root...),
like every other file access in the agent.
"""

from __future__ import annotations

import dataclasses
import os
import struct
import zlib

from parca_agent_tpu.elf.reader import ElfFile
from parca_agent_tpu.utils import poison
from parca_agent_tpu.utils.poison import PoisonInput, read_bounded
from parca_agent_tpu.process.maps import host_path
from parca_agent_tpu.utils.vfs import VFS, RealFS

DEFAULT_DEBUG_DIRS = ("/usr/lib/debug",)


def debuglink(ef: ElfFile) -> tuple[str, int] | None:
    """(filename, crc32) from .gnu_debuglink, if present."""
    sec = ef.section(".gnu_debuglink")
    if sec is None:
        return None
    data = ef.section_data(sec)
    end = data.find(b"\x00")
    if end < 0 or len(data) < end + 4:
        return None
    name = data[:end].decode(errors="replace")
    crc_off = (end + 4) // 4 * 4
    if len(data) < crc_off + 4:
        return None
    crc = struct.unpack_from("<I", data, crc_off)[0]
    return name, crc


@dataclasses.dataclass
class Finder:
    fs: VFS = dataclasses.field(default_factory=RealFS)
    debug_dirs: tuple[str, ...] = DEFAULT_DEBUG_DIRS

    def find(self, pid: int, binary_path: str, data: bytes | None = None,
             build_id: str | None = None) -> str | None:
        """Path (host-side, through /proc/PID/root) of the best separate
        debuginfo file, or None."""
        if data is None:
            try:
                data = read_bounded(self.fs, host_path(pid, binary_path),
                                    poison.ELF_READ_CAP)
            except (OSError, PoisonInput):
                return None
        try:
            ef = ElfFile(data)
        except PoisonInput:
            return None
        if build_id is None:
            from parca_agent_tpu.elf.buildid import gnu_build_id

            build_id = gnu_build_id(ef)

        # 1. by build id
        if build_id and len(build_id) > 2:
            for d in self.debug_dirs:
                p = host_path(
                    pid, f"{d}/.build-id/{build_id[:2]}/{build_id[2:]}.debug"
                )
                if self.fs.exists(p):
                    return p

        # 2. by .gnu_debuglink + CRC
        link = debuglink(ef)
        if link is not None:
            name, crc = link
            bin_dir = os.path.dirname(binary_path)
            candidates = [
                f"{bin_dir}/{name}",
                f"{bin_dir}/.debug/{name}",
            ]
            candidates += [f"{d}{bin_dir}/{name}" for d in self.debug_dirs]
            for c in candidates:
                p = host_path(pid, c)
                if not self.fs.exists(p):
                    continue
                try:
                    # Bounded: candidates live under the target's mount
                    # namespace — a staged sparse bomb must not be read.
                    if zlib.crc32(read_bounded(self.fs, p,
                                               poison.ELF_READ_CAP)) == crc:
                        return p
                except (OSError, PoisonInput):
                    continue

        # 3. canonical path
        for d in self.debug_dirs:
            p = host_path(pid, f"{d}{binary_path}.debug")
            if self.fs.exists(p):
                return p
        return None
