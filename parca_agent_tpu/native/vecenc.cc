// Native varint emission for the pprof window encoder (pprof/vec.py).
//
// The numpy byte-plane encoder is whole-array vectorized, but at north-star
// scale (~25M frame varints per window) its gather/scatter passes go
// memory-system-superlinear: measured 1.67 s for 25M values vs 0.15 s for
// 3.1M (11x for 8x) on the dev host. One sequential C pass emits the same
// stream in ~0.1 s: positions arrive sorted ascending, so the write
// pattern is a forward walk with tiny holes (the per-id section headers).
//
// Same wire contract as proto.put_varint (unsigned LEB128; callers
// pre-mask negatives to two's-complement uint64). The reference's encoder
// leans on Go's gzip/proto machinery for this role (pkg/profiler/pprof.go);
// here the hot loop is native with the numpy path as a build-less fallback.

#include <cstddef>
#include <cstdint>

extern "C" {

// Byte length of each value's unsigned LEB128 varint (1..10), matching
// vec.varint_len: ceil(bit_length/7), with 0 -> 1 byte.
void pa_varint_lens(const uint64_t* vals, int64_t n, int32_t* out) {
  for (int64_t i = 0; i < n; i++) {
    int bits = 64 - __builtin_clzll(vals[i] | 1);
    out[i] = (bits + 6) / 7;
  }
}

// Emit vals[i]'s varint at out + pos[i]. Regions are caller-sized
// (pa_varint_lens / vec.varint_len) and non-overlapping; the minimal
// LEB128 encoding written here fills each region exactly. Returns -1, or
// the first index whose region would leave [0, out_len) — checked before
// writing (the numpy path raises IndexError on a bad caller; silent heap
// corruption here would be strictly worse).
int64_t pa_put_varints(uint8_t* out, int64_t out_len, const int64_t* pos,
                       const uint64_t* vals, int64_t n) {
  for (int64_t i = 0; i < n; i++) {
    uint64_t v = vals[i];
    int bits = 64 - __builtin_clzll(v | 1);
    int64_t len = (bits + 6) / 7;
    if (pos[i] < 0 || pos[i] + len > out_len) return i;
    uint8_t* p = out + pos[i];
    while (v >= 0x80) {
      *p++ = static_cast<uint8_t>(v) | 0x80;
      v >>= 7;
    }
    *p = static_cast<uint8_t>(v);
  }
  return -1;
}

// Fixed-width (non-minimal) varints for the template patch path
// (vec.put_varints_padded): continuation bit on all but the last of
// `width` bytes. Caller guarantees width >= varint_len(max value).
int64_t pa_put_varints_padded(uint8_t* out, int64_t out_len,
                              const int64_t* pos, const uint64_t* vals,
                              int64_t n, int32_t width) {
  if (width < 1 && n > 0) return 0;  // final byte write is unconditional
  for (int64_t i = 0; i < n; i++) {
    if (pos[i] < 0 || pos[i] + width > out_len) return i;
    uint8_t* p = out + pos[i];
    uint64_t v = vals[i];
    for (int32_t k = 0; k < width - 1; k++) {
      *p++ = static_cast<uint8_t>(v & 0x7F) | 0x80;
      v >>= 7;
    }
    *p = static_cast<uint8_t>(v & 0x7F);
  }
  return -1;
}

// Batched multilinear row hash for the dict aggregator's feed path
// (ops/hashing.py row_hash_np). The numpy twin materializes the full
// [N, 2*slots+3] uint32 lane matrix (hi | lo | pid | ulen | klen) and
// multiply-sums it — ~1 GB of transient traffic per 1M-row window at
// 128 slots, almost all of it zero padding. One native pass walks only
// each row's LIVE prefix (depth[i] = user_len + kernel_len; the
// WindowSnapshot contract zero-pads past it, and a zero lane
// contributes coef*0 == 0 to a multilinear hash), so per-row work is
// proportional to stack depth, not the 128-slot pad. All arithmetic is
// uint32 with natural wraparound — bit-identical to the numpy path's
// uint32 multiply/sum/mix for any contract-valid (zero-padded) row.
//
// Layout contract (validated by the Python wrapper): coefs is row-major
// [n_fam, coef_stride] with coef_stride >= 2*slots + 3; family f hashes
// hi-lane s with coefs[f*stride + s], lo-lane s with
// coefs[f*stride + slots + s], then pid/ulen/klen at 2*slots + {0,1,2}.
// out is row-major [n_fam, n]. n_fam is capped at 4 (the hash-family
// count baked into ops/hashing.py) — checked here because writing
// through a caller-undersized acc would corrupt the stack.
int64_t pa_row_hash(const uint64_t* stacks, int64_t n, int64_t slots,
                    const uint32_t* pids, const uint32_t* ulen,
                    const uint32_t* klen, const int32_t* depth,
                    const uint32_t* coefs, int64_t coef_stride,
                    const uint32_t* biases, int64_t n_fam, uint32_t* out) {
  if (n_fam < 1 || n_fam > 4 || coef_stride < 2 * slots + 3) return 0;
  for (int64_t i = 0; i < n; i++) {
    uint32_t acc[4] = {0, 0, 0, 0};
    const uint64_t* row = stacks + i * slots;
    int64_t d = depth[i];
    if (d < 0) d = 0;
    if (d > slots) d = slots;
    for (int64_t s = 0; s < d; s++) {
      uint64_t v = row[s];
      if (!v) continue;  // zero lane: coef*0 contributes nothing
      uint32_t hi = static_cast<uint32_t>(v >> 32);
      uint32_t lo = static_cast<uint32_t>(v);
      for (int64_t f = 0; f < n_fam; f++) {
        const uint32_t* c = coefs + f * coef_stride;
        acc[f] += c[s] * hi + c[slots + s] * lo;
      }
    }
    for (int64_t f = 0; f < n_fam; f++) {
      const uint32_t* c = coefs + f * coef_stride;
      uint32_t x = acc[f] + c[2 * slots] * pids[i] +
                   c[2 * slots + 1] * ulen[i] + c[2 * slots + 2] * klen[i] +
                   biases[f];
      // mix32 finalizer (ops/hashing.py mix32, seed 0).
      x ^= x >> 16;
      x *= 0x85EBCA6Bu;
      x ^= x >> 13;
      x *= 0xC2B2AE35u;
      x ^= x >> 16;
      out[f * n + i] = x;
    }
  }
  return -1;
}

// Ragged byte-run copy for vec.ragged_gather: run i is
// src[src_pos[i], src_pos[i]+lens[i]) -> dst[dst_pos[i], ...). The numpy
// fallback pays per-ELEMENT fancy indexing (repeat + arange + gather —
// ~3 int64 index ops per byte); the template layout's sample-prefix and
// statics splices move tens of MB per window, where a forward memcpy
// walk is ~20x cheaper. All positions/lengths are BYTE offsets (the
// Python wrapper scales by itemsize). Returns -1, or the first index
// whose run leaves either buffer — checked before any write.
int64_t pa_ragged_copy(uint8_t* dst, int64_t dst_len, const uint8_t* src,
                       int64_t src_len, const int64_t* src_pos,
                       const int64_t* dst_pos, const int64_t* lens,
                       int64_t n) {
  for (int64_t i = 0; i < n; i++) {
    int64_t l = lens[i];
    if (l < 0 || src_pos[i] < 0 || src_pos[i] + l > src_len ||
        dst_pos[i] < 0 || dst_pos[i] + l > dst_len)
      return i;
    __builtin_memcpy(dst + dst_pos[i], src + src_pos[i],
                     static_cast<size_t>(l));
  }
  return -1;
}

}  // extern "C"
