// perf_event whole-machine stack sampler behind a C ABI.
//
// The native capture component of the framework (the role the eBPF C
// program plays in the reference, bpf/cpu/cpu.bpf.c: per-CPU 100 Hz
// sampling with kernel+user call chains). Where the reference's BPF
// program aggregates in kernel maps, this sampler ships raw records and
// the (much faster, batched) aggregation happens in the Aggregator --
// capture stays dumb, aggregation stays pluggable.
//
// One perf_event_open(PERF_COUNT_SW_CPU_CLOCK, freq) per online CPU with
// PERF_SAMPLE_TID | PERF_SAMPLE_CALLCHAIN (the perf-subsystem equivalent
// of the reference's kernel + frame-pointer unwind paths). In DWARF mode
// (pa_sampler_create2 with PA_CAPTURE_USER_STACK) the kernel also
// snapshots user registers and a slice of the user stack per sample
// (PERF_SAMPLE_REGS_USER | PERF_SAMPLE_STACK_USER -- how `perf record
// --call-graph dwarf` captures; the role of the reference's in-kernel
// DWARF walker inputs, bpf/cpu/cpu.bpf.c:464-674), and the drain-time
// batched unwinder (parca_agent_tpu/unwind/walker.py) applies the
// .eh_frame tables to recover frameless user stacks.
//
// Each CPU gets a mmap'd ring; drain() walks every ring and packs records
// into the caller's buffer.
//
// v1 record (no user-stack capture):
//   u32 pid | u32 tid | u32 n_kernel | u32 n_user
//   | u64 frames[n_kernel + n_user]                      (kernel first)
//
// v2 record (PA_CAPTURE_USER_STACK mode):
//   u32 pid | u32 tid | u32 n_kernel | u32 n_user
//   | u64 rip | u64 rsp | u64 rbp                        (0 if unavailable)
//   | u32 dyn_size | u32 _pad
//   | u64 frames[n_kernel + n_user]
//   | u8  stack[align8(dyn_size)]                        (memory at rsp)
//
// Drain contract: returns bytes written. A record that does not fit in the
// caller's buffer is LEFT IN ITS RING (that ring's tail is committed only
// up to the records already copied) and the truncation counter increments;
// the caller drains again to fetch the remainder. Records are never
// discarded once their ring tail has been committed.
//
// Build: make -C parca_agent_tpu/native  (g++ -shared -fPIC)

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <cstdlib>

#include <atomic>

#include <fcntl.h>
#include <linux/perf_event.h>
#include <sys/ioctl.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMaxFrames = 127;  // reference depth cap (cpu.bpf.c:22-27)
constexpr size_t kRingPagesFp = 64;      // 256 KiB of ring per CPU
constexpr size_t kRingPagesStack = 512;  // 2 MiB per CPU when dumping stacks

// PERF_CONTEXT_* sentinels that delimit kernel vs user frames in callchains.
constexpr uint64_t kContextKernel = 0xffffffffffffff80ull;  // PERF_CONTEXT_KERNEL
constexpr uint64_t kContextUser = 0xfffffffffffffe00ull;    // PERF_CONTEXT_USER
constexpr uint64_t kContextMax = 0xfffffffffffff000ull;     // any marker >= this

// x86_64 perf_regs indices (arch/x86/include/uapi/asm/perf_regs.h).
constexpr int kRegBp = 6;
constexpr int kRegSp = 7;
constexpr int kRegIp = 8;
constexpr uint64_t kRegsMask = (1ull << kRegBp) | (1ull << kRegSp) | (1ull << kRegIp);

struct PerCpu {
  int fd = -1;
  void* ring = nullptr;
  size_t ring_size = 0;
  uint64_t tail = 0;  // our consumer position (data_tail mirror)
};

struct Sampler {
  PerCpu* cpus = nullptr;
  int n_cpus = 0;
  int freq = 0;
  bool capture_stack = false;
  uint32_t dump_bytes = 0;
  std::atomic<bool> running{false};
  uint64_t lost = 0;       // PERF_RECORD_LOST accounting
  uint64_t truncated = 0;  // drain calls that ran out of caller buffer
  uint8_t* scratch = nullptr;  // wrapped-record copy buffer
  size_t scratch_size = 0;
  // Dedup-drain hash table (lazily allocated; see pa_sampler_drain_dedup).
  uint64_t* dd_hash = nullptr;
  long* dd_off = nullptr;
  size_t dd_cap = 0;
  bool dd_dirty = false;    // previous dedup drain registered entries
  uint64_t dedup_hits = 0;  // records merged instead of re-emitted
  uint64_t dd_overflow = 0; // probe budget exhausted: emitted unregistered
  // Capture-side row-hash tables (pa_sampler_set_hash): Python owns the
  // seeded multilinear coefficients (ops/hashing.py _COEFS/_BIASES) and
  // installs contiguous copies here, so the hashes the dedup drain carries
  // are bit-identical to row_hash_np. n_fam == 0 means not installed and
  // pa_sampler_drain_dedup2 refuses (-3): the caller falls back to the
  // hashless v1d drain.
  uint32_t* hash_coefs = nullptr;  // [n_fam][stride]
  uint32_t hash_biases[8] = {0, 0, 0, 0, 0, 0, 0, 0};
  long hash_stride = 0;
  long hash_slots = 0;
  int hash_nfam = 0;
};

// FNV-1a over the sample identity (pid, tid, nk, nu, frames).
uint64_t fnv1a(const uint8_t* p, size_t n, uint64_t h = 1469598103934665603ull) {
  for (size_t i = 0; i < n; i++) {
    h ^= p[i];
    h *= 1099511628211ull;
  }
  return h;
}

// fmix32 finalizer (murmur3-style) — the C twin of ops/hashing.py mix32.
inline uint32_t fmix32(uint32_t x) {
  x ^= x >> 16;
  x *= 0x85EBCA6Bu;
  x ^= x >> 13;
  x *= 0xC2B2AE35u;
  x ^= x >> 16;
  return x;
}

// Multilinear row hash over a split (kernel, user) frame pair — the
// drain-side twin of ops/hashing.py row_hash_np over the snapshot row the
// record decodes to. The snapshot row stores USER frames first then the
// kernel tail, zero-padded to `slots`; the lane matrix is
// [hi(slots) | lo(slots) | pid | user_len | kernel_len] with family
// coefficients c: zero pad lanes contribute c*0, so walking only the live
// depth is bit-identical to the full lane matrix (same argument the
// vecenc.cc pa_row_hash kernel rests on). Frames may not exceed `slots`
// (caller-guarded: kMaxFrames < STACK_SLOTS).
inline void stack_hash_mix(const uint64_t* kframes, uint32_t nk,
                           const uint64_t* uframes, uint32_t nu,
                           uint32_t pid, const uint32_t* coefs, long stride,
                           const uint32_t* biases, int n_fam, long slots,
                           uint32_t* out) {
  for (int f = 0; f < n_fam; f++) {
    const uint32_t* c = coefs + f * stride;
    uint32_t acc = 0;
    // User frames occupy row slots [0, nu); kernel tail [nu, nu + nk).
    for (uint32_t s = 0; s < nu; s++) {
      uint64_t fr = uframes[s];
      if (!fr) continue;
      acc += c[s] * static_cast<uint32_t>(fr >> 32)
           + c[slots + s] * static_cast<uint32_t>(fr);
    }
    for (uint32_t s = 0; s < nk; s++) {
      uint64_t fr = kframes[s];
      if (!fr) continue;
      acc += c[nu + s] * static_cast<uint32_t>(fr >> 32)
           + c[slots + nu + s] * static_cast<uint32_t>(fr);
    }
    acc += c[2 * slots] * pid + c[2 * slots + 1] * nu
         + c[2 * slots + 2] * nk;
    out[f] = fmix32(acc + biases[f]);
  }
}

long perf_open(int cpu, int freq, bool capture_stack, uint32_t dump_bytes) {
  perf_event_attr attr;
  std::memset(&attr, 0, sizeof(attr));
  attr.size = sizeof(attr);
  attr.type = PERF_TYPE_SOFTWARE;
  attr.config = PERF_COUNT_SW_CPU_CLOCK;
  attr.sample_freq = static_cast<uint64_t>(freq);
  attr.freq = 1;  // PerfBitFreq in the reference (cpu.go:236-243)
  attr.sample_type = PERF_SAMPLE_TID | PERF_SAMPLE_CALLCHAIN;
  if (capture_stack) {
    attr.sample_type |= PERF_SAMPLE_REGS_USER | PERF_SAMPLE_STACK_USER;
    attr.sample_regs_user = kRegsMask;
    attr.sample_stack_user = dump_bytes;
  }
  attr.disabled = 1;
  attr.inherit = 0;
  attr.exclude_hv = 1;
  attr.sample_max_stack = kMaxFrames;
  // pid = -1, cpu = N: whole-machine, per-CPU (needs perf_event_paranoid
  // <= 0 or CAP_PERFMON, like the reference needs CAP_BPF).
  return syscall(SYS_perf_event_open, &attr, -1, cpu, -1, PERF_FLAG_FD_CLOEXEC);
}

void destroy_partial(Sampler* s, int opened) {
  for (int j = 0; j < opened; j++) {
    munmap(s->cpus[j].ring, s->cpus[j].ring_size);
    close(s->cpus[j].fd);
  }
  delete[] s->cpus;
  delete[] s->scratch;
  delete[] s->dd_hash;
  delete[] s->dd_off;
  delete[] s->hash_coefs;
  delete s;
}


// Shared perf-ring record walk: wrap/scratch handling, LOST accounting,
// context-marker frame splitting, and the leave-in-ring tail-commit
// protocol live HERE, once, for every drain flavor. `emit` receives each
// parsed sample (payload/rec_end cover the bytes after the callchain for
// mode-specific parsing) and returns false when the caller's buffer is
// full — the record is then left in its ring for the next drain.
template <typename EmitFn>
void walk_rings(Sampler* s, EmitFn&& emit) {
  bool out_full = false;
  size_t page = static_cast<size_t>(sysconf(_SC_PAGESIZE));
  for (int i = 0; i < s->n_cpus && !out_full; i++) {
    PerCpu& pc = s->cpus[i];
    auto* meta = static_cast<perf_event_mmap_page*>(pc.ring);
    uint8_t* data = static_cast<uint8_t*>(pc.ring) + page;
    uint64_t data_size = pc.ring_size - page;
    uint64_t head = __atomic_load_n(&meta->data_head, __ATOMIC_ACQUIRE);
    uint64_t tail = meta->data_tail;
    while (tail < head) {
      auto* hdr = reinterpret_cast<perf_event_header*>(
          data + (tail % data_size));
      // Records can wrap the ring; copy out when they do.
      uint8_t* rec = reinterpret_cast<uint8_t*>(hdr);
      if ((tail % data_size) + hdr->size > data_size) {
        uint64_t first = data_size - (tail % data_size);
        if (hdr->size <= s->scratch_size) {
          std::memcpy(s->scratch, rec, first);
          std::memcpy(s->scratch + first, data, hdr->size - first);
          rec = s->scratch;
          hdr = reinterpret_cast<perf_event_header*>(rec);
        } else {  // oversized wrapped record: skip
          tail += hdr->size;
          continue;
        }
      }
      if (hdr->type == PERF_RECORD_LOST) {
        // { header; u64 id; u64 lost; }
        s->lost += *reinterpret_cast<uint64_t*>(rec + sizeof(*hdr) + 8);
      } else if (hdr->type == PERF_RECORD_SAMPLE) {
        // layout for our sample_type (in ABI order):
        //   u32 pid, tid; u64 nr; u64 ips[nr];
        //   [u64 regs_abi; u64 regs[3] if abi != NONE]
        //   [u64 stack_size; u8 stack[stack_size]; u64 dyn_size if size]
        uint8_t* p = rec + sizeof(*hdr);
        uint8_t* rec_end = rec + hdr->size;
        uint32_t pid, tid;
        std::memcpy(&pid, p, 4);
        std::memcpy(&tid, p + 4, 4);
        p += 8;
        uint64_t nr;
        std::memcpy(&nr, p, 8);
        p += 8;
        if (nr <= kMaxFrames + 8 && p + 8 * nr <= rec_end) {
          uint64_t kframes[kMaxFrames], uframes[kMaxFrames];
          uint32_t nk = 0, nu = 0;
          int mode = 0;  // 0 unknown, 1 kernel, 2 user
          for (uint64_t f = 0; f < nr; f++) {
            uint64_t ip;
            std::memcpy(&ip, p + 8 * f, 8);
            if (ip >= kContextMax) {
              if (ip == kContextKernel) mode = 1;
              else if (ip == kContextUser) mode = 2;
              else mode = 0;
              continue;
            }
            if (mode == 1 && nk < kMaxFrames) kframes[nk++] = ip;
            else if (mode == 2 && nu < kMaxFrames) uframes[nu++] = ip;
          }
          p += 8 * nr;
          if (!emit(pid, tid, kframes, nk, uframes, nu, p, rec_end)) {
            // Leave this record (and the rest of this ring) for the
            // next drain; commit only what we already consumed.
            s->truncated++;
            out_full = true;
            break;
          }
        }
      }
      tail += hdr->size;
    }
    __atomic_store_n(&meta->data_tail, tail, __ATOMIC_RELEASE);
    pc.tail = tail;
  }
}

}  // namespace

extern "C" {

// flags for pa_sampler_create2
enum { PA_CAPTURE_USER_STACK = 1 };

// Returns nullptr on failure; errno preserved from the first failing call.
// dump_bytes (user-stack slice per sample) must be a multiple of 8 and
// < 64 KiB per the perf ABI; 0 picks the 16 KiB default.
Sampler* pa_sampler_create2(int freq_hz, int flags, uint32_t dump_bytes) {
  long n = sysconf(_SC_NPROCESSORS_ONLN);
  if (n <= 0) return nullptr;
  bool capture_stack = (flags & PA_CAPTURE_USER_STACK) != 0;
  if (capture_stack) {
    if (dump_bytes == 0) dump_bytes = 16 * 1024;
    dump_bytes &= ~7u;
    if (dump_bytes > 63 * 1024) dump_bytes = 63 * 1024;
  } else {
    dump_bytes = 0;
  }
  Sampler* s = new Sampler();
  s->n_cpus = static_cast<int>(n);
  s->freq = freq_hz;
  s->capture_stack = capture_stack;
  s->dump_bytes = dump_bytes;
  s->cpus = new PerCpu[n];
  s->scratch_size = 128 * 1024;
  s->scratch = new uint8_t[s->scratch_size];
  size_t page = static_cast<size_t>(sysconf(_SC_PAGESIZE));
  size_t data_pages = capture_stack ? kRingPagesStack : kRingPagesFp;
  size_t ring_size = (data_pages + 1) * page;
  for (int i = 0; i < n; i++) {
    long fd = perf_open(i, freq_hz, capture_stack, dump_bytes);
    if (fd < 0) {
      int saved = errno;
      destroy_partial(s, i);
      errno = saved;
      return nullptr;
    }
    void* ring = mmap(nullptr, ring_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                      static_cast<int>(fd), 0);
    if (ring == MAP_FAILED) {
      int saved = errno;
      close(static_cast<int>(fd));
      destroy_partial(s, i);
      errno = saved;
      return nullptr;
    }
    s->cpus[i].fd = static_cast<int>(fd);
    s->cpus[i].ring = ring;
    s->cpus[i].ring_size = ring_size;
  }
  return s;
}

Sampler* pa_sampler_create(int freq_hz) {
  return pa_sampler_create2(freq_hz, 0, 0);
}

int pa_sampler_n_cpus(Sampler* s) { return s ? s->n_cpus : 0; }
uint64_t pa_sampler_lost(Sampler* s) { return s ? s->lost : 0; }
uint64_t pa_sampler_truncated(Sampler* s) { return s ? s->truncated : 0; }
int pa_sampler_capture_stack(Sampler* s) {
  return s && s->capture_stack ? 1 : 0;
}

int pa_sampler_start(Sampler* s) {
  if (!s) return -1;
  for (int i = 0; i < s->n_cpus; i++) {
    if (ioctl(s->cpus[i].fd, PERF_EVENT_IOC_ENABLE, 0) != 0) return -1;
  }
  s->running.store(true);
  return 0;
}

int pa_sampler_stop(Sampler* s) {
  if (!s) return -1;
  for (int i = 0; i < s->n_cpus; i++) {
    ioctl(s->cpus[i].fd, PERF_EVENT_IOC_DISABLE, 0);
  }
  s->running.store(false);
  return 0;
}

// Drain all rings into out (capacity cap bytes). Returns bytes written;
// see the drain contract at the top of this file. Returns -1 only on
// invalid arguments.
long pa_sampler_drain(Sampler* s, uint8_t* out, long cap) {
  if (!s || !out || cap < 0) return -1;
  long written = 0;
  walk_rings(s, [&](uint32_t pid, uint32_t tid, uint64_t* kframes,
                    uint32_t nk, uint64_t* uframes, uint32_t nu,
                    uint8_t* p, uint8_t* rec_end) -> bool {
    uint64_t rip = 0, rsp = 0, rbp = 0;
    uint8_t* stack = nullptr;
    uint64_t dyn = 0;
    bool parse_ok = true;
    if (s->capture_stack) {
      // REGS_USER: abi word, then one u64 per set mask bit in
      // ascending bit order: BP(6), SP(7), IP(8).
      if (p + 8 <= rec_end) {
        uint64_t abi;
        std::memcpy(&abi, p, 8);
        p += 8;
        if (abi != 0 /* PERF_SAMPLE_REGS_ABI_NONE */) {
          if (p + 24 <= rec_end) {
            std::memcpy(&rbp, p, 8);
            std::memcpy(&rsp, p + 8, 8);
            std::memcpy(&rip, p + 16, 8);
            p += 24;
          } else {
            parse_ok = false;
          }
        }
      } else {
        parse_ok = false;
      }
      // STACK_USER: size word, raw bytes, dyn_size word.
      if (parse_ok && p + 8 <= rec_end) {
        uint64_t size;
        std::memcpy(&size, p, 8);
        p += 8;
        if (size) {
          if (p + size + 8 <= rec_end) {
            stack = p;
            p += size;
            std::memcpy(&dyn, p, 8);
            p += 8;
            if (dyn > size) dyn = size;
          } else {
            parse_ok = false;
          }
        }
      }
    }

    if (!(parse_ok && nk + nu + (rip ? 1 : 0) > 0 && nk + nu <= kMaxFrames))
      return true;  // unusable sample: consumed, nothing emitted
    uint64_t dyn_pad = (dyn + 7) & ~7ull;
    long need = 16 + 8l * (nk + nu);
    if (s->capture_stack) need += 32 + static_cast<long>(dyn_pad);
    if (written + need > cap) return false;
    uint8_t* o = out + written;
    std::memcpy(o, &pid, 4);
    std::memcpy(o + 4, &tid, 4);
    std::memcpy(o + 8, &nk, 4);
    std::memcpy(o + 12, &nu, 4);
    o += 16;
    if (s->capture_stack) {
      uint32_t dyn32 = static_cast<uint32_t>(dyn);
      uint32_t zero = 0;
      std::memcpy(o, &rip, 8);
      std::memcpy(o + 8, &rsp, 8);
      std::memcpy(o + 16, &rbp, 8);
      std::memcpy(o + 24, &dyn32, 4);
      std::memcpy(o + 28, &zero, 4);
      o += 32;
    }
    std::memcpy(o, kframes, 8l * nk);
    std::memcpy(o + 8l * nk, uframes, 8l * nu);
    o += 8l * (nk + nu);
    if (s->capture_stack && dyn_pad) {
      std::memcpy(o, stack, dyn);
      std::memset(o + dyn, 0, dyn_pad - dyn);
    }
    written += need;
    return true;
  });
  return written;
}

// ---- dedup drain: capture-side (pid, tid, stack) -> count -------------
//
// The envelope restorer: the reference aggregates (pid, stack) -> count
// IN KERNEL (bpf/cpu/cpu.bpf.c:110-116,457-461) so its userspace never
// sees per-sample records; the raw drain above ships every sample. At
// 100 Hz x many CPUs the stream is dominated by repeats of a small hot
// set, so this drain dedups AT THE DRAIN BOUNDARY in native code: an
// open-addressing (FNV-1a, memcmp-verified) table maps each record's
// identity to its already-written output record and bumps a count field
// instead of re-emitting. Python then decodes ~unique rows per drain.
//
// v1d record:
//   u32 pid | u32 tid | u32 n_kernel | u32 n_user | u32 count | u32 _pad
//   | u64 frames[n_kernel + n_user]                      (kernel first)
//
// FP/callchain mode only (-2 in DWARF mode: v2 records carry per-sample
// stack slices, which are never byte-identical). Dedup is best-effort
// within one drain pass — table overflow or cross-pass repeats emit
// separate records, which the aggregator merges anyway; counts are exact
// either way.

long pa_sampler_drain_dedup(Sampler* s, uint8_t* out, long cap) {
  if (!s || !out || cap < 0) return -1;
  if (s->capture_stack) return -2;
  if (!s->dd_hash) {
    s->dd_cap = 1 << 16;
    s->dd_hash = new uint64_t[s->dd_cap]();  // zeroed: first pass skips memset
    s->dd_off = new long[s->dd_cap];
  }
  // The 512 KB clear only matters if the previous pass registered
  // entries; idle drains (empty rings) skip it entirely.
  if (s->dd_dirty) {
    std::memset(s->dd_hash, 0, s->dd_cap * sizeof(uint64_t));
    s->dd_dirty = false;
  }
  const uint64_t dd_mask = s->dd_cap - 1;

  long written = 0;
  walk_rings(s, [&](uint32_t pid, uint32_t tid, uint64_t* kframes,
                    uint32_t nk, uint64_t* uframes, uint32_t nu,
                    uint8_t*, uint8_t*) -> bool {
    uint32_t nf = nk + nu;
    if (nf == 0 || nf > kMaxFrames) return true;  // consumed, not emitted
    uint32_t ident[4] = {pid, tid, nk, nu};
    uint64_t h = fnv1a(reinterpret_cast<uint8_t*>(ident), 16);
    h = fnv1a(reinterpret_cast<uint8_t*>(kframes), 8ul * nk, h);
    h = fnv1a(reinterpret_cast<uint8_t*>(uframes), 8ul * nu, h);
    if (h == 0) h = 1;
    uint64_t idx = h & dd_mask;
    for (int probes = 0; probes < 64; probes++) {
      if (s->dd_hash[idx] == 0) break;
      if (s->dd_hash[idx] == h) {
        // ident covers nk/nu, so the frame memcmp lengths below are
        // validated by the 16-byte header compare.
        uint8_t* o = out + s->dd_off[idx];
        if (std::memcmp(o, ident, 16) == 0 &&
            std::memcmp(o + 24, kframes, 8ul * nk) == 0 &&
            std::memcmp(o + 24 + 8ul * nk, uframes, 8ul * nu) == 0) {
          uint32_t c;
          std::memcpy(&c, o + 16, 4);
          c++;
          std::memcpy(o + 16, &c, 4);
          s->dedup_hits++;
          return true;
        }
      }
      idx = (idx + 1) & dd_mask;
    }
    long need = 24 + 8l * nf;
    if (written + need > cap) return false;
    uint8_t* o = out + written;
    uint32_t one = 1, zero = 0;
    std::memcpy(o, ident, 16);
    std::memcpy(o + 16, &one, 4);
    std::memcpy(o + 20, &zero, 4);
    std::memcpy(o + 24, kframes, 8l * nk);
    std::memcpy(o + 24 + 8l * nk, uframes, 8l * nu);
    if (s->dd_hash[idx] == 0) {  // probe budget not exhausted
      s->dd_hash[idx] = h;
      s->dd_off[idx] = written;
      s->dd_dirty = true;
    } else {
      // Table saturated along this probe chain: the record is emitted
      // unregistered, so later repeats in this pass emit separately too.
      // Counts stay exact; only the pre-aggregation envelope degrades.
      // This counter lets production tell overflow from true uniqueness.
      s->dd_overflow++;
    }
    written += need;
    return true;
  });
  return written;
}

uint64_t pa_sampler_dedup_hits(Sampler* s) { return s ? s->dedup_hits : 0; }

uint64_t pa_sampler_dedup_overflow(Sampler* s) {
  return s ? s->dd_overflow : 0;
}

// v1d decoders: like v1 below but with the 24-byte header carrying the
// drain-side count.
long pa_decode_v1d_count(const uint8_t* buf, long len, long stack_slots) {
  long pos = 0, n = 0;
  while (pos + 24 <= len) {
    uint32_t hdr[4];
    std::memcpy(hdr, buf + pos, 16);
    long nf = (long)hdr[2] + (long)hdr[3];
    if (nf > (long)kMaxFrames || nf > stack_slots ||
        pos + 24 + 8 * nf > len)
      break;
    pos += 24 + 8 * nf;
    n++;
  }
  return n;
}

long pa_decode_v1d(const uint8_t* buf, long len,
                   int32_t* pids, int32_t* tids,
                   int32_t* ulen, int32_t* klen, int64_t* counts,
                   uint64_t* stacks, long stack_slots, long cap) {
  long pos = 0, n = 0;
  while (pos + 24 <= len && n < cap) {
    uint32_t hdr[6];
    std::memcpy(hdr, buf + pos, 24);
    long nk = hdr[2], nu = hdr[3];
    long nf = nk + nu;
    if (nf > (long)kMaxFrames || nf > stack_slots ||
        pos + 24 + 8 * nf > len)
      break;
    pids[n] = (int32_t)hdr[0];
    tids[n] = (int32_t)hdr[1];
    klen[n] = (int32_t)nk;
    ulen[n] = (int32_t)nu;
    counts[n] = (int64_t)hdr[4];
    uint64_t* row = stacks + n * stack_slots;
    std::memcpy(row, buf + pos + 24 + 8 * nk, 8 * nu);
    std::memcpy(row + nu, buf + pos + 24, 8 * nk);
    pos += 24 + 8 * nf;
    n++;
  }
  return n;
}

// ---- v1h drain: dedup + capture-side hash carry -----------------------
//
// The hash half of the feed endgame (docs/perf.md "feed endgame"): the
// h1/h2/h3 triple the dictionary aggregator keys on is computed HERE,
// while the record's frames are hot in cache from the dedup memcmp,
// instead of re-walking every row on the Python side one drain later.
// The mix is the same multilinear family as ops/hashing.py (Python
// installs its seeded coefficient tables via pa_sampler_set_hash — the C
// side cannot regenerate numpy-seeded streams), so the carried triple is
// bit-identical to row_hash_np over the decoded snapshot row.
//
// v1h record:
//   u32 pid | u32 tid | u32 n_kernel | u32 n_user | u32 count
//   | u32 h1 | u32 h2 | u32 h3
//   | u64 frames[n_kernel + n_user]                      (kernel first)

// Install per-family hash constants. coefs is [n_fam][stride] row-major
// with stride >= 2*slots + 3 lanes; biases is [n_fam]. Returns 0, or -1
// on invalid arguments. slots is the snapshot row width (STACK_SLOTS) —
// the lane layout splits at it, so drain records and snapshot rows hash
// identically only when it matches the Python side's constant.
int pa_sampler_set_hash(Sampler* s, const uint32_t* coefs, long stride,
                        const uint32_t* biases, int n_fam, long slots) {
  if (!s || !coefs || !biases || n_fam < 1 || n_fam > 8 ||
      slots < (long)kMaxFrames || stride < 2 * slots + 3)
    return -1;
  delete[] s->hash_coefs;
  s->hash_coefs = new uint32_t[(size_t)n_fam * stride];
  std::memcpy(s->hash_coefs, coefs, (size_t)n_fam * stride * 4);
  std::memcpy(s->hash_biases, biases, (size_t)n_fam * 4);
  s->hash_stride = stride;
  s->hash_slots = slots;
  s->hash_nfam = n_fam;
  return 0;
}

// Like pa_sampler_drain_dedup, emitting v1h records with the hash triple
// computed once per UNIQUE record (dedup hits only bump the count — the
// hash depends on neither count nor the probe order). Returns -3 when no
// hash tables are installed (caller falls back to the v1d drain).
long pa_sampler_drain_dedup2(Sampler* s, uint8_t* out, long cap) {
  if (!s || !out || cap < 0) return -1;
  if (s->capture_stack) return -2;
  if (s->hash_nfam < 3) return -3;
  if (!s->dd_hash) {
    s->dd_cap = 1 << 16;
    s->dd_hash = new uint64_t[s->dd_cap]();
    s->dd_off = new long[s->dd_cap];
  }
  if (s->dd_dirty) {
    std::memset(s->dd_hash, 0, s->dd_cap * sizeof(uint64_t));
    s->dd_dirty = false;
  }
  const uint64_t dd_mask = s->dd_cap - 1;

  long written = 0;
  walk_rings(s, [&](uint32_t pid, uint32_t tid, uint64_t* kframes,
                    uint32_t nk, uint64_t* uframes, uint32_t nu,
                    uint8_t*, uint8_t*) -> bool {
    uint32_t nf = nk + nu;
    if (nf == 0 || nf > kMaxFrames) return true;  // consumed, not emitted
    uint32_t ident[4] = {pid, tid, nk, nu};
    uint64_t h = fnv1a(reinterpret_cast<uint8_t*>(ident), 16);
    h = fnv1a(reinterpret_cast<uint8_t*>(kframes), 8ul * nk, h);
    h = fnv1a(reinterpret_cast<uint8_t*>(uframes), 8ul * nu, h);
    if (h == 0) h = 1;
    uint64_t idx = h & dd_mask;
    for (int probes = 0; probes < 64; probes++) {
      if (s->dd_hash[idx] == 0) break;
      if (s->dd_hash[idx] == h) {
        uint8_t* o = out + s->dd_off[idx];
        if (std::memcmp(o, ident, 16) == 0 &&
            std::memcmp(o + 32, kframes, 8ul * nk) == 0 &&
            std::memcmp(o + 32 + 8ul * nk, uframes, 8ul * nu) == 0) {
          uint32_t c;
          std::memcpy(&c, o + 16, 4);
          c++;
          std::memcpy(o + 16, &c, 4);
          s->dedup_hits++;
          return true;
        }
      }
      idx = (idx + 1) & dd_mask;
    }
    long need = 32 + 8l * nf;
    if (written + need > cap) return false;
    uint32_t triple[3];
    stack_hash_mix(kframes, nk, uframes, nu, pid, s->hash_coefs,
                   s->hash_stride, s->hash_biases, 3, s->hash_slots,
                   triple);
    uint8_t* o = out + written;
    uint32_t one = 1;
    std::memcpy(o, ident, 16);
    std::memcpy(o + 16, &one, 4);
    std::memcpy(o + 20, triple, 12);
    std::memcpy(o + 32, kframes, 8l * nk);
    std::memcpy(o + 32 + 8l * nk, uframes, 8l * nu);
    if (s->dd_hash[idx] == 0) {
      s->dd_hash[idx] = h;
      s->dd_off[idx] = written;
      s->dd_dirty = true;
    } else {
      s->dd_overflow++;
    }
    written += need;
    return true;
  });
  return written;
}

// v1h decoders: the v1d pair plus the carried hash triple.
long pa_decode_v1h_count(const uint8_t* buf, long len, long stack_slots) {
  long pos = 0, n = 0;
  while (pos + 32 <= len) {
    uint32_t hdr[4];
    std::memcpy(hdr, buf + pos, 16);
    long nf = (long)hdr[2] + (long)hdr[3];
    if (nf > (long)kMaxFrames || nf > stack_slots ||
        pos + 32 + 8 * nf > len)
      break;
    pos += 32 + 8 * nf;
    n++;
  }
  return n;
}

long pa_decode_v1h(const uint8_t* buf, long len,
                   int32_t* pids, int32_t* tids,
                   int32_t* ulen, int32_t* klen, int64_t* counts,
                   uint32_t* h1, uint32_t* h2, uint32_t* h3,
                   uint64_t* stacks, long stack_slots, long cap) {
  long pos = 0, n = 0;
  while (pos + 32 <= len && n < cap) {
    uint32_t hdr[8];
    std::memcpy(hdr, buf + pos, 32);
    long nk = hdr[2], nu = hdr[3];
    long nf = nk + nu;
    if (nf > (long)kMaxFrames || nf > stack_slots ||
        pos + 32 + 8 * nf > len)
      break;
    pids[n] = (int32_t)hdr[0];
    tids[n] = (int32_t)hdr[1];
    klen[n] = (int32_t)nk;
    ulen[n] = (int32_t)nu;
    counts[n] = (int64_t)hdr[4];
    h1[n] = hdr[5];
    h2[n] = hdr[6];
    h3[n] = hdr[7];
    uint64_t* row = stacks + n * stack_slots;
    std::memcpy(row, buf + pos + 32 + 8 * nk, 8 * nu);
    std::memcpy(row + nu, buf + pos + 32, 8 * nk);
    pos += 32 + 8 * nf;
    n++;
  }
  return n;
}

// Direct hash entry (no Sampler, no perf privileges): the bit-identity
// tests drive the SAME helper the dedup drain uses over arbitrary split
// (kernel, user) frame pairs and compare against row_hash_np. Returns 0,
// or -1 on invalid arguments.
int pa_stack_hash(const uint64_t* kframes, long nk,
                  const uint64_t* uframes, long nu, uint32_t pid,
                  const uint32_t* coefs, long stride,
                  const uint32_t* biases, long n_fam, long slots,
                  uint32_t* out) {
  if ((!kframes && nk > 0) || (!uframes && nu > 0) ||
      !coefs || !biases || !out ||
      nk < 0 || nu < 0 || n_fam < 1 || n_fam > 8 ||
      nk + nu > slots || stride < 2 * slots + 3)
    return -1;
  stack_hash_mix(kframes, (uint32_t)nk, uframes, (uint32_t)nu, pid,
                 coefs, stride, biases, (int)n_fam, slots, out);
  return 0;
}

// ---- v1 drain decode: packed records -> columnar arrays ---------------
// Per record: u32 pid, tid, nk, nu | (nk + nu) u64 frames, KERNEL first
// (the drain writer above). Decoding in native code replaces two Python
// per-record loops on the once-a-second capture path. Both functions
// stop at a corrupt/truncated tail exactly like the Python decoder, so
// the prefix parsed so far is kept.

// stack_slots is passed here too so count and decode apply the SAME
// acceptance rule and can never disagree on the record count.
long pa_decode_v1_count(const uint8_t* buf, long len, long stack_slots) {
  long pos = 0, n = 0;
  while (pos + 16 <= len) {
    uint32_t hdr[4];
    std::memcpy(hdr, buf + pos, 16);
    long nf = (long)hdr[2] + (long)hdr[3];
    if (nf > (long)kMaxFrames || nf > stack_slots ||
        pos + 16 + 8 * nf > len)
      break;
    pos += 16 + 8 * nf;
    n++;
  }
  return n;
}

// stacks: [cap][stack_slots] u64, written USER frames first then kernel
// tail (the WindowSnapshot row contract); rows must be pre-zeroed by the
// caller. Returns the number of records written.
long pa_decode_v1(const uint8_t* buf, long len,
                  int32_t* pids, int32_t* tids,
                  int32_t* ulen, int32_t* klen,
                  uint64_t* stacks, long stack_slots, long cap) {
  long pos = 0, n = 0;
  while (pos + 16 <= len && n < cap) {
    uint32_t hdr[4];
    std::memcpy(hdr, buf + pos, 16);
    long nk = hdr[2], nu = hdr[3];
    long nf = nk + nu;
    if (nf > (long)kMaxFrames || nf > stack_slots ||
        pos + 16 + 8 * nf > len)
      break;
    pids[n] = (int32_t)hdr[0];
    tids[n] = (int32_t)hdr[1];
    klen[n] = (int32_t)nk;
    ulen[n] = (int32_t)nu;
    uint64_t* row = stacks + n * stack_slots;
    std::memcpy(row, buf + pos + 16 + 8 * nk, 8 * nu);
    std::memcpy(row + nu, buf + pos + 16, 8 * nk);
    pos += 16 + 8 * nf;
    n++;
  }
  return n;
}

void pa_sampler_destroy(Sampler* s) {
  if (!s) return;
  pa_sampler_stop(s);
  for (int i = 0; i < s->n_cpus; i++) {
    if (s->cpus[i].ring) munmap(s->cpus[i].ring, s->cpus[i].ring_size);
    if (s->cpus[i].fd >= 0) close(s->cpus[i].fd);
  }
  delete[] s->cpus;
  delete[] s->scratch;
  delete[] s->dd_hash;
  delete[] s->dd_off;
  delete[] s->hash_coefs;
  delete s;
}

}  // extern "C"
