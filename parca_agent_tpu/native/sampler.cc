// perf_event whole-machine stack sampler behind a C ABI.
//
// The native capture component of the framework (the role the eBPF C
// program plays in the reference, bpf/cpu/cpu.bpf.c: per-CPU 100 Hz
// sampling with kernel+user call chains). Where the reference's BPF
// program aggregates in kernel maps, this sampler ships raw records and
// the (much faster, batched) aggregation happens in the Aggregator --
// capture stays dumb, aggregation stays pluggable.
//
// One perf_event_open(PERF_COUNT_SW_CPU_CLOCK, freq) per online CPU with
// PERF_SAMPLE_TID | PERF_SAMPLE_CALLCHAIN (the perf-subsystem equivalent
// of the reference's two unwind paths: the kernel walks both kernel and
// frame-pointer user stacks for us). Each CPU gets a mmap'd ring; drain()
// walks every ring and packs records into the caller's buffer:
//
//   record := u32 pid | u32 tid | u32 n_kernel | u32 n_user
//             | u64 frames[n_kernel + n_user]            (kernel first)
//
// Python (capture/live.py) turns these into WindowSnapshot rows.
//
// Build: make -C parca_agent_tpu/native  (g++ -shared -fPIC)

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <cstdlib>

#include <atomic>

#include <fcntl.h>
#include <linux/perf_event.h>
#include <sys/ioctl.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMaxFrames = 127;  // reference depth cap (cpu.bpf.c:22-27)
constexpr size_t kRingPages = 64;     // 256 KiB of ring per CPU + header page

// PERF_CONTEXT_* sentinels that delimit kernel vs user frames in callchains.
constexpr uint64_t kContextKernel = 0xffffffffffffff80ull;  // PERF_CONTEXT_KERNEL
constexpr uint64_t kContextUser = 0xfffffffffffffe00ull;    // PERF_CONTEXT_USER
constexpr uint64_t kContextMax = 0xfffffffffffff000ull;     // any marker >= this

struct PerCpu {
  int fd = -1;
  void* ring = nullptr;
  size_t ring_size = 0;
  uint64_t tail = 0;  // our consumer position (data_tail mirror)
};

struct Sampler {
  PerCpu* cpus = nullptr;
  int n_cpus = 0;
  int freq = 0;
  std::atomic<bool> running{false};
  uint64_t lost = 0;  // PERF_RECORD_LOST accounting
};

long perf_open(int cpu, int freq) {
  perf_event_attr attr;
  std::memset(&attr, 0, sizeof(attr));
  attr.size = sizeof(attr);
  attr.type = PERF_TYPE_SOFTWARE;
  attr.config = PERF_COUNT_SW_CPU_CLOCK;
  attr.sample_freq = static_cast<uint64_t>(freq);
  attr.freq = 1;  // PerfBitFreq in the reference (cpu.go:236-243)
  attr.sample_type = PERF_SAMPLE_TID | PERF_SAMPLE_CALLCHAIN;
  attr.disabled = 1;
  attr.inherit = 0;
  attr.exclude_hv = 1;
  attr.sample_max_stack = kMaxFrames;
  // pid = -1, cpu = N: whole-machine, per-CPU (needs perf_event_paranoid
  // <= 0 or CAP_PERFMON, like the reference needs CAP_BPF).
  return syscall(SYS_perf_event_open, &attr, -1, cpu, -1, PERF_FLAG_FD_CLOEXEC);
}

void destroy_partial(Sampler* s, int opened) {
  for (int j = 0; j < opened; j++) {
    munmap(s->cpus[j].ring, s->cpus[j].ring_size);
    close(s->cpus[j].fd);
  }
  delete[] s->cpus;
  delete s;
}

}  // namespace

extern "C" {

// Returns nullptr on failure; errno preserved from the first failing call.
Sampler* pa_sampler_create(int freq_hz) {
  long n = sysconf(_SC_NPROCESSORS_ONLN);
  if (n <= 0) return nullptr;
  Sampler* s = new Sampler();
  s->n_cpus = static_cast<int>(n);
  s->freq = freq_hz;
  s->cpus = new PerCpu[n];
  size_t page = static_cast<size_t>(sysconf(_SC_PAGESIZE));
  size_t ring_size = (kRingPages + 1) * page;
  for (int i = 0; i < n; i++) {
    long fd = perf_open(i, freq_hz);
    if (fd < 0) {
      int saved = errno;
      destroy_partial(s, i);
      errno = saved;
      return nullptr;
    }
    void* ring = mmap(nullptr, ring_size, PROT_READ | PROT_WRITE, MAP_SHARED,
                      static_cast<int>(fd), 0);
    if (ring == MAP_FAILED) {
      int saved = errno;
      close(static_cast<int>(fd));
      destroy_partial(s, i);
      errno = saved;
      return nullptr;
    }
    s->cpus[i].fd = static_cast<int>(fd);
    s->cpus[i].ring = ring;
    s->cpus[i].ring_size = ring_size;
  }
  return s;
}

int pa_sampler_n_cpus(Sampler* s) { return s ? s->n_cpus : 0; }
uint64_t pa_sampler_lost(Sampler* s) { return s ? s->lost : 0; }

int pa_sampler_start(Sampler* s) {
  if (!s) return -1;
  for (int i = 0; i < s->n_cpus; i++) {
    if (ioctl(s->cpus[i].fd, PERF_EVENT_IOC_ENABLE, 0) != 0) return -1;
  }
  s->running.store(true);
  return 0;
}

int pa_sampler_stop(Sampler* s) {
  if (!s) return -1;
  for (int i = 0; i < s->n_cpus; i++) {
    ioctl(s->cpus[i].fd, PERF_EVENT_IOC_DISABLE, 0);
  }
  s->running.store(false);
  return 0;
}

// Drain all rings into out (capacity cap bytes). Returns bytes written,
// or -1 when a record would not fit (caller should grow the buffer).
// Packing format documented at the top of this file.
long pa_sampler_drain(Sampler* s, uint8_t* out, long cap) {
  if (!s || !out) return -1;
  long written = 0;
  size_t page = static_cast<size_t>(sysconf(_SC_PAGESIZE));
  for (int i = 0; i < s->n_cpus; i++) {
    PerCpu& pc = s->cpus[i];
    auto* meta = static_cast<perf_event_mmap_page*>(pc.ring);
    uint8_t* data = static_cast<uint8_t*>(pc.ring) + page;
    uint64_t data_size = pc.ring_size - page;
    uint64_t head = __atomic_load_n(&meta->data_head, __ATOMIC_ACQUIRE);
    uint64_t tail = meta->data_tail;
    while (tail < head) {
      auto* hdr = reinterpret_cast<perf_event_header*>(
          data + (tail % data_size));
      // Records can wrap the ring; copy out when they do.
      uint8_t stackbuf[8 * 1024];
      uint8_t* rec = reinterpret_cast<uint8_t*>(hdr);
      if ((tail % data_size) + hdr->size > data_size) {
        uint64_t first = data_size - (tail % data_size);
        if (hdr->size <= sizeof(stackbuf)) {
          std::memcpy(stackbuf, rec, first);
          std::memcpy(stackbuf + first, data, hdr->size - first);
          rec = stackbuf;
          hdr = reinterpret_cast<perf_event_header*>(rec);
        } else {  // oversized wrapped record: skip
          tail += hdr->size;
          continue;
        }
      }
      if (hdr->type == PERF_RECORD_LOST) {
        // { header; u64 id; u64 lost; }
        s->lost += *reinterpret_cast<uint64_t*>(rec + sizeof(*hdr) + 8);
      } else if (hdr->type == PERF_RECORD_SAMPLE) {
        // layout for our sample_type: u32 pid, tid; u64 nr; u64 ips[nr]
        uint8_t* p = rec + sizeof(*hdr);
        uint32_t pid, tid;
        std::memcpy(&pid, p, 4);
        std::memcpy(&tid, p + 4, 4);
        p += 8;
        uint64_t nr;
        std::memcpy(&nr, p, 8);
        p += 8;
        if (nr <= kMaxFrames + 8) {  // frames + context markers
          uint64_t kframes[kMaxFrames], uframes[kMaxFrames];
          uint32_t nk = 0, nu = 0;
          int mode = 0;  // 0 unknown, 1 kernel, 2 user
          for (uint64_t f = 0; f < nr; f++) {
            uint64_t ip;
            std::memcpy(&ip, p + 8 * f, 8);
            if (ip >= kContextMax) {
              if (ip == kContextKernel) mode = 1;
              else if (ip == kContextUser) mode = 2;
              else mode = 0;
              continue;
            }
            if (mode == 1 && nk < kMaxFrames) kframes[nk++] = ip;
            else if (mode == 2 && nu < kMaxFrames) uframes[nu++] = ip;
          }
          if (nk + nu > 0 && nk + nu <= kMaxFrames) {
            long need = 16 + 8l * (nk + nu);
            if (written + need > cap) return -1;
            uint8_t* o = out + written;
            std::memcpy(o, &pid, 4);
            std::memcpy(o + 4, &tid, 4);
            std::memcpy(o + 8, &nk, 4);
            std::memcpy(o + 12, &nu, 4);
            std::memcpy(o + 16, kframes, 8l * nk);
            std::memcpy(o + 16 + 8l * nk, uframes, 8l * nu);
            written += need;
          }
        }
      }
      tail += hdr->size;
    }
    __atomic_store_n(&meta->data_tail, tail, __ATOMIC_RELEASE);
    pc.tail = tail;
  }
  return written;
}

void pa_sampler_destroy(Sampler* s) {
  if (!s) return;
  pa_sampler_stop(s);
  for (int i = 0; i < s->n_cpus; i++) {
    if (s->cpus[i].ring) munmap(s->cpus[i].ring, s->cpus[i].ring_size);
    if (s->cpus[i].fd >= 0) close(s->cpus[i].fd);
  }
  delete[] s->cpus;
  delete s;
}

}  // extern "C"
