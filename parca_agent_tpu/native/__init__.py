"""Native (C++) runtime pieces shipped as source and built on demand.

`sampler.cc` is the perf_event ring drainer (role of the reference's
bpf/cpu/cpu.bpf.c capture program); capture/live.py compiles it with the
adjacent Makefile on first use and loads it via ctypes. `vecenc.cc` is
the varint emission kernel behind pprof/vec.py. Both share the
build-on-demand policy below; what differs per caller is only what a
build failure means (the sampler raises SamplerUnavailable, the varint
kernel falls back to its numpy path).
"""

from __future__ import annotations

import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))


def ensure_built(target: str, source: str, force: bool = False) -> str:
    """Compile `target` (.so) from `source` (.cc) via the adjacent
    Makefile if missing or stale; returns the .so path.

    Shared objects are never checked in (gitignored): a fresh checkout
    always compiles from the reviewed source. Raises RuntimeError with
    the compiler output on failure — callers decide whether that is
    fatal (sampler) or a fallback trigger (varint kernel)."""
    lib = os.path.join(_DIR, target)
    src = os.path.join(_DIR, source)
    if force or not os.path.exists(lib) or \
            os.path.getmtime(lib) < os.path.getmtime(src):
        r = subprocess.run(["make", "-C", _DIR, target],
                           capture_output=True, text=True)
        if r.returncode != 0:
            raise RuntimeError(f"native build failed:\n{r.stderr}")
    return lib
