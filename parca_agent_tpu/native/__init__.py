"""Native (C++) runtime pieces shipped as source and built on demand.

`sampler.cc` is the perf_event ring drainer (role of the reference's
bpf/cpu/cpu.bpf.c capture program); capture/live.py compiles it with the
adjacent Makefile on first use and loads it via ctypes.
"""
