"""Unprivileged procfs capture source.

The capture hierarchy (mirrors the reference's L0, redesigned for this
stack — SURVEY.md section 2.11 keeps eBPF conceptually, but this framework
must also run where neither eBPF nor perf_event_open is permitted):

  1. native perf_event sampler (capture/live.py + native/) — real user+kernel
     call stacks, needs perf_event_open permission;
  2. THIS: /proc/<pid>/stat CPU-tick accounting — whole-machine per-process
     CPU attribution with depth-1 stacks, needs only procfs read access.

Per window: sample utime+stime of every PID at poll_hz; the per-PID tick
delta over the window becomes the sample count (1 tick = 1/USER_HZ s of
CPU). The single stack frame is the process's runtime entry point
(ELF entry + load bias) so the profile symbolizes to the binary — honest
"which process burns CPU" attribution, never fabricated call chains. The
mapping table is the PID's real /proc/maps, so address normalization,
build ids, and debuginfo upload all exercise the true pipeline.
"""

from __future__ import annotations

import time

import numpy as np

from parca_agent_tpu.capture.formats import (
    STACK_SLOTS,
    MappingTable,
    WindowSnapshot,
)
from parca_agent_tpu.process.maps import (
    ProcessMapCache,
    build_mapping_table,
    host_path,
)
from parca_agent_tpu.utils.vfs import VFS, RealFS

USER_HZ = 100  # kernel tick rate exposed in /proc/*/stat


def read_cpu_ticks(fs: VFS, pid: int) -> int | None:
    """utime+stime from /proc/pid/stat (fields 14/15, after the comm that
    may itself contain spaces/parens)."""
    try:
        data = fs.read_bytes(f"/proc/{pid}/stat")
    except OSError:
        return None
    # comm is parenthesized and may contain ')' — split after the LAST ')'.
    rp = data.rfind(b")")
    if rp < 0:
        return None
    fields = data[rp + 2:].split()
    if len(fields) < 13:
        return None
    try:
        return int(fields[11]) + int(fields[12])  # utime, stime
    except ValueError:
        return None


class ProcfsSampler:
    def __init__(self, fs: VFS | None = None, frequency_hz: int = 100,
                 window_s: float = 10.0, poll_hz: float = 2.0,
                 clock=time.monotonic, sleep=time.sleep):
        self._fs = fs or RealFS()
        self._freq = frequency_hz
        self._window = window_s
        self._poll_interval = 1.0 / poll_hz
        self._clock = clock
        self._sleep = sleep
        self._maps = ProcessMapCache(fs=self._fs)
        self._prev: dict[int, int] = {}
        self._started = False
        # (path, start, offset) -> runtime entry addr; constant per mapping.
        self._entry_cache: dict[tuple, int | None] = {}
        # Ingest containment: wired by the CLI like the perf sampler's
        # registry — a pid whose maps file is poison is charged and
        # skipped for the window, never allowed to abort the whole
        # window's collect.
        self.quarantine = None

    def _pid_mappings(self, pid: int) -> list:
        """executable_mappings with the poison taxonomy contained: an
        exited pid or a poisoned maps file degrades to 'no mappings' for
        this pid (charged when a registry is wired)."""
        from parca_agent_tpu.utils.poison import PoisonInput

        try:
            return self._maps.executable_mappings(pid)
        except OSError:
            return []
        except PoisonInput as e:
            if self.quarantine is not None:
                self.quarantine.record_error(
                    pid, getattr(e, "site", "maps.parse"), e)
            return []

    def _pids(self) -> list[int]:
        try:
            return [int(n) for n in self._fs.listdir("/proc") if n.isdigit()]
        except OSError:
            return []

    def sample_ticks(self) -> dict[int, int]:
        out = {}
        for pid in self._pids():
            t = read_cpu_ticks(self._fs, pid)
            if t is not None:
                out[pid] = t
        return out

    def _entry_address(self, pid: int) -> int | None:
        """Runtime entry point: ELF entry + load bias of the exec mapping."""
        from parca_agent_tpu.elf.base import BaseError, compute_base
        from parca_agent_tpu.elf.reader import ElfFile
        from parca_agent_tpu.utils import poison
        from parca_agent_tpu.utils.poison import PoisonInput, read_bounded

        maps = self._pid_mappings(pid)
        if not maps:
            return None
        m = maps[0]
        key = (m.path, m.start, m.offset)
        if key in self._entry_cache:
            return self._entry_cache[key]
        try:
            ef = ElfFile(read_bounded(self._fs, host_path(pid, m.path),
                                      poison.ELF_READ_CAP))
            base = compute_base(ef, ef.exec_load_segment(),
                                m.start, m.end, m.offset)
            addr = (ef.entry + base) % 2**64
        except (OSError, PoisonInput, BaseError):
            # Unreadable/poison binary (incl. injected elf.read faults —
            # PoisonInput covers the whole ingest taxonomy): attribute
            # to the mapping start.
            addr = m.start
        if len(self._entry_cache) > 4096:
            self._entry_cache.clear()
        self._entry_cache[key] = addr
        return addr

    def collect(self, deltas: dict[int, int]) -> WindowSnapshot:
        """Tick deltas -> snapshot with real mappings + entry-point frames."""
        rows = []
        per_pid_maps = {}
        for pid, ticks in sorted(deltas.items()):
            if ticks <= 0:
                continue
            addr = self._entry_address(pid)
            if addr is None:
                continue
            per_pid_maps[pid] = self._pid_mappings(pid)
            # Scale kernel ticks (USER_HZ) to the nominal sampling frequency
            # so counts are comparable with real samplers at frequency_hz.
            count = max(1, ticks * self._freq // USER_HZ)
            rows.append((pid, addr, count))

        n = len(rows)
        stacks = np.zeros((n, STACK_SLOTS), np.uint64)
        pids = np.zeros(n, np.int32)
        counts = np.zeros(n, np.int64)
        for i, (pid, addr, count) in enumerate(rows):
            pids[i] = pid
            stacks[i, 0] = addr
            counts[i] = count
        table = build_mapping_table(per_pid_maps) if per_pid_maps \
            else MappingTable.empty()
        return WindowSnapshot(
            pids=pids,
            tids=pids.copy(),
            counts=counts,
            user_len=np.full(n, 1, np.int32),
            kernel_len=np.zeros(n, np.int32),
            stacks=stacks,
            mappings=table,
            period_ns=int(1e9 / self._freq),
            window_ns=int(self._window * 1e9),
            time_ns=time.time_ns(),
        )

    def accumulate(self, window_deltas: dict[int, int]) -> None:
        """One poll step: fold tick deltas since the previous step into
        window_deltas. New PIDs first seen mid-window contribute their full
        tick count (a process born inside the window spent all of it here);
        PIDs that exit keep whatever they accumulated — the reason polling
        runs at poll_hz instead of only at window edges."""
        cur = self.sample_ticks()
        for pid, t in cur.items():
            prev = self._prev.get(pid)
            if prev is None:
                # PID first seen mid-run: a genuinely new process, count all
                # its ticks. (prev == 0 is a real observation, not missing.)
                delta = t if self._started else 0
            else:
                delta = t - prev
            if delta > 0:
                window_deltas[pid] = window_deltas.get(pid, 0) + delta
        self._prev = cur

    def poll(self) -> WindowSnapshot:
        """Block for one window, accumulating tick deltas at poll_hz."""
        if not self._started:
            self._prev = self.sample_ticks()
            self._started = True
        window_deltas: dict[int, int] = {}
        deadline = self._clock() + self._window
        while True:
            remaining = deadline - self._clock()
            if remaining <= 0:
                break
            self._sleep(min(self._poll_interval, remaining))
            self.accumulate(window_deltas)
        return self.collect(window_deltas)
