"""Capture layer: window snapshot contracts and sample sources.

The reference's L0 is an eBPF program aggregating (pid, stack) -> count in
kernel BPF maps, drained every 10 s (reference bpf/cpu/cpu.bpf.c:110-116,
pkg/profiler/cpu/cpu.go:505). Our capture layer is re-designed around a single
immutable *WindowSnapshot* value — fixed-width, zero-padded arrays that map
directly onto TPU-friendly layouts — produced by pluggable sources:

  - SyntheticSource: parameterized workload generator (BASELINE configs #2/#4)
  - ReplaySource:    replays saved snapshot fixtures (testdata replay)
  - native perf source: C++ perf_event sampler (parca_agent_tpu/native)
"""

from parca_agent_tpu.capture.formats import (  # noqa: F401
    MAX_STACK_DEPTH,
    STACK_SLOTS,
    KERNEL_ADDR_START,
    MappingTable,
    WindowSnapshot,
    load_snapshot,
    save_snapshot,
)
