"""Window snapshot data contracts (Phase 0 of SURVEY.md section 7).

The seam every later phase plugs into. A WindowSnapshot is the drained state
of one aggregation window (default 10 s @ 100 Hz): for each distinct
(pid, stack) observed by the capture side, one row with the raw user+kernel
address trace and its sample count, plus the per-PID virtual-memory mapping
table needed to normalize user addresses.

Shape contract (chosen for TPU layout, not for the kernel ABI):

  pids        int32  [N]          process id (tgid in kernel terms)
  tids        int32  [N]          thread id of the sampled thread
  counts      int64  [N]          number of samples with this exact stack
  user_len    int32  [N]          number of valid user frames in stacks[i]
  kernel_len  int32  [N]          number of valid kernel frames in stacks[i]
  stacks      uint64 [N, 128]     user frames [0:user_len), kernel frames
                                  [user_len:user_len+kernel_len), zero-padded.
                                  Leaf-most frame first (index 0 = sampled pc).

The reference keeps user and kernel stacks in separate BPF maps keyed by
stack id (reference bpf/cpu/cpu.bpf.c:179-207) and joins them in userspace
(pkg/profiler/cpu/cpu.go:634-686); we pre-join at drain time so the device
sees one dense matrix. 128 slots = the reference's 127-frame depth cap
(bpf/cpu/cpu.bpf.c:22-27) rounded up to the TPU lane width.

Mapping table (the subset of /proc/PID/maps that address normalization
needs, reference pkg/process/maps.go:73-128):

  map_pids    int32  [M]   owner pid, rows sorted by (pid, start)
  map_starts  uint64 [M]   virtual start address (inclusive)
  map_ends    uint64 [M]   virtual end address (exclusive)
  map_offsets uint64 [M]   file offset of the mapping
  map_objs    int32  [M]   index into the object table (-1 = anonymous)
  map_bases   uint64 [M]   normalization base: object vaddr = addr - base
                           (pprof GetBase semantics, reference
                           pkg/objectfile/object_file.go:156-238; defaults
                           to start - offset when the ELF was unreadable,
                           which matches file-offset normalization)
  obj_paths   list[str]    backing object path per object id
  obj_buildids list[str]   lowercase hex build id ('' if unknown)

Addresses at or above KERNEL_ADDR_START are kernel text; they are never
normalized through the mapping table (reference pkg/profiler/cpu/cpu.go:
652-659 treats kernel addresses via kallsyms only).
"""

from __future__ import annotations

import dataclasses
import io
import zlib
from typing import BinaryIO, Sequence

import numpy as np

from parca_agent_tpu.utils.vfs import atomic_write_bytes

# palint: persistence-root — snapshot fixture files are replay/bench
# inputs adopted across process restarts; writes must be tmp+rename.

# Reference caps stacks at 127 frames (bpf/cpu/cpu.bpf.c:22-27). We pad the
# frame axis to 128 so a stack row is exactly one TPU lane-width vector.
MAX_STACK_DEPTH = 127
STACK_SLOTS = 128

# Start of the x86_64 kernel half of the canonical address space.
KERNEL_ADDR_START = 0xFFFF_8000_0000_0000

_MAGIC = b"PATPSNAP"
# v2 added the mapping `bases` column; v1 files load with bases defaulted.
_VERSION = 2


@dataclasses.dataclass(frozen=True)
class MappingTable:
    """Per-window union of the executable mappings of every sampled PID."""

    pids: np.ndarray      # int32 [M]
    starts: np.ndarray    # uint64 [M]
    ends: np.ndarray      # uint64 [M]
    offsets: np.ndarray   # uint64 [M]
    objs: np.ndarray      # int32 [M]
    obj_paths: tuple[str, ...] = ()
    obj_buildids: tuple[str, ...] = ()
    bases: np.ndarray | None = None  # uint64 [M]; None -> starts - offsets

    def __post_init__(self):
        object.__setattr__(self, "pids", np.asarray(self.pids, np.int32))
        object.__setattr__(self, "starts", np.asarray(self.starts, np.uint64))
        object.__setattr__(self, "ends", np.asarray(self.ends, np.uint64))
        object.__setattr__(self, "offsets", np.asarray(self.offsets, np.uint64))
        object.__setattr__(self, "objs", np.asarray(self.objs, np.int32))
        object.__setattr__(self, "obj_paths", tuple(self.obj_paths))
        object.__setattr__(self, "obj_buildids", tuple(self.obj_buildids))
        if self.bases is None:
            object.__setattr__(self, "bases", self.starts - self.offsets)
        else:
            object.__setattr__(self, "bases", np.asarray(self.bases, np.uint64))
        m = len(self.pids)
        for name in ("starts", "ends", "offsets", "objs", "bases"):
            if len(getattr(self, name)) != m:
                raise ValueError(f"mapping column {name!r} length mismatch")
        if len(self.obj_buildids) not in (0, len(self.obj_paths)):
            raise ValueError("obj_buildids must match obj_paths")
        if m:
            order = np.lexsort((self.starts, self.pids))
            if not np.array_equal(order, np.arange(m)):
                raise ValueError("mapping rows must be sorted by (pid, start)")
            # VMAs are disjoint within a process (kernel invariant); the
            # aggregators' binary-search join relies on it.
            same_pid = self.pids[1:] == self.pids[:-1]
            if np.any(same_pid & (self.starts[1:] < self.ends[:-1])):
                raise ValueError("mappings overlap within a pid")
            if np.any(self.ends < self.starts):
                raise ValueError("mapping end precedes start")

    def __len__(self) -> int:
        return len(self.pids)

    @staticmethod
    def empty() -> "MappingTable":
        z64 = np.zeros(0, np.uint64)
        z32 = np.zeros(0, np.int32)
        return MappingTable(z32, z64, z64, z64, z32)

    def rows_for_pid(self, pid: int) -> np.ndarray:
        """Indices of this pid's mappings (contiguous because sorted)."""
        lo = np.searchsorted(self.pids, pid, side="left")
        hi = np.searchsorted(self.pids, pid, side="right")
        return np.arange(lo, hi)


@dataclasses.dataclass(frozen=True)
class WindowSnapshot:
    """Drained capture state for one aggregation window."""

    pids: np.ndarray        # int32 [N]
    tids: np.ndarray        # int32 [N]
    counts: np.ndarray      # int64 [N]
    user_len: np.ndarray    # int32 [N]
    kernel_len: np.ndarray  # int32 [N]
    stacks: np.ndarray      # uint64 [N, STACK_SLOTS]
    mappings: MappingTable
    period_ns: int = 10_000_000      # 100 Hz sampling period
    window_ns: int = 10_000_000_000  # 10 s aggregation window
    time_ns: int = 0                 # window start, unix nanos

    def __post_init__(self):
        object.__setattr__(self, "pids", np.asarray(self.pids, np.int32))
        object.__setattr__(self, "tids", np.asarray(self.tids, np.int32))
        object.__setattr__(self, "counts", np.asarray(self.counts, np.int64))
        object.__setattr__(self, "user_len", np.asarray(self.user_len, np.int32))
        object.__setattr__(self, "kernel_len", np.asarray(self.kernel_len, np.int32))
        object.__setattr__(self, "stacks", np.asarray(self.stacks, np.uint64))
        n = len(self.pids)
        for name in ("tids", "counts", "user_len", "kernel_len"):
            if len(getattr(self, name)) != n:
                raise ValueError(f"snapshot column {name!r} length mismatch")
        if self.stacks.shape != (n, STACK_SLOTS):
            raise ValueError(
                f"stacks must be [N, {STACK_SLOTS}], got {self.stacks.shape}"
            )
        depth = self.user_len + self.kernel_len
        if n and int(depth.max(initial=0)) > MAX_STACK_DEPTH:
            raise ValueError(f"stack depth exceeds {MAX_STACK_DEPTH}")
        if n and (int(self.user_len.min()) < 0 or int(self.kernel_len.min()) < 0):
            raise ValueError("negative frame count")

    def __len__(self) -> int:
        return len(self.pids)

    @property
    def depths(self) -> np.ndarray:
        return self.user_len + self.kernel_len

    def validate_padding(self) -> None:
        """Check that slots past the declared depth are zero (fixture QA)."""
        idx = np.arange(STACK_SLOTS, dtype=np.int32)[None, :]
        live = idx < self.depths[:, None]
        if np.any(np.where(live, np.uint64(0), self.stacks) != 0):
            raise ValueError("nonzero padding past declared stack depth")

    def total_samples(self) -> int:
        return int(self.counts.sum())


def fold_rows_first_seen(keys: np.ndarray, counts):
    """Fold duplicate key rows into (unique key, summed weight) pairs in
    FIRST-OCCURRENCE order — the host twin of the reference's in-kernel
    ``(pid, stack) -> count`` fold (bpf/cpu/cpu.bpf.c:110-116): samples
    are reduced to unique work BEFORE they cross an expensive boundary
    (there the kernel->user copy, here the host->device feed dispatch
    and the one-shot kernel's padded upload).

    ``keys`` is a 1-D array whose elements compare by content (callers
    build an ``np.void`` byte view over their key columns). Returns
    ``None`` when every row is already unique (the common one-shot case
    — callers skip the rebuild entirely), else ``(rep, inverse,
    weights)``: ``rep[j]`` is the first input row carrying unique key j,
    ``inverse[i]`` maps input row i to its unique slot, and
    ``weights[j]`` is the exact int64 sum of its rows' counts. First-
    occurrence ordering is what keeps downstream id assignment (miss
    order = insertion order) bit-identical to the unfolded stream."""
    uniq, first, inverse = np.unique(keys, return_index=True,
                                     return_inverse=True)
    if len(uniq) == len(keys):
        return None
    order = np.argsort(first, kind="stable")
    rank = np.empty(len(order), np.int64)
    rank[order] = np.arange(len(order), dtype=np.int64)
    inv = rank[inverse.reshape(-1)]
    counts = np.asarray(counts, np.int64)
    if int(counts.sum()) < 2**53:
        # float64 bincount is exact below 2^53 total mass (the same
        # guard columns_to_snapshot's weighted dedup uses).
        weights = np.bincount(inv, weights=counts,
                              minlength=len(order)).astype(np.int64)
    else:
        weights = np.zeros(len(order), np.int64)
        np.add.at(weights, inv, counts)
    return first[order].astype(np.int64), inv, weights


def filter_snapshot_rows(snap: WindowSnapshot,
                         mask: np.ndarray) -> WindowSnapshot:
    """Snapshot restricted to the rows where mask is True (columns are
    sliced; the mapping table is shared — per-pid lookups for dropped
    pids simply never happen)."""
    import dataclasses

    return dataclasses.replace(
        snap, pids=snap.pids[mask], tids=snap.tids[mask],
        counts=snap.counts[mask], user_len=snap.user_len[mask],
        kernel_len=snap.kernel_len[mask], stacks=snap.stacks[mask])


def merge_mapping_tables(tables: Sequence[MappingTable]) -> MappingTable:
    """Union several windows' mapping tables into one.

    Rows are deduplicated exactly on (pid, start, end, offset, base, object);
    objects are deduplicated by (path, build_id) so the same libc mapped by
    every node collapses to one object id (the fan-in the reference's
    debuginfo dedup relies on, pkg/debuginfo/manager.go:116-127).
    Genuinely conflicting tables — the same pid with overlapping but
    different ranges — fail MappingTable's own overlap validation."""
    tables = [t for t in tables if len(t)]
    if not tables:
        return MappingTable.empty()
    obj_ids: dict[tuple[str, str], int] = {}
    paths: list[str] = []
    buildids: list[str] = []
    cols: list[np.ndarray] = []
    for t in tables:
        bids = t.obj_buildids or ("",) * len(t.obj_paths)
        remap = np.full(max(len(t.obj_paths), 1), -1, np.int64)
        for i, (p, b) in enumerate(zip(t.obj_paths, bids)):
            key = (p, b)
            if key not in obj_ids:
                obj_ids[key] = len(paths)
                paths.append(p)
                buildids.append(b)
            remap[i] = obj_ids[key]
        objs = t.objs.astype(np.int64)
        pos = (objs >= 0) & (objs < len(remap))
        objs = np.where(pos, remap[np.clip(objs, 0, len(remap) - 1)], -1)
        rec = np.zeros((len(t), 6), np.uint64)
        rec[:, 0] = t.pids.astype(np.uint64)
        rec[:, 1] = t.starts
        rec[:, 2] = t.ends
        rec[:, 3] = t.offsets
        rec[:, 4] = t.bases
        rec[:, 5] = objs.astype(np.uint64)  # -1 wraps; exact dedup only
        cols.append(rec)
    rec = np.concatenate(cols, axis=0)
    void = np.ascontiguousarray(rec).view(
        np.dtype((np.void, rec.shape[1] * 8))).ravel()
    _, first = np.unique(void, return_index=True)
    rec = rec[np.sort(first)]
    pids = rec[:, 0].astype(np.int32)
    order = np.lexsort((rec[:, 1], pids))
    rec = rec[order]
    return MappingTable(
        pids=rec[:, 0].astype(np.int32),
        starts=rec[:, 1],
        ends=rec[:, 2],
        offsets=rec[:, 3],
        objs=rec[:, 5].astype(np.int64).astype(np.int32),
        obj_paths=tuple(paths),
        obj_buildids=tuple(buildids),
        bases=rec[:, 4],
    )


def concat_snapshots(windows: Sequence[WindowSnapshot]) -> WindowSnapshot:
    """Concatenate several windows (e.g. one per fleet node) into one:
    row arrays appended, mapping tables unioned. Rows are NOT deduplicated —
    aggregation semantics already sum identical (pid, stack) rows, which is
    what makes this the fleet-merge correctness oracle input."""
    ws = list(windows)
    if not ws:
        raise ValueError("concat_snapshots needs at least one window")
    return WindowSnapshot(
        pids=np.concatenate([w.pids for w in ws]),
        tids=np.concatenate([w.tids for w in ws]),
        counts=np.concatenate([w.counts for w in ws]),
        user_len=np.concatenate([w.user_len for w in ws]),
        kernel_len=np.concatenate([w.kernel_len for w in ws]),
        stacks=np.concatenate([w.stacks for w in ws], axis=0),
        mappings=merge_mapping_tables([w.mappings for w in ws]),
        period_ns=ws[0].period_ns,
        window_ns=ws[0].window_ns,
        time_ns=min(w.time_ns for w in ws),
    )


def _write_arr(out: BinaryIO, arr: np.ndarray) -> None:
    data = np.ascontiguousarray(arr).tobytes()
    out.write(len(data).to_bytes(8, "little"))
    out.write(data)


def _read_arr(buf: BinaryIO, dtype, shape) -> np.ndarray:
    n = int.from_bytes(buf.read(8), "little")
    raw = buf.read(n)
    if len(raw) != n:
        raise ValueError("truncated snapshot array")
    return np.frombuffer(raw, dtype=dtype).reshape(shape).copy()


def _write_strs(out: BinaryIO, strs: Sequence[str]) -> None:
    blob = b"\x00".join(s.encode() for s in strs)
    out.write(len(strs).to_bytes(8, "little"))
    out.write(len(blob).to_bytes(8, "little"))
    out.write(blob)


def _read_strs(buf: BinaryIO) -> tuple[str, ...]:
    k = int.from_bytes(buf.read(8), "little")
    n = int.from_bytes(buf.read(8), "little")
    blob = buf.read(n)
    if k == 0:
        return ()
    parts = blob.split(b"\x00")
    if len(parts) != k:
        raise ValueError("corrupt snapshot string table")
    return tuple(p.decode() for p in parts)


def save_snapshot(snap: WindowSnapshot, path_or_file) -> None:
    """Serialize a snapshot: MAGIC | version | zlib(payload).

    The replayable map-dump fixture format called for by SURVEY.md section 4
    (BASELINE config #2) — lets the aggregator be tested and benchmarked
    without a kernel or capture privileges.
    """
    payload = io.BytesIO()
    n = len(snap)
    m = len(snap.mappings)
    payload.write(n.to_bytes(8, "little"))
    payload.write(m.to_bytes(8, "little"))
    for v in (snap.period_ns, snap.window_ns, snap.time_ns):
        payload.write(int(v).to_bytes(8, "little"))
    for arr in (snap.pids, snap.tids, snap.counts, snap.user_len,
                snap.kernel_len, snap.stacks):
        _write_arr(payload, arr)
    mt = snap.mappings
    for arr in (mt.pids, mt.starts, mt.ends, mt.offsets, mt.objs, mt.bases):
        _write_arr(payload, arr)
    _write_strs(payload, mt.obj_paths)
    _write_strs(payload, mt.obj_buildids)

    compressed = zlib.compress(payload.getvalue(), 6)
    blob = _MAGIC + _VERSION.to_bytes(4, "little") + compressed
    if hasattr(path_or_file, "write"):
        path_or_file.write(blob)
    else:
        # Crash-atomic (palint crash-only-io): a torn snapshot file
        # reads as "bad magic"/short payload at the next load — tmp +
        # rename means the path either holds the old fixture or the
        # complete new one, never a half.
        atomic_write_bytes(path_or_file, blob)


def load_snapshot(path_or_file) -> WindowSnapshot:
    if hasattr(path_or_file, "read"):
        raw = path_or_file.read()
    else:
        with open(path_or_file, "rb") as f:
            raw = f.read()
    if raw[: len(_MAGIC)] != _MAGIC:
        raise ValueError("not a snapshot file (bad magic)")
    version = int.from_bytes(raw[len(_MAGIC): len(_MAGIC) + 4], "little")
    if version not in (1, _VERSION):
        raise ValueError(f"unsupported snapshot version {version}")
    try:
        buf = io.BytesIO(zlib.decompress(raw[len(_MAGIC) + 4:]))
    except zlib.error as e:
        raise ValueError(f"corrupt snapshot payload: {e}") from e
    n = int.from_bytes(buf.read(8), "little")
    m = int.from_bytes(buf.read(8), "little")
    period_ns = int.from_bytes(buf.read(8), "little")
    window_ns = int.from_bytes(buf.read(8), "little")
    time_ns = int.from_bytes(buf.read(8), "little")
    pids = _read_arr(buf, np.int32, (n,))
    tids = _read_arr(buf, np.int32, (n,))
    counts = _read_arr(buf, np.int64, (n,))
    user_len = _read_arr(buf, np.int32, (n,))
    kernel_len = _read_arr(buf, np.int32, (n,))
    stacks = _read_arr(buf, np.uint64, (n, STACK_SLOTS))
    mt = MappingTable(
        _read_arr(buf, np.int32, (m,)),
        _read_arr(buf, np.uint64, (m,)),
        _read_arr(buf, np.uint64, (m,)),
        _read_arr(buf, np.uint64, (m,)),
        _read_arr(buf, np.int32, (m,)),
        bases=_read_arr(buf, np.uint64, (m,)) if version >= 2 else None,
        obj_paths=_read_strs(buf),
        obj_buildids=_read_strs(buf),
    )
    return WindowSnapshot(
        pids, tids, counts, user_len, kernel_len, stacks, mt,
        period_ns=period_ns, window_ns=window_ns, time_ns=time_ns,
    )
