"""Replay source: feed saved WindowSnapshot fixtures through the agent.

The reference has no replay path — its aggregation can only be exercised
against live BPF maps (SURVEY.md section 4 closing note). ReplaySource is the
fixture seam that lets every downstream layer run kernel-free.
"""

from __future__ import annotations

import os
from typing import Iterator, Sequence

from parca_agent_tpu.capture.formats import WindowSnapshot, load_snapshot


class ReplaySource:
    """Iterates snapshots from files or in-memory values.

    Implements the capture-source protocol: ``poll()`` returns the next
    window's snapshot or None when exhausted.
    """

    def __init__(self, items: Sequence[WindowSnapshot | str | os.PathLike]):
        self._items = list(items)
        self._pos = 0

    def poll(self) -> WindowSnapshot | None:
        if self._pos >= len(self._items):
            return None
        item = self._items[self._pos]
        self._pos += 1
        if isinstance(item, WindowSnapshot):
            return item
        return load_snapshot(item)

    def __iter__(self) -> Iterator[WindowSnapshot]:
        while (snap := self.poll()) is not None:
            yield snap
