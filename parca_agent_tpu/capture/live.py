"""Live capture source over the native perf_event sampler.

Python side of parca_agent_tpu/native/sampler.cc (the capture role of the
reference's pkg/profiler/cpu/cpu.go:234-275 perf_event_open + attach): the
shared library is built on demand with the local toolchain, loaded via
ctypes, and drained once per window. Raw records are decoded with numpy,
deduplicated into (pid, stack) -> count rows (the aggregation the
reference's BPF map does kernel-side happens here, vectorized), and joined
with the live /proc mapping table.

Two capture modes:

  FP mode (default): kernel + frame-pointer user chains via
  PERF_SAMPLE_CALLCHAIN (v1 record: u32 pid | u32 tid | u32 n_kernel |
  u32 n_user | u64 frames[...], kernel-first).

  DWARF mode (capture_stack=True): additionally snapshots user registers
  and a stack slice per sample (v2 record, see sampler.cc header); at
  drain time the batched walker (unwind/walker.py) unwinds frameless user
  stacks against .eh_frame tables built by the watch-processes loop —
  the role of the reference's debug_pids + in-kernel DWARF walker
  (pkg/profiler/cpu/cpu.go:390-459, bpf/cpu/cpu.bpf.c:464-674).

Drain overflow is lossless: the native side returns the records that fit
and keeps the rest in the rings (truncation counter incremented); poll()
immediately drains again.
"""

from __future__ import annotations

import ctypes
import os
import re
import struct
import threading
import time

import numpy as np

from parca_agent_tpu.capture.formats import (
    MAX_STACK_DEPTH,
    STACK_SLOTS,
    MappingTable,
    WindowSnapshot,
    filter_snapshot_rows,
)
from parca_agent_tpu.process.maps import ProcessMapCache, build_mapping_table
from parca_agent_tpu.process.objectfile import ObjectFileCache
from parca_agent_tpu.utils.log import get_logger

_log = get_logger("capture")

PA_CAPTURE_USER_STACK = 1


class SamplerUnavailable(RuntimeError):
    pass


def build_native(force: bool = False) -> str:
    """Compile libpasampler.so if missing or stale; returns its path
    (shared build-on-demand policy: native.ensure_built)."""
    from parca_agent_tpu.native import ensure_built

    try:
        return ensure_built("libpasampler.so", "sampler.cc", force=force)
    except RuntimeError as e:
        raise SamplerUnavailable(str(e)) from None


def load_native():
    lib = ctypes.CDLL(build_native(), use_errno=True)
    lib.pa_sampler_create.restype = ctypes.c_void_p
    lib.pa_sampler_create.argtypes = [ctypes.c_int]
    lib.pa_sampler_create2.restype = ctypes.c_void_p
    lib.pa_sampler_create2.argtypes = [ctypes.c_int, ctypes.c_int,
                                       ctypes.c_uint32]
    lib.pa_sampler_n_cpus.restype = ctypes.c_int
    lib.pa_sampler_n_cpus.argtypes = [ctypes.c_void_p]
    lib.pa_sampler_lost.restype = ctypes.c_uint64
    lib.pa_sampler_lost.argtypes = [ctypes.c_void_p]
    lib.pa_sampler_truncated.restype = ctypes.c_uint64
    lib.pa_sampler_truncated.argtypes = [ctypes.c_void_p]
    lib.pa_sampler_start.restype = ctypes.c_int
    lib.pa_sampler_start.argtypes = [ctypes.c_void_p]
    lib.pa_sampler_stop.restype = ctypes.c_int
    lib.pa_sampler_stop.argtypes = [ctypes.c_void_p]
    lib.pa_sampler_drain.restype = ctypes.c_long
    lib.pa_sampler_drain.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_uint8),
                                     ctypes.c_long]
    lib.pa_sampler_destroy.restype = None
    lib.pa_sampler_destroy.argtypes = [ctypes.c_void_p]
    u8p = ctypes.POINTER(ctypes.c_uint8)
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.pa_decode_v1_count.restype = ctypes.c_long
    lib.pa_decode_v1_count.argtypes = [u8p, ctypes.c_long, ctypes.c_long]
    lib.pa_decode_v1.restype = ctypes.c_long
    lib.pa_decode_v1.argtypes = [
        u8p, ctypes.c_long, i32p, i32p, i32p, i32p,
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_long, ctypes.c_long]
    lib.pa_sampler_drain_dedup.restype = ctypes.c_long
    lib.pa_sampler_drain_dedup.argtypes = [ctypes.c_void_p, u8p,
                                           ctypes.c_long]
    lib.pa_sampler_dedup_hits.restype = ctypes.c_uint64
    lib.pa_sampler_dedup_hits.argtypes = [ctypes.c_void_p]
    lib.pa_sampler_dedup_overflow.restype = ctypes.c_uint64
    lib.pa_sampler_dedup_overflow.argtypes = [ctypes.c_void_p]
    lib.pa_decode_v1d_count.restype = ctypes.c_long
    lib.pa_decode_v1d_count.argtypes = [u8p, ctypes.c_long, ctypes.c_long]
    lib.pa_decode_v1d.restype = ctypes.c_long
    lib.pa_decode_v1d.argtypes = [
        u8p, ctypes.c_long, i32p, i32p, i32p, i32p,
        ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_uint64), ctypes.c_long, ctypes.c_long]
    # v1h (hash-carrying dedup drain) entry points — guarded so a stale
    # pre-carry .so still loads; the sampler then simply runs hashless
    # (PerfEventSampler checks hash_carry before using them).
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    try:
        lib.pa_sampler_set_hash.restype = ctypes.c_int
        lib.pa_sampler_set_hash.argtypes = [
            ctypes.c_void_p, u32p, ctypes.c_long, u32p, ctypes.c_int,
            ctypes.c_long]
        lib.pa_sampler_drain_dedup2.restype = ctypes.c_long
        lib.pa_sampler_drain_dedup2.argtypes = [ctypes.c_void_p, u8p,
                                                ctypes.c_long]
        lib.pa_decode_v1h_count.restype = ctypes.c_long
        lib.pa_decode_v1h_count.argtypes = [u8p, ctypes.c_long,
                                            ctypes.c_long]
        lib.pa_decode_v1h.restype = ctypes.c_long
        lib.pa_decode_v1h.argtypes = [
            u8p, ctypes.c_long, i32p, i32p, i32p, i32p,
            ctypes.POINTER(ctypes.c_int64), u32p, u32p, u32p,
            u64p, ctypes.c_long, ctypes.c_long]
        lib.pa_stack_hash.restype = ctypes.c_int
        lib.pa_stack_hash.argtypes = [
            u64p, ctypes.c_long, u64p, ctypes.c_long, ctypes.c_uint32,
            u32p, ctypes.c_long, u32p, ctypes.c_long, ctypes.c_long,
            u32p]
    except AttributeError:
        pass
    return lib


def decode_records(buf: bytes) -> list[tuple[int, int, np.ndarray, np.ndarray]]:
    """Packed v1 drain buffer -> [(pid, tid, kernel_frames, user_frames)]."""
    out = []
    pos = 0
    n = len(buf)
    while pos + 16 <= n:
        pid, tid, nk, nu = struct.unpack_from("<IIII", buf, pos)
        pos += 16
        if nk + nu > MAX_STACK_DEPTH or pos + 8 * (nk + nu) > n:
            break  # corrupt/truncated tail
        frames = np.frombuffer(buf, np.uint64, nk + nu, pos)
        pos += 8 * (nk + nu)
        out.append((pid, tid, frames[:nk], frames[nk:]))
    return out


def decode_records_v2(buf: bytes) -> list[
        tuple[int, int, np.ndarray, np.ndarray, int, int, int, np.ndarray]]:
    """Packed v2 drain buffer ->
    [(pid, tid, kframes, uframes, rip, rsp, rbp, stack_bytes)]."""
    out = []
    pos = 0
    n = len(buf)
    while pos + 48 <= n:
        pid, tid, nk, nu = struct.unpack_from("<IIII", buf, pos)
        rip, rsp, rbp, dyn, _pad = struct.unpack_from(
            "<QQQII", buf, pos + 16)
        pos += 48
        dyn_pad = (dyn + 7) & ~7
        if nk + nu > MAX_STACK_DEPTH or pos + 8 * (nk + nu) + dyn_pad > n:
            break  # corrupt/truncated tail
        frames = np.frombuffer(buf, np.uint64, nk + nu, pos)
        pos += 8 * (nk + nu)
        stack = np.frombuffer(buf, np.uint8, dyn, pos)
        pos += dyn_pad
        out.append((pid, tid, frames[:nk], frames[nk:], rip, rsp, rbp,
                    stack))
    return out


def decode_records_columnar(lib, buf, nbytes: int) -> tuple:
    """Native one-pass v1 decode straight into the columnar arrays
    columns_to_snapshot needs — replaces two Python per-record loops on
    the once-a-second capture path. `buf` is a ctypes uint8 buffer (or
    bytes) whose first `nbytes` bytes are valid.

    Returns (pids, tids, ulen, klen, stacks) with user frames first per
    row (the WindowSnapshot contract; the native decoder reorders from
    the drain's kernel-first packing).
    """
    if isinstance(buf, (bytes, bytearray)):
        buf = (ctypes.c_uint8 * nbytes).from_buffer_copy(buf[:nbytes])
    p = ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8))
    n = int(lib.pa_decode_v1_count(p, nbytes, STACK_SLOTS))
    pids = np.zeros(n, np.int32)
    tids = np.zeros(n, np.int32)
    ulen = np.zeros(n, np.int32)
    klen = np.zeros(n, np.int32)
    stacks = np.zeros((n, STACK_SLOTS), np.uint64)
    if n:
        i32p = ctypes.POINTER(ctypes.c_int32)
        got = int(lib.pa_decode_v1(
            p, nbytes,
            pids.ctypes.data_as(i32p),
            tids.ctypes.data_as(i32p),
            ulen.ctypes.data_as(i32p),
            klen.ctypes.data_as(i32p),
            stacks.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            STACK_SLOTS, n))
        assert got == n, (got, n)
    return pids, tids, ulen, klen, stacks


def decode_records_columnar_v1d(lib, buf, nbytes: int) -> tuple:
    """Native one-pass v1d decode (dedup-drain records, 24-byte header
    with a count field) into columnar arrays. Returns (pids, tids, ulen,
    klen, stacks, counts) with user frames first per row."""
    if isinstance(buf, (bytes, bytearray)):
        buf = (ctypes.c_uint8 * nbytes).from_buffer_copy(buf[:nbytes])
    p = ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8))
    n = int(lib.pa_decode_v1d_count(p, nbytes, STACK_SLOTS))
    pids = np.zeros(n, np.int32)
    tids = np.zeros(n, np.int32)
    ulen = np.zeros(n, np.int32)
    klen = np.zeros(n, np.int32)
    counts = np.zeros(n, np.int64)
    stacks = np.zeros((n, STACK_SLOTS), np.uint64)
    if n:
        i32p = ctypes.POINTER(ctypes.c_int32)
        got = int(lib.pa_decode_v1d(
            p, nbytes,
            pids.ctypes.data_as(i32p),
            tids.ctypes.data_as(i32p),
            ulen.ctypes.data_as(i32p),
            klen.ctypes.data_as(i32p),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            stacks.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            STACK_SLOTS, n))
        assert got == n, (got, n)
    return pids, tids, ulen, klen, stacks, counts


def decode_records_columnar_v1h(lib, buf, nbytes: int) -> tuple:
    """Native one-pass v1h decode (hash-carrying dedup-drain records,
    32-byte header with count + h1/h2/h3) into columnar arrays. Returns
    (pids, tids, ulen, klen, stacks, counts, h1, h2, h3) with user frames
    first per row; the hash triple is bit-identical to row_hash_np over
    the decoded row (the drain computed it with the same installed
    coefficient tables)."""
    if isinstance(buf, (bytes, bytearray)):
        buf = (ctypes.c_uint8 * nbytes).from_buffer_copy(buf[:nbytes])
    p = ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8))
    n = int(lib.pa_decode_v1h_count(p, nbytes, STACK_SLOTS))
    pids = np.zeros(n, np.int32)
    tids = np.zeros(n, np.int32)
    ulen = np.zeros(n, np.int32)
    klen = np.zeros(n, np.int32)
    counts = np.zeros(n, np.int64)
    h1 = np.zeros(n, np.uint32)
    h2 = np.zeros(n, np.uint32)
    h3 = np.zeros(n, np.uint32)
    stacks = np.zeros((n, STACK_SLOTS), np.uint64)
    if n:
        i32p = ctypes.POINTER(ctypes.c_int32)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        got = int(lib.pa_decode_v1h(
            p, nbytes,
            pids.ctypes.data_as(i32p),
            tids.ctypes.data_as(i32p),
            ulen.ctypes.data_as(i32p),
            klen.ctypes.data_as(i32p),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            h1.ctypes.data_as(u32p),
            h2.ctypes.data_as(u32p),
            h3.ctypes.data_as(u32p),
            stacks.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            STACK_SLOTS, n))
        assert got == n, (got, n)
    return pids, tids, ulen, klen, stacks, counts, h1, h2, h3


def mapping_table_for_pids(maps_cache, objs_cache, pids,
                           quarantine=None) -> MappingTable:
    """MappingTable for a set of pids via the shared caches; pids that
    exited (maps unreadable) or are unattributable (< 0) are skipped —
    their rows keep raw addresses. Shared by the window-end snapshot
    build and the streaming feeder's per-drain mini-snapshots so the two
    paths cannot drift.

    Ingest containment (docs/robustness.md): with a quarantine registry,
    a pid whose maps file is poison (PoisonInput) or whose processing
    blows the per-pid deadline is charged against its error budget and
    skipped — its samples stay unmapped and ride the degradation ladder —
    instead of aborting the table build for every pid in the window.
    Without a registry, PoisonInput propagates (the pre-containment
    drop-on-error behavior the bench's ingest_poison baseline measures).
    Scalar-level pids skip maps parsing entirely; address-level pids
    keep maps (normalized addresses must travel) but skip ELF opens
    (build_mapping_table's degraded path — the ELF is the suspect)."""
    from parca_agent_tpu.utils.poison import PoisonInput

    per_pid = {}
    healthy = {}
    for pid in pids:
        pid = int(pid)
        if pid < 0:
            continue
        level = quarantine.level(pid) if quarantine is not None else 0
        if level >= 2:
            continue  # scalar ladder level: counts only, no mapping work
        t0 = quarantine.clock() if quarantine is not None else 0.0
        try:
            per_pid[pid] = maps_cache.executable_mappings(pid)
        except OSError:
            continue
        except PoisonInput as e:
            if quarantine is None:
                raise
            quarantine.record_error(pid, getattr(e, "site", "maps.parse"),
                                    e)
            continue
        if quarantine is not None:
            quarantine.check_deadline(pid, t0)
            if quarantine.level(pid) == 0:
                healthy[pid] = per_pid[pid]
        else:
            healthy[pid] = per_pid[pid]
    # Build ids come from opening mapped ELFs — only healthy pids pay
    # (and risk) that; a shared path mapped by any healthy pid still
    # contributes its id for everyone.
    return build_mapping_table(per_pid, objs_cache.build_ids(healthy),
                               objcache=objs_cache, quarantine=quarantine)


def columns_to_snapshot(
    pids, tids, ulen, klen, stacks,
    mappings: MappingTable, period_ns: int, window_ns: int,
    weights=None, hashes=None,
) -> WindowSnapshot:
    """Dedup identical (pid, tid, stack) rows into counted rows (the role
    the BPF stack_counts map plays in the reference). Columnar input from
    the native decoder or from records_to_snapshot's packing. `weights`
    carries per-row pre-aggregated counts (the native dedup drain emits
    them); rows still merge here — drain passes and table overflows leave
    best-effort duplicates — with counts summed.

    `hashes` is an optional capture-carried (h1, h2, h3) uint32 triple
    aligned with the input rows (the v1h drain). When given, the return
    is (snapshot, (h1, h2, h3)) with the triple gathered onto the
    snapshot's deduped rows — exact, because dedup-equal rows hash to
    equal triples (the hash is a function of pid/ulen/klen/stack only)."""
    pids = np.asarray(pids, np.int32)
    if weights is not None:
        weights = np.asarray(weights, np.int64)
    if hashes is not None:
        hashes = tuple(np.asarray(h, np.uint32) for h in hashes)
    if len(pids) and int(pids.min()) < 0:
        # perf delivers unattributable/idle-context samples as pid -1;
        # they carry no process to profile, and downstream the uint32
        # cast would alias the device kernels' dead-row sentinel
        # (aggregator/tpu.py pack guard). Drop the records, not the
        # window.
        keep = pids >= 0
        pids, tids = pids[keep], np.asarray(tids)[keep]
        ulen, klen = np.asarray(ulen)[keep], np.asarray(klen)[keep]
        stacks = np.asarray(stacks)[keep]
        if weights is not None:
            weights = weights[keep]
        if hashes is not None:
            hashes = tuple(h[keep] for h in hashes)
    n = len(pids)
    if n == 0:
        snap = WindowSnapshot(
            pids=np.zeros(0, np.int32), tids=np.zeros(0, np.int32),
            counts=np.zeros(0, np.int64), user_len=np.zeros(0, np.int32),
            kernel_len=np.zeros(0, np.int32),
            stacks=np.zeros((0, STACK_SLOTS), np.uint64),
            mappings=mappings, period_ns=period_ns, window_ns=window_ns,
            time_ns=time.time_ns(),
        )
        if hashes is not None:
            return snap, tuple(np.zeros(0, np.uint32) for _ in range(3))
        return snap
    # Vectorized row dedup (same byte-view trick as CPUAggregator),
    # comparing only up to the window's deepest stack: slots past it are
    # zero in every row, so the result is identical and the sort compares
    # ~3x less data at typical depths.
    max_depth = int((ulen + klen).max())
    rec = np.zeros((n, max_depth + 4), np.uint64)
    rec[:, 0] = pids.astype(np.uint64)
    rec[:, 1] = tids.astype(np.uint64)
    rec[:, 2] = ulen.astype(np.uint64)
    rec[:, 3] = klen.astype(np.uint64)
    rec[:, 4:] = stacks[:, :max_depth]
    void = np.ascontiguousarray(rec).view(
        np.dtype((np.void, rec.shape[1] * 8))).ravel()
    _, first, inverse = np.unique(void, return_index=True, return_inverse=True)
    if weights is None:
        # Unweighted bincount accumulates in exact integers already.
        counts = np.bincount(inverse, minlength=len(first)).astype(np.int64)
    else:
        # Weighted bincount sums in float64 — exact only below 2^53 per
        # key. Window mass is bounded far under that in practice (the
        # aggregator raises at 2^31), so take the fast path and fall
        # back to the integral-but-~10-30x-slower scatter-add on the
        # pathological mass, keeping "counts are exact either way"
        # unconditional rather than resting on float precision.
        if int(weights.sum(dtype=np.int64)) < 2**53:
            counts = np.bincount(
                inverse, weights=weights, minlength=len(first)).astype(
                    np.int64)
        else:
            counts = np.zeros(len(first), np.int64)
            np.add.at(counts, inverse, weights.astype(np.int64))
    snap = WindowSnapshot(
        pids=pids[first], tids=tids[first], counts=counts,
        user_len=ulen[first], kernel_len=klen[first], stacks=stacks[first],
        mappings=mappings, period_ns=period_ns, window_ns=window_ns,
        time_ns=time.time_ns(),
    )
    if hashes is not None:
        return snap, tuple(h[first] for h in hashes)
    return snap


def records_to_snapshot(
    records, mappings: MappingTable, period_ns: int, window_ns: int,
) -> WindowSnapshot:
    """Tuple-record variant of columns_to_snapshot (the DWARF path's
    walker rewrites per-record user chains, so it stays tuple-shaped)."""
    n = len(records)
    pids = np.zeros(n, np.int32)
    tids = np.zeros(n, np.int32)
    ulen = np.zeros(n, np.int32)
    klen = np.zeros(n, np.int32)
    stacks = np.zeros((n, STACK_SLOTS), np.uint64)
    for i, (pid, tid, kframes, uframes) in enumerate(records):
        # perf carries pid/tid as u32 (-1 = unattributable); store with
        # int32 wraparound semantics like the native columnar decoder,
        # so columns_to_snapshot's negative-pid drop sees them as -1.
        pids[i] = pid if pid < 2**31 else pid - 2**32
        tids[i] = tid if tid < 2**31 else tid - 2**32
        nu, nk = len(uframes), len(kframes)
        ulen[i] = nu
        klen[i] = nk
        # formats.py contract: user frames first, then kernel tail.
        stacks[i, :nu] = uframes
        stacks[i, nu:nu + nk] = kframes
    return columns_to_snapshot(pids, tids, ulen, klen, stacks,
                               mappings, period_ns, window_ns)


class UnwindTableCache:
    """Per-pid merged compact unwind tables with background builds and 5 s
    refresh (the role of the reference's watchProcesses loop,
    pkg/profiler/cpu/cpu.go:390-459: match processes, build/refresh their
    unwind tables off the hot path)."""

    def __init__(self, map_cache: ProcessMapCache,
                 comm_regex: str | None = None,
                 refresh_s: float = 5.0, fs=None):
        from parca_agent_tpu.unwind.table import UnwindTableBuilder
        from parca_agent_tpu.utils.vfs import RealFS

        self._fs = fs or RealFS()
        self._builder = UnwindTableBuilder(fs=self._fs)
        # Ingest containment: set (post-construction, by the sampler's
        # quarantine property) to the shared per-pid registry; builds
        # charge poison to the owning pid and skip laddered pids.
        self.quarantine = None
        self._maps = map_cache
        self._regex = re.compile(comm_regex) if comm_regex else None
        self._refresh = refresh_s
        self._tables: dict[int, np.ndarray] = {}
        self._built_at: dict[int, float] = {}
        self._lock = threading.Lock()
        self._queue: list[int] = []
        self._qset: set[int] = set()
        self._cv = threading.Condition(self._lock)
        self._stop = False
        self._worker: threading.Thread | None = None
        self._last_evict = 0.0
        self.stats = {"builds": 0, "build_errors": 0}

    def _comm(self, pid: int) -> str:
        try:
            return self._fs.read_bytes(
                f"/proc/{pid}/comm").decode().strip()
        except OSError:
            return ""

    def matches(self, pid: int) -> bool:
        if self._regex is None:
            return True
        return bool(self._regex.search(self._comm(pid)))

    def table_for(self, pid: int) -> "ShardedTable | None":
        """The pid's table if built; queues a (re)build when missing or
        stale. Never blocks the drain path."""
        now = time.monotonic()
        with self._lock:
            t = self._tables.get(pid)
            fresh = now - self._built_at.get(pid, 0) < self._refresh
            if (t is None or not fresh) and pid not in self._qset:
                self._qset.add(pid)
                self._queue.append(pid)
                self._cv.notify()
                self._ensure_worker()
            return t

    def evict(self, pid: int) -> None:
        """Drop a pid's table immediately (generation-stamped identity
        invalidation, process/identity.py: a recycled pid must not
        unwind through its dead predecessor's tables). A queued rebuild
        may stay queued — it reads the pid's CURRENT maps, which is
        exactly the fresh state we want."""
        with self._lock:
            self._tables.pop(pid, None)
            self._built_at.pop(pid, None)

    def _ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._run, name="unwind-table-builder", daemon=True)
            self._worker.start()

    def _run(self) -> None:
        while True:
            pid = None
            with self._cv:
                if not self._queue and not self._stop:
                    self._cv.wait(timeout=1.0)
                if self._stop:
                    return
                if self._queue:
                    pid = self._queue.pop(0)
            if pid is None:
                # Idle tick: matched processes may ALL have exited, in
                # which case no build ever requeues and the per-build
                # sweep below would never run. _evict_dead self-rate-
                # limits, so idle ticks cost one monotonic read.
                self._evict_dead()
                continue
            from parca_agent_tpu.unwind.table import ShardedTable

            try:
                self._builder.quarantine = self.quarantine
                maps = self._maps.executable_mappings(pid)
                # Store range-partitioned (the reference's (pid, shard)
                # layout, maps.go:286-395): the walker's two-level lookup
                # consumes shards directly, and huge processes keep full
                # coverage (no 3-shard truncation; see shard_table).
                table = ShardedTable.from_table(
                    self._builder.table_for_pid(pid, maps))
                with self._lock:
                    self._tables[pid] = table
                    self._built_at[pid] = time.monotonic()
                self.stats["builds"] += 1
            except Exception as e:
                # table_for_pid contains the PoisonInput taxonomy itself
                # (charging the pid's budget), but a maps read can raise
                # MapsError here and defense-in-depth still wants the
                # blanket guard (MemoryError from a hostile allocation).
                # Record built_at so the poison pid is not re-queued every
                # drain, and keep the worker alive for the other pids.
                from parca_agent_tpu.utils.poison import PoisonInput

                if self.quarantine is not None \
                        and isinstance(e, PoisonInput):
                    self.quarantine.record_error(
                        pid, getattr(e, "site", "unwind.build"), e)
                with self._lock:
                    self._built_at[pid] = time.monotonic()
                self.stats["build_errors"] += 1
                _log.warn("unwind table build failed", pid=pid,
                          error=repr(e))
            finally:
                with self._lock:
                    self._qset.discard(pid)
                self._evict_dead()

    def _evict_dead(self) -> None:
        """Drop tables for exited pids so an always-on agent's table
        memory tracks the LIVE process set instead of growing forever
        under pid churn (same bounded-memory stance as the aggregator's
        cold-id rotation). Runs opportunistically after builds, at most
        once per refresh interval."""
        now = time.monotonic()
        if now - self._last_evict < self._refresh:
            return
        self._last_evict = now
        with self._lock:
            pids = list(self._tables)
        dead = [p for p in pids
                if not self._fs.exists(f"/proc/{p}/comm")]
        if not dead:
            return
        with self._lock:
            for p in dead:
                self._tables.pop(p, None)
                self._built_at.pop(p, None)
        self.stats["evicted"] = self.stats.get("evicted", 0) + len(dead)
        _log.debug("evicted unwind tables for exited pids", count=len(dead))

    def build_now(self, pid: int) -> "ShardedTable | None":
        """Synchronous build (tests / tools)."""
        from parca_agent_tpu.unwind.table import ShardedTable
        from parca_agent_tpu.utils.poison import PoisonInput

        try:
            self._builder.quarantine = self.quarantine
            maps = self._maps.executable_mappings(pid)
        except OSError:
            return None
        except PoisonInput as e:
            if self.quarantine is not None:
                self.quarantine.record_error(
                    pid, getattr(e, "site", "maps.parse"), e)
            return None
        table = ShardedTable.from_table(
            self._builder.table_for_pid(pid, maps))
        with self._lock:
            self._tables[pid] = table
            self._built_at[pid] = time.monotonic()
        self.stats["builds"] += 1
        return table

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()


def unwind_records(records_v2, tables: UnwindTableCache,
                   trust_fp_frames: int | None = None, stats=None):
    """v2 records -> v1-shaped records with DWARF-walked user stacks.

    Every register-carrying sample of a table-matched pid is batch-walked
    and the LONGER of the walked vs frame-pointer chain wins — the
    reference likewise runs its DWARF walker instead of the FP path for
    every sample of a targeted process (cpu.bpf.c:724-757); walking only
    short FP chains would keep truncated mixed stacks (an FP-built leaf
    over a frameless caller stops the FP chain early yet still has >= 2
    frames). trust_fp_frames is a throughput knob: samples whose FP chain
    already has that many frames skip the walk (None = walk all).
    """
    from parca_agent_tpu.unwind.walker import WalkStats, walk_batch

    by_pid: dict[int, list[int]] = {}
    for i, r in enumerate(records_v2):
        by_pid.setdefault(r[0], []).append(i)

    out = [(r[0], r[1], r[2], r[3]) for r in records_v2]
    total_stats = stats if stats is not None else WalkStats()
    for pid, idxs in by_pid.items():
        need = [i for i in idxs
                if records_v2[i][4] != 0
                and (trust_fp_frames is None
                     or len(records_v2[i][3]) < trust_fp_frames)]
        if not need or not tables.matches(pid):
            continue
        table = tables.table_for(pid)
        if table is None or len(table) == 0:
            continue
        m = len(need)
        dmax = max(len(records_v2[i][7]) for i in need)
        rip = np.zeros(m, np.uint64)
        rsp = np.zeros(m, np.uint64)
        rbp = np.zeros(m, np.uint64)
        dyn = np.zeros(m, np.int64)
        stacks = np.zeros((m, max(dmax, 8)), np.uint8)
        for k, i in enumerate(need):
            _, _, _, _, ip, sp, bp, stk = records_v2[i]
            rip[k], rsp[k], rbp[k] = ip, sp, bp
            dyn[k] = len(stk)
            stacks[k, : len(stk)] = stk
        frames, depth, st = walk_batch(table, rip, rsp, rbp, stacks, dyn)
        total_stats.add(st)
        for k, i in enumerate(need):
            # The record's kernel frames stay on the row; the walked user
            # chain must fit the remaining depth budget or the combined
            # stack would overflow records_to_snapshot's STACK_SLOTS rows.
            budget = MAX_STACK_DEPTH - len(records_v2[i][2])
            d = min(int(depth[k]), budget)
            # Only adopt the walk when it beats the FP chain.
            if d > len(records_v2[i][3]):
                pid_, tid_, kf, _uf = out[i]
                out[i] = (pid_, tid_, kf, frames[k, :d].copy())
    return out


class PerfEventSampler:
    """Capture source: poll() blocks one window then drains the rings."""

    def __init__(self, frequency_hz: int = 100, window_s: float = 10.0,
                 drain_cap_mb: int = 64, capture_stack: bool = False,
                 stack_dump_bytes: int = 16 * 1024,
                 dwarf_comm_regex: str | None = None,
                 trust_fp_frames: int | None = None):
        self._lib = load_native()
        self._freq = frequency_hz
        self._window = window_s
        self._cap = drain_cap_mb << 20
        self._maps = ProcessMapCache()
        self._objs = ObjectFileCache()
        # Ingest containment: the CLI wires the shared per-pid quarantine
        # registry here (via the `quarantine` property) so the window-end
        # mapping build AND the DWARF unwind-table cache charge poisoned
        # pids instead of failing the snapshot (runtime/quarantine.py).
        self._quarantine = None
        # One reusable drain buffer: allocating + zeroing drain_cap_mb per
        # drain pass is pure churn on the capture path; only the n written
        # bytes are ever read back.
        self._drainbuf = (ctypes.c_uint8 * self._cap)()
        # (lost, truncated, dedup, dd_overflow) snapshotted at close
        self._final_counters = (0, 0, 0, 0)
        # Optional per-drain tee (FP mode): called on the polling thread
        # with each drain's columnar chunk so a streaming consumer (the
        # window feeder) can ship it to the aggregation device DURING the
        # window. A failing tee disables itself for the agent's lifetime
        # (the window-end snapshot path is unaffected either way).
        self.on_drain = None
        self.capture_stack = capture_stack
        flags = PA_CAPTURE_USER_STACK if capture_stack else 0
        self._handle = self._lib.pa_sampler_create2(
            frequency_hz, flags, stack_dump_bytes)
        if not self._handle:
            err = ctypes.get_errno()
            raise SamplerUnavailable(
                f"perf_event_open failed (errno {err}): needs CAP_PERFMON or "
                f"kernel.perf_event_paranoid <= 0"
            )
        if self._lib.pa_sampler_start(self._handle) != 0:
            # Free the per-CPU perf fds before raising: the caller
            # degrades to another capture source and this object is
            # discarded unclosed.
            self._lib.pa_sampler_destroy(self._handle)
            self._handle = None
            raise SamplerUnavailable("failed to enable perf events")
        self.n_cpus = self._lib.pa_sampler_n_cpus(self._handle)
        # Capture-side hash carry (docs/perf.md "feed endgame"): install
        # the Python-seeded multilinear coefficient tables so the dedup
        # drain can stamp each unique record with its h1/h2/h3 triple
        # while the frames are hot in cache. FP mode only (the DWARF
        # walker rewrites user chains after the drain, invalidating any
        # drain-time hash). PARCA_NO_CAPTURE_HASH=1 pins the hashless
        # v1d drain — the build-less fallback stays exact either way.
        self.hash_carry = False
        if not capture_stack \
                and not os.environ.get("PARCA_NO_CAPTURE_HASH"):
            try:
                from parca_agent_tpu.ops.hashing import hash_params

                coefs, biases = hash_params(3, STACK_SLOTS)
                u32p = ctypes.POINTER(ctypes.c_uint32)
                ok = self._lib.pa_sampler_set_hash(
                    self._handle, coefs.ctypes.data_as(u32p),
                    coefs.shape[1], biases.ctypes.data_as(u32p), 3,
                    STACK_SLOTS)
                self.hash_carry = ok == 0
            except AttributeError:
                # Stale pre-carry .so: run hashless; the feeder hashes
                # host-side exactly as before.
                pass
        self._tables = UnwindTableCache(
            self._maps, comm_regex=dwarf_comm_regex) if capture_stack \
            else None
        self._trust_fp_frames = trust_fp_frames
        from parca_agent_tpu.unwind.walker import WalkStats

        self.walk_stats = WalkStats()

    @property
    def quarantine(self):
        return self._quarantine

    @quarantine.setter
    def quarantine(self, registry) -> None:
        self._quarantine = registry
        if self._tables is not None:
            self._tables.quarantine = registry

    # Counter properties stay truthful after close(): the native handle
    # is gone then (the C getters would see NULL and answer 0), so close
    # snapshots the final values.
    @property
    def lost_samples(self) -> int:
        if self._handle:
            return int(self._lib.pa_sampler_lost(self._handle))
        return self._final_counters[0]

    @property
    def truncated_drains(self) -> int:
        if self._handle:
            return int(self._lib.pa_sampler_truncated(self._handle))
        return self._final_counters[1]

    @property
    def dedup_hits(self) -> int:
        """Samples merged into an existing row at the drain boundary
        (capture-side pre-aggregation effectiveness; measured ~92% of
        samples on a steady synthetic load)."""
        if self._handle:
            return int(self._lib.pa_sampler_dedup_hits(self._handle))
        return self._final_counters[2]

    @property
    def dedup_overflow(self) -> int:
        """Records emitted without table registration because the dedup
        probe chain saturated — distinguishes hash-table overflow from
        genuine stack uniqueness when the dedup rate drops."""
        if self._handle:
            return int(self._lib.pa_sampler_dedup_overflow(self._handle))
        return self._final_counters[3]

    def _drain_passes(self, consume, dedup: bool = False,
                      hashed: bool = False) -> None:
        """Lossless drain: loops while the native side reports records
        left behind for lack of buffer space, handing each pass's
        (buffer, n_bytes) to `consume` before the buffer is reused."""
        if hashed:
            drain = self._lib.pa_sampler_drain_dedup2
        else:
            drain = (self._lib.pa_sampler_drain_dedup if dedup
                     else self._lib.pa_sampler_drain)
        for _ in range(64):  # safety bound; one pass is the norm
            before = self.truncated_drains
            n = drain(
                self._handle, self._drainbuf, ctypes.c_long(self._cap))
            if n < 0:
                raise SamplerUnavailable("sampler drain failed")
            if n:
                consume(self._drainbuf, int(n))
            if self.truncated_drains == before:
                break

    def _drain(self) -> bytes:
        chunks = []
        self._drain_passes(
            lambda buf, n: chunks.append(ctypes.string_at(buf, n)))
        return b"".join(chunks)

    def _drain_columnar(self) -> list[tuple]:
        """Lossless DEDUP drain with the native columnar decoder applied
        per pass, straight off the reusable drain buffer (no bytes copy).
        The native side pre-aggregates repeats to (row, count) so Python
        decodes ~unique rows (the reference's in-kernel envelope). With
        hash carry installed the chunks additionally tail the h1/h2/h3
        triple (9 columns instead of 6); a refused v1h drain permanently
        falls back to the hashless v1d drain mid-session."""
        cols = []
        if self.hash_carry:
            try:
                self._drain_passes(
                    lambda buf, n: cols.append(
                        decode_records_columnar_v1h(self._lib, buf, n)),
                    hashed=True)
                return cols
            except SamplerUnavailable:
                _log.warn("v1h drain refused; disabling capture-side "
                          "hash carry for this sampler")
                self.hash_carry = False
                cols = []
        self._drain_passes(
            lambda buf, n: cols.append(
                decode_records_columnar_v1d(self._lib, buf, n)),
            dedup=True)
        return cols

    def poll(self) -> WindowSnapshot:
        deadline = time.monotonic() + self._window
        # Drain mid-window too so a ring never wraps (the reference sizes
        # BPF maps for a full window; perf rings are smaller).
        records = []
        col_chunks: list[tuple] = []
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            time.sleep(min(1.0, remaining))
            if self.capture_stack:
                raw = self._drain()
                v2 = decode_records_v2(raw)
                # Queue table builds early so they're ready within the
                # window (matches the 5 s watch cadence).
                for pid in {r[0] for r in v2}:
                    if self._tables.matches(pid):
                        self._tables.table_for(pid)
                records.extend(
                    unwind_records(v2, self._tables,
                                   trust_fp_frames=self._trust_fp_frames,
                                   stats=self.walk_stats))
            else:
                chunks = self._drain_columnar()
                col_chunks.extend(chunks)
                if self.on_drain is not None:
                    for c in chunks:
                        try:
                            self.on_drain(c)
                        except Exception as e:  # noqa: BLE001 - tee only
                            _log.warn("on_drain tee failed; disabling "
                                      "streaming for this agent",
                                      error=repr(e))
                            self.on_drain = None
                            break

        if self.capture_stack:
            pid_iter = sorted({r[0] for r in records})
        else:
            cols = [np.concatenate([c[i] for c in col_chunks])
                    if col_chunks else z
                    for i, z in enumerate((
                        np.zeros(0, np.int32), np.zeros(0, np.int32),
                        np.zeros(0, np.int32), np.zeros(0, np.int32),
                        np.zeros((0, STACK_SLOTS), np.uint64),
                        np.zeros(0, np.int64)))]
            pid_iter = np.unique(cols[0]).tolist()
        table = mapping_table_for_pids(self._maps, self._objs, pid_iter,
                                       quarantine=self.quarantine)
        period_ns = int(1e9 / self._freq)
        window_ns = int(self._window * 1e9)
        if self.capture_stack:
            return records_to_snapshot(records, table, period_ns, window_ns)
        return columns_to_snapshot(*cols[:5], table, period_ns, window_ns,
                                   weights=cols[5])

    def close(self) -> None:
        if self._handle:
            self._final_counters = (self.lost_samples,
                                    self.truncated_drains, self.dedup_hits,
                                    self.dedup_overflow)
            self._lib.pa_sampler_destroy(self._handle)
            self._handle = None
        if self._tables is not None:
            self._tables.close()


class CommFilterSource:
    """Snapshot-source wrapper keeping only rows whose pid's comm matches
    one of the given regexes — the reference's hidden --debug-process-names
    debug flag (main.go DebugProcessNames: 'Only attach profilers to
    specified processes', matched against comm). Whole-machine capture
    stays on; rows are dropped at the window boundary, so the filter
    composes with any source. Comm verdicts are cached per pid with a
    TTL: pids get reused by the kernel and processes exec() into new
    comms, so a verdict is a lease, not a fact (and the TTL also bounds
    the cache under pid churn).

    NOTE: drains tee'd mid-window (streaming) bypass this filter; the CLI
    therefore runs debug-filtered sessions one-shot.
    """

    def __init__(self, source, patterns, read_comm=None,
                 cache_ttl_s: float = 60.0, clock=time.monotonic):
        self._source = source
        self._regexes = [re.compile(p) for p in patterns if p]
        self._cache: dict[int, tuple[bool, float]] = {}
        self._ttl = cache_ttl_s
        self._clock = clock

        def _default_read(pid: int) -> str:
            try:
                with open(f"/proc/{pid}/comm", "rb") as f:
                    return f.read().decode().strip()
            except OSError:
                return ""

        self._read_comm = read_comm or _default_read

    def __getattr__(self, name):
        return getattr(self._source, name)

    def _keep(self, pid: int, now: float) -> bool:
        got = self._cache.get(pid)
        if got is not None and now - got[1] < self._ttl:
            return got[0]
        comm = self._read_comm(pid)
        verdict = any(r.search(comm) for r in self._regexes)
        self._cache[pid] = (verdict, now)
        return verdict

    def poll(self):
        snap = self._source.poll()
        if snap is None or not len(snap) or not self._regexes:
            return snap
        now = self._clock()
        uniq = np.unique(snap.pids)
        if len(self._cache) > 4 * len(uniq) + 1024:
            # Bound the cache under pid churn: drop expired leases.
            self._cache = {p: v for p, v in self._cache.items()
                           if now - v[1] < self._ttl}
        kept = np.array([p for p in uniq.tolist()
                         if self._keep(int(p), now)], np.int32)
        if len(kept) == len(uniq):
            return snap
        return filter_snapshot_rows(snap, np.isin(snap.pids, kept))

    def close(self) -> None:
        self._source.close()
