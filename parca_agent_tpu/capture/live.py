"""Live capture source over the native perf_event sampler.

Python side of parca_agent_tpu/native/sampler.cc (the capture role of the
reference's pkg/profiler/cpu/cpu.go:234-275 perf_event_open + attach): the
shared library is built on demand with the local toolchain, loaded via
ctypes, and drained once per window. Raw records are decoded with numpy,
deduplicated into (pid, stack) -> count rows (the aggregation the
reference's BPF map does kernel-side happens here, vectorized), and joined
with the live /proc mapping table.

Record format (sampler.cc): u32 pid | u32 tid | u32 n_kernel | u32 n_user
| u64 frames[n_kernel + n_user] (kernel-first; we store user-first in the
snapshot per the formats.py contract).
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import time

import numpy as np

from parca_agent_tpu.capture.formats import (
    MAX_STACK_DEPTH,
    STACK_SLOTS,
    MappingTable,
    WindowSnapshot,
)
from parca_agent_tpu.process.maps import ProcessMapCache, build_mapping_table
from parca_agent_tpu.process.objectfile import ObjectFileCache

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_LIB = os.path.join(_NATIVE_DIR, "libpasampler.so")


class SamplerUnavailable(RuntimeError):
    pass


def build_native(force: bool = False) -> str:
    """Compile libpasampler.so if missing; returns its path."""
    src = os.path.join(_NATIVE_DIR, "sampler.cc")
    if force or not os.path.exists(_LIB) or \
            os.path.getmtime(_LIB) < os.path.getmtime(src):
        r = subprocess.run(["make", "-C", _NATIVE_DIR, "libpasampler.so"],
                           capture_output=True, text=True)
        if r.returncode != 0:
            raise SamplerUnavailable(f"native build failed:\n{r.stderr}")
    return _LIB


def load_native():
    lib = ctypes.CDLL(build_native(), use_errno=True)
    lib.pa_sampler_create.restype = ctypes.c_void_p
    lib.pa_sampler_create.argtypes = [ctypes.c_int]
    lib.pa_sampler_n_cpus.restype = ctypes.c_int
    lib.pa_sampler_n_cpus.argtypes = [ctypes.c_void_p]
    lib.pa_sampler_lost.restype = ctypes.c_uint64
    lib.pa_sampler_lost.argtypes = [ctypes.c_void_p]
    lib.pa_sampler_start.restype = ctypes.c_int
    lib.pa_sampler_start.argtypes = [ctypes.c_void_p]
    lib.pa_sampler_stop.restype = ctypes.c_int
    lib.pa_sampler_stop.argtypes = [ctypes.c_void_p]
    lib.pa_sampler_drain.restype = ctypes.c_long
    lib.pa_sampler_drain.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_uint8),
                                     ctypes.c_long]
    lib.pa_sampler_destroy.restype = None
    lib.pa_sampler_destroy.argtypes = [ctypes.c_void_p]
    return lib


def decode_records(buf: bytes) -> list[tuple[int, int, np.ndarray, np.ndarray]]:
    """Packed drain buffer -> [(pid, tid, kernel_frames, user_frames)]."""
    out = []
    pos = 0
    n = len(buf)
    while pos + 16 <= n:
        pid, tid, nk, nu = struct.unpack_from("<IIII", buf, pos)
        pos += 16
        if nk + nu > MAX_STACK_DEPTH or pos + 8 * (nk + nu) > n:
            break  # corrupt/truncated tail
        frames = np.frombuffer(buf, np.uint64, nk + nu, pos)
        pos += 8 * (nk + nu)
        out.append((pid, tid, frames[:nk], frames[nk:]))
    return out


def records_to_snapshot(
    records, mappings: MappingTable, period_ns: int, window_ns: int,
) -> WindowSnapshot:
    """Dedup identical (pid, tid, stack) records into counted rows
    (the role the BPF stack_counts map plays in the reference)."""
    n = len(records)
    if n == 0:
        return WindowSnapshot(
            pids=np.zeros(0, np.int32), tids=np.zeros(0, np.int32),
            counts=np.zeros(0, np.int64), user_len=np.zeros(0, np.int32),
            kernel_len=np.zeros(0, np.int32),
            stacks=np.zeros((0, STACK_SLOTS), np.uint64),
            mappings=mappings, period_ns=period_ns, window_ns=window_ns,
            time_ns=time.time_ns(),
        )
    pids = np.zeros(n, np.int32)
    tids = np.zeros(n, np.int32)
    ulen = np.zeros(n, np.int32)
    klen = np.zeros(n, np.int32)
    stacks = np.zeros((n, STACK_SLOTS), np.uint64)
    for i, (pid, tid, kframes, uframes) in enumerate(records):
        pids[i] = pid
        tids[i] = tid
        nu, nk = len(uframes), len(kframes)
        ulen[i] = nu
        klen[i] = nk
        # formats.py contract: user frames first, then kernel tail.
        stacks[i, :nu] = uframes
        stacks[i, nu:nu + nk] = kframes

    # Vectorized row dedup (same byte-view trick as CPUAggregator).
    rec = np.zeros((n, STACK_SLOTS + 4), np.uint64)
    rec[:, 0] = pids.astype(np.uint64)
    rec[:, 1] = tids.astype(np.uint64)
    rec[:, 2] = ulen.astype(np.uint64)
    rec[:, 3] = klen.astype(np.uint64)
    rec[:, 4:] = stacks
    void = np.ascontiguousarray(rec).view(
        np.dtype((np.void, rec.shape[1] * 8))).ravel()
    _, first, inverse = np.unique(void, return_index=True, return_inverse=True)
    counts = np.bincount(inverse, minlength=len(first)).astype(np.int64)
    return WindowSnapshot(
        pids=pids[first], tids=tids[first], counts=counts,
        user_len=ulen[first], kernel_len=klen[first], stacks=stacks[first],
        mappings=mappings, period_ns=period_ns, window_ns=window_ns,
        time_ns=time.time_ns(),
    )


class PerfEventSampler:
    """Capture source: poll() blocks one window then drains the rings."""

    def __init__(self, frequency_hz: int = 100, window_s: float = 10.0,
                 drain_cap_mb: int = 64):
        self._lib = load_native()
        self._freq = frequency_hz
        self._window = window_s
        self._cap = drain_cap_mb << 20
        self._maps = ProcessMapCache()
        self._objs = ObjectFileCache()
        self._handle = self._lib.pa_sampler_create(frequency_hz)
        if not self._handle:
            err = ctypes.get_errno()
            raise SamplerUnavailable(
                f"perf_event_open failed (errno {err}): needs CAP_PERFMON or "
                f"kernel.perf_event_paranoid <= 0"
            )
        if self._lib.pa_sampler_start(self._handle) != 0:
            raise SamplerUnavailable("failed to enable perf events")
        self.n_cpus = self._lib.pa_sampler_n_cpus(self._handle)

    @property
    def lost_samples(self) -> int:
        return int(self._lib.pa_sampler_lost(self._handle))

    def _drain(self) -> bytes:
        buf = (ctypes.c_uint8 * self._cap)()
        n = self._lib.pa_sampler_drain(
            self._handle, buf, ctypes.c_long(self._cap))
        if n < 0:
            raise SamplerUnavailable("drain buffer overflow; raise drain_cap_mb")
        return bytes(buf[:n])

    def poll(self) -> WindowSnapshot:
        deadline = time.monotonic() + self._window
        # Drain mid-window too so a ring never wraps (the reference sizes
        # BPF maps for a full window; perf rings are smaller).
        chunks = []
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            time.sleep(min(1.0, remaining))
            chunks.append(self._drain())
        records = decode_records(b"".join(chunks))
        per_pid = {}
        for pid in sorted({r[0] for r in records}):
            try:
                per_pid[pid] = self._maps.executable_mappings(pid)
            except OSError:
                continue
        table = build_mapping_table(per_pid, self._objs.build_ids(per_pid))
        return records_to_snapshot(
            records, table, int(1e9 / self._freq), int(self._window * 1e9),
        )

    def close(self) -> None:
        if self._handle:
            self._lib.pa_sampler_destroy(self._handle)
            self._handle = None
