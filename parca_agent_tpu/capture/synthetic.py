"""Synthetic workload generator (BASELINE configs #2 and #4).

Produces WindowSnapshots with the statistical shape of a busy machine:
a Zipf-distributed population of unique stacks over many PIDs, realistic
address-space layout (a few executable mappings per PID, leaf frames deep in
shared-library ranges), and a fraction of samples carrying kernel tails.

Deterministic given a seed — the same (seed, params) always produces the
same snapshot, so fixtures don't need to be checked in.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from parca_agent_tpu.capture.formats import (
    KERNEL_ADDR_START,
    MAX_STACK_DEPTH,
    STACK_SLOTS,
    MappingTable,
    WindowSnapshot,
)


@dataclasses.dataclass(frozen=True)
class SyntheticSpec:
    n_pids: int = 1_000
    n_unique_stacks: int = 10_000
    n_rows: int | None = None        # rows in the snapshot; default = n_unique_stacks
    total_samples: int = 1_000_000   # sum of counts across rows
    mean_depth: int = 24             # mean user-stack depth
    kernel_fraction: float = 0.2     # fraction of rows with a kernel tail
    max_kernel_depth: int = 16
    mappings_per_pid: int = 4
    # Function pool per object: location entropy knob. The default makes
    # frame addresses near-unique per pid (every stack draws ~24 frames
    # from 4096 functions but a pid owns only ~n_rows/n_pids stacks) —
    # the adversarial case for location dedup. Small pools model real
    # hosts, where a pid's hot frames repeat across most of its stacks.
    n_funcs: int = 4096
    seed: int = 0


def _mapping_layout(spec: SyntheticSpec, rng: np.random.Generator):
    """Build per-PID mapping tables: one main executable + shared objects.

    Shared objects get one object id reused across PIDs (as on a real host,
    where every process maps the same libc) so build-id dedup paths see
    realistic fan-in (reference pkg/debuginfo/manager.go:116-127).
    """
    n_shared = max(1, spec.mappings_per_pid - 1)
    obj_paths = ["/app/bin/worker"] + [f"/usr/lib/libshared{i}.so" for i in range(n_shared)]
    obj_buildids = [f"{i:040x}" for i in range(1, len(obj_paths) + 1)]

    pids = np.repeat(
        np.arange(1000, 1000 + spec.n_pids, dtype=np.int32), spec.mappings_per_pid
    )
    m = len(pids)
    per = spec.mappings_per_pid

    # Main executable at a per-PID ASLR-ish base; shared objs at high bases
    # common across PIDs (same object, same offset pattern).
    exe_base = 0x0000_5500_0000_0000 + (
        rng.integers(0, 1 << 20, spec.n_pids, dtype=np.uint64) << np.uint64(12)
    )
    shared_base = 0x0000_7F00_0000_0000 + (
        np.arange(n_shared, dtype=np.uint64) << np.uint64(28)
    )

    starts = np.zeros(m, np.uint64)
    ends = np.zeros(m, np.uint64)
    offsets = np.zeros(m, np.uint64)
    objs = np.zeros(m, np.int32)
    size = np.uint64(1 << 24)  # 16 MiB of text per mapping
    for j in range(per):
        sl = slice(j, m, per)
        if j == 0:
            starts[sl] = exe_base
            objs[sl] = 0
        else:
            starts[sl] = shared_base[j - 1]
            objs[sl] = j
        ends[sl] = starts[sl] + size
        offsets[sl] = np.uint64(0x1000) * np.uint64(j)

    order = np.lexsort((starts, pids))
    return MappingTable(
        pids[order], starts[order], ends[order], offsets[order], objs[order],
        tuple(obj_paths), tuple(obj_buildids),
    ), exe_base, shared_base, size


def generate(spec: SyntheticSpec) -> WindowSnapshot:
    if spec.mappings_per_pid < 2:
        raise ValueError("mappings_per_pid must be >= 2 (exe + >=1 shared)")
    rng = np.random.default_rng(spec.seed)
    n_rows = spec.n_rows if spec.n_rows is not None else spec.n_unique_stacks
    table, exe_base, shared_base, msize = _mapping_layout(spec, rng)

    # Each unique stack belongs to one pid; pids get a Zipf share of stacks.
    pid_of_stack = rng.integers(0, spec.n_pids, spec.n_unique_stacks)
    depths = np.clip(
        rng.poisson(spec.mean_depth, spec.n_unique_stacks), 2, MAX_STACK_DEPTH - spec.max_kernel_depth
    ).astype(np.int32)

    # Frame addresses: a pool of "functions" per object; leaf-first.
    n_funcs = spec.n_funcs
    func_off = (rng.integers(0, n_funcs, (spec.n_unique_stacks, STACK_SLOTS), dtype=np.uint64)
                << np.uint64(8)) + np.uint64(0x40)
    which_obj = rng.integers(0, len(shared_base) + 1, (spec.n_unique_stacks, STACK_SLOTS))
    base = np.where(
        which_obj == 0,
        exe_base[pid_of_stack][:, None],
        shared_base[np.clip(which_obj - 1, 0, len(shared_base) - 1)],
    ).astype(np.uint64)
    addrs = base + (func_off % msize)

    # Kernel tails for a subset of stacks.
    has_kernel = rng.random(spec.n_unique_stacks) < spec.kernel_fraction
    kdepth = np.where(
        has_kernel, rng.integers(1, spec.max_kernel_depth + 1, spec.n_unique_stacks), 0
    ).astype(np.int32)
    kaddrs = (np.uint64(KERNEL_ADDR_START)
              + (rng.integers(0, 65536, (spec.n_unique_stacks, spec.max_kernel_depth),
                              dtype=np.uint64) << np.uint64(6)))

    slot = np.arange(STACK_SLOTS, dtype=np.int32)[None, :]
    stacks = np.where(slot < depths[:, None], addrs, np.uint64(0))
    # Place kernel frames directly after the user frames.
    kslot = slot - depths[:, None]
    in_kernel = (kslot >= 0) & (kslot < kdepth[:, None])
    kgather = np.take_along_axis(
        kaddrs, np.clip(kslot, 0, spec.max_kernel_depth - 1), axis=1
    )
    stacks = np.where(in_kernel, kgather, stacks)

    # Rows: exactly n_rows DISTINCT (pid, stack) pairs — what a capture-side
    # hash map hands the drain path — with Zipf-skewed counts so heavy
    # hitters exist for the sketch benchmarks. A capture map never holds
    # zero-count entries, so rows drawing zero get 1 and the excess is
    # taken back from the heaviest rows, conserving total_samples exactly.
    n_take = min(n_rows, spec.n_unique_stacks)
    if spec.total_samples < n_take:
        raise ValueError("total_samples must be >= number of distinct rows")
    if n_take == 0:
        return WindowSnapshot(
            pids=np.zeros(0, np.int32), tids=np.zeros(0, np.int32),
            counts=np.zeros(0, np.int64), user_len=np.zeros(0, np.int32),
            kernel_len=np.zeros(0, np.int32),
            stacks=np.zeros((0, STACK_SLOTS), np.uint64),
            mappings=table, time_ns=1_700_000_000_000_000_000,
        )
    uniq = rng.permutation(spec.n_unique_stacks)[:n_take]
    weights = 1.0 / np.arange(1, n_take + 1, dtype=np.float64) ** 1.1
    per_row = rng.multinomial(spec.total_samples, weights / weights.sum())
    counts = np.maximum(per_row, 1).astype(np.int64)
    excess = int(counts.sum()) - spec.total_samples
    if excess > 0:
        order = np.argsort(counts)[::-1]
        for i in order:
            take = min(excess, int(counts[i]) - 1)
            counts[i] -= take
            excess -= take
            if excess == 0:
                break

    sel = np.sort(uniq.astype(np.int64))
    pids = (1000 + pid_of_stack[sel]).astype(np.int32)
    snap = WindowSnapshot(
        pids=pids,
        tids=pids,  # main thread
        counts=counts,
        user_len=depths[sel],
        kernel_len=kdepth[sel],
        stacks=stacks[sel],
        mappings=table,
        time_ns=1_700_000_000_000_000_000,
    )
    snap.validate_padding()
    return snap


def split_fleet(snap: WindowSnapshot, n_nodes: int, dup_every: int = 3,
                seed: int = 0) -> list[WindowSnapshot]:
    """Split one window's rows across n_nodes simulated fleet nodes.

    Every dup_every-th row with count >= 2 lands on TWO nodes with its count
    split, so cross-node dedup in the fleet merge is exercised for real; the
    concatenation of the returned windows is count-for-count the original
    window, which is what makes it the merge-correctness oracle input
    (BASELINE config #5 test harness, SURVEY.md section 4 closing note)."""
    rng = np.random.default_rng(seed)
    n = len(snap)
    node = rng.integers(0, n_nodes, n).astype(np.int64)
    dup = (np.arange(n) % dup_every == 0) & (snap.counts >= 2)
    idx2 = np.flatnonzero(dup)
    all_idx = np.concatenate([np.arange(n), idx2])
    all_counts = np.concatenate([
        np.where(dup, snap.counts // 2, snap.counts),
        snap.counts[idx2] - snap.counts[idx2] // 2,
    ])
    all_node = np.concatenate([node, (node[idx2] + 1) % n_nodes])
    windows = []
    for k in range(n_nodes):
        sel = all_node == k
        rows = all_idx[sel]
        windows.append(WindowSnapshot(
            pids=snap.pids[rows], tids=snap.tids[rows],
            counts=all_counts[sel], user_len=snap.user_len[rows],
            kernel_len=snap.kernel_len[rows], stacks=snap.stacks[rows],
            mappings=snap.mappings, period_ns=snap.period_ns,
            window_ns=snap.window_ns, time_ns=snap.time_ns))
    return windows
