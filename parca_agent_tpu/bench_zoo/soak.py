"""Wall-clock endurance: the workload zoo run until the clock says stop.

``run_soak`` wires ONE persistent agent (admission, quarantine, perf-map
cache, generation-stamped identity, carry-enabled DictAggregator behind
the streaming feeder) and drives an endless interleave of zoo scenario
schedules through the real ``CPUProfiler.run_iteration`` loop until
``wall_s`` elapses. Windows run back-to-back — hour-scale window counts
compressed into minutes of wall time — while every window samples the
process RSS and the per-subsystem byte lanes
(``DictAggregator.footprint_bytes``, identity table, admission/
quarantine registries).

The verdict is mechanical, not vibes:

* ``rss_slope_ok`` — least-squares RSS growth per window (after a
  fixed warm-up) under ``rss_slope_limit``;
* ``lanes_ok`` — every byte/entry lane's post-warm-up slope under
  ``lane_slope_limit`` (a cache that grows without bound fails here
  long before it OOMs);
* ``windows_lost_zero`` and ``mass_conserved`` — the zoo's own bars,
  cumulative over the whole soak.

Sampling rides the ``soak.tick`` chaos site and is fail-open: an
injected fault costs that window's sample only (counted tick_errors),
never the window or the verdict arithmetic. ``python -m
parca_agent_tpu.bench_zoo.soak`` is the ``make soak`` /
``make soak-smoke`` entry point; it honors PARCA_FAULTS like the CLI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

from parca_agent_tpu.bench_zoo.runner import (
    _RecordingDict, _ZooStreamFeeder, _wall_equivalent)
from parca_agent_tpu.bench_zoo.scenarios import SCENARIOS, build_schedule
from parca_agent_tpu.process.identity import ProcessIdentityTracker
from parca_agent_tpu.profiler.cpu import CPUProfiler
from parca_agent_tpu.runtime.admission import (
    AdmissionController, TenantResolver)
from parca_agent_tpu.runtime.quarantine import QuarantineRegistry
from parca_agent_tpu.runtime.window_clock import check_window_s
from parca_agent_tpu.symbolize.perfmap import PerfMapCache
from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.vfs import FakeFS

try:
    _PAGE = os.sysconf("SC_PAGE_SIZE")
except (AttributeError, ValueError, OSError):  # pragma: no cover
    _PAGE = 4096

# Fixed warm-up excluded from every slope: allocator arenas, jit
# compiles, and cold caches all land in the first windows and are not
# leaks. Short runs fall back to skipping the first half.
WARMUP_WINDOWS = 32
_MIN_SLOPE_POINTS = 8

# Bytes of RSS growth per window the verdict tolerates after warm-up.
# Python allocator noise is real; a genuine per-window leak clears this
# in minutes and the per-lane slopes catch the culprit cache by name.
DEFAULT_RSS_SLOPE_LIMIT = 2048.0

# Per-lane growth per window (bytes for the byte lanes, entries for the
# count lanes). The zoo population recurs every cycle, so every cache
# must plateau once it has seen the whole zoo.
DEFAULT_LANE_SLOPE_LIMIT = 256.0


def _rss_bytes() -> int:
    with open("/proc/self/statm", "rb") as f:
        return int(f.read().split()[1]) * _PAGE


class _SlopeReg:
    """Streaming least-squares y-per-x slope: running sums only, so the
    soak's own bookkeeping stays O(1) per window (a sampler that grows a
    list per window would fail its own RSS bar on a long run)."""

    __slots__ = ("n", "sx", "sy", "sxx", "sxy")

    def __init__(self) -> None:
        self.n = 0
        self.sx = self.sy = self.sxx = self.sxy = 0.0

    def add(self, x: float, y: float) -> None:
        self.n += 1
        self.sx += x
        self.sy += y
        self.sxx += x * x
        self.sxy += x * y

    def slope(self) -> float:
        if self.n < 2:
            return 0.0
        d = self.n * self.sxx - self.sx * self.sx
        if d == 0.0:
            return 0.0
        return (self.n * self.sxy - self.sx * self.sy) / d


class SoakStatus:
    """Live soak telemetry, shared with the web endpoints: the soak
    loop updates it per window, /metrics and /healthz read snapshots.
    Never-red by construction — it carries numbers and the last
    verdict, it cannot veto readiness."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._d: dict = {
            "running": False, "scenario": "", "scenarios": (),
            "windows_elapsed": 0, "rss_bytes": 0, "lanes": {},
            "verdict": None,
        }

    def update(self, **kw) -> None:
        with self._lock:
            self._d.update(kw)

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._d)
            out["lanes"] = dict(out["lanes"])
            return out


class _SoakSource:
    """Capture source over an endless zoo interleave: pops windows from
    deterministically re-seeded scenario schedules, applies each
    window's world mutations, and returns None only when the wall clock
    says the soak is over."""

    def __init__(self, seed: int, scale: float, fs: FakeFS,
                 world: dict[int, int], deadline: float,
                 names=None, clock=time.monotonic):
        self._seed = int(seed)
        self._scale = float(scale)
        self._fs = fs
        self._world = world
        self._deadline = deadline
        self._names = names
        self._clock = clock
        self._cycle = 0
        self._queue: list = []  # (scenario_name, ZooWindow)
        self.current = -1
        self.scenario = ""
        self.samples_fed = 0
        self.cycles = 0

    def _replenish(self) -> None:
        # One full schedule per cycle, re-seeded so content varies but
        # the whole soak replays bit-identically from (seed, wall).
        schedule = build_schedule(self._seed + self._cycle, self._names)
        for e in schedule:
            scn = SCENARIOS[e["scenario"]]()
            for zw in scn.build(e["seed"], self._scale):
                self._queue.append((e["scenario"], zw))
        self._cycle += 1
        self.cycles = self._cycle

    def poll(self):
        if self._clock() >= self._deadline:
            return None
        if not self._queue:
            self._replenish()
        name, zw = self._queue.pop(0)
        for path in sorted(zw.files):
            self._fs.put(path, zw.files[path])
        self._world.update(zw.starttimes)
        self.current += 1
        self.scenario = name
        self.samples_fed += int(zw.snapshot.counts.sum())
        return zw.snapshot


class _CountingWriter:
    """Ship sink that keeps totals, never blobs: a writer that retained
    every profile would be the leak the soak is hunting."""

    def __init__(self) -> None:
        self.profiles = 0
        self.bytes_total = 0

    def write(self, labels: dict, blob) -> None:
        self.profiles += 1
        self.bytes_total += len(blob)


def run_soak(wall_s: float = 60.0, seed: int = 1234, scale: float = 0.5,
             window_s: float = 1.0,
             rss_slope_limit: float = DEFAULT_RSS_SLOPE_LIMIT,
             lane_slope_limit: float = DEFAULT_LANE_SLOPE_LIMIT,
             names=None, status: SoakStatus | None = None,
             series_points: int = 256) -> dict:
    """Run the endurance soak for ``wall_s`` seconds and return the
    verdict artifact. Deterministic content for a given (seed, scale);
    the wall clock only decides how many windows fit."""
    check_window_s(window_s)
    wall_s = float(wall_s)
    if wall_s <= 0:
        raise ValueError(f"wall_s must be > 0, got {wall_s}")

    fs = FakeFS()
    world: dict[int, int] = {}
    resolver = TenantResolver(fs=fs)
    adm_kwargs, qua_kwargs = _wall_equivalent({}, window_s)
    admission = AdmissionController(resolver, **adm_kwargs)
    quarantine = QuarantineRegistry(**qua_kwargs)
    perf = PerfMapCache(fs=fs, churn_budget=8)
    identity = ProcessIdentityTracker(
        starttime_of=world.__getitem__, enabled=True)
    identity.add_invalidator("quarantine", quarantine.forget_pid)
    identity.add_invalidator("tenant", resolver.forget)
    identity.add_invalidator("perfmap", perf.evict)

    t_start = time.monotonic()
    source = _SoakSource(seed, scale, fs, world, t_start + wall_s,
                         names=names)
    writer = _CountingWriter()
    if status is not None:
        # The scenario universe up front so /metrics can render the
        # one-hot family with a stable label set from window zero.
        status.update(running=True,
                      scenarios=tuple(names) if names else tuple(SCENARIOS))
    agg = _RecordingDict(capacity=1 << 14, carry=True)
    agg.zoo_source = source
    identity.add_invalidator("aggregator", agg.invalidate_pid)
    feeder = _ZooStreamFeeder(agg, source)

    samples_shipped = 0
    tick_errors = 0
    regs: dict[str, _SlopeReg] = {}
    warm_regs: dict[str, _SlopeReg] = {}
    series: list[dict] = []  # downsampled, for the artifact
    lanes_last: dict[str, float] = {}

    def _observe(name: str, w: int, value: float) -> None:
        lanes_last[name] = value
        regs.setdefault(name, _SlopeReg()).add(w, value)
        if w >= WARMUP_WINDOWS:
            warm_regs.setdefault(name, _SlopeReg()).add(w, value)

    def _tick(_attempts: int) -> None:
        nonlocal samples_shipped, tick_errors
        w = source.current
        # Fold this window's shipped mass OUT of the recorders so the
        # soak's own accounting is O(1), then sample under the chaos
        # site: an injected fault costs this sample only.
        for rec in (agg.mass_by_window, feeder.mass_by_window):
            for _k in list(rec):
                samples_shipped += rec.pop(_k)
        try:
            faults.inject("soak.tick")
            rss = _rss_bytes()
            _observe("rss_bytes", w, float(rss))
            for lane, val in agg.footprint_bytes().items():
                _observe(lane, w, float(val))
            _observe("identity_tracked_pids", w,
                     float(identity.snapshot().get("tracked_pids", 0)))
            _observe("quarantine_entries", w,
                     float(len(quarantine.snapshot().get("pids", {}))))
            if w % max(1, (source.current + 1) // series_points) == 0 \
                    and len(series) < 2 * series_points:
                series.append({"window": w, "rss_bytes": rss,
                               "scenario": source.scenario})
            if status is not None:
                status.update(running=True, scenario=source.scenario,
                              windows_elapsed=w + 1, rss_bytes=rss,
                              lanes=dict(lanes_last))
        except Exception:  # noqa: BLE001 - counted, never the window
            tick_errors += 1

    profiler = CPUProfiler(
        source, agg, profile_writer=writer, quarantine=quarantine,
        admission=admission, identity=identity, fast_encode=True,
        streaming_feeder=feeder, on_iteration=_tick)

    while profiler.run_iteration():
        pass
    wall_used = time.monotonic() - t_start

    # Late stragglers (last window's mass folds after the final tick).
    for rec in (agg.mass_by_window, feeder.mass_by_window):
        for _k in list(rec):
            samples_shipped += rec.pop(_k)

    windows = source.current + 1
    # Slopes are judged on post-warm-up samples only; a run too short
    # to clear warm-up has no leak-vs-startup signal, so it reports the
    # slopes as unmeasured rather than failing on its own cold caches.
    slope_measured = bool(warm_regs) and all(
        r.n >= _MIN_SLOPE_POINTS for r in warm_regs.values())
    slopes = {name: reg.slope()
              for name, reg in (warm_regs if slope_measured
                                else regs).items()}
    rss_slope = slopes.pop("rss_bytes", 0.0)
    bad_lanes = {name: s for name, s in slopes.items()
                 if s > lane_slope_limit}
    bars = {
        "ran_windows": windows >= 1,
        "windows_lost_zero": int(profiler.metrics.errors_total) == 0,
        "mass_conserved": samples_shipped == source.samples_fed,
        "rss_slope_ok": (not slope_measured
                         or rss_slope <= rss_slope_limit),
        "lanes_ok": not slope_measured or not bad_lanes,
    }
    verdict = {
        "wall_s": float(wall_s),
        "wall_used_s": float(wall_used),
        "seed": int(seed),
        "scale": float(scale),
        "window_s": float(window_s),
        "windows": windows,
        "cycles": source.cycles,
        "windows_lost": int(profiler.metrics.errors_total),
        "samples_fed": int(source.samples_fed),
        "samples_shipped": int(samples_shipped),
        "profiles_written": writer.profiles,
        "shipped_bytes": writer.bytes_total,
        "path_fallbacks": feeder.stats["path_fallbacks"],
        "tick_errors": tick_errors,
        "slope_measured": slope_measured,
        "rss_slope_bytes_per_window": float(rss_slope),
        "rss_slope_limit": float(rss_slope_limit),
        "lane_slopes": {k: float(v) for k, v in slopes.items()},
        "lane_slope_limit": float(lane_slope_limit),
        "bad_lanes": {k: float(v) for k, v in bad_lanes.items()},
        "lanes_final": {k: float(v) for k, v in lanes_last.items()},
        "series": series,
        "bars": bars,
        "passed": all(bars.values()),
    }
    if status is not None:
        status.update(running=False, verdict=verdict,
                      windows_elapsed=windows)
    return verdict


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="workload-zoo endurance soak (make soak)")
    ap.add_argument("--wall", type=float, default=300.0,
                    help="soak wall time in seconds")
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--scale", type=float, default=0.5)
    ap.add_argument("--window", type=float, default=1.0,
                    help="registry window_s (cadence semantics under "
                         "test; windows still run back-to-back)")
    ap.add_argument("--rss-slope-limit", type=float,
                    default=DEFAULT_RSS_SLOPE_LIMIT)
    ap.add_argument("--lane-slope-limit", type=float,
                    default=DEFAULT_LANE_SLOPE_LIMIT)
    ap.add_argument("--out", default="", help="write the verdict "
                    "artifact to this JSON path")
    args = ap.parse_args(argv)

    spec = os.environ.get("PARCA_FAULTS", "")
    if spec:
        faults.install(faults.FaultInjector.from_spec(
            spec, seed=int(os.environ.get("PARCA_FAULT_SEED", "0"))))
    out = run_soak(wall_s=args.wall, seed=args.seed, scale=args.scale,
                   window_s=args.window,
                   rss_slope_limit=args.rss_slope_limit,
                   lane_slope_limit=args.lane_slope_limit)
    brief = {k: out[k] for k in (
        "windows", "cycles", "windows_lost", "samples_fed",
        "samples_shipped", "rss_slope_bytes_per_window", "bad_lanes",
        "tick_errors", "path_fallbacks", "passed")}
    print(json.dumps(brief, indent=2, sort_keys=True))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2, sort_keys=True)
        print(f"soak artifact: {args.out}")
    return 0 if out["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
