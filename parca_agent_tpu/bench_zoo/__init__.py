"""Workload zoo: scenario breadth as a scored matrix.

Seeded, deterministic hostile-world scenarios (pid reuse under tenant
migration, JIT perf-map churn, fork/exec storms, deep stacks,
kernel-heavy mixes, multi-tenant bursts), each driven through the REAL
profiler window loop and scored against per-scenario bars. Entry
points: ``build_schedule`` (deterministic sweep plan), ``run_scenario``
(one matrix row), ``run_zoo`` (the whole matrix — what ``make
bench-zoo`` runs). See docs/robustness.md's workload-zoo section.
"""

from parca_agent_tpu.bench_zoo.runner import run_scenario, run_zoo
from parca_agent_tpu.bench_zoo.scenarios import (
    SCENARIOS, Scenario, ZooWindow, build_schedule, make_snapshot)

__all__ = [
    "SCENARIOS", "Scenario", "ZooWindow", "build_schedule",
    "make_snapshot", "run_scenario", "run_zoo",
]
