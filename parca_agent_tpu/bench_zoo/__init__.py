"""Workload zoo: scenario breadth as a scored matrix.

Seeded, deterministic hostile-world scenarios (pid reuse under tenant
migration, JIT perf-map churn, fork/exec storms, deep stacks,
kernel-heavy mixes, multi-tenant bursts), each driven through the REAL
profiler window loop and scored against per-scenario bars. Entry
points: ``build_schedule`` (deterministic sweep plan), ``run_scenario``
(one matrix row: path x cadence x optional device outage), ``run_zoo``
(the scalar matrix — what tests pin), ``run_matrix`` (the full
endurance matrix — what ``make bench-zoo`` runs), and ``run_soak``
(bench_zoo/soak.py: wall-time endurance with RSS/byte-lane verdicts —
``make soak``). See docs/robustness.md's endurance-matrix section.
"""

from parca_agent_tpu.bench_zoo.runner import (
    CADENCES, OUTAGES, PATHS, run_matrix, run_scenario, run_zoo)
from parca_agent_tpu.bench_zoo.scenarios import (
    SCENARIOS, Scenario, ZooWindow, build_schedule, make_snapshot)
from parca_agent_tpu.bench_zoo.soak import run_soak

__all__ = [
    "CADENCES", "OUTAGES", "PATHS", "SCENARIOS", "Scenario", "ZooWindow",
    "build_schedule", "make_snapshot", "run_matrix", "run_scenario",
    "run_soak", "run_zoo",
]
