"""Workload-zoo runner: score scenarios through the REAL window loop.

No simulation shortcuts: every scenario's windows flow through a live
``CPUProfiler.run_iteration`` with the production components wired the
way cli.py wires them — DictAggregator (scalar close path), Symbolizer
over a PerfMapCache + KsymCache on the scenario's FakeFS, quarantine
registry, admission controller with its TenantResolver reading the
scenario's fake cgroups, and the generation-stamped
ProcessIdentityTracker with the same invalidator set the CLI registers.
The scenario only supplies the WORLD: snapshots, procfs files, and
per-pid starttimes, mutated window by window exactly as a hostile host
would mutate them under the agent.

Scoring: every row carries the base bars (windows_lost == 0, sample
mass conserved end to end, close-latency ceiling) plus the scenario's
own (reuse detected, abuser quarantined, byte identity, ...). A row
passes only if every bar holds; ``run_zoo`` is the matrix sweep
``make bench-zoo`` and tests/test_zoo.py drive.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time

import numpy as np

from parca_agent_tpu.aggregator.dict import DictAggregator
from parca_agent_tpu.bench_zoo.scenarios import (
    SCENARIOS, Scenario, ZooWindow, build_schedule)
from parca_agent_tpu.process.identity import ProcessIdentityTracker
from parca_agent_tpu.profiler.cpu import CPUProfiler
from parca_agent_tpu.runtime.admission import (
    AdmissionController, TenantResolver)
from parca_agent_tpu.runtime.quarantine import QuarantineRegistry
from parca_agent_tpu.symbolize.ksym import KsymCache
from parca_agent_tpu.symbolize.perfmap import PerfMapCache
from parca_agent_tpu.symbolize.symbolizer import Symbolizer
from parca_agent_tpu.utils.vfs import FakeFS

# Per-scenario close-latency ceiling (seconds). The zoo runs tiny
# windows on the scalar path; a close that takes longer than this is a
# regression even on a loaded CI box.
DEFAULT_CLOSE_CEILING_S = 2.0


class _ZooSource:
    """Capture source over a scenario's window stream: applies each
    window's world mutations (procfs files, starttimes) BEFORE handing
    the snapshot over, exactly as the real world mutates under a poll."""

    def __init__(self, windows: list[ZooWindow], fs: FakeFS,
                 world: dict[int, int]):
        self._windows = windows
        self._fs = fs
        self._world = world
        self.current = -1

    def poll(self):
        i = self.current + 1
        if i >= len(self._windows):
            return None
        zw = self._windows[i]
        for path in sorted(zw.files):
            self._fs.put(path, zw.files[path])
        self._world.update(zw.starttimes)
        self.current = i
        return zw.snapshot


class _ZooWriter:
    """Profile sink recording (window, labels, pprof bytes) triples."""

    def __init__(self, source: _ZooSource):
        self._source = source
        self.shipped: list[tuple[int, dict, bytes]] = []

    def write(self, labels: dict, blob: bytes) -> None:
        self.shipped.append((self._source.current, dict(labels), blob))


class _RecordingAggregator:
    """Transparent DictAggregator proxy that keeps each window's
    pre-ladder profile objects for scoring (the profiler ships the same
    objects, so symbolization results are visible here too)."""

    def __init__(self, inner: DictAggregator):
        self._inner = inner
        self.windows: list[list] = []

    def aggregate(self, snapshot):
        profiles = self._inner.aggregate(snapshot)
        self.windows.append(list(profiles))
        return profiles

    def __getattr__(self, name):
        return getattr(self._inner, name)


@dataclasses.dataclass
class RunContext:
    """Everything a scenario's check() may inspect after the run."""

    profiles_by_window: list[list]
    shipped: list[tuple[int, dict, bytes]]
    truth: dict
    aggregator: DictAggregator
    identity: ProcessIdentityTracker
    admission: AdmissionController
    quarantine: QuarantineRegistry
    resolver: TenantResolver
    perf: PerfMapCache


def _digest(ctx: RunContext) -> str:
    """Canonical run digest: the seeded-determinism handle. Covers the
    scored substance (per-window profile tables + shipped bytes), never
    wall-clock measurements."""
    h = hashlib.sha256()
    for w, profs in enumerate(ctx.profiles_by_window):
        for p in sorted(profs, key=lambda p: p.pid):
            h.update(repr((
                w, p.pid, p.values.tolist(),
                p.stack_loc_ids[:, :8].tolist(), p.stack_depths.tolist(),
                p.loc_address.tolist(), p.loc_normalized.tolist(),
                p.loc_mapping_id.tolist(),
                [(m.id, m.path, m.start) for m in p.mappings],
                sorted(f[0] for f in p.functions),
            )).encode())
    for w, labels, blob in ctx.shipped:
        h.update(repr((w, sorted(labels.items()))).encode())
        h.update(hashlib.sha256(blob).digest())
    return h.hexdigest()


def run_scenario(scenario, seed: int, scale: float = 1.0,
                 hardened: bool | None = None) -> dict:
    """One matrix row: build the scenario's windows, drive them through
    the real profiler loop, and score against the bars. ``hardened``
    None follows PARCA_NO_PID_GENERATION (the control-arm pin)."""
    scn: Scenario = (SCENARIOS[scenario]()
                     if isinstance(scenario, str) else scenario)
    if hardened is None:
        hardened = os.environ.get("PARCA_NO_PID_GENERATION", "") != "1"
    windows = scn.build(seed, scale)
    cfg = scn.config(scale)

    fs = FakeFS()
    world: dict[int, int] = {}
    resolver = TenantResolver(fs=fs)
    admission = AdmissionController(resolver, **cfg.get("admission", {}))
    quarantine = QuarantineRegistry(**cfg.get("quarantine", {}))
    perf = PerfMapCache(fs=fs, churn_budget=int(cfg.get("churn_budget", 8)))
    ksym = None
    if cfg.get("kallsyms"):
        fs.put("/proc/kallsyms", cfg["kallsyms"])
        ksym = KsymCache(fs=fs)
    symbolizer = Symbolizer(ksym=ksym, perf=perf,
                            quarantine=quarantine, admission=admission)
    inner = DictAggregator(capacity=1 << 14)
    agg = _RecordingAggregator(inner)
    identity = ProcessIdentityTracker(
        starttime_of=world.__getitem__, enabled=hardened)
    # The same invalidator set cli.py registers: every bare-pid cache
    # drops the dead generation's state on a starttime mismatch.
    identity.add_invalidator("aggregator", inner.invalidate_pid)
    identity.add_invalidator("quarantine", quarantine.forget_pid)
    identity.add_invalidator("tenant", resolver.forget)
    identity.add_invalidator("perfmap", perf.evict)

    source = _ZooSource(windows, fs, world)
    writer = _ZooWriter(source)
    profiler = CPUProfiler(
        source, agg, symbolizer=symbolizer, profile_writer=writer,
        quarantine=quarantine, admission=admission, identity=identity)

    close_lat: list[float] = []
    t0 = time.perf_counter()
    while profiler.run_iteration():
        close_lat.append(profiler.metrics.last_aggregate_duration_s)
    wall_s = time.perf_counter() - t0

    ctx = RunContext(
        profiles_by_window=agg.windows, shipped=writer.shipped,
        truth=scn.truth, aggregator=inner, identity=identity,
        admission=admission, quarantine=quarantine, resolver=resolver,
        perf=perf)

    samples_fed = int(sum(int(zw.snapshot.counts.sum()) for zw in windows))
    samples_shipped = int(sum(p.total() for profs in agg.windows
                              for p in profs))
    ceiling = float(cfg.get("close_latency_ceiling_s",
                            DEFAULT_CLOSE_CEILING_S))
    outcome = {
        "scenario": scn.name,
        "axis": scn.axis,
        "description": scn.description,
        "seed": int(seed),
        "scale": float(scale),
        "hardened": bool(hardened),
        "windows": len(windows),
        "degraded_builds": int(scn.truth.get("degraded_builds", 0)),
        "windows_lost": int(profiler.metrics.errors_total),
        "windows_closed": len(agg.windows),
        "profiles_written": int(profiler.metrics.profiles_written),
        "samples_fed": samples_fed,
        "samples_shipped": samples_shipped,
        "close_latency_max_s": max(close_lat, default=0.0),
        "close_latency_ceiling_s": ceiling,
        "wall_s": wall_s,
        "identity": identity.metrics(),
        "admission": dict(admission.stats),
        "quarantine": dict(quarantine.stats),
        "perfmap": dict(perf.stats),
        "tenant_resolver": dict(resolver.stats),
    }
    bars = {
        "windows_lost_zero": outcome["windows_lost"] == 0,
        "every_window_closed": outcome["windows_closed"] == len(windows),
        "mass_conserved": samples_shipped == samples_fed,
        "close_latency_ceiling":
            outcome["close_latency_max_s"] <= ceiling,
    }
    bars.update(scn.check(outcome, ctx))
    outcome["bars"] = bars
    outcome["passed"] = all(bars.values())
    outcome["digest"] = _digest(ctx)
    return outcome


def run_zoo(seed: int, scale: float = 1.0, names=None,
            hardened: bool | None = None) -> dict:
    """The full matrix sweep: a deterministic schedule of scenario rows,
    each scored through the real window loop."""
    schedule = build_schedule(seed, names)
    rows = [run_scenario(e["scenario"], e["seed"], scale=scale,
                         hardened=hardened) for e in schedule]
    return {
        "seed": int(seed),
        "scale": float(scale),
        "schedule": schedule,
        "rows": rows,
        "scenarios_passed": sum(r["passed"] for r in rows),
        "scenarios_total": len(rows),
        "passed": bool(rows) and all(r["passed"] for r in rows),
    }
