"""Workload-zoo runner: score scenarios through the REAL window loop.

No simulation shortcuts: every scenario's windows flow through a live
``CPUProfiler.run_iteration`` with the production components wired the
way cli.py wires them — DictAggregator (scalar close path), Symbolizer
over a PerfMapCache + KsymCache on the scenario's FakeFS, quarantine
registry, admission controller with its TenantResolver reading the
scenario's fake cgroups, and the generation-stamped
ProcessIdentityTracker with the same invalidator set the CLI registers.
The scenario only supplies the WORLD: snapshots, procfs files, and
per-pid starttimes, mutated window by window exactly as a hostile host
would mutate them under the agent.

Three axes beyond the scenario itself (``run_matrix``):

* **path** — the same windows ride the scalar close path (``scalar``),
  the fast-encode pipeline (``pipeline``: window_counts + vectorized
  encoder + encode worker), or a streaming feeder with the carry cache
  (``streaming``: chunked ``feed`` + packed close over a carry-enabled
  dict). The fast arms must ship byte-identical pprof sequences and all
  three must conserve the same per-window mass.
* **cadence** — every row re-runs at a sub-second window
  (``window_s=1.0``). Scenario knobs are authored at the reference
  10 s window, so the runner scales them to their wall-time-equivalent
  values (:func:`_wall_equivalent`) and the registries' own
  window_clock conversion restores the exact per-window numbers; a
  compensated run therefore must make identical per-window decisions,
  and the scalar digest must be bit-identical across cadences. That
  round trip is what the cadence bar pins.
* **outage** — scalar rows re-run with a fallback aggregator and a
  DeviceHealthRegistry while a mid-run ``device.dispatch`` hang (or a
  hung bring-up ``device.probe``) is injected. The row must degrade
  through the health ladder and recover (shadow window -> healthy)
  with zero lost windows while the hostile workload keeps running.

Scoring: every row carries the base bars (windows_lost == 0, sample
mass conserved end to end, close-latency ceiling) plus the scenario's
own (reuse detected, abuser quarantined, byte identity, ...). A row
passes only if every bar holds; ``run_zoo`` is the matrix sweep
``make bench-zoo`` and tests/test_zoo.py drive, ``run_matrix`` is the
full path x cadence x outage cross-product.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time

import numpy as np

from parca_agent_tpu.aggregator.dict import DictAggregator
from parca_agent_tpu.bench_zoo.scenarios import (
    SCENARIOS, WINDOW_NS, Scenario, ZooWindow, _mapping, build_schedule,
    make_snapshot)
from parca_agent_tpu.process.identity import ProcessIdentityTracker
from parca_agent_tpu.profiler.cpu import CPUProfiler
from parca_agent_tpu.runtime.admission import (
    AdmissionController, TenantResolver)
from parca_agent_tpu.runtime.device_health import (
    DeviceHealthRegistry, STATE_HEALTHY, STATE_PROBING)
from parca_agent_tpu.runtime.quarantine import QuarantineRegistry
from parca_agent_tpu.runtime.window_clock import (
    REFERENCE_WINDOW_S, check_window_s)
from parca_agent_tpu.symbolize.ksym import KsymCache
from parca_agent_tpu.symbolize.perfmap import PerfMapCache
from parca_agent_tpu.symbolize.symbolizer import Symbolizer
from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.vfs import FakeFS

# Per-scenario close-latency ceiling (seconds). The zoo runs tiny
# windows on the scalar path; a close that takes longer than this is a
# regression even on a loaded CI box.
DEFAULT_CLOSE_CEILING_S = 2.0

# The three close paths every scenario must survive.
PATHS = ("scalar", "pipeline", "streaming")

# Reference cadence plus the 10x sub-second re-run.
CADENCES = (REFERENCE_WINDOW_S, 1.0)

# Mid-run device faults (faults.py SITES) each scenario is crossed with.
OUTAGES = ("dispatch", "probe")

# Outage-row device watchdog: the injected dispatch hang must overrun
# it, a real zoo aggregate (milliseconds on these snapshots, once the
# per-shape kernel compiles are warmed) must not — even when a gen-2
# GC pause or ambient suite contention stalls the dispatch thread for
# a few hundred ms, so keep ~100x headroom on the real side and ~4x
# on the injected side.
_OUTAGE_DEVICE_TIMEOUT_S = 0.5
_OUTAGE_HANG_MS = 2000

# Idle drain windows appended to outage rows: production does not stop
# polling after an outage, so the row gets the same courtesy — enough
# extra windows for the ladder to absorb one spurious re-demote (a
# wall-contention stall can make a warm ~ms dispatch overrun the
# watchdog and burn a shadow attempt) and still prove recovery.
_OUTAGE_DRAIN_WINDOWS = 8

# The scenario knob names AdmissionController/QuarantineRegistry treat
# as wall-time window counts vs per-reference-window rates, with the
# constructor defaults repeated here: wall-equivalence must scale the
# DEFAULTED values too, or a compensated sub-second run would make
# different per-window decisions than the reference run.
_ADMISSION_DEFAULTS = {
    "quota_samples": 0, "quota_pids": 0, "burst_windows": 3,
    "degrade_after": 2, "escalate_after": 3, "recover_windows": 3,
    "storm_new_pids": 0,
}
_ADMISSION_WINDOW_KNOBS = ("burst_windows", "degrade_after",
                           "escalate_after", "recover_windows")
_ADMISSION_RATE_KNOBS = ("quota_samples", "quota_pids", "storm_new_pids")
_QUARANTINE_DEFAULTS = {
    "quarantine_windows": 3, "max_quarantine_windows": 60,
    "probation_windows": 2, "healthy_after_windows": 6,
}


def _wall_equivalent(cfg: dict, window_s: float) -> tuple[dict, dict]:
    """Scale a scenario's reference-cadence knobs to wall-time-equivalent
    values at ``window_s``. Window-count knobs shrink by window_s/10 and
    rate knobs grow by 10/window_s, so the registries' own window_clock
    conversion restores the exact per-window numbers — the compensated
    run is the SAME run at a different tick rate, which is exactly what
    the cadence-invariance bar needs to hold. Per-event knobs
    (max_strikes, escalate trip counts) pass through untouched."""
    scale_w = window_s / REFERENCE_WINDOW_S
    adm = dict(_ADMISSION_DEFAULTS)
    adm.update(cfg.get("admission", {}))
    for k in _ADMISSION_WINDOW_KNOBS:
        adm[k] = adm[k] * scale_w
    for k in _ADMISSION_RATE_KNOBS:
        adm[k] = adm[k] / scale_w
    adm["window_s"] = window_s
    qua = dict(_QUARANTINE_DEFAULTS)
    qua.update(cfg.get("quarantine", {}))
    for k in _QUARANTINE_DEFAULTS:
        qua[k] = qua[k] * scale_w
    qua["window_s"] = window_s
    return adm, qua


class _FakeClock:
    """Deterministic seconds source for the outage rows' probe deadline:
    the runner advances it by window_s per iteration, so a hung probe
    overruns its deadline on the WINDOW clock even though zoo windows
    execute in microseconds of wall time."""

    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t


class _ZooSource:
    """Capture source over a scenario's window stream: applies each
    window's world mutations (procfs files, starttimes) BEFORE handing
    the snapshot over, exactly as the real world mutates under a poll."""

    def __init__(self, windows: list[ZooWindow], fs: FakeFS,
                 world: dict[int, int]):
        self._windows = windows
        self._fs = fs
        self._world = world
        self.current = -1

    def poll(self):
        i = self.current + 1
        if i >= len(self._windows):
            return None
        zw = self._windows[i]
        for path in sorted(zw.files):
            self._fs.put(path, zw.files[path])
        self._world.update(zw.starttimes)
        self.current = i
        return zw.snapshot


class _ZooWriter:
    """Profile sink recording (window, labels, pprof bytes) triples."""

    def __init__(self, source: _ZooSource):
        self._source = source
        self.shipped: list[tuple[int, dict, bytes]] = []

    def write(self, labels: dict, blob: bytes) -> None:
        # bytes() copy: the fast-encode arms ship views into the
        # encoder's reusable buffers, which later windows overwrite.
        self.shipped.append((self._source.current, dict(labels),
                             bytes(blob)))


class _RecordingAggregator:
    """Transparent DictAggregator proxy that keeps each window's
    pre-ladder profile objects for scoring (the profiler ships the same
    objects, so symbolization results are visible here too). Entries are
    also tagged with the window the call was DISPATCHED for: an
    abandoned (hung) device aggregate completes late, after the source
    advanced, and must not be misattributed to a later window."""

    def __init__(self, inner: DictAggregator, source: _ZooSource | None = None):
        self._inner = inner
        self._zoo_source = source
        self.windows: list[list] = []
        self.tagged: list[tuple[int, list]] = []

    def aggregate(self, snapshot):
        w = self._zoo_source.current if self._zoo_source is not None else -1
        profiles = self._inner.aggregate(snapshot)
        self.windows.append(list(profiles))
        self.tagged.append((w, list(profiles)))
        return profiles

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _RecordingDict(DictAggregator):
    """DictAggregator whose one-shot closes record per-window mass. The
    fast arms never materialize PidProfile objects, so this tap (plus
    the streaming feeder's) is where their mass-conservation bar reads
    from. A real subclass, not a proxy: the WindowEncoder reads
    aggregator internals directly."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.zoo_source: _ZooSource | None = None
        self.mass_by_window: dict[int, int] = {}

    def window_counts(self, snapshot, hashes=None):
        counts = super().window_counts(snapshot, hashes)
        w = self.zoo_source.current if self.zoo_source is not None else -1
        self.mass_by_window[w] = (self.mass_by_window.get(w, 0)
                                  + int(np.asarray(counts).sum()))
        return counts


class _ZooStreamFeeder:
    """Minimal streaming feeder for the zoo's streaming-carry arm: each
    polled snapshot is fed to the (carry-enabled) aggregator in
    drain-sized chunks — the carry cache and the coalesce fold see a
    multi-drain window, like the production tee does — and the window
    closes packed at take_window_if_complete. The chunked path is a
    chaos site (``zoo.path``): an injected fault is counted
    (path_fallbacks), the open window is discarded, and None hands the
    window to the profiler's one-shot close instead — same mass, never
    a lost window."""

    CHUNKS = 4

    def __init__(self, agg: DictAggregator, source: _ZooSource):
        self._agg = agg
        self._source = source
        self.mass_by_window: dict[int, int] = {}
        self.stats = {"windows_streamed": 0, "path_fallbacks": 0,
                      "last_window_feed_s": 0.0}

    def device_blocked(self) -> bool:
        return False

    def _chunk_bounds(self, pids) -> list[int]:
        """Drain boundaries that never split a pid's row run: per-pid
        location registration is batch-local (np.unique order inside
        each feed), so a pid fed across two drains would register its
        locations in a different order than the one-shot close and
        break the cross-arm byte-identity bar. Zoo snapshots group rows
        by pid; if a pid ever appeared in two runs the window degrades
        to a single drain rather than ship divergent bytes."""
        n = len(pids)
        edges = [i for i in range(1, n) if pids[i] != pids[i - 1]]
        if len(edges) + 1 != len(set(pids)):
            return [0, n]
        bounds = [0]
        for k in range(1, self.CHUNKS):
            target = round(k * n / self.CHUNKS)
            best = min(edges, key=lambda e: abs(e - target), default=None)
            if best is not None and best > bounds[-1]:
                bounds.append(best)
        bounds.append(n)
        return bounds

    def take_window_if_complete(self, snapshot):
        t0 = time.perf_counter()
        n = int(np.asarray(snapshot.counts).shape[0])
        if n == 0:
            return None  # nothing streamed: the one-shot close owns it
        try:
            faults.inject("zoo.path")
            bounds = self._chunk_bounds(
                np.asarray(snapshot.pids).tolist())
            for lo, hi in zip(bounds, bounds[1:]):
                self._agg.feed(snapshot, lo=lo, hi=hi)
            counts = self._agg.close_window(copy=True)
        except Exception:  # noqa: BLE001 - fail-open: one-shot close path
            self.stats["path_fallbacks"] += 1
            self._agg.discard_open_window()
            return None
        self.stats["windows_streamed"] += 1
        self.stats["last_window_feed_s"] = time.perf_counter() - t0
        w = self._source.current
        self.mass_by_window[w] = (self.mass_by_window.get(w, 0)
                                  + int(np.asarray(counts).sum()))
        return counts


@dataclasses.dataclass
class RunContext:
    """Everything a scenario's check() may inspect after the run."""

    profiles_by_window: list[list]
    shipped: list[tuple[int, dict, bytes]]
    truth: dict
    aggregator: DictAggregator
    identity: ProcessIdentityTracker
    admission: AdmissionController
    quarantine: QuarantineRegistry
    resolver: TenantResolver
    perf: PerfMapCache


def _digest(ctx: RunContext) -> str:
    """Canonical run digest: the seeded-determinism handle. Covers the
    scored substance (per-window profile tables + shipped bytes), never
    wall-clock measurements."""
    h = hashlib.sha256()
    for w, profs in enumerate(ctx.profiles_by_window):
        for p in sorted(profs, key=lambda p: p.pid):
            h.update(repr((
                w, p.pid, p.values.tolist(),
                p.stack_loc_ids[:, :8].tolist(), p.stack_depths.tolist(),
                p.loc_address.tolist(), p.loc_normalized.tolist(),
                p.loc_mapping_id.tolist(),
                [(m.id, m.path, m.start) for m in p.mappings],
                sorted(f[0] for f in p.functions),
            )).encode())
    for w, labels, blob in ctx.shipped:
        h.update(repr((w, sorted(labels.items()))).encode())
        h.update(hashlib.sha256(blob).digest())
    return h.hexdigest()


def _shipped_seq(shipped) -> list[tuple[str, str]]:
    """Cross-arm byte-identity handle: the ordered (pid, blob sha)
    sequence. Window indices are deliberately excluded — the encode
    pipeline ships asynchronously, so the writer's window tag can lag a
    ship, but FIFO ordering makes the sequence itself comparable."""
    return [(labels.get("pid", ""), hashlib.sha256(blob).hexdigest())
            for _w, labels, blob in shipped]


def run_scenario(scenario, seed: int, scale: float = 1.0,
                 hardened: bool | None = None, path: str = "scalar",
                 window_s: float = REFERENCE_WINDOW_S,
                 outage: str | None = None) -> dict:
    """One matrix row: build the scenario's windows, drive them through
    the real profiler loop on the requested close ``path`` at the
    requested cadence, and score against the bars. ``hardened`` None
    follows PARCA_NO_PID_GENERATION (the control-arm pin); ``outage``
    (scalar path only) injects a mid-run device fault and scores the
    health ladder's degrade/recover arc."""
    scn: Scenario = (SCENARIOS[scenario]()
                     if isinstance(scenario, str) else scenario)
    if hardened is None:
        hardened = os.environ.get("PARCA_NO_PID_GENERATION", "") != "1"
    if path not in PATHS:
        raise ValueError(f"unknown zoo path {path!r} (want one of {PATHS})")
    if outage is not None and outage not in OUTAGES:
        raise ValueError(f"unknown outage {outage!r} "
                         f"(want one of {OUTAGES})")
    if outage is not None and path != "scalar":
        raise ValueError("outage rows run the scalar close path (the "
                         "guarded device dispatch)")
    check_window_s(window_s)
    windows = scn.build(seed, scale)
    n_scenario_windows = len(windows)
    if outage is not None and windows:
        # Idle drains (see _OUTAGE_DRAIN_WINDOWS): one sample per window
        # from a pid no scenario uses, so mass stays live end to end
        # without colliding with any scenario pid's identity.
        drain_pid = 1 << 22
        maps = {drain_pid: [_mapping(0x400000, 0x500000, "/app/idle")]}
        last_ns = windows[-1].snapshot.time_ns
        for d in range(_OUTAGE_DRAIN_WINDOWS):
            snap = make_snapshot(
                [(drain_pid, 1, 1, [0x400010], [])], maps,
                last_ns + (d + 1) * WINDOW_NS)
            windows.append(ZooWindow(snap, starttimes={drain_pid: 1}))
    cfg = scn.config(scale)
    adm_kwargs, qua_kwargs = _wall_equivalent(cfg, window_s)

    fs = FakeFS()
    world: dict[int, int] = {}
    resolver = TenantResolver(fs=fs)
    admission = AdmissionController(resolver, **adm_kwargs)
    quarantine = QuarantineRegistry(**qua_kwargs)
    perf = PerfMapCache(fs=fs, churn_budget=int(cfg.get("churn_budget", 8)))
    ksym = None
    if cfg.get("kallsyms"):
        fs.put("/proc/kallsyms", cfg["kallsyms"])
        ksym = KsymCache(fs=fs)
    identity = ProcessIdentityTracker(
        starttime_of=world.__getitem__, enabled=hardened)
    # The same invalidator set cli.py registers: every bare-pid cache
    # drops the dead generation's state on a starttime mismatch.
    identity.add_invalidator("quarantine", quarantine.forget_pid)
    identity.add_invalidator("tenant", resolver.forget)
    identity.add_invalidator("perfmap", perf.evict)

    source = _ZooSource(windows, fs, world)
    writer = _ZooWriter(source)

    agg = None            # scalar arms: recording proxy over the dict
    fastagg = None        # fast arms: recording DictAggregator subclass
    feeder = None
    fb = None
    health = None
    fake_clock = None
    profiler_kwargs: dict = {}
    if path == "scalar":
        symbolizer = Symbolizer(ksym=ksym, perf=perf,
                                quarantine=quarantine, admission=admission)
        inner = DictAggregator(capacity=1 << 14)
        agg = _RecordingAggregator(inner, source=source)
        identity.add_invalidator("aggregator", inner.invalidate_pid)
        scale_w = window_s / REFERENCE_WINDOW_S
        if outage is not None:
            # The ladder under test: a CPU fallback dict plus a health
            # registry whose cooldowns are wall-equivalent one window.
            fb_inner = DictAggregator(capacity=1 << 14)
            fb = _RecordingAggregator(fb_inner, source=source)
            identity.add_invalidator("fallback-aggregator",
                                     fb_inner.invalidate_pid)
            if outage == "dispatch":
                # Cooldown of two wall-equivalent windows: the arc must
                # visibly pass through a planned fallback window before
                # the shadow gate, not demote-and-promote in one tick.
                health = DeviceHealthRegistry(
                    probe=None, promote_after=0,
                    cooldown_windows=2 * scale_w,
                    max_cooldown_windows=8 * scale_w,
                    start_state=STATE_HEALTHY, window_s=window_s)
            else:  # probe: bring-up hangs, deadline trips on the window
                #        clock, the re-probe succeeds, shadow promotes.
                fake_clock = _FakeClock()
                health = DeviceHealthRegistry(
                    probe=lambda: (True, "ok"), probe_timeout_s=5.0,
                    probe_deadline_s=0.05, promote_after=1,
                    cooldown_windows=1 * scale_w,
                    max_cooldown_windows=4 * scale_w,
                    start_state=STATE_PROBING, clock=fake_clock,
                    window_s=window_s)
            profiler_kwargs = {
                "fallback_aggregator": fb,
                "device_health": health,
                "device_timeout_s": _OUTAGE_DEVICE_TIMEOUT_S,
            }
        profiler = CPUProfiler(
            source, agg, symbolizer=symbolizer, profile_writer=writer,
            quarantine=quarantine, admission=admission, identity=identity,
            **profiler_kwargs)
    else:
        # Fast arms ship unsymbolized (the fast-encode contract); the
        # streaming arm additionally exercises the carry cache across
        # chunked drains. No fallback: an arm that cannot close its
        # window on its own path has failed the row.
        fastagg = _RecordingDict(capacity=1 << 14,
                                 carry=(path == "streaming"))
        fastagg.zoo_source = source
        identity.add_invalidator("aggregator", fastagg.invalidate_pid)
        if path == "streaming":
            feeder = _ZooStreamFeeder(fastagg, source)
        profiler = CPUProfiler(
            source, fastagg, profile_writer=writer,
            quarantine=quarantine, admission=admission, identity=identity,
            fast_encode=True, streaming_feeder=feeder,
            encode_pipeline=(path == "pipeline"))

    if outage is not None and windows:
        # Outage rows run every device window under a tight watchdog
        # (_OUTAGE_DEVICE_TIMEOUT_S): warm every window shape's kernel
        # compile on a throwaway dict first — the jit cache is keyed
        # per snapshot shape AND per dict capacity, so a cold process
        # would read a mid-arc compile (0.3-0.5 s) as an unplanned
        # hang and burn the recovery arc's shadow window on it.
        warm = DictAggregator(capacity=1 << 14)
        for w in windows:
            warm.window_counts(w.snapshot)

    hang_at = (max(1, n_scenario_windows // 2)
               if outage == "dispatch" else None)
    prior_injector = faults.get()
    close_lat: list[float] = []
    t0 = time.perf_counter()
    try:
        if outage == "probe":
            faults.install(faults.FaultInjector.from_spec(
                f"device.probe:hang:ms={_OUTAGE_HANG_MS},count=1",
                seed=seed))
        if health is not None:
            health.start()
        it = 0
        while True:
            if hang_at is not None and it == hang_at:
                faults.install(faults.FaultInjector.from_spec(
                    f"device.dispatch:hang:ms={_OUTAGE_HANG_MS},count=1",
                    seed=seed))
            if fake_clock is not None:
                fake_clock.t += window_s
            if not profiler.run_iteration():
                break
            close_lat.append(profiler.metrics.last_aggregate_duration_s)
            if hang_at is not None and it == hang_at:
                faults.install(prior_injector)
            if outage is not None:
                # Windows run back-to-back here, but production gets a
                # full window of wall time between polls for an
                # abandoned dispatch to land. Grant the same, or the
                # inflight gate forces every remaining window to the
                # fallback and a pending shadow starves forever.
                done = getattr(profiler, "_device_inflight", None)
                if done is not None:
                    done.wait(2 * _OUTAGE_HANG_MS / 1000.0)
            if outage == "probe":
                # A launched re-probe delivers on its own thread; bound
                # the race so the promotion arc lands on schedule.
                deadline = time.monotonic() + 2.0
                while (health._probe_started_at is not None
                       and time.monotonic() < deadline):
                    time.sleep(0.001)
            it += 1
        wall_s = time.perf_counter() - t0
    finally:
        faults.install(prior_injector)
        if profiler._pipeline is not None:
            # The manual loop bypasses run()'s teardown: drain the
            # encode worker so every closed window is shipped.
            profiler._pipeline.close()

    # -- assemble the scored substance per path -----------------------------
    if path == "scalar" and outage is not None:
        # Merge device and fallback recorders per window, preferring the
        # fallback entry: on hang and shadow windows the CPU result is
        # what shipped, and the abandoned device aggregate may complete
        # late (its entry is tagged with the window it was dispatched
        # for, not the window it finished in).
        by_w: dict[int, list] = {}
        for w, profs in agg.tagged:
            by_w.setdefault(w, profs)
        for w, profs in fb.tagged:
            by_w[w] = profs
        profiles_by_window = [by_w.get(i, []) for i in range(len(windows))]
        windows_closed = len(by_w)
        mass_by_window = [sum(int(p.total()) for p in profs)
                          for profs in profiles_by_window]
    elif path == "scalar":
        profiles_by_window = agg.windows
        windows_closed = len(agg.windows)
        mass_by_window = [sum(int(p.total()) for p in profs)
                          for profs in profiles_by_window]
    else:
        profiles_by_window = []
        masses = dict(fastagg.mass_by_window)
        if feeder is not None:
            for w, m in feeder.mass_by_window.items():
                masses[w] = masses.get(w, 0) + m
        windows_closed = len(masses)
        mass_by_window = [masses.get(i, 0) for i in range(len(windows))]

    ctx = RunContext(
        profiles_by_window=profiles_by_window, shipped=writer.shipped,
        truth=scn.truth,
        aggregator=(agg._inner if agg is not None else fastagg),
        identity=identity, admission=admission, quarantine=quarantine,
        resolver=resolver, perf=perf)

    samples_fed = int(sum(int(zw.snapshot.counts.sum()) for zw in windows))
    samples_shipped = int(sum(mass_by_window))
    ceiling = float(cfg.get("close_latency_ceiling_s",
                            DEFAULT_CLOSE_CEILING_S))
    outcome = {
        "scenario": scn.name,
        "axis": scn.axis,
        "description": scn.description,
        "seed": int(seed),
        "scale": float(scale),
        "hardened": bool(hardened),
        "path": path,
        "window_s": float(window_s),
        "outage": outage,
        "windows": len(windows),
        "degraded_builds": int(scn.truth.get("degraded_builds", 0)),
        "windows_lost": int(profiler.metrics.errors_total),
        "windows_closed": windows_closed,
        "profiles_written": int(profiler.metrics.profiles_written),
        "samples_fed": samples_fed,
        "samples_shipped": samples_shipped,
        "mass_by_window": mass_by_window,
        "shipped_seq": _shipped_seq(writer.shipped),
        "close_latency_max_s": max(close_lat, default=0.0),
        "close_latency_ceiling_s": ceiling,
        "wall_s": wall_s,
        "identity": identity.metrics(),
        "admission": dict(admission.stats),
        "quarantine": dict(quarantine.stats),
        "perfmap": dict(perf.stats),
        "tenant_resolver": dict(resolver.stats),
    }
    if feeder is not None:
        outcome["streaming"] = dict(feeder.stats)
    if health is not None:
        outcome["device_health"] = dict(health.stats)
        outcome["device_state"] = health.state
    bars = {
        "windows_lost_zero": outcome["windows_lost"] == 0,
        "every_window_closed": outcome["windows_closed"] == len(windows),
        "mass_conserved": samples_shipped == samples_fed,
        "close_latency_ceiling":
            outcome["close_latency_max_s"] <= ceiling,
    }
    if path == "scalar" and outage is None:
        # Scenario-specific truths inspect scalar profile objects and
        # assume no mid-run backend churn; path/outage rows are scored
        # on the base + axis bars above/below instead.
        bars.update(scn.check(outcome, ctx))
    if health is not None:
        hung = (health.stats["hangs_total"] if outage == "dispatch"
                else health.stats["probes_hung"])
        bars["outage_injected"] = hung >= 1
        bars["outage_demoted"] = health.stats["demotions_total"] >= 1 \
            and health.stats["fallback_windows_total"] >= 1
        bars["outage_recovered"] = health.state == STATE_HEALTHY \
            and health.stats["promotions_total"] >= 1
    outcome["bars"] = bars
    outcome["passed"] = all(bars.values())
    outcome["digest"] = _digest(ctx)
    return outcome


def run_zoo(seed: int, scale: float = 1.0, names=None,
            hardened: bool | None = None) -> dict:
    """The scalar matrix sweep: a deterministic schedule of scenario
    rows, each scored through the real window loop."""
    schedule = build_schedule(seed, names)
    rows = [run_scenario(e["scenario"], e["seed"], scale=scale,
                         hardened=hardened) for e in schedule]
    return {
        "seed": int(seed),
        "scale": float(scale),
        "schedule": schedule,
        "rows": rows,
        "scenarios_passed": sum(r["passed"] for r in rows),
        "scenarios_total": len(rows),
        "passed": bool(rows) and all(r["passed"] for r in rows),
    }


def run_matrix(seed: int, scale: float = 1.0, names=None,
               cadences=CADENCES, outages=OUTAGES) -> dict:
    """The full endurance matrix: every scheduled scenario runs as a
    three-arm row (scalar / pipeline / streaming-carry) at every
    cadence, plus the device-outage cross-product, with the cross-arm
    bars (pprof byte identity between the fast arms, per-window mass
    identity across all three, scalar digest identity across cadences)
    scored per scenario."""
    schedule = build_schedule(seed, names)
    rows: list[dict] = []
    cross: list[dict] = []
    for e in schedule:
        per_arm: dict[tuple[str, float], dict] = {}
        for w in cadences:
            for path in PATHS:
                row = run_scenario(e["scenario"], e["seed"], scale=scale,
                                   path=path, window_s=w)
                per_arm[(path, w)] = row
                rows.append(row)
        for mode in outages:
            for w in cadences:
                rows.append(run_scenario(e["scenario"], e["seed"],
                                         scale=scale, path="scalar",
                                         window_s=w, outage=mode))
        scalar_digests = {w: per_arm[("scalar", w)]["digest"]
                          for w in cadences}
        bars = {}
        for w in cadences:
            sc = per_arm[("scalar", w)]
            pi = per_arm[("pipeline", w)]
            st = per_arm[("streaming", w)]
            bars[f"path_bytes_identical@{w:g}s"] = \
                bool(pi["shipped_seq"]) \
                and pi["shipped_seq"] == st["shipped_seq"]
            bars[f"path_mass_identical@{w:g}s"] = (
                sc["mass_by_window"] == pi["mass_by_window"]
                == st["mass_by_window"])
        bars["cadence_digest_identical"] = \
            len(set(scalar_digests.values())) == 1
        cross.append({
            "scenario": e["scenario"], "seed": e["seed"], "bars": bars,
            "scalar_digests": {f"{w:g}": d
                               for w, d in scalar_digests.items()},
            "passed": all(bars.values()),
        })
    passed = (bool(rows) and all(r["passed"] for r in rows)
              and all(c["passed"] for c in cross))
    return {
        "seed": int(seed),
        "scale": float(scale),
        "paths": list(PATHS),
        "cadences": [float(w) for w in cadences],
        "outages": list(outages),
        "schedule": schedule,
        "rows": rows,
        "cross": cross,
        "rows_passed": sum(r["passed"] for r in rows),
        "rows_total": len(rows),
        "passed": passed,
    }
