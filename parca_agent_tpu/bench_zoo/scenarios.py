"""Workload zoo scenarios: seeded, deterministic hostile-world generators.

Each scenario is one axis of the breadth matrix ROADMAP.md's robustness
arc calls for — pid reuse under tenant migration, JIT perf-map churn,
fork/exec storms, deep native stacks, kernel-heavy mixes, multi-tenant
bursts. A scenario compiles, from a seed, to a list of ``ZooWindow``s:
per-window :class:`WindowSnapshot` inputs plus the WORLD mutations
(procfs files, starttimes) that must land before the window is polled.
The runner (bench_zoo/runner.py) drives those windows through the REAL
profiler window loop — ``CPUProfiler.run_iteration`` with a live
DictAggregator, Symbolizer, quarantine, admission, and identity tracker
— and scores each scenario against its bars.

Determinism contract: everything a scenario emits derives from
``np.random.default_rng(seed)`` and fixed constants; the same (seed,
scale) always yields the same window stream, and the runner's digest of
the shipped output is the regression handle (tests/test_zoo.py pins it).

Window *builds* are fail-open against the injected ``zoo.scenario``
fault: a window whose build raises degrades to an idle filler window —
the run narrows, it never dies (tests/test_zoo.py's chaos drill pins
this, same contract as every other ingest site).
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import numpy as np

from parca_agent_tpu.capture.formats import (
    KERNEL_ADDR_START, MAX_STACK_DEPTH, STACK_SLOTS, WindowSnapshot)
from parca_agent_tpu.process.maps import ProcMapping, build_mapping_table
from parca_agent_tpu.utils import faults

# Fixed epoch for window timestamps: wall-clock must never leak into a
# seeded run (byte-identity bars compare shipped pprof blobs).
T0_NS = 1_750_000_000_000_000_000
WINDOW_NS = 10_000_000_000


@dataclasses.dataclass
class ZooWindow:
    """One window of scenario input: the snapshot the source hands the
    profiler, plus the world state that must exist when it does."""

    snapshot: WindowSnapshot
    files: dict[str, bytes] = dataclasses.field(default_factory=dict)
    starttimes: dict[int, int] = dataclasses.field(default_factory=dict)
    degraded: bool = False   # build failed open to an idle filler


def _mapping(start: int, end: int, path: str,
             offset: int = 0) -> ProcMapping:
    return ProcMapping(start=start, end=end, perms="r-xp", offset=offset,
                       dev="08:01", inode=1, path=path)


def make_snapshot(rows, per_pid_maps, time_ns: int) -> WindowSnapshot:
    """rows: [(pid, tid, count, user_addrs, kernel_addrs)] ->
    WindowSnapshot, with the mapping table folded from per_pid_maps
    exactly the way the live capture path folds /proc/<pid>/maps."""
    n = len(rows)
    pids = np.zeros(n, np.int32)
    tids = np.zeros(n, np.int32)
    counts = np.zeros(n, np.int64)
    ulen = np.zeros(n, np.int32)
    klen = np.zeros(n, np.int32)
    stacks = np.zeros((n, STACK_SLOTS), np.uint64)
    for i, (pid, tid, count, user, kernel) in enumerate(rows):
        pids[i], tids[i], counts[i] = pid, tid, count
        ulen[i], klen[i] = len(user), len(kernel)
        frames = list(user) + list(kernel)
        stacks[i, :len(frames)] = np.asarray(frames, np.uint64)
    table = build_mapping_table(per_pid_maps)
    return WindowSnapshot(pids, tids, counts, ulen, klen, stacks, table,
                          time_ns=time_ns)


def _cgroup_pod(uid: str) -> bytes:
    return f"0::/kubepods/burstable/pod{uid}/zoo\n".encode()


def _cgroup_svc(unit: str) -> bytes:
    return f"0::/system.slice/{unit}.service\n".encode()


def _status(pid: int) -> bytes:
    return f"Name:\tzoo\nNSpid:\t{pid}\n".encode()


class Scenario:
    """One matrix row. Subclasses define the axis, the per-window world,
    and the bars; ``build`` owns the shared fail-open/seeding frame."""

    name = ""
    axis = ""
    description = ""

    def __init__(self):
        self.truth: dict = {}

    # -- knobs the runner wires into the real components ---------------------
    def windows(self, scale: float) -> int:
        return 8

    def config(self, scale: float) -> dict:
        return {}

    # -- window stream -------------------------------------------------------
    def build(self, seed: int, scale: float) -> list[ZooWindow]:
        rng = np.random.default_rng(int(seed))
        self.truth = {}
        self._prepare(rng, scale)
        out: list[ZooWindow] = []
        for w in range(self.windows(scale)):
            try:
                faults.inject("zoo.scenario")
                out.append(self._window(w, rng, scale))
            except Exception:  # noqa: BLE001 - counted, fail-open
                # A failed window build (injected fault or scenario bug)
                # degrades to an idle filler: the matrix row narrows, the
                # run and every later window survive.
                out.append(self._idle(w))
        self.truth["degraded_builds"] = sum(zw.degraded for zw in out)
        return out

    def _idle(self, w: int) -> ZooWindow:
        maps = {1: [_mapping(0x400000, 0x500000, "/app/idle")]}
        snap = make_snapshot([(1, 1, 1, [0x400010], [])], maps,
                             T0_NS + w * WINDOW_NS)
        return ZooWindow(snap, starttimes={1: 1}, degraded=True)

    def _prepare(self, rng, scale: float) -> None:
        raise NotImplementedError

    def _window(self, w: int, rng, scale: float) -> ZooWindow:
        raise NotImplementedError

    # -- scoring -------------------------------------------------------------
    def check(self, outcome: dict, ctx) -> dict:
        """Scenario-specific bars: {bar_name: bool}. May annotate
        ``outcome`` with measured evidence (the runner keeps it)."""
        return {}


def _paths_by_mapping(prof) -> dict[int, str]:
    return {m.id: m.path for m in prof.mappings}


def _stack_mass_by_path(prof) -> dict[str, int]:
    """Window mass per mapping path, attributing each deduped stack by
    its leaf frame's mapping (the frame a flamegraph pins the sample to)."""
    out: dict[str, int] = {}
    paths = _paths_by_mapping(prof)
    for s in range(prof.n_samples):
        depth = int(prof.stack_depths[s])
        if depth <= 0:
            continue
        leaf_loc = int(prof.stack_loc_ids[s, 0])  # leaf-first frame order
        mid = int(prof.loc_mapping_id[leaf_loc - 1])
        path = paths.get(mid, "")
        out[path] = out.get(path, 0) + int(prof.values[s])
    return out


class PidReuseScenario(Scenario):
    """Pid reuse under tenant migration: tenant A's pods exit, the kernel
    recycles their pids for tenant B's pods, and the NEW binary occupies
    the SAME virtual addresses. Every bare-pid cache in the agent now
    holds a dead process's state; without generation stamping the
    aggregator's per-pid registry attributes tenant B's samples to
    tenant A's binary (the cross-process attribution this PR hardens
    away — ``PARCA_NO_PID_GENERATION=1`` pins the old behaviour for the
    control arm)."""

    name = "pid_reuse"
    axis = "identity"
    description = ("pid recycling across tenant migration; bars: reuse "
                   "detected, zero cross-process sample attribution")

    OLD_PATH = "/app/alpha"
    NEW_PATH = "/app/beta"
    REUSE_W = 3

    def config(self, scale: float) -> dict:
        return {"admission": {"quota_samples": 0}}

    def _prepare(self, rng, scale: float) -> None:
        n = max(2, round(6 * scale))
        self._reused = [1200 + i for i in range(n)]
        self._bystanders = [1900, 1901]
        # Gen A's stack shapes, reused VERBATIM by gen B: identical
        # addresses are what make the stale registry hit silent.
        self._addrs = {
            pid: [0x400000 + np.sort(rng.integers(
                0, 0x200000 // 16, size=int(d))).astype(np.uint64) * 16
                for d in rng.integers(4, 9, size=3)]
            for pid in self._reused}
        self._by_addrs = {
            pid: [0x700000 + np.arange(5, dtype=np.uint64) * 64]
            for pid in self._bystanders}
        self.truth.update({
            "reused_pids": list(self._reused),
            "reuse_window": self.REUSE_W,
            "old_path": self.OLD_PATH,
            "new_path": self.NEW_PATH,
        })

    def _window(self, w: int, rng, scale: float) -> ZooWindow:
        files: dict[str, bytes] = {}
        starttimes: dict[int, int] = {}
        span = (0x400000, 0x600000)
        if w == 0:
            for pid in self._reused:
                files[f"/proc/{pid}/cgroup"] = _cgroup_pod("aaaaaaaa-1111")
                starttimes[pid] = 1000 + pid
            for pid in self._bystanders:
                files[f"/proc/{pid}/cgroup"] = _cgroup_svc("zoo-bystander")
                starttimes[pid] = 1000 + pid
        if w == self.REUSE_W:
            # The migration instant: same pids, new starttime, new
            # binary at the same addresses, new tenant cgroup.
            for pid in self._reused:
                files[f"/proc/{pid}/cgroup"] = _cgroup_pod("bbbbbbbb-2222")
                starttimes[pid] = 500000 + pid
        path = self.OLD_PATH if w < self.REUSE_W else self.NEW_PATH
        maps = {pid: [_mapping(span[0], span[1], path)]
                for pid in self._reused}
        maps.update({pid: [_mapping(0x700000, 0x800000, "/app/bystander")]
                     for pid in self._bystanders})
        rows = []
        for pid in self._reused:
            for addrs in self._addrs[pid]:
                rows.append((pid, pid, int(rng.integers(40, 120)),
                             addrs, []))
        for pid in self._bystanders:
            rows.append((pid, pid, int(rng.integers(40, 120)),
                         self._by_addrs[pid][0], []))
        return ZooWindow(make_snapshot(rows, maps, T0_NS + w * WINDOW_NS),
                         files=files, starttimes=starttimes)

    def check(self, outcome: dict, ctx) -> dict:
        mis = 0
        new_mass = 0
        reused = set(self.truth["reused_pids"])
        for w, profs in enumerate(ctx.profiles_by_window):
            if w < self.REUSE_W:
                continue
            for p in profs:
                if p.pid not in reused:
                    continue
                by_path = _stack_mass_by_path(p)
                mis += by_path.get(self.OLD_PATH, 0)
                new_mass += by_path.get(self.NEW_PATH, 0)
        outcome["misattributed_mass"] = mis
        outcome["post_reuse_mass_new_binary"] = new_mass
        detected = outcome["identity"].get("reuse_detected_total", 0)
        if outcome["hardened"]:
            return {
                "reuse_detected": detected >= len(reused),
                "zero_cross_process_attribution": mis == 0
                    and new_mass > 0,
            }
        # Control arm: the un-stamped agent MUST reproduce the bug, or
        # the hardened arm's zero proves nothing.
        return {
            "misattribution_reproduced": mis > 0,
            "reuse_undetected": detected == 0,
        }


class JitChurnScenario(Scenario):
    """JIT perf-map churn: healthy JITs append and settle; a runaway (or
    adversarial) runtime rewrites its map with new content on every
    read. Bars: legit updates re-parse and resolve, the abuser trips the
    churn budget and lands in quarantine, and neither costs a window."""

    name = "jit_churn"
    axis = "jit"
    description = ("perf-map reparse on change + churn-abuse budget; "
                   "bars: jit names resolve, abuser quarantined")

    ABUSER = 3999
    UPDATE_W = 4

    def config(self, scale: float) -> dict:
        return {"churn_budget": 3,
                "quarantine": {"max_strikes": 1, "quarantine_windows": 3}}

    def _prepare(self, rng, scale: float) -> None:
        self._stable = [3100 + i for i in range(max(2, round(3 * scale)))]
        self._jit_addrs = {
            pid: (0x7F00_0000_0000 + np.uint64(pid) * np.uint64(0x10000)
                  + np.arange(8, dtype=np.uint64) * np.uint64(0x40))
            for pid in self._stable + [self.ABUSER]}
        self.truth.update({"stable_pids": list(self._stable),
                           "abuser": self.ABUSER,
                           "hot_pid": self._stable[0]})

    def _perf_map(self, pid: int, version: int, extra: bool) -> bytes:
        tag = f"v{version}_" if version else ""
        lines = [f"{int(a):x} 40 jit_{tag}{pid}_fn{k}"
                 for k, a in enumerate(self._jit_addrs[pid])]
        if extra:
            hot = int(self._jit_addrs[pid][-1]) + 0x40
            lines.append(f"{hot:x} 40 jit_{pid}_hot")
        return ("\n".join(lines) + "\n").encode()

    def _window(self, w: int, rng, scale: float) -> ZooWindow:
        files: dict[str, bytes] = {}
        starttimes: dict[int, int] = {}
        all_pids = self._stable + [self.ABUSER]
        if w == 0:
            for pid in all_pids:
                files[f"/proc/{pid}/status"] = _status(pid)
                files[f"/proc/{pid}/cgroup"] = _cgroup_svc("zoo-jit")
                starttimes[pid] = 2000 + pid
            for pid in self._stable:
                files[f"/proc/{pid}/root/tmp/perf-{pid}.map"] = \
                    self._perf_map(pid, 0, extra=False)
        hot = self.truth["hot_pid"]
        if w == self.UPDATE_W:
            # The one LEGIT mid-run update: the JIT compiled a new hot
            # function and appended it — must re-parse and resolve.
            files[f"/proc/{hot}/root/tmp/perf-{hot}.map"] = \
                self._perf_map(hot, 0, extra=True)
        # The abuser rewrites with fresh content every single window.
        files[f"/proc/{self.ABUSER}/root/tmp/perf-{self.ABUSER}.map"] = \
            self._perf_map(self.ABUSER, w + 1, extra=False)
        maps = {pid: [_mapping(0x400000, 0x500000, "/app/jithost")]
                for pid in all_pids}
        rows = []
        for pid in all_pids:
            jit = self._jit_addrs[pid]
            picks = rng.integers(0, len(jit), size=2)
            for j in picks:
                rows.append((pid, pid, int(rng.integers(30, 90)),
                             [jit[int(j)], np.uint64(0x400040)], []))
        if w >= self.UPDATE_W:
            hot_addr = np.uint64(int(self._jit_addrs[hot][-1]) + 0x40)
            rows.append((hot, hot, int(rng.integers(30, 90)),
                         [hot_addr, np.uint64(0x400040)], []))
        return ZooWindow(make_snapshot(rows, maps, T0_NS + w * WINDOW_NS),
                         files=files, starttimes=starttimes)

    def check(self, outcome: dict, ctx) -> dict:
        names: set[str] = set()
        for profs in ctx.profiles_by_window:
            for p in profs:
                names.update(f[0] for f in p.functions)
        hot = self.truth["hot_pid"]
        pm = outcome["perfmap"]
        return {
            "jit_names_resolved": any(
                n.startswith(f"jit_{pid}_fn")
                for pid in self.truth["stable_pids"] for n in names),
            "legit_update_resolved": f"jit_{hot}_hot" in names,
            "reparse_counted": pm.get("reparse_total", 0) >= 1,
            "churn_budget_tripped": pm.get("churn_trips_total", 0) >= 1,
            "abuser_contained":
                outcome["quarantine"].get("trips_total", 0) >= 1,
        }


class ForkStormScenario(Scenario):
    """Fork/exec storm + container churn: one window introduces a burst
    of never-seen pids (a CI fan-out, a crash-looping deployment) whose
    discovery cost — maps parses, registry inserts, tenant resolution on
    dead-by-read cgroups — lands before any quota sees a sample. The
    admission controller's storm detector must shed via the existing
    governor ladder; the windows themselves must all ship."""

    name = "fork_storm"
    axis = "churn"
    description = ("new-pid burst sheds via admission ladder; bars: "
                   "storm detected, shed fired, no window lost")

    STORM_W = 2

    def windows(self, scale: float) -> int:
        return 6

    def config(self, scale: float) -> dict:
        return {"admission": {"quota_samples": 0, "storm_new_pids": 24}}

    def _prepare(self, rng, scale: float) -> None:
        self._base = [4100 + i for i in range(8)]
        self._storm = [5000 + i for i in range(max(40, round(160 * scale)))]
        self.truth.update({"storm_window": self.STORM_W,
                           "storm_size": len(self._storm)})

    def _window(self, w: int, rng, scale: float) -> ZooWindow:
        files: dict[str, bytes] = {}
        starttimes: dict[int, int] = {}
        if w == 0:
            for pid in self._base:
                files[f"/proc/{pid}/cgroup"] = _cgroup_svc("zoo-base")
                starttimes[pid] = 3000 + pid
        maps = {pid: [_mapping(0x400000, 0x500000, "/app/base")]
                for pid in self._base}
        rows = [(pid, pid, int(rng.integers(50, 150)),
                 0x400000 + np.arange(4, dtype=np.uint64) * 256, [])
                for pid in self._base]
        if w == self.STORM_W:
            for pid in self._storm:
                # Storm pids have no cgroup file — exec'd and gone before
                # the resolver reads; they join the unknown tenant.
                starttimes[pid] = 3500 + pid
                maps[pid] = [_mapping(0x400000, 0x480000, "/app/storm")]
                rows.append((pid, pid, int(rng.integers(1, 4)),
                             [np.uint64(0x400100 + 16 * (pid % 64))], []))
        return ZooWindow(make_snapshot(rows, maps, T0_NS + w * WINDOW_NS),
                         files=files, starttimes=starttimes)

    def check(self, outcome: dict, ctx) -> dict:
        adm = outcome["admission"]
        return {
            "storm_detected": adm.get("fork_storm_windows_total", 0) >= 1,
            "storm_shed_fired": adm.get("fork_storm_sheds_total", 0) >= 1,
            "shed_step_taken": adm.get("shed_steps_total", 0) >= 1,
        }


class DeepStacksScenario(Scenario):
    """Deep native/DWARF stacks at the 127-frame capture cap, with every
    window byte-for-byte identical input. Bars: full depth survives to
    the shipped profile, and identical input windows ship identical
    pprof bytes (the registry reuse across windows must be invisible)."""

    name = "deep_stacks"
    axis = "depth"
    description = ("MAX_STACK_DEPTH stacks, identical windows; bars: "
                   "full depth shipped, pprof byte identity")

    def windows(self, scale: float) -> int:
        return 6

    def _prepare(self, rng, scale: float) -> None:
        self._pids = [6100 + i for i in range(4)]
        self._deep = {
            pid: 0x400000 + (np.uint64(pid - 6100) * np.uint64(0x100000)
                 + np.arange(MAX_STACK_DEPTH, dtype=np.uint64)
                 * np.uint64(16))
            for pid in self._pids}
        self._counts = {pid: int(rng.integers(80, 200))
                        for pid in self._pids}
        self.truth["max_depth"] = MAX_STACK_DEPTH

    def _window(self, w: int, rng, scale: float) -> ZooWindow:
        starttimes = {pid: 4000 + pid for pid in self._pids} if w == 0 \
            else {}
        maps = {pid: [_mapping(0x400000, 0x1400000, "/app/deep")]
                for pid in self._pids}
        rows = [(pid, pid, self._counts[pid], self._deep[pid], [])
                for pid in self._pids]
        # time_ns is deliberately CONSTANT: the byte-identity bar
        # compares whole shipped pprof blobs across windows.
        return ZooWindow(make_snapshot(rows, maps, T0_NS),
                         starttimes=starttimes)

    def check(self, outcome: dict, ctx) -> dict:
        import hashlib

        max_depth = 0
        for profs in ctx.profiles_by_window:
            for p in profs:
                if p.n_samples:
                    max_depth = max(max_depth, int(p.stack_depths.max()))
        per_pid: dict[str, set[str]] = {}
        for _w, labels, blob in ctx.shipped:
            per_pid.setdefault(labels.get("pid", "?"), set()).add(
                hashlib.sha256(blob).hexdigest())
        outcome["max_depth_shipped"] = max_depth
        return {
            "full_depth_shipped": max_depth == MAX_STACK_DEPTH,
            "pprof_byte_identity": bool(per_pid)
                and all(len(v) == 1 for v in per_pid.values()),
            "every_window_shipped":
                len(ctx.shipped) == len(self._pids) * self.windows(1.0),
        }


class KernelHeavyScenario(Scenario):
    """Kernel-heavy mix: most of the window's mass carries kernel tails
    (soft-irq storms, syscall-bound services). Kernel frames must stay
    un-normalized, resolve through kallsyms, and conserve mass."""

    name = "kernel_heavy"
    axis = "kernel"
    description = ("kernel-tail-dominated windows; bars: kernel mass "
                   "exact, kallsyms names resolve")

    _SYMS = ["zoo_sys_read", "zoo_sys_write", "zoo_do_softirq",
             "zoo_tcp_rcv", "zoo_page_fault", "zoo_schedule"]

    def windows(self, scale: float) -> int:
        return 6

    def config(self, scale: float) -> dict:
        lines = [f"{int(KERNEL_ADDR_START) + (k + 1) * 0x1000:x} T {n}"
                 for k, n in enumerate(self._SYMS)]
        return {"kallsyms": ("\n".join(lines) + "\n").encode()}

    def _prepare(self, rng, scale: float) -> None:
        self._pids = [7100 + i for i in range(6)]
        self.truth["kernel_mass"] = 0

    def _window(self, w: int, rng, scale: float) -> ZooWindow:
        starttimes = {pid: 5000 + pid for pid in self._pids} if w == 0 \
            else {}
        maps = {pid: [_mapping(0x400000, 0x500000, "/app/kern")]
                for pid in self._pids}
        rows = []
        for pid in self._pids:
            user = 0x400000 + np.arange(3, dtype=np.uint64) * 128
            for s in range(3):
                count = int(rng.integers(40, 100))
                if s < 2:  # two of three stacks carry a kernel tail
                    k = int(rng.integers(0, len(self._SYMS)))
                    kern = [np.uint64(int(KERNEL_ADDR_START)
                                      + (k + 1) * 0x1000 + 8)]
                    self.truth["kernel_mass"] += count
                else:
                    kern = []
                rows.append((pid, pid + s, count, user, kern))
        return ZooWindow(make_snapshot(rows, maps, T0_NS + w * WINDOW_NS),
                         starttimes=starttimes)

    def check(self, outcome: dict, ctx) -> dict:
        kernel_mass = 0
        names: set[str] = set()
        for profs in ctx.profiles_by_window:
            for p in profs:
                names.update(f[0] for f in p.functions)
                kern_locs = set(
                    (np.flatnonzero(p.loc_is_kernel) + 1).tolist())
                for s in range(p.n_samples):
                    d = int(p.stack_depths[s])
                    ids = set(p.stack_loc_ids[s, :d].tolist())
                    if ids & kern_locs:
                        kernel_mass += int(p.values[s])
        outcome["kernel_mass_shipped"] = kernel_mass
        return {
            "kernel_mass_exact":
                kernel_mass == self.truth["kernel_mass"],
            "kallsyms_resolved": any(n.startswith("zoo_") for n in names),
        }


class TenantBurstScenario(Scenario):
    """Multi-tenant burst: one tenant sustains 4x its sample quota while
    two stay in budget. The ladder must degrade ONLY the burster — and
    degrade fidelity, never samples (mass conservation is a base bar)."""

    name = "tenant_burst"
    axis = "tenancy"
    description = ("one tenant 4x over quota; bars: burster degraded, "
                   "neighbors untouched, zero sample loss")

    BURST_W = 2

    def config(self, scale: float) -> dict:
        return {"admission": {"quota_samples": 3000, "burst_windows": 1,
                              "degrade_after": 2, "recover_windows": 6}}

    def _prepare(self, rng, scale: float) -> None:
        self._tenants = {
            "a": [9100 + i for i in range(3)],
            "b": [9200 + i for i in range(3)],
            "c": [9300 + i for i in range(3)],   # the burster
        }
        self.truth["burster"] = "c"

    def _window(self, w: int, rng, scale: float) -> ZooWindow:
        files: dict[str, bytes] = {}
        starttimes: dict[int, int] = {}
        if w == 0:
            uids = {"a": "aaaa0000-0001", "b": "bbbb0000-0002",
                    "c": "cccc0000-0003"}
            for t, pids in self._tenants.items():
                for pid in pids:
                    files[f"/proc/{pid}/cgroup"] = _cgroup_pod(uids[t])
                    starttimes[pid] = 6000 + pid
        maps = {pid: [_mapping(0x400000, 0x500000, f"/app/tenant_{t}")]
                for t, pids in self._tenants.items() for pid in pids}
        rows = []
        for t, pids in self._tenants.items():
            burst = t == "c" and w >= self.BURST_W
            per_pid = 4000 if burst else 300
            for pid in pids:
                rows.append((pid, pid,
                             per_pid + int(rng.integers(0, 50)),
                             0x400000 + np.arange(5, dtype=np.uint64) * 64,
                             []))
        return ZooWindow(make_snapshot(rows, maps, T0_NS + w * WINDOW_NS),
                         files=files, starttimes=starttimes)

    def check(self, outcome: dict, ctx) -> dict:
        lvl = {t: max(ctx.admission.level_for(pid) for pid in pids)
               for t, pids in self._tenants.items()}
        outcome["tenant_levels"] = lvl
        return {
            "burster_degraded": lvl["c"] > 0,
            "neighbors_untouched": lvl["a"] == 0 and lvl["b"] == 0,
            "degradation_charged":
                outcome["admission"].get("samples_degraded_total", 0) > 0,
        }


SCENARIOS: dict[str, Callable[[], Scenario]] = {
    cls.name: cls for cls in (
        PidReuseScenario, JitChurnScenario, ForkStormScenario,
        DeepStacksScenario, KernelHeavyScenario, TenantBurstScenario)
}


def build_schedule(seed: int, names=None) -> list[dict]:
    """Deterministic run order + per-scenario seeds for one zoo sweep.
    Same seed -> same schedule, independent of dict iteration order."""
    names = sorted(names if names is not None else SCENARIOS)
    rng = np.random.default_rng(int(seed))
    order = [names[int(i)] for i in rng.permutation(len(names))]
    return [{"scenario": n, "seed": int(rng.integers(1, 2**31))}
            for n in order]
