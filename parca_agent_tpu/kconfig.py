"""Environment probing: kernel config + container detection.

Role of the reference's pkg/kconfig/kconfig.go: CheckBPFEnabled parses
/proc/config.gz (or /boot/config-$(uname -r)) for the CONFIG_BPF* options
capture needs (:46-205); IsInContainer uses cpuset/sched heuristics
(:207+). Capture here needs perf_event_open rather than BPF, so the
required-option set adds CONFIG_PERF_EVENTS and the BPF ones stay
advisory (reported, not fatal) for the eventual eBPF source.
"""

from __future__ import annotations

import gzip
import io

from parca_agent_tpu.utils.vfs import VFS, RealFS

REQUIRED_OPTIONS = ("CONFIG_PERF_EVENTS",)
ADVISORY_OPTIONS = (
    "CONFIG_BPF", "CONFIG_BPF_SYSCALL", "CONFIG_HAVE_EBPF_JIT",
    "CONFIG_BPF_JIT", "CONFIG_BPF_EVENTS",
)


def parse_kernel_config(text: str) -> dict[str, str]:
    out = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "=" in line:
            k, v = line.split("=", 1)
            out[k] = v
    return out


def read_kernel_config(fs: VFS | None = None) -> dict[str, str]:
    fs = fs or RealFS()
    try:
        raw = fs.read_bytes("/proc/config.gz")
        text = gzip.GzipFile(fileobj=io.BytesIO(raw)).read().decode()
        return parse_kernel_config(text)
    except OSError:
        pass
    try:
        rel = fs.read_bytes("/proc/sys/kernel/osrelease").decode().strip()
        return parse_kernel_config(
            fs.read_bytes(f"/boot/config-{rel}").decode()
        )
    except OSError:
        return {}


def check_profiling_enabled(
    fs: VFS | None = None,
) -> tuple[bool, list[str], list[str]]:
    """(ok, missing_required, missing_advisory). Empty kernel config
    (common in containers without /proc/config.gz) is treated as
    ok-unknown."""
    cfg = read_kernel_config(fs)
    if not cfg:
        return True, [], []
    missing = [o for o in REQUIRED_OPTIONS if cfg.get(o) not in ("y", "m")]
    advisory = [o for o in ADVISORY_OPTIONS if cfg.get(o) not in ("y", "m")]
    return not missing, missing, advisory


def is_in_container(fs: VFS | None = None) -> bool:
    """cgroup/sched heuristics (kconfig.go:207+): pid 1's cgroup path is
    not "/" inside containers, or /.dockerenv exists."""
    fs = fs or RealFS()
    if fs.exists("/.dockerenv") or fs.exists("/run/.containerenv"):
        return True
    try:
        data = fs.read_bytes("/proc/1/cgroup").decode(errors="replace")
    except OSError:
        return False
    for line in data.splitlines():
        parts = line.split(":", 2)
        if len(parts) == 3 and parts[2] not in ("/", "/init.scope"):
            return True
    return False
