"""DWARF unwind-table pipeline (reference pkg/stack/unwind, layer L3)."""

from parca_agent_tpu.unwind.table import (
    CFA_EXPR_PLT1,
    CFA_EXPR_PLT2,
    CFA_TYPE_EXPRESSION,
    CFA_TYPE_RBP,
    CFA_TYPE_RSP,
    RBP_TYPE_OFFSET,
    RBP_TYPE_REGISTER,
    RBP_TYPE_UNDEFINED,
    ROW_DTYPE,
    ShardedTable,
    UnwindTableBuilder,
    build_compact_table,
    identify_expression,
    lookup_rows,
    shard_table,
)

__all__ = [
    "CFA_EXPR_PLT1", "CFA_EXPR_PLT2", "CFA_TYPE_EXPRESSION", "CFA_TYPE_RBP",
    "CFA_TYPE_RSP", "RBP_TYPE_OFFSET", "RBP_TYPE_REGISTER",
    "RBP_TYPE_UNDEFINED", "ROW_DTYPE", "ShardedTable", "UnwindTableBuilder",
    "build_compact_table", "identify_expression", "lookup_rows", "shard_table",
]
