"""Drain-time batched DWARF stack walker.

The consumer of the compact unwind tables (unwind/table.py) — the role the
reference's in-kernel walker plays (bpf/cpu/cpu.bpf.c:464-674: binary-search
the row for pc, compute CFA from rsp/rbp/two PLT expressions, read the
return address at CFA-8 and the saved RBP at CFA+offset, repeat up to 127
frames). The reference walks live memory with bpf_probe_read_user at sample
time; here the kernel snapshots user registers and a stack slice per sample
(PERF_SAMPLE_REGS_USER/STACK_USER, capture/live.py) and the walk happens at
drain time, vectorized with numpy ACROSS ALL SAMPLES of a pid at once: each
iteration advances every still-active sample by one frame (one batched
binary search + gathered 8-byte reads), the same data-parallel shape as the
aggregators' mapping join.

Termination mirrors the reference: pc not covered by the table with
rbp != 0 (pc_not_covered), unsupported rule (unsupported_expression),
return address 0 or out of the captured slice (truncated), pc not covered
AND rbp == 0 (stack bottom, success — cpu.bpf.c:636-660), or the
127-frame cap.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from parca_agent_tpu.capture.formats import MAX_STACK_DEPTH
from parca_agent_tpu.dwarf.frame import REG_RBP, REG_RSP
from parca_agent_tpu.unwind.table import (
    CFA_TYPE_EXPRESSION,
    CFA_TYPE_RBP,
    CFA_TYPE_RSP,
    CFA_EXPR_PLT1,
    CFA_EXPR_PLT2,
    RBP_TYPE_OFFSET,
    RBP_TYPE_REGISTER,
    RBP_TYPE_UNDEFINED,
    ShardedTable,
    lookup_rows,
)


def _lookup(table, pcs) -> "np.ndarray":
    """pc -> governing row index on either table form (merged ndarray or
    ShardedTable two-level)."""
    if isinstance(table, ShardedTable):
        return table.lookup(pcs)
    return lookup_rows(table, pcs)


def _rows(table, idx) -> "np.ndarray":
    if isinstance(table, ShardedTable):
        return table.rows(idx)
    return table[idx]


@dataclasses.dataclass
class WalkStats:
    """Per-batch outcome counters (role of the reference's percpu_stats,
    bpf/cpu/cpu.bpf.c:161-279)."""

    total: int = 0
    success: int = 0          # reached rbp==0 stack bottom
    truncated: int = 0        # ran out of captured stack / frame cap
    pc_not_covered: int = 0
    unsupported: int = 0      # expression/register rules we don't execute

    def add(self, other: "WalkStats") -> None:
        for f in dataclasses.fields(self):
            setattr(self, f.name,
                    getattr(self, f.name) + getattr(other, f.name))


def _read_u64(stacks: np.ndarray, dyn: np.ndarray, sample: np.ndarray,
              addr_off: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Gather little-endian u64s from per-sample stack slices.

    stacks: uint8 [n, D]; dyn: int64 [n] valid bytes; sample/addr_off: [m]
    row index and byte offset per read. Returns (values, ok)."""
    ok = (addr_off >= 0) & (addr_off + 8 <= dyn[sample])
    safe = np.where(ok, addr_off, 0).astype(np.int64)
    cols = safe[:, None] + np.arange(8, dtype=np.int64)[None, :]
    b = stacks[sample[:, None], cols].astype(np.uint64)
    weights = (np.uint64(1) << (np.arange(8, dtype=np.uint64) * np.uint64(8)))
    vals = (b * weights[None, :]).sum(axis=1, dtype=np.uint64)
    return np.where(ok, vals, np.uint64(0)), ok


def walk_batch(
    table: np.ndarray,
    rip: np.ndarray,
    rsp: np.ndarray,
    rbp: np.ndarray,
    stacks: np.ndarray,
    dyn: np.ndarray,
    max_frames: int = MAX_STACK_DEPTH,
) -> tuple[np.ndarray, np.ndarray, WalkStats]:
    """Unwind n samples against one pid's merged compact table.

    rip/rsp/rbp: uint64 [n] captured registers; stacks: uint8 [n, D] memory
    at [rsp, rsp+dyn); dyn: valid bytes per sample. Returns (frames uint64
    [n, max_frames] leaf-first return addresses, depth int32 [n], stats).
    """
    n = len(rip)
    frames = np.zeros((n, max_frames), np.uint64)
    depth = np.zeros(n, np.int32)
    stats = WalkStats(total=n)
    if n == 0 or len(table) == 0:
        stats.pc_not_covered = n
        return frames, depth, stats

    pc = rip.astype(np.uint64).copy()
    sp = rsp.astype(np.uint64).copy()
    bp = rbp.astype(np.uint64).copy()
    sp0 = rsp.astype(np.uint64).copy()
    dyn = np.asarray(dyn, np.int64)
    active = pc != 0

    done_success = np.zeros(n, bool)
    done_notcov = ~active  # rip==0: nothing to walk
    done_unsupported = np.zeros(n, bool)

    for f in range(max_frames):
        if not active.any():
            break
        # Lookup pc-1 for return addresses (they point AFTER the call);
        # frame 0 is the sampled rip itself and is looked up as-is.
        lookup_pc = pc if f == 0 else pc - np.uint64(1)
        idx = _lookup(table, np.where(active, lookup_pc, np.uint64(0)))
        covered = idx >= 0
        newly_uncov = active & ~covered
        # Stack bottom per the reference (cpu.bpf.c:636-660): success only
        # when the pc is NOT covered by the table AND rbp == 0. A zero rbp
        # while the pc is still covered (rbp used as a scratch register
        # under an UNDEFINED rule) keeps walking.
        bottom = newly_uncov & (bp == 0) & (depth > 0)
        done_success |= bottom
        done_notcov |= newly_uncov & ~bottom
        active &= covered

        # Record this frame for samples still walking.
        frames[active, f] = pc[active]
        depth[active] = f + 1

        safe = np.maximum(idx, 0)
        row = _rows(table, safe)
        cfa_t = row["cfa_type"]
        cfa_off = row["cfa_off"].astype(np.int64)

        is_rsp = cfa_t == CFA_TYPE_RSP
        is_rbp = cfa_t == CFA_TYPE_RBP
        is_expr = cfa_t == CFA_TYPE_EXPRESSION
        # The two recognized PLT expressions (dwarf_expression.go:31-57):
        # cfa = rsp + 8 + (((rip & 15) >= threshold) << 3).
        thr = np.where(cfa_off == CFA_EXPR_PLT1, 11,
                       np.where(cfa_off == CFA_EXPR_PLT2, 10, 99))
        plt_extra = ((pc & np.uint64(15)) >=
                     thr.astype(np.uint64)).astype(np.uint64) << np.uint64(3)
        cfa = np.where(
            is_rsp, sp + cfa_off.astype(np.uint64),
            np.where(is_rbp, bp + cfa_off.astype(np.uint64),
                     sp + np.uint64(8) + plt_extra))
        supported = is_rsp | is_rbp | (is_expr & (thr != 99))
        newly_unsup = active & ~supported
        done_unsupported |= newly_unsup
        active &= supported

        # Return address at CFA-8 (x86_64 ABI; rows with other RA rules
        # were filtered to END_OF_FDE at build time, unwind/table.py).
        aidx = np.flatnonzero(active)
        if len(aidx) == 0:
            continue
        ra_off = (cfa[aidx] - np.uint64(8) - sp0[aidx]).astype(np.int64)
        ra, ok = _read_u64(stacks, dyn, aidx, ra_off)

        # Saved RBP. OFFSET reads memory at CFA+off; UNDEFINED keeps the
        # current value (cpu.bpf.c:584-621); REGISTER takes the named
        # register's current-frame value — the walker tracks rsp and rbp,
        # so rules naming those resolve (previous rbp = this frame's
        # rsp/rbp); other registers aren't tracked and stay unsupported.
        # The reference bails on ALL register rules (cpu.bpf.c:530-533),
        # so this is a strict superset of its coverage.
        rbp_t = row["rbp_type"][aidx]
        rbp_off = row["rbp_off"][aidx].astype(np.int64)
        off_rows = rbp_t == RBP_TYPE_OFFSET
        reg_rows = rbp_t == RBP_TYPE_REGISTER
        reg_rsp = reg_rows & (rbp_off == REG_RSP)
        reg_rbp = reg_rows & (rbp_off == REG_RBP)
        new_bp = bp[aidx].copy()
        if off_rows.any():
            sel = aidx[off_rows]
            bp_off = (cfa[sel] + rbp_off[off_rows].astype(np.uint64)
                      - sp0[sel]).astype(np.int64)
            bp_vals, bp_ok = _read_u64(stacks, dyn, sel, bp_off)
            new_bp[off_rows] = np.where(bp_ok, bp_vals, np.uint64(0))
        if reg_rsp.any():
            new_bp[reg_rsp] = sp[aidx][reg_rsp]
        # reg_rbp is the identity (new_bp already holds the current rbp).
        keep = off_rows | reg_rsp | reg_rbp | (rbp_t == RBP_TYPE_UNDEFINED)

        # Advance; classify terminations. rbp == 0 does NOT terminate here:
        # the bottom-of-stack test happens at the next iteration's coverage
        # check (see above), matching the reference's ordering.
        trunc = ~ok | (ra == 0)
        unsup = ok & ~trunc & ~keep
        done_unsupported[aidx[unsup]] = True

        cont = ~trunc & keep
        active[aidx] = cont
        pc[aidx] = ra
        sp[aidx] = cfa[aidx]
        bp[aidx] = new_bp

    # Samples still active at the frame cap get one final bottom test (the
    # loop's coverage check never ran for their last return address); the
    # rest that died on a bad read are truncated-but-usable prefixes.
    if active.any():
        idx = _lookup(table, np.where(active, pc - np.uint64(1),
                                      np.uint64(0)))
        done_success |= active & (idx < 0) & (bp == 0)
    stats.success = int(done_success.sum())
    stats.pc_not_covered = int((done_notcov & (depth == 0)).sum())
    stats.unsupported = int(done_unsupported.sum())
    stats.truncated = int(
        stats.total - stats.success - stats.pc_not_covered
        - stats.unsupported)
    return frames, depth, stats
