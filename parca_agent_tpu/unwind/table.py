"""Per-process compact unwind tables from `.eh_frame`.

Role of the reference's pkg/stack/unwind/unwind_table.go + the row
serialization in pkg/profiler/cpu/maps.go:279-421: for each executable
file-backed mapping, parse the DSO's .eh_frame, execute every FDE's CFI
program (dwarf/frame.py), relocate by the mapping base when the object is
ASLR-eligible (ET_DYN, unwind_table.go:143-158), and emit fixed-width
16-byte rows sorted by PC, range-partitioned into <=3 shards of 250k rows
(maps.go:40-43).

Row layout (numpy structured dtype, 16 B):
  pc         uint64   first runtime address the rule covers
  cfa_type   uint8    RSP / RBP / EXPRESSION / END_OF_FDE
  rbp_type   uint8    UNDEFINED / OFFSET / REGISTER / EXPRESSION
  cfa_off    int16    CFA = reg + cfa_off (or expression id for EXPRESSION)
  rbp_off    int16    saved RBP at CFA + rbp_off (OFFSET type)
  _pad       uint16

The return address is assumed at CFA-8 (x86_64 ABI); FDE rows whose RA rule
deviates are marked END_OF_FDE (unsupported) exactly like rows the
reference's unwinder refuses (cpu.bpf.c unsupported-expression stats).

The vectorized `lookup_rows` is the host twin of the BPF program's
`find_offset_for_pc` binary search (reference bpf/cpu/cpu.bpf.c:302-341);
device-side lookups reuse the mapping-join binary search in aggregator/tpu.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from parca_agent_tpu.dwarf.frame import (
    REG_RA,
    REG_RBP,
    REG_RSP,
    FrameError,
    RuleType,
    execute_fde,
    parse_eh_frame,
)
from parca_agent_tpu.elf.executable import is_aslr_eligible
from parca_agent_tpu.elf.reader import ElfError, ElfFile
from parca_agent_tpu.process.maps import ProcMapping, host_path
from parca_agent_tpu.utils import faults, poison
from parca_agent_tpu.utils.poison import PoisonInput, read_bounded
from parca_agent_tpu.utils.vfs import VFS, RealFS

ROW_DTYPE = np.dtype([
    ("pc", np.uint64),
    ("cfa_type", np.uint8),
    ("rbp_type", np.uint8),
    ("cfa_off", np.int16),
    ("rbp_off", np.int16),
    ("_pad", np.uint16),
])
assert ROW_DTYPE.itemsize == 16

# cfa_type values (role of the reference's BpfCfaType, maps.go:46-53)
CFA_TYPE_RSP = 1
CFA_TYPE_RBP = 2
CFA_TYPE_EXPRESSION = 3
CFA_TYPE_END_OF_FDE = 4

# rbp_type values (role of BpfRbpType, maps.go:55-62)
RBP_TYPE_UNDEFINED = 0
RBP_TYPE_OFFSET = 1
RBP_TYPE_REGISTER = 2
RBP_TYPE_EXPRESSION = 3

# Recognized PLT CFA expressions (reference pkg/stack/unwind/
# dwarf_expression.go:31-57): sp + 8 + (((ip & 15) >= {11,10}) << 3).
_PLT1 = bytes([0x77, 0x08, 0x80, 0x00, 0x3F, 0x1A, 0x3B, 0x2A, 0x33, 0x24, 0x22])
_PLT2 = bytes([0x77, 0x08, 0x80, 0x00, 0x3F, 0x1A, 0x3A, 0x2A, 0x33, 0x24, 0x22])
CFA_EXPR_UNKNOWN = 0
CFA_EXPR_PLT1 = 1
CFA_EXPR_PLT2 = 2

MAX_ROWS_PER_SHARD = 250_000   # maps.go:41, synced with the capture program
MAX_SHARDS = 3                 # maps.go:42-43


def identify_expression(expr: bytes) -> int:
    if expr == _PLT1:
        return CFA_EXPR_PLT1
    if expr == _PLT2:
        return CFA_EXPR_PLT2
    return CFA_EXPR_UNKNOWN


def build_compact_table(eh_frame: bytes, section_addr: int = 0,
                        bias: int = 0) -> np.ndarray:
    """One DSO's .eh_frame -> sorted compact rows (runtime PCs = link + bias)."""
    fdes = parse_eh_frame(eh_frame, section_addr)
    rows: list[tuple[int, int, int, int, int]] = []
    for fde in fdes:
        try:
            frows = execute_fde(fde)
        except (FrameError, IndexError):
            continue
        for r in frows:
            pc = (r.loc + bias) % 2**64
            cfa = r.cfa
            rbp = r.rule(REG_RBP)
            ra = r.rule(REG_RA)

            if cfa.type == RuleType.CFA and cfa.reg in (REG_RSP, REG_RBP) \
                    and -32768 <= cfa.offset <= 32767:
                cfa_type = CFA_TYPE_RSP if cfa.reg == REG_RSP else CFA_TYPE_RBP
                cfa_off = cfa.offset
            elif cfa.type == RuleType.CFA_EXPRESSION:
                eid = identify_expression(cfa.expr)
                if eid == CFA_EXPR_UNKNOWN:
                    rows.append((pc, CFA_TYPE_END_OF_FDE, 0, 0, 0))
                    continue
                cfa_type = CFA_TYPE_EXPRESSION
                cfa_off = eid
            else:
                rows.append((pc, CFA_TYPE_END_OF_FDE, 0, 0, 0))
                continue

            # x86_64: RA must sit at CFA-8. The initial CIE rule is exactly
            # that; anything else the capture-side walker can't follow.
            if not (ra.type == RuleType.OFFSET and ra.offset == -8):
                rows.append((pc, CFA_TYPE_END_OF_FDE, 0, 0, 0))
                continue

            if rbp.type == RuleType.OFFSET and -32768 <= rbp.offset <= 32767:
                rbp_type, rbp_off = RBP_TYPE_OFFSET, rbp.offset
            elif rbp.type == RuleType.REGISTER:
                rbp_type, rbp_off = RBP_TYPE_REGISTER, rbp.reg
            elif rbp.type in (RuleType.EXPRESSION, RuleType.VAL_EXPRESSION):
                rbp_type, rbp_off = RBP_TYPE_EXPRESSION, 0
            else:
                rbp_type, rbp_off = RBP_TYPE_UNDEFINED, 0

            rows.append((pc, cfa_type, rbp_type, cfa_off, rbp_off))
        # End-of-function marker so lookups past the last row of one
        # function don't leak into the gap before the next FDE.
        rows.append(((fde.pc_end + bias) % 2**64, CFA_TYPE_END_OF_FDE, 0, 0, 0))

    table = np.zeros(len(rows), ROW_DTYPE)
    for i, (pc, ct, rt, co, ro) in enumerate(rows):
        table[i] = (pc, ct, rt, co, ro, 0)
    return sort_rows(table)


def sort_rows(table: np.ndarray) -> np.ndarray:
    """Sort by pc with END_OF_FDE markers FIRST among equal pcs: when one
    function ends exactly where the next begins, the next FDE's real rule
    must govern that pc, so the marker must lose the tie in lookup_rows'
    last-row-wins search."""
    is_end = table["cfa_type"] == CFA_TYPE_END_OF_FDE
    order = np.lexsort((~is_end, table["pc"]))
    return table[order]


@dataclasses.dataclass
class UnwindTableBuilder:
    """unwind_table_for_pid: procfs + ELF -> one merged compact table.

    (reference UnwindTableForPid, unwind_table.go:117-183)

    With a quarantine registry attached, poison inputs (corrupt ELF /
    .eh_frame — PoisonInput from the parsers, chaos site `unwind.build`)
    feed the owning pid's error budget, and pids already on the
    degradation ladder skip the build entirely: their profiles ship
    addresses-only (or scalar), and the suspect binaries are not re-read
    until probation.
    """

    fs: VFS = dataclasses.field(default_factory=RealFS)
    quarantine: object = None

    def table_for_mapping(self, pid: int, m: ProcMapping) -> np.ndarray | None:
        try:
            faults.inject("unwind.build")
            data = read_bounded(self.fs, host_path(pid, m.path),
                                poison.ELF_READ_CAP, site="unwind.build")
            ef = ElfFile(data)
        except PoisonInput as e:
            self._poisoned(pid, e)
            return None
        except OSError:
            return None
        sec = ef.section(".eh_frame")
        if sec is None:
            return None
        # ASLR: ET_DYN objects are relocated by the mapping; fixed ET_EXEC
        # binaries keep link addresses (unwind_table.go:143-158). The bias
        # is the same quantity compute_base derives for ET_DYN.
        bias = 0
        if is_aslr_eligible(ef):
            seg = ef.exec_load_segment()
            if seg is None:
                return None
            from parca_agent_tpu.elf.base import compute_base

            bias = compute_base(ef, seg, m.start, m.end, m.offset)
        try:
            return build_compact_table(ef.section_data(sec), sec.addr, bias)
        except PoisonInput as e:  # FrameError / ElfError from section data
            self._poisoned(pid, e)
            return None

    def _poisoned(self, pid: int, e: PoisonInput) -> None:
        if self.quarantine is not None:
            self.quarantine.record_error(pid, getattr(e, "site",
                                                      "unwind.build"), e)

    def table_for_pid(self, pid: int,
                      mappings: list[ProcMapping]) -> np.ndarray:
        if self.quarantine is not None and self.quarantine.level(pid) > 0:
            return np.zeros(0, ROW_DTYPE)  # ladder: no unwind for this pid
        t0 = self.quarantine.clock() if self.quarantine is not None else 0.0
        parts = []
        for m in mappings:
            if not (m.executable and m.file_backed):
                continue
            t = self.table_for_mapping(pid, m)
            if t is not None and len(t):
                parts.append(t)
        if self.quarantine is not None:
            # Per-pid deadline over the whole build: a CFI section that
            # executes slowly (huge FDE programs) is poison by time.
            self.quarantine.check_deadline(pid, t0)
        if not parts:
            return np.zeros(0, ROW_DTYPE)
        return sort_rows(np.concatenate(parts))


def shard_table(table: np.ndarray,
                max_shards: int | None = None) -> list[np.ndarray]:
    """Range-partition into shards of MAX_ROWS_PER_SHARD rows
    (maps.go:286-395).

    The reference truncates at 3 shards (750k rows/process) because each
    shard is one BPF map value with a kernel-verifier-bounded binary
    search (cpu.bpf.c:35-39); host/device memory has no such bound, so BY
    DEFAULT every shard is kept and giant processes keep full unwind
    coverage. Pass max_shards=MAX_SHARDS to reproduce the reference's
    hard cap (the truncation tests pin that behavior)."""
    shards = [table[i: i + MAX_ROWS_PER_SHARD]
              for i in range(0, len(table), MAX_ROWS_PER_SHARD)]
    return shards if max_shards is None else shards[:max_shards]


class ShardedTable:
    """Two-level pc lookup over range-partitioned shards — the host twin
    of the reference's (pid, shard) map layout, where find_unwind_table
    picks the shard by pc range and find_offset_for_pc binary-searches
    within it (cpu.bpf.c:380-411 then :302-341).

    Shards are uniform MAX_ROWS_PER_SHARD-row slices (last one ragged),
    so a global row index maps to (idx // SHARD, idx % SHARD) and callers
    can gather rows by the indices `lookup` returns.
    """

    def __init__(self, shards: list[np.ndarray]):
        if not shards:
            shards = [np.zeros(0, ROW_DTYPE)]
        for s in shards[:-1]:
            if len(s) != MAX_ROWS_PER_SHARD:
                raise ValueError("interior shards must be full "
                                 f"({MAX_ROWS_PER_SHARD} rows)")
        self.shards = shards
        # First pc per shard; pcs below starts[0] precede the table.
        self.starts = np.array(
            [s["pc"][0] if len(s) else np.uint64(0) for s in shards],
            np.uint64)
        self.n_rows = int(sum(len(s) for s in shards))

    @classmethod
    def from_table(cls, table: np.ndarray) -> "ShardedTable":
        return cls(shard_table(table))

    def __len__(self) -> int:
        return self.n_rows

    def lookup(self, pcs) -> np.ndarray:
        """Global governing-row index per pc, or -1 (same contract as
        lookup_rows on the merged table)."""
        pcs = np.asarray(pcs, np.uint64)
        si = np.searchsorted(self.starts, pcs, side="right").astype(
            np.int64) - 1
        out = np.full(len(pcs), -1, np.int64)
        for i, shard in enumerate(self.shards):
            sel = si == i
            if not sel.any():
                continue
            local = lookup_rows(shard, pcs[sel])
            out[sel] = np.where(
                local < 0, -1, local + i * MAX_ROWS_PER_SHARD)
        return out

    def rows(self, idx) -> np.ndarray:
        """Gather rows by global index (callers pass non-negative idx)."""
        idx = np.asarray(idx, np.int64)
        out = np.zeros(len(idx), ROW_DTYPE)
        si = idx // MAX_ROWS_PER_SHARD
        local = idx % MAX_ROWS_PER_SHARD
        for i, shard in enumerate(self.shards):
            sel = si == i
            if sel.any():
                out[sel] = shard[local[sel]]
        return out


def lookup_rows(table: np.ndarray, pcs) -> np.ndarray:
    """Vectorized binary search: index of the governing row per pc, or -1
    when the pc precedes the table or lands on an END_OF_FDE row (the
    'pc_not_covered' outcome in the reference's stats, cpu.bpf.c:161-279)."""
    pcs = np.asarray(pcs, np.uint64)
    idx = np.searchsorted(table["pc"], pcs, side="right").astype(np.int64) - 1
    safe = np.maximum(idx, 0)
    bad = (idx < 0) | (table["cfa_type"][safe] == CFA_TYPE_END_OF_FDE)
    return np.where(bad, -1, idx)
