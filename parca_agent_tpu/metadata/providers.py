"""Metadata providers: each contributes labels for a PID.

Role of the reference's pkg/metadata/{metadata,process,cgroup,system,
compiler,target,service_discovery}.go. The Provider protocol mirrors
metadata.go:24-28 — {name, labels(pid), should_cache}; stateless providers
are cached by the labels manager, stateful ones (service discovery) serve
from their own state (metadata.go:30-78).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Protocol

from parca_agent_tpu.utils.poison import PoisonInput
from parca_agent_tpu.utils.vfs import VFS, RealFS


class Provider(Protocol):
    name: str
    should_cache: bool

    def labels(self, pid: int) -> dict[str, str]: ...


class CgroupParseError(PoisonInput):
    """A `/proc/<pid>/cgroup` file past its sanity caps. The file is
    kernel-generated, but its CONTENT is attacker-influenced (cgroup
    paths are named by whoever creates the cgroup) and pid reuse means
    the read can race an exit — same PoisonInput discipline as the
    maps/perfmap parsers (docs/robustness.md "ingest containment")."""

    site = "cgroup.parse"


# A real cgroup file is a handful of lines (one per v1 hierarchy plus
# the v2 line); hundreds means something is feeding us garbage. The
# byte cap bounds the READ itself through poison.read_bounded at every
# call site (CgroupProvider below, runtime/admission.py TenantResolver).
CGROUP_MAX_BYTES = 1 << 20
_CGROUP_MAX_ROWS = 256


def parse_cgroup_path(data: bytes) -> str | None:
    """Primary cgroup path out of a `/proc/<pid>/cgroup` blob — prefer
    the v2 line ("0::/path"), else the cpu controller, else the first
    well-formed line. Malformed lines are skipped (kernel files can
    still truncate mid-write on pid exit); a file past the row cap
    raises CgroupParseError (a PoisonInput, chargeable to the pid)."""
    best = None
    rows = 0
    for line in data.decode(errors="replace").splitlines():
        rows += 1
        if rows > _CGROUP_MAX_ROWS:
            raise CgroupParseError(
                f"cgroup file exceeds row cap ({_CGROUP_MAX_ROWS})")
        parts = line.split(":", 2)
        if len(parts) != 3:
            continue
        if parts[0] == "0" and parts[1] == "":
            return parts[2]
        if best is None or "cpu" in parts[1].split(","):
            best = parts[2]
    return best


@dataclasses.dataclass
class ProcessProvider:
    """comm + executable path (reference process.go)."""

    fs: VFS = dataclasses.field(default_factory=RealFS)
    name: str = "process"
    should_cache: bool = True

    def labels(self, pid: int) -> dict[str, str]:
        out: dict[str, str] = {}
        try:
            out["comm"] = self.fs.read_bytes(
                f"/proc/{pid}/comm"
            ).decode(errors="replace").strip()
        except OSError:
            pass
        try:
            # /proc/pid/exe is a symlink; the cmdline's argv[0] is the
            # VFS-friendly stand-in (FakeFS has no symlinks).
            cmdline = self.fs.read_bytes(f"/proc/{pid}/cmdline")
            argv0 = cmdline.split(b"\x00", 1)[0].decode(errors="replace")
            if argv0:
                out["executable"] = argv0
        except OSError:
            pass
        return out


@dataclasses.dataclass
class CgroupProvider:
    """Primary cgroup path (reference cgroup.go:25-60)."""

    fs: VFS = dataclasses.field(default_factory=RealFS)
    name: str = "cgroup"
    should_cache: bool = True

    def labels(self, pid: int) -> dict[str, str]:
        from parca_agent_tpu.utils.poison import read_bounded

        # Bounded like every other /proc reader (PR 4 taxonomy): the
        # read itself is capped, the parse is row-capped, and poison
        # costs this pid its cgroup label, never the label pass.
        try:
            data = read_bounded(self.fs, f"/proc/{pid}/cgroup",
                                CGROUP_MAX_BYTES, site="cgroup.parse")
            best = parse_cgroup_path(data)
        except (OSError, PoisonInput):
            return {}
        return {"cgroup_name": best} if best else {}


@dataclasses.dataclass
class TenantProvider:
    """PID -> tenant identity label, fed by the admission layer's
    TenantResolver (runtime/admission.py). The label key is the
    admission layer's TENANT_LABEL ("tenant"), so the read path's
    `tenant=` selector shorthand (/query, /hotspots) slices by exactly
    the identity the quotas enforce."""

    resolver: object = None
    name: str = "tenant"
    should_cache: bool = True

    def labels(self, pid: int) -> dict[str, str]:
        if self.resolver is None:
            return {}
        tenant = self.resolver.resolve(pid)
        return {"tenant": tenant} if tenant else {}


@dataclasses.dataclass
class SystemProvider:
    """Kernel release (reference system.go:41-90)."""

    fs: VFS = dataclasses.field(default_factory=RealFS)
    name: str = "system"
    should_cache: bool = True

    def labels(self, pid: int) -> dict[str, str]:
        try:
            rel = self.fs.read_bytes(
                "/proc/sys/kernel/osrelease"
            ).decode().strip()
            return {"kernel_release": rel}
        except OSError:
            return {}


_GO_BUILDINFO = re.compile(rb"\xff Go buildinf:")


@dataclasses.dataclass
class CompilerProvider:
    """Compiler/runtime classification of the main executable (role of
    reference compiler.go:48-80, which uses the ainur library): Go binaries
    via the go build-id note / buildinfo magic, else C/C++; plus
    static/stripped bits from the ELF structure."""

    fs: VFS = dataclasses.field(default_factory=RealFS)
    name: str = "compiler"
    should_cache: bool = True

    def labels(self, pid: int) -> dict[str, str]:
        from parca_agent_tpu.elf.buildid import go_build_id
        from parca_agent_tpu.elf.reader import ElfFile
        from parca_agent_tpu.utils import poison
        from parca_agent_tpu.utils.poison import PoisonInput, read_bounded

        try:
            # /proc/pid/exe is a symlink to the main executable; reading
            # through it works on the real fs, and FakeFS tests key it
            # directly. Bounded: the target controls what it execs.
            data = read_bounded(self.fs, f"/proc/{pid}/exe",
                                poison.ELF_READ_CAP)
        except (OSError, PoisonInput):
            return {}
        try:
            ef = ElfFile(data)
        except PoisonInput:
            return {}
        is_go = go_build_id(ef) is not None or \
            ef.section(".go.buildinfo") is not None
        has_dynamic = any(s.name == ".dynamic" for s in ef.sections)
        stripped = ef.section(".symtab") is None
        return {
            "compiler": "go" if is_go else "cc",
            "static": str(not has_dynamic).lower(),
            "stripped": str(stripped).lower(),
        }


@dataclasses.dataclass
class TargetProvider:
    """Node name + operator-supplied external labels (reference
    target.go:24-45)."""

    node: str = ""
    external: dict[str, str] = dataclasses.field(default_factory=dict)
    name: str = "target"
    should_cache: bool = False  # cheap, and external labels can be reloaded

    def labels(self, pid: int) -> dict[str, str]:
        out = dict(self.external)
        if self.node:
            out["node"] = self.node
        return out


@dataclasses.dataclass
class ServiceDiscoveryProvider:
    """PID -> discovery group labels, fed by the discovery manager's state
    (reference service_discovery.go:28+ consuming the SyncCh)."""

    name: str = "service_discovery"
    should_cache: bool = False  # stateful; state IS the cache
    _state: dict[int, dict[str, str]] = dataclasses.field(default_factory=dict)

    def update(self, groups) -> None:
        """groups: iterable of discovery.Group."""
        state: dict[int, dict[str, str]] = {}
        for g in groups:
            for pid in g.pids:
                state.setdefault(pid, {}).update(g.labels)
        self._state = state

    def labels(self, pid: int) -> dict[str, str]:
        return dict(self._state.get(pid, {}))
