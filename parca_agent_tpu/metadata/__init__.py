"""Per-PID metadata providers (reference pkg/metadata)."""

from parca_agent_tpu.metadata.providers import (
    CgroupProvider,
    CompilerProvider,
    ProcessProvider,
    Provider,
    ServiceDiscoveryProvider,
    SystemProvider,
    TargetProvider,
)

__all__ = [
    "Provider", "ProcessProvider", "CgroupProvider", "SystemProvider",
    "CompilerProvider", "TargetProvider", "ServiceDiscoveryProvider",
]
