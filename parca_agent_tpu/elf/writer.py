"""ELF serializer: write a new ELF64 from chosen sections.

Role of the reference's from-scratch pkg/elfwriter (elfwriter.go:64-790 +
filtering_elfwriter.go): compose a valid ELF image containing a filtered
subset of an input file's sections — the mechanism behind debuginfo
extraction ("strip to only what symbolization needs", extract.go:46-123).

Layout produced: ELF header | program headers | section bodies | .shstrtab
| section header table. Only PT_LOAD program headers are copied from the
source, verbatim (vaddr/offset/filesz as originally linked, reference
elfwriter.go:64-790 writeSegments role): the extracted file is not
loadable, but elfexec-style base computation (elf/base.py compute_base,
pprof GetBase) reads the executable PT_LOAD's vaddr and offset from the
DEBUG file when the runtime binary is gone, so those values must survive
extraction unchanged. Other segment types are dropped — their file
offsets would point at unrelated bytes in the filtered image (a copied
PT_NOTE would make section-less note fallbacks parse garbage); kept note
CONTENT still travels via its sections.
"""

from __future__ import annotations

import dataclasses
import struct

from parca_agent_tpu.elf.reader import (
    PT_LOAD,
    ElfFile,
    Section,
    Segment,
    SHT_NOBITS,
)

SHT_NULL = 0
SHT_STRTAB = 3


class ElfWriter:
    """Collect (section, data) pairs + verbatim segments, then serialize."""

    def __init__(self, e_type: int, e_machine: int, entry: int = 0,
                 endian: str = "<"):
        self.e_type = e_type
        self.e_machine = e_machine
        self.entry = entry
        self.end = endian
        self._sections: list[tuple[Section, bytes]] = []
        self._segments: list[Segment] = []

    def add_section(self, sec: Section, data: bytes) -> None:
        self._sections.append((sec, data))

    def add_segment(self, seg: Segment) -> None:
        """Record a program header to emit as-is (original offsets/vaddrs;
        see module docstring for why they are not remapped)."""
        self._segments.append(seg)

    def serialize(self) -> bytes:
        ehsize, shentsize, phentsize = 64, 64, 56
        # Section name string table; index 0 is the empty name.
        names = bytearray(b"\x00")
        name_off = {}
        for sec, _ in self._sections:
            name_off[sec.name] = len(names)
            names += sec.name.encode() + b"\x00"
        shstr_name_off = len(names)
        names += b".shstrtab\x00"

        # Body layout after the ELF header and program header table,
        # honoring alignment.
        phoff = ehsize if self._segments else 0
        bodies: list[tuple[int, bytes]] = []
        pos = ehsize + len(self._segments) * phentsize
        laid: list[tuple[Section, int, int]] = []  # (sec, offset, size)
        for sec, data in self._sections:
            align = max(1, sec.addralign)
            if sec.type != SHT_NOBITS:
                pos = (pos + align - 1) // align * align
                bodies.append((pos, bytes(data)))
                laid.append((sec, pos, len(data)))
                pos += len(data)
            else:
                laid.append((sec, pos, sec.size))
        shstr_off = pos
        bodies.append((pos, bytes(names)))
        pos += len(names)
        shoff = (pos + 7) // 8 * 8

        n_secs = len(self._sections) + 2  # + null + shstrtab
        shstrndx = n_secs - 1

        out = bytearray(shoff + n_secs * shentsize)
        ident = b"\x7fELF" + bytes([2, 1 if self.end == "<" else 2, 1]) + b"\x00" * 9
        out[0:16] = ident
        struct.pack_into(self.end + "HHIQQQIHHHHHH", out, 16,
                         self.e_type, self.e_machine, 1, self.entry,
                         phoff, shoff, 0, ehsize,
                         phentsize if self._segments else 0,
                         len(self._segments), shentsize, n_secs,
                         shstrndx)
        for i, seg in enumerate(self._segments):
            struct.pack_into(self.end + "IIQQQQQQ", out,
                             phoff + i * phentsize, seg.type, seg.flags,
                             seg.offset, seg.vaddr, seg.paddr, seg.filesz,
                             seg.memsz, seg.align)
        for off, data in bodies:
            out[off: off + len(data)] = data

        def put_sh(i, name, typ, flags, addr, off, size, link, info,
                   align, entsize):
            struct.pack_into(self.end + "IIQQQQIIQQ", out,
                             shoff + i * shentsize, name, typ, flags, addr,
                             off, size, link, info, align, entsize)

        put_sh(0, 0, SHT_NULL, 0, 0, 0, 0, 0, 0, 0, 0)
        # Callers (filter_elf) hand in sections whose link indices already
        # point into THIS writer's table order; they are written verbatim.
        for new_i, (sec, off, size) in enumerate(laid, start=1):
            put_sh(new_i, name_off[sec.name], sec.type, sec.flags, sec.addr,
                   off, size, sec.link, sec.info,
                   max(1, sec.addralign), sec.entsize)
        put_sh(shstrndx, shstr_name_off, SHT_STRTAB, 0, 0, shstr_off,
               len(names), 0, 0, 1, 0)
        return bytes(out)


def compose_elf(parts: list[tuple[bytes, "callable"]]) -> bytes:
    """Compose ONE ELF from sections of several source files (the
    reference's AggregatingWriter role, aggregating_elfwriter.go:27-76).

    The FIRST part is the primary: it contributes the file identity
    (header fields, PT_LOAD program headers) as well as its
    predicate-matched sections. Each later (data, keep) part contributes
    its matching sections; same-named sections from later parts are
    skipped (first wins), so e.g. a separate debug file's .debug_* can
    be merged under the runtime binary's .note.gnu.build-id without
    duplicating tables. Linked sections (.symtab -> .strtab) are pulled
    per-part and link indices remapped into the combined table; when the
    dedup drops a later part's link target, the link resolves by NAME to
    the earlier part's section — callers composing same-named tables
    from DIFFERENT builds must ensure the winning table is the right one
    (same caller contract as the reference's AggregatingWriter).
    """
    w: ElfWriter | None = None
    seen: dict[str, int] = {}  # name -> combined table index (1-based)
    for data, keep in parts:
        ef = ElfFile(data)
        if w is None:
            w = ElfWriter(ef.e_type, ef.e_machine, ef.entry, ef.end)
            for seg in ef.segments:
                if seg.type == PT_LOAD:
                    w.add_segment(seg)
        chosen = _select_sections(ef, keep)
        # Drop names an earlier part already contributed (first wins).
        kept = [i for i in chosen if ef.sections[i].name not in seen]
        base = len(w._sections)
        new_index = {old: base + new
                     for new, old in enumerate(kept, start=1)}
        for i in kept:
            sec = ef.sections[i]
            # A link target dropped by the dedup resolves BY NAME to the
            # earlier part's section (e.g. part 2's .symtab links part
            # 1's .strtab) so no surviving section dangles at link=0.
            link = new_index.get(sec.link, 0)
            if link == 0 and sec.link:
                link = seen.get(ef.sections[sec.link].name, 0)
            seen[sec.name] = new_index[i]
            w.add_section(dataclasses.replace(sec, link=link), ef.section_data(sec))
    if w is None:
        raise ValueError("compose_elf needs at least one part")
    return w.serialize()


def _select_sections(ef: ElfFile, keep) -> list[int]:
    """Predicate-matched section indices plus their link closure
    (shared by filter_elf and compose_elf)."""
    secs = ef.sections
    chosen: list[int] = []
    for i, sec in enumerate(secs):
        if i == 0 or sec.type == SHT_NULL:
            continue
        if sec.name == ".shstrtab":
            continue  # writer regenerates it
        if keep(sec):
            chosen.append(i)
    pulled = True
    while pulled:
        pulled = False
        for i in list(chosen):
            link = secs[i].link
            if link and link != 0 and link not in chosen \
                    and secs[link].name != ".shstrtab":
                chosen.append(link)
                pulled = True
    chosen.sort()
    return chosen


def filter_elf(data: bytes, keep) -> bytes:
    """Copy an ELF keeping predicate-matched sections (the FilteringWriter
    role, filtering_elfwriter.go:26-196). Sections a kept section `link`s to
    (e.g. .symtab -> .strtab) are pulled in automatically and link indices
    remapped."""
    ef = ElfFile(data)
    secs = ef.sections
    chosen = _select_sections(ef, keep)

    w = ElfWriter(ef.e_type, ef.e_machine, ef.entry, ef.end)
    # Only PT_LOAD survives: that is all base computation reads, and any
    # other segment type (PT_NOTE especially) carries a file offset that
    # now points at unrelated bytes in the filtered image — a reader's
    # section-less note fallback would parse garbage from it. Kept note
    # CONTENT still travels via its sections.
    for seg in ef.segments:
        if seg.type == PT_LOAD:
            w.add_segment(seg)
    new_index = {old: new for new, old in enumerate(chosen, start=1)}
    for i in chosen:
        sec = secs[i]
        new_link = new_index.get(sec.link, 0)
        w.add_section(dataclasses.replace(sec, link=new_link),
                      ef.section_data(sec))
    return w.serialize()
