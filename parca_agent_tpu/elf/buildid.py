"""Build-id extraction (role of reference pkg/buildid/buildid.go:36-122).

Precedence mirrors the reference:
  1. Go build id   — .note.go.buildid note (name "Go", type 4), the id the
                     Go toolchain stamps (reference fastGoBuildID +
                     internal/go/buildid fallback);
  2. GNU build id  — .note.gnu.build-id note (name "GNU", type 3), hex;
  3. fallback      — hash of .text contents, so stripped/noteless binaries
                     still get a stable identity.
"""

from __future__ import annotations

import hashlib

from parca_agent_tpu.elf.reader import ElfFile

NT_GNU_BUILD_ID = 3
NT_GO_BUILD_ID = 4


def go_build_id(ef: ElfFile) -> str | None:
    sec = ef.section(".note.go.buildid")
    if sec is not None:
        from parca_agent_tpu.elf.reader import parse_notes

        for note in parse_notes(ef.section_data(sec), ef.end):
            if note.name == "Go" and note.type == NT_GO_BUILD_ID and note.desc:
                return note.desc.rstrip(b"\x00").decode(errors="replace")
    return None


def gnu_build_id(ef: ElfFile) -> str | None:
    for note in ef.notes():
        if note.name == "GNU" and note.type == NT_GNU_BUILD_ID and note.desc:
            return note.desc.hex()
    return None


def text_hash_id(ef: ElfFile) -> str | None:
    sec = ef.section(".text")
    if sec is None:
        return None
    data = ef.section_data(sec)
    if not data:
        return None
    return hashlib.blake2b(data, digest_size=20).hexdigest()


def build_id(data_or_elf) -> str | None:
    """Best-available build id for an ELF image (bytes or ElfFile)."""
    ef = data_or_elf if isinstance(data_or_elf, ElfFile) else ElfFile(data_or_elf)
    return go_build_id(ef) or gnu_build_id(ef) or text_hash_id(ef)
