"""Build-id extraction (role of reference pkg/buildid/buildid.go:36-122).

Precedence mirrors the reference:
  1. Go build id   — .note.go.buildid note (name "Go", type 4), the id the
                     Go toolchain stamps (reference fastGoBuildID), else
                     the legacy text-segment magic scan (reference
                     internal/go/buildid readRaw: pre-1.x toolchains and
                     `go tool link -B none` binaries carry only the
                     `\\xff Go build ID: "..."\\xff` marker at the start
                     of text);
  2. GNU build id  — .note.gnu.build-id note (name "GNU", type 3), hex;
  3. fallback      — hash of .text contents, so stripped/noteless binaries
                     still get a stable identity.
"""

from __future__ import annotations

import hashlib

from parca_agent_tpu.elf.reader import ElfFile

NT_GNU_BUILD_ID = 3
NT_GO_BUILD_ID = 4

# Legacy in-text marker (internal/go/buildid/buildid.go:240-242): the id
# is the quoted string between goBuildPrefix and goBuildEnd, stamped
# within the first 32 kB of the text segment (readSize).
_GO_MAGIC = b'\xff Go build ID: "'
_GO_END = b'"\n \xff'
_GO_SCAN_LIMIT = 32 * 1024

# Poison cap: real ids are <=83 chars (Go) / 40 hex chars (GNU sha1). A
# note desc claiming kilobytes is malformed input, not an identity —
# treat the candidate as absent and fall through the precedence chain
# (docs/robustness.md "ingest containment").
_MAX_ID_LEN = 256


def go_build_id(ef: ElfFile) -> str | None:
    sec = ef.section(".note.go.buildid")
    if sec is not None:
        from parca_agent_tpu.elf.reader import parse_notes

        for note in parse_notes(ef.section_data(sec), ef.end):
            if note.name == "Go" and note.type == NT_GO_BUILD_ID \
                    and note.desc and len(note.desc) <= _MAX_ID_LEN:
                return note.desc.rstrip(b"\x00").decode(errors="replace")
    return None


def legacy_go_build_id(ef: ElfFile) -> str | None:
    """Scan the head of the text segment for the legacy quoted marker
    (internal/go/buildid readRaw semantics: the id is everything between
    goBuildPrefix and the goBuildEnd terminator, no length cap). Only the
    first 32 kB are examined (the toolchain stamps the marker at text
    start and its own reader reads exactly that much), sliced without
    materializing the whole section."""
    sec = ef.section(".text")
    if sec is None:
        return None
    end = min(sec.offset + min(sec.size, _GO_SCAN_LIMIT), len(ef.data))
    data = ef.data[sec.offset:end]
    i = data.find(_GO_MAGIC)
    if i < 0:
        return None
    start = i + len(_GO_MAGIC)
    j = data.find(_GO_END, start)
    if j < 0:
        return None
    raw = data[start:j]
    if not raw or b"\x00" in raw or len(raw) > _MAX_ID_LEN:
        return None
    return raw.decode(errors="replace")


def gnu_build_id(ef: ElfFile) -> str | None:
    for note in ef.notes():
        if note.name == "GNU" and note.type == NT_GNU_BUILD_ID \
                and note.desc and len(note.desc) <= _MAX_ID_LEN:
            return note.desc.hex()
    return None


def text_hash_id(ef: ElfFile) -> str | None:
    sec = ef.section(".text")
    if sec is None:
        return None
    data = ef.section_data(sec)
    if not data:
        return None
    return hashlib.blake2b(data, digest_size=20).hexdigest()


def build_id(data_or_elf) -> str | None:
    """Best-available build id for an ELF image (bytes or ElfFile)."""
    ef = data_or_elf if isinstance(data_or_elf, ElfFile) else ElfFile(data_or_elf)
    # GNU note before the legacy text scan: a note-less binary with a
    # GNU build id that happens to carry the legacy marker bytes in its
    # text head must keep its GNU identity (the reference gates the
    # legacy path on the Go note section and never raw-scans,
    # pkg/buildid/buildid.go:43-56).
    return (go_build_id(ef) or gnu_build_id(ef) or legacy_go_build_id(ef)
            or text_hash_id(ef))
