"""Base-address computation for runtime->object address normalization.

Role of the reference's vendored pprof elfexec.GetBase (internal/pprof/
elfexec/elfexec.go:221, used at pkg/objectfile/object_file.go:156-238):
given the ELF type, the executable PT_LOAD segment, and one /proc mapping
(start, limit, offset) of that file, compute `base` so that
object_address = runtime_address - base.

Semantics per ELF type (matching pprof's rules for the cases a profiler
meets; kernel-relocation special cases handled via stext_offset):

  ET_EXEC — fixed link address: base is normally 0. Kernel images are
      ET_EXEC yet relocated (KASLR): when stext_offset is provided and
      disagrees with the mapping, base = start - stext_offset.
  ET_REL  — relocatable object: offset must be 0; base = start.
  ET_DYN  — PIE/DSO: base = (start - offset) + (seg.offset - seg.vaddr);
      i.e. runtime bias of the file's page 0 plus the link-time delta
      between the segment's file offset and virtual address.
"""

from __future__ import annotations

from parca_agent_tpu.elf.reader import ET_DYN, ET_EXEC, ET_REL, ElfFile, Segment


class BaseError(ValueError):
    pass


def compute_base(
    ef_or_type,
    load_segment: Segment | None,
    start: int,
    limit: int,
    offset: int,
    stext_offset: int | None = None,
) -> int:
    e_type = ef_or_type.e_type if isinstance(ef_or_type, ElfFile) else ef_or_type

    if start == 0 and offset == 0 and limit == ~0 & (2**64 - 1):
        # Whole-address-space pseudo mapping (profile with no mappings).
        return 0

    if e_type == ET_EXEC:
        if stext_offset is not None:
            # Relocated kernel: _stext's runtime address vs link address.
            return (start - stext_offset) % 2**64
        if load_segment is None:
            return 0
        if offset == 0 and start != 0 and start == load_segment.vaddr:
            return 0
        # Mapping not at the linked address: the file was loaded shifted
        # (e.g. prelink leftovers); bias by the difference.
        if offset == 0 and start != 0:
            return (start - load_segment.vaddr) % 2**64
        return 0

    if e_type == ET_REL:
        if offset != 0:
            raise BaseError(f"ET_REL mapping with nonzero offset {offset:#x}")
        return start % 2**64

    if e_type == ET_DYN:
        if load_segment is None:
            return (start - offset) % 2**64
        return (start - offset + load_segment.offset - load_segment.vaddr) % 2**64

    raise BaseError(f"unsupported ELF type {e_type}")


def object_address(runtime_addr: int, base: int) -> int:
    return (runtime_addr - base) % 2**64
