"""Executable classification (role of reference pkg/executable/executable.go).

IsASLRElegible in the reference: a DSO/PIE (ET_DYN) moves under ASLR and its
unwind tables must be relocated by the mapping start before upload
(pkg/stack/unwind/unwind_table.go:143-158); a fixed ET_EXEC binary must not.
"""

from __future__ import annotations

from parca_agent_tpu.elf.reader import ET_DYN, ElfFile


def is_aslr_eligible(data_or_elf) -> bool:
    ef = data_or_elf if isinstance(data_or_elf, ElfFile) else ElfFile(data_or_elf)
    return ef.e_type == ET_DYN
