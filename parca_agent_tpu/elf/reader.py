"""ELF64 reader over in-memory bytes.

The subset the agent needs (role of the reference's pkg/elfreader +
debug/elf usage): identification, file header, program headers, section
headers + names, note iteration, and symbol tables. Little- and big-endian
ELF64 are supported; ELF32 is rejected (the capture targets are x86_64 /
aarch64 processes, matching the reference's scope in bpf/cpu/cpu.bpf.c).

Poison hardening (docs/robustness.md "ingest containment"): the bytes come
from arbitrary host processes via /proc/<pid>/root, so every read is
bounds-checked and every table capped; anything malformed raises ElfError,
which is a PoisonInput — callers attribute it to the owning pid instead of
failing the window. `faults.inject("elf.read")` is the chaos site.
"""

from __future__ import annotations

import dataclasses
import struct

from parca_agent_tpu.utils import faults
from parca_agent_tpu.utils.poison import PoisonInput

ET_REL = 1
ET_EXEC = 2
ET_DYN = 3
ET_CORE = 4

PT_LOAD = 1
PT_NOTE = 4

SHT_NOTE = 7
SHT_NOBITS = 8
SHT_SYMTAB = 2
SHT_DYNSYM = 11

PF_X = 1
PF_W = 2
PF_R = 4

SHF_COMPRESSED = 0x800


class ElfError(PoisonInput):
    site = "elf.read"


# Symbol entries are 24 bytes; a smaller sh_entsize would make the read
# loop walk overlapping garbage.
_SYM_ENTSIZE_MIN = 24


@dataclasses.dataclass(frozen=True)
class Segment:
    type: int
    flags: int
    offset: int
    vaddr: int
    paddr: int
    filesz: int
    memsz: int
    align: int


@dataclasses.dataclass(frozen=True)
class Section:
    name: str
    type: int
    flags: int
    addr: int
    offset: int
    size: int
    link: int
    info: int
    addralign: int
    entsize: int


@dataclasses.dataclass(frozen=True)
class Note:
    name: str
    type: int
    desc: bytes


@dataclasses.dataclass(frozen=True)
class Symbol:
    name: str
    value: int
    size: int
    info: int
    shndx: int

    @property
    def type(self) -> int:
        return self.info & 0xF


class ElfFile:
    """Parsed ELF64 image over a bytes buffer."""

    def __init__(self, data: bytes):
        faults.inject("elf.read")
        if len(data) < 64 or data[:4] != b"\x7fELF":
            raise ElfError("not an ELF file")
        ei_class = data[4]
        ei_data = data[5]
        if ei_class != 2:
            raise ElfError("only ELF64 is supported")
        if ei_data == 1:
            self.end = "<"
        elif ei_data == 2:
            self.end = ">"
        else:
            raise ElfError("bad EI_DATA")
        self.data = data
        (self.e_type, self.e_machine, _ver, self.entry, self.phoff,
         self.shoff, _flags, _ehsize, self.phentsize, self.phnum,
         self.shentsize, self.shnum, self.shstrndx) = struct.unpack_from(
            self.end + "HHIQQQIHHHHHH", data, 16
        )
        self._sections: list[Section] | None = None

    # -- program headers ----------------------------------------------------

    @property
    def segments(self) -> list[Segment]:
        out = []
        for i in range(self.phnum):
            off = self.phoff + i * self.phentsize
            if off + 56 > len(self.data):
                raise ElfError("program header out of bounds")
            (p_type, p_flags, p_offset, p_vaddr, p_paddr, p_filesz,
             p_memsz, p_align) = struct.unpack_from(
                self.end + "IIQQQQQQ", self.data, off
            )
            out.append(Segment(p_type, p_flags, p_offset, p_vaddr, p_paddr,
                               p_filesz, p_memsz, p_align))
        return out

    def load_segments(self) -> list[Segment]:
        return [s for s in self.segments if s.type == PT_LOAD]

    def exec_load_segment(self) -> Segment | None:
        """First executable PT_LOAD (the reference picks the program header
        covering the sampled address; the x-bit one is the text segment)."""
        for s in self.load_segments():
            if s.flags & PF_X:
                return s
        return None

    # -- section headers ----------------------------------------------------

    @property
    def sections(self) -> list[Section]:
        if self._sections is not None:
            return self._sections
        raw = []
        for i in range(self.shnum):
            off = self.shoff + i * self.shentsize
            if off + 64 > len(self.data):
                raise ElfError("section header out of bounds")
            (sh_name, sh_type, sh_flags, sh_addr, sh_offset, sh_size,
             sh_link, sh_info, sh_addralign, sh_entsize) = struct.unpack_from(
                self.end + "IIQQQQIIQQ", self.data, off
            )
            raw.append((sh_name, Section("", sh_type, sh_flags, sh_addr,
                                         sh_offset, sh_size, sh_link, sh_info,
                                         sh_addralign, sh_entsize)))
        names = b""
        if 0 < self.shstrndx < len(raw):
            st = raw[self.shstrndx][1]
            names = self.data[st.offset: st.offset + st.size]
        out = []
        for sh_name, sec in raw:
            end = names.find(b"\x00", sh_name)
            nm = names[sh_name:end].decode(errors="replace") if 0 <= sh_name < len(names) else ""
            out.append(dataclasses.replace(sec, name=nm))
        self._sections = out
        return out

    def section(self, name: str) -> Section | None:
        for s in self.sections:
            if s.name == name:
                return s
        return None

    def section_data(self, sec: Section) -> bytes:
        if sec.type == SHT_NOBITS:
            return b""
        if sec.offset + sec.size > len(self.data):
            raise ElfError(f"section {sec.name!r} out of bounds")
        return self.data[sec.offset: sec.offset + sec.size]

    # -- notes --------------------------------------------------------------

    def notes(self) -> list[Note]:
        """All notes from SHT_NOTE sections, falling back to PT_NOTE
        segments when the section table is stripped."""
        blobs = [self.section_data(s) for s in self.sections if s.type == SHT_NOTE]
        if not blobs:
            blobs = [
                self.data[seg.offset: seg.offset + seg.filesz]
                for seg in self.segments
                if seg.type == PT_NOTE
            ]
        out = []
        for blob in blobs:
            out.extend(parse_notes(blob, self.end))
        return out

    # -- symbols ------------------------------------------------------------

    def symbols(self, section_name: str = ".symtab") -> list[Symbol]:
        sec = self.section(section_name)
        if sec is None or sec.entsize == 0:
            return []
        if sec.entsize < _SYM_ENTSIZE_MIN:
            raise ElfError(
                f"symbol entsize {int(sec.entsize)} below entry size")
        strsec = self.sections[sec.link] if sec.link < len(self.sections) else None
        strs = self.section_data(strsec) if strsec else b""
        data = self.section_data(sec)
        out = []
        for off in range(0, len(data) - 23, int(sec.entsize)):
            st_name, st_info, _other, st_shndx, st_value, st_size = \
                struct.unpack_from(self.end + "IBBHQQ", data, off)
            end = strs.find(b"\x00", st_name)
            nm = strs[st_name:end].decode(errors="replace") if 0 <= st_name < len(strs) else ""
            out.append(Symbol(nm, st_value, st_size, st_info, st_shndx))
        return out


def parse_notes(blob: bytes, end: str = "<") -> list[Note]:
    """Iterate 4-byte-aligned note records: namesz descsz type name desc."""
    out = []
    pos = 0
    while pos + 12 <= len(blob):
        namesz, descsz, ntype = struct.unpack_from(end + "III", blob, pos)
        pos += 12
        if namesz > len(blob) - pos:
            break  # truncated record: name overruns the blob
        name = blob[pos: pos + namesz].rstrip(b"\x00").decode(errors="replace")
        pos += (namesz + 3) & ~3
        if descsz > max(len(blob) - pos, 0):
            break  # truncated record: desc overruns the blob
        desc = blob[pos: pos + descsz]
        pos += (descsz + 3) & ~3
        out.append(Note(name, ntype, desc))
    return out
