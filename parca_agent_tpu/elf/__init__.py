"""Minimal ELF toolkit (reference layer L2/L6 foundations).

Own parser/serializer rather than a dependency: the agent needs exactly the
subset the reference carries in pkg/elfreader, pkg/elfwriter, pkg/buildid
and internal/pprof/elfexec — headers, program/section tables, notes, symbol
tables, and base-address computation — and needs them against in-memory
bytes from an injectable VFS.
"""

from parca_agent_tpu.elf.reader import ElfError, ElfFile, Note, Section, Segment
from parca_agent_tpu.elf.buildid import build_id
from parca_agent_tpu.elf.base import compute_base
from parca_agent_tpu.elf.executable import is_aslr_eligible

__all__ = [
    "ElfError", "ElfFile", "Note", "Section", "Segment",
    "build_id", "compute_base", "is_aslr_eligible",
]
