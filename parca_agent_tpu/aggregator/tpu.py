"""Device (TPU/XLA) aggregation backend — the flagship kernel.

Re-expresses the reference's per-interval profile build (the `obtainProfiles`
hot loop, reference pkg/profiler/cpu/cpu.go:505-718) as ONE jit-compiled XLA
program batched over all PIDs at once:

  1. row hash      — two independent multilinear hashes over the padded
                     stack row (pid, user_len, kernel_len, 128 frames);
  2. stack dedup   — `lax.sort` by (pid, h1, h2), then FULL row comparison
                     between neighbors (a hash collision can therefore never
                     merge two distinct stacks), `segment_sum` of counts;
  3. location dedup— flatten live frames of the unique stacks, sort by
                     (pid, addr_hi, addr_lo), boundary-scan to per-PID
                     1-based location ids, scatter-compact the unique
                     locations into a bounded [L_cap] table (the same
                     bounded-memory role the reference's 250k-row unwind
                     shards play, reference pkg/profiler/cpu/maps.go:40-43);
  4. mapping join  — branchless vectorized binary search of every unique
                     location against the (pid, start)-sorted mapping table
                     (the data-parallel analog of `find_offset_for_pc`,
                     reference bpf/cpu/cpu.bpf.c:302-341).

Addresses travel as (hi, lo) uint32 pairs — TPUs have no native 64-bit
integer datapath, and JAX x64 stays off. The host wrapper does only what
cannot or should not live on device: u64 normalization arithmetic
(addr - start + offset), per-PID profile splitting, and string tables.

Shapes are static per (N_pad, M_pad, L_cap) bucket so recompilation stops
after the first few windows; N is padded to the next power of two.
"""

from __future__ import annotations

import dataclasses
import functools
import math

import numpy as np

from parca_agent_tpu.aggregator.base import PidProfile
from parca_agent_tpu.aggregator.cpu import _pid_mappings
from parca_agent_tpu.capture.formats import (
    KERNEL_ADDR_START,
    STACK_SLOTS,
    MappingTable,
    WindowSnapshot,
    fold_rows_first_seen,
)
from parca_agent_tpu.ops.hashing import fold_u64_rows, multilinear_hash_u32
from parca_agent_tpu.runtime import device_telemetry as dtel

_U32_MAX = 0xFFFFFFFF


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def _shift_down(a, fill):
    """[a0, a1, ...] -> [fill, a0, a1, ...] dropping the last element."""
    import jax.numpy as jnp

    return jnp.concatenate([jnp.full(a.shape[:0] + (1,), fill, a.dtype), a[:-1]])


def _lex_le3(a1, a2, a3, b1, b2, b3):
    """(a1,a2,a3) <= (b1,b2,b3) lexicographically, elementwise uint32."""
    return (a1 < b1) | ((a1 == b1) & ((a2 < b2) | ((a2 == b2) & (a3 <= b3))))


@functools.lru_cache(maxsize=4)
def _jitted_kernel():
    import jax

    return jax.jit(
        _window_kernel,
        static_argnames=("n_pad", "l_cap", "m_pad", "f_cap", "hash_locs",
                         "interpret"),
    )


def _window_kernel(
    pid,        # uint32 [N]   (padding rows = U32_MAX)
    cnt,        # int32  [N]   (padding rows = 0)
    ulen,       # int32  [N]
    klen,       # int32  [N]
    shi,        # uint32 [N,S] stack address high halves
    slo,        # uint32 [N,S] stack address low halves
    valid,      # bool   [N]
    map_pid,    # uint32 [M]   (padding rows = U32_MAX)
    map_shi,    # uint32 [M]   mapping start hi
    map_slo,    # uint32 [M]   mapping start lo
    map_ehi,    # uint32 [M]   mapping end hi
    map_elo,    # uint32 [M]   mapping end lo
    *,
    n_pad: int,
    l_cap: int,
    m_pad: int,
    f_cap: int,
    hash_locs: bool = False,
    interpret: bool = True,
):
    import jax
    import jax.numpy as jnp

    n, s = shi.shape

    # ---- 1. row hash ------------------------------------------------------
    lanes = fold_u64_rows(
        shi, slo, extra=[pid, ulen.astype(jnp.uint32), klen.astype(jnp.uint32)]
    )
    h1 = multilinear_hash_u32(lanes, 0)
    h2 = multilinear_hash_u32(lanes, 1)

    # ---- 2. exact stack dedup --------------------------------------------
    pid_s, h1_s, h2_s, perm = jax.lax.sort(
        (pid, h1, h2, jnp.arange(n, dtype=jnp.int32)), num_keys=3, is_stable=True
    )
    cnt_s = cnt[perm]
    ulen_s = ulen[perm]
    klen_s = klen[perm]
    shi_s = shi[perm]
    slo_s = slo[perm]
    valid_s = valid[perm]

    same_meta = (
        (pid_s == _shift_down(pid_s, jnp.uint32(_U32_MAX)))
        & (ulen_s == _shift_down(ulen_s, jnp.int32(-1)))
        & (klen_s == _shift_down(klen_s, jnp.int32(-1)))
    )
    same_stack = jnp.all(
        (shi_s == jnp.concatenate([shi_s[:1], shi_s[:-1]]))
        & (slo_s == jnp.concatenate([slo_s[:1], slo_s[:-1]])),
        axis=1,
    )
    same_stack = same_stack.at[0].set(False)
    new_group = (~(same_meta & same_stack)) & valid_s
    new_group = new_group.at[0].set(valid_s[0])

    group = jnp.cumsum(new_group.astype(jnp.int32)) - 1
    group = jnp.maximum(group, 0)
    n_groups = new_group.astype(jnp.int32).sum()

    values = jax.ops.segment_sum(cnt_s, group, num_segments=n_pad)
    rep_pos = jax.ops.segment_min(
        jnp.arange(n, dtype=jnp.int32), group, num_segments=n_pad
    )
    rep_pos = jnp.minimum(rep_pos, n - 1)  # padded groups -> harmless gather

    out_pid = pid_s[rep_pos]
    out_ulen = ulen_s[rep_pos]
    out_klen = klen_s[rep_pos]
    out_shi = shi_s[rep_pos]
    out_slo = slo_s[rep_pos]
    group_live = jnp.arange(n, dtype=jnp.int32) < n_groups

    # ---- 3. location dedup ------------------------------------------------
    depth = out_ulen + out_klen
    slot = jnp.arange(s, dtype=jnp.int32)[None, :]
    frame_live = (slot < depth[:, None]) & group_live[:, None]

    # Compact the live frames of the unique stacks into a [f_cap] buffer
    # before sorting: the padded [n, 128] frame matrix is ~4-5x dead slots
    # at real stack depths, and sort cost is the kernel's dominant term.
    # f_cap is sized from the EXACT host-side frame count (pack_window_
    # inputs), so the scatter never drops a live frame.
    flat_live = frame_live.reshape(-1)
    tgt = jnp.where(flat_live,
                    jnp.cumsum(flat_live.astype(jnp.int32)) - 1,
                    jnp.int32(f_cap))
    fpid = jnp.full((f_cap,), _U32_MAX, jnp.uint32).at[tgt].set(
        jnp.broadcast_to(out_pid[:, None], (n, s)).reshape(-1), mode="drop")
    fhi = jnp.full((f_cap,), _U32_MAX, jnp.uint32).at[tgt].set(
        out_shi.reshape(-1), mode="drop")
    flo = jnp.full((f_cap,), _U32_MAX, jnp.uint32).at[tgt].set(
        out_slo.reshape(-1), mode="drop")
    fsrc = jnp.full((f_cap,), n * s, jnp.int32).at[tgt].set(
        jnp.arange(n * s, dtype=jnp.int32), mode="drop")

    if hash_locs:
        # Hash-table location dedup (the sub-RTT close PR): every live
        # frame's raw 96-bit (pid, hi, lo) key probes/claims an
        # open-addressing table (the Pallas batch-probe kernel,
        # aggregator/pallas_probe.py) instead of riding the f_cap-lane
        # bitonic sort — the stateless kernel's dominant cost. The sort
        # that remains runs over the cap_loc TABLE entries (~2x unique
        # locations), restoring the sort path's exact output order, so
        # the pprof bytes are identical. Identity is the full key —
        # a probe-base hash collision only lengthens a chain.
        from parca_agent_tpu.aggregator.pallas_probe import (
            make_loc_table_builder,
        )

        cap_loc = 2 * l_cap  # load factor <= 0.5 once l_cap fits n_locs
        base = multilinear_hash_u32(
            jnp.stack([fpid, fhi, flo], axis=-1), 3)
        builder = make_loc_table_builder(f_cap, cap_loc,
                                         interpret=interpret)
        slot, tpid_t, thi_t, tlo_t = builder(fpid, fhi, flo, base)
        flive = fpid != jnp.uint32(_U32_MAX)
        # A live frame that could not place means the table is full
        # (l_cap undersized): report n_locs = l_cap + 1 so the caller's
        # existing doubling retry fires — same contract as the sort
        # path's overflow.
        overflowed = (flive & (slot < 0)).any()
        tslot = jnp.arange(cap_loc, dtype=jnp.int32)
        spid, shi2, slo2, sslot = jax.lax.sort(
            (tpid_t, thi_t, tlo_t, tslot), num_keys=3, is_stable=True)
        tlive = spid != jnp.uint32(_U32_MAX)
        n_locs = jnp.where(overflowed, jnp.int32(l_cap + 1),
                           tlive.astype(jnp.int32).sum())
        loc_seq = jnp.cumsum(tlive.astype(jnp.int32))
        new_pid = (spid != _shift_down(spid, jnp.uint32(_U32_MAX))) & tlive
        new_pid = new_pid.at[0].set(tlive[0])
        pid_seg = jnp.maximum(jnp.cumsum(new_pid.astype(jnp.int32)) - 1, 0)
        pid_first_seq = jax.ops.segment_min(
            jnp.where(tlive, loc_seq, jnp.int32(2**31 - 1)),
            pid_seg,
            num_segments=cap_loc,
        )
        rank_sorted = jnp.where(tlive, loc_seq - pid_first_seq[pid_seg] + 1,
                                0)
        # slot -> per-pid rank (sslot is a permutation of the table), then
        # frame -> rank via each frame's claimed slot.
        rank_by_slot = jnp.zeros((cap_loc,), jnp.int32).at[sslot].set(
            rank_sorted)
        frame_rank = jnp.where(slot >= 0,
                               rank_by_slot[jnp.maximum(slot, 0)], 0)
        loc_ids = (
            jnp.zeros((n * s,), jnp.int32).at[fsrc].set(
                frame_rank, mode="drop").reshape(n, s)
        )
        # Sorted live prefix == the sort path's compacted table (dead
        # entries: pid U32_MAX, hi/lo 0 — identical fills).
        loc_pid = spid[:l_cap]
        loc_hi = shi2[:l_cap]
        loc_lo = slo2[:l_cap]
    else:
        fpid_s, fhi_s, flo_s, fidx = jax.lax.sort(
            (fpid, fhi, flo, fsrc),
            num_keys=3,
            is_stable=True,
        )
        # Liveness is derivable (dead f_cap slots carry the U32_MAX fill
        # pid, real pids are int32-ranged), so it does not ride the sort —
        # the f_cap-lane bitonic sort is this kernel's dominant cost and
        # every dropped array is ~20% of its traffic.
        flive_s = fpid_s != jnp.uint32(_U32_MAX)

        same_loc = (
            (fpid_s == _shift_down(fpid_s, jnp.uint32(_U32_MAX)))
            & (fhi_s == _shift_down(fhi_s, jnp.uint32(0)))
            & (flo_s == _shift_down(flo_s, jnp.uint32(0)))
        )
        same_loc = same_loc.at[0].set(False)
        new_loc = (~same_loc) & flive_s
        new_loc = new_loc.at[0].set(flive_s[0])
        n_locs = new_loc.astype(jnp.int32).sum()

        # Global 1-based location sequence number, constant within a group.
        loc_seq = jnp.cumsum(new_loc.astype(jnp.int32))

        # First loc sequence number within each pid segment -> per-pid rank.
        new_pid = (fpid_s != _shift_down(fpid_s, jnp.uint32(_U32_MAX))) \
            & flive_s
        new_pid = new_pid.at[0].set(flive_s[0])
        pid_seg = jnp.maximum(jnp.cumsum(new_pid.astype(jnp.int32)) - 1, 0)
        pid_first_seq = jax.ops.segment_min(
            jnp.where(flive_s, loc_seq, jnp.int32(2**31 - 1)),
            pid_seg,
            num_segments=n_pad,
        )
        rank = jnp.where(flive_s, loc_seq - pid_first_seq[pid_seg] + 1, 0)

        # Scatter per-frame ranks back to representative-row layout [N, S]
        # (padding entries carry fidx == n*s and drop out).
        loc_ids = (
            jnp.zeros((n * s,), jnp.int32).at[fidx].set(rank, mode="drop")
            .reshape(n, s)
        )

        # Compact the unique locations into the bounded [L_cap] table.
        tgt = jnp.where(new_loc, loc_seq - 1, jnp.int32(l_cap))
        loc_pid = (
            jnp.full((l_cap,), _U32_MAX, jnp.uint32).at[tgt].set(
                fpid_s, mode="drop")
        )
        loc_hi = jnp.zeros((l_cap,), jnp.uint32).at[tgt].set(fhi_s,
                                                             mode="drop")
        loc_lo = jnp.zeros((l_cap,), jnp.uint32).at[tgt].set(flo_s,
                                                             mode="drop")

    # ---- 4. mapping join --------------------------------------------------
    # rank_le[q] = number of mapping rows with key <= (pid, addr); candidate
    # row = rank_le - 1. Branchless binary search, all queries in lockstep.
    steps = max(1, math.ceil(math.log2(m_pad + 1)))

    def body(_, lohi):
        lo_b, hi_b = lohi
        cont = lo_b < hi_b
        mid = jnp.minimum((lo_b + hi_b) // 2, m_pad - 1)
        le = _lex_le3(
            map_pid[mid], map_shi[mid], map_slo[mid], loc_pid, loc_hi, loc_lo
        )
        new_lo = jnp.where(le, mid + 1, lo_b)
        new_hi = jnp.where(le, hi_b, mid)
        return jnp.where(cont, new_lo, lo_b), jnp.where(cont, new_hi, hi_b)

    lo_b = jnp.zeros((l_cap,), jnp.int32)
    hi_b = jnp.full((l_cap,), m_pad, jnp.int32)
    lo_b, hi_b = jax.lax.fori_loop(0, steps, body, (lo_b, hi_b))
    cand = lo_b - 1
    safe = jnp.maximum(cand, 0)
    addr_lt_end = (loc_hi < map_ehi[safe]) | (
        (loc_hi == map_ehi[safe]) & (loc_lo < map_elo[safe])
    )
    hit = (cand >= 0) & (map_pid[safe] == loc_pid) & addr_lt_end
    loc_map_row = jnp.where(hit, safe, jnp.int32(-1))

    return (
        n_groups,
        n_locs,
        out_pid,
        depth,
        values,
        loc_ids,
        loc_pid,
        loc_hi,
        loc_lo,
        loc_map_row,
    )


def shadow_compare(device_profiles, cpu_profiles) -> bool:
    """A/B correctness gate between two aggregations of the SAME window
    (the device-health registry's shadow-window promotion check,
    runtime/device_health.py — the same invariants the bench's A/B
    phases assert): per pid, total sample mass and unique-stack count
    must agree, order-insensitively. A backend that answers promptly but
    WRONGLY (a half-reset dict table after a wedge, a corrupted transfer)
    fails here and stays demoted."""
    def digest(profiles):
        return {int(p.pid): (int(p.total()), int(len(p.values)))
                for p in profiles}

    return digest(device_profiles) == digest(cpu_profiles)


def _coalesce_snapshot_rows(snapshot: WindowSnapshot) -> WindowSnapshot:
    """Fold rows that are EXACT duplicates in everything the kernel
    consumes — (pid, user_len, kernel_len, full padded stack row) — into
    one row with summed counts, in first-occurrence order (capture/
    formats.py fold_rows_first_seen; docs/perf.md "ingest wall").
    Cross-tid repetition is the common source: a 100-thread service
    hands the drain one row per (pid, tid, stack) but the kernel keys
    on (pid, stack), so the fold shrinks the padded upload and every
    sort lane behind it. Identity-preserving by construction — the
    kernel's own dedup would have merged exactly these rows (full-row
    compare), summing the same counts; tids are not packed at all."""
    n = len(snapshot)
    if n < 2:
        return snapshot
    rec = np.empty((n, STACK_SLOTS + 1), np.uint64)
    # pid fits 32 bits, user/kernel lens fit 8 each: one header word.
    rec[:, 0] = (snapshot.pids.astype(np.uint64) << np.uint64(32)) \
        | (snapshot.user_len.astype(np.uint64) << np.uint64(8)) \
        | snapshot.kernel_len.astype(np.uint64)
    rec[:, 1:] = snapshot.stacks
    folded = fold_rows_first_seen(
        np.ascontiguousarray(rec).view(
            np.dtype((np.void, (STACK_SLOTS + 1) * 8))).ravel(),
        snapshot.counts)
    if folded is None:
        return snapshot
    rep, _inv, weights = folded
    return dataclasses.replace(
        snapshot, pids=snapshot.pids[rep], tids=snapshot.tids[rep],
        counts=weights, user_len=snapshot.user_len[rep],
        kernel_len=snapshot.kernel_len[rep], stacks=snapshot.stacks[rep])


def pack_window_inputs(snapshot: WindowSnapshot, l_cap: int | None = None):
    """Pad a WindowSnapshot into the kernel's uint32 operand layout.

    Returns (host_arrays, dims): the 12 kernel operands as host numpy
    arrays, and the static shape bucket {n_pad, l_cap, m_pad}. Single
    source of truth for the layout — used by TPUAggregator.aggregate, the
    benchmark, and the driver entry point.
    """
    n = len(snapshot)
    n_pad = _next_pow2(max(1, n))
    table = snapshot.mappings
    m = len(table)
    m_pad = max(1, _next_pow2(m))

    # Counts ride int32 lanes on device; guard the whole window's total (an
    # upper bound on any merged group's sum) before the astype below wraps.
    if int(snapshot.counts.sum()) >= 2**31:
        raise ValueError("window sample total exceeds int32")
    # The kernel uses pid == U32_MAX as its dead-row/dead-frame sentinel
    # (liveness is derived from it, not carried through the sort). pid -1
    # (perf's unattributable context) would alias it after the uint32
    # cast and silently lose that profile — reject it loudly here; the
    # capture layer attributes samples to real tgids.
    if n and int(snapshot.pids.min()) < 0:
        raise ValueError("negative pid in snapshot (would alias the "
                         "kernel's dead-row sentinel)")

    pid = np.full(n_pad, _U32_MAX, np.uint32)
    pid[:n] = snapshot.pids.astype(np.uint32)
    cnt = np.zeros(n_pad, np.int32)
    cnt[:n] = snapshot.counts.astype(np.int32)
    ulen = np.zeros(n_pad, np.int32)
    ulen[:n] = snapshot.user_len
    klen = np.zeros(n_pad, np.int32)
    klen[:n] = snapshot.kernel_len
    shi = np.zeros((n_pad, STACK_SLOTS), np.uint32)
    slo = np.zeros((n_pad, STACK_SLOTS), np.uint32)
    shi[:n] = (snapshot.stacks >> np.uint64(32)).astype(np.uint32)
    slo[:n] = snapshot.stacks.astype(np.uint32)
    valid = np.zeros(n_pad, bool)
    valid[:n] = True

    map_pid = np.full(m_pad, _U32_MAX, np.uint32)
    map_shi = np.full(m_pad, _U32_MAX, np.uint32)
    map_slo = np.full(m_pad, _U32_MAX, np.uint32)
    map_ehi = np.zeros(m_pad, np.uint32)
    map_elo = np.zeros(m_pad, np.uint32)
    map_pid[:m] = table.pids.astype(np.uint32)
    map_shi[:m] = (table.starts >> np.uint64(32)).astype(np.uint32)
    map_slo[:m] = table.starts.astype(np.uint32)
    map_ehi[:m] = (table.ends >> np.uint64(32)).astype(np.uint32)
    map_elo[:m] = table.ends.astype(np.uint32)

    total_frames = int((snapshot.user_len + snapshot.kernel_len).sum())
    if l_cap is None:
        # Exact unique-(pid, frame) count, an upper bound on the kernel's
        # deduplicated location count: every l_cap overflow costs the
        # caller a full recompile (~20-40s on a TPU), while this host
        # count is sub-second even at 1M rows. Vectorized (no per-row
        # Python): col j of row i enumerates that row's live frames.
        depth = (snapshot.user_len.astype(np.int64)
                 + snapshot.kernel_len.astype(np.int64))
        row_idx = np.repeat(np.arange(n, dtype=np.int64), depth)
        col_idx = np.arange(total_frames, dtype=np.int64) - \
            np.repeat(np.cumsum(depth) - depth, depth)
        key = np.empty((total_frames, 2), np.uint64)
        key[:, 0] = snapshot.pids[row_idx].astype(np.uint64)
        key[:, 1] = snapshot.stacks[row_idx, col_idx]
        n_locs = len(np.unique(
            np.ascontiguousarray(key).view(
                np.dtype((np.void, 16))).ravel()))
        l_cap = max(16, _next_pow2(max(1, n_locs)))
    # Frame-compaction buffer: sized from the exact frame count, so the
    # kernel's compaction scatter can never drop a live frame.
    f_cap = max(16, _next_pow2(max(1, total_frames)))

    args = (pid, cnt, ulen, klen, shi, slo, valid,
            map_pid, map_shi, map_slo, map_ehi, map_elo)
    return args, {"n_pad": n_pad, "l_cap": l_cap, "m_pad": m_pad,
                  "f_cap": f_cap}


@dataclasses.dataclass
class TPUAggregator:
    """Aggregation backend running the window kernel on the default JAX
    backend (TPU in production; CPU in tests via JAX_PLATFORMS=cpu).

    The unique-location table is a bounded buffer: the first attempt sizes
    it at next_pow2(total_live_frames / 4) — profiling windows dedup far
    below their frame count — and if the kernel reports n_locs above the
    cap, the window is re-run with the cap doubled. Results are therefore
    always exact; the cap bounds memory, it never truncates.
    """

    name: str = "tpu"

    # Location dedup implementation: "hash" re-expresses the dominant
    # f_cap-lane sort as a hash-table build+probe (the Pallas kernel,
    # aggregator/pallas_probe.py — the full-rebuild/backfill fix, docs/
    # perf.md "sub-RTT close"); "sort" is the proven lax pipeline;
    # "auto" (default) uses hash when Pallas is available and falls back
    # to sort automatically — including at runtime if the hash kernel
    # fails to build/lower on this backend. Output bytes are identical
    # either way (enforced by tests and the bench's close_overlap phase).
    dedup: str = "auto"

    # Unique-location count beyond which the one-shot kernel is the wrong
    # tool (the location dedup sort dominates: ~45 s at the adversarial
    # 26.5 M-location synthetic, docs/perf.md) and the streaming dict
    # aggregator should be used instead. Advisory only — results stay
    # exact either way.
    LOC_WARN_THRESHOLD = 1 << 22
    _loc_warned: bool = False
    _hash_disabled: bool = False

    def _use_hash(self) -> bool:
        if self._hash_disabled or self.dedup == "sort":
            dtel.note_backend("loc_dedup", requested=self.dedup,
                              resolved="lax",
                              fallback=self._hash_disabled)
            return False
        from parca_agent_tpu.aggregator.pallas_probe import pallas_available

        if pallas_available():
            dtel.note_backend("loc_dedup", requested=self.dedup,
                              resolved="pallas", fallback=False)
            return True
        if self.dedup == "hash":
            from parca_agent_tpu.utils.log import get_logger

            get_logger("aggregator.tpu").warn(
                "hash dedup requested but Pallas is unavailable; using "
                "the lax sort kernel")
        self._hash_disabled = True
        # Pallas wanted (auto/hash) but unavailable: the latched
        # fallback the one-hot gauge surfaces.
        dtel.note_backend("loc_dedup", requested=self.dedup,
                          resolved="lax", fallback=True)
        return False

    def aggregate(self, snapshot: WindowSnapshot) -> list[PidProfile]:
        import jax.numpy as jnp

        n = len(snapshot)
        if n == 0:
            return []
        snapshot = _coalesce_snapshot_rows(snapshot)
        table = snapshot.mappings
        host_args, dims = pack_window_inputs(snapshot)
        dev_args = tuple(jnp.asarray(a) for a in host_args)
        use_hash = self._use_hash()

        while True:
            try:
                import time as _time

                from parca_agent_tpu.aggregator.pallas_probe import (
                    default_interpret,
                )

                interp = default_interpret()
                t0 = _time.perf_counter()
                out = _jitted_kernel()(*dev_args, hash_locs=use_hash,
                                       interpret=interp, **dims)
            except Exception as e:  # noqa: BLE001 - hash path only
                if not use_hash:
                    raise
                # Automatic fallback: a Pallas build/lowering failure on
                # this backend degrades to the lax sort kernel — never a
                # lost window, at worst the old speed. Latched so the
                # per-window hot path does not retry a broken lowering.
                self._hash_disabled = True
                use_hash = False
                dtel.note_backend("loc_dedup", resolved="lax",
                                  fallback=True)
                from parca_agent_tpu.utils.log import get_logger

                get_logger("aggregator.tpu").warn(
                    "hash location dedup failed; falling back to the lax "
                    "sort kernel", error=repr(e)[:200])
                continue
            outs = tuple(map(np.asarray, out))
            (n_groups, n_locs, out_pid, depth, values, loc_ids,
             loc_pid, loc_hi, loc_lo, loc_map_row) = outs
            # One observation covers dispatch + fetch (the one-shot
            # kernel is synchronous by design); the jit static key is
            # the shape signature, so every l_cap doubling retry reads
            # as the recompile it really is.
            dtel.record(
                "loc_dedup", _time.perf_counter() - t0,
                shape=(dims["n_pad"], dims["l_cap"], dims["m_pad"],
                       dims["f_cap"], use_hash, interp),
                h2d_bytes=sum(int(a.nbytes) for a in host_args),
                d2h_bytes=sum(int(a.nbytes) for a in outs))
            dtel.note_backend("loc_dedup", interpret=interp)
            if int(n_locs) <= dims["l_cap"]:
                break
            dims["l_cap"] *= 2

        if int(n_locs) > self.LOC_WARN_THRESHOLD and not self._loc_warned:
            # Keyed on the MEASURED unique-location count (known only
            # after the kernel ran), once per aggregator: the per-window
            # hot path must not log every window.
            self._loc_warned = True
            from parca_agent_tpu.utils.log import get_logger

            get_logger("aggregator.tpu").warn(
                "window location entropy is in the one-shot kernel's "
                "adversarial regime; --aggregator dict (the streaming "
                "dictionary) aggregates such windows orders of magnitude "
                "faster", unique_locations=int(n_locs),
                threshold=self.LOC_WARN_THRESHOLD)

        return self._build_profiles(
            snapshot, table,
            int(n_groups), int(n_locs), out_pid, depth, values, loc_ids,
            loc_pid, loc_hi, loc_lo, loc_map_row,
        )

    def _build_profiles(
        self, snapshot, table, n_groups, n_locs, out_pid, depth, values,
        loc_ids, loc_pid, loc_hi, loc_lo, loc_map_row,
    ) -> list[PidProfile]:
        u_pid = out_pid[:n_groups].astype(np.int64)
        u_depth = depth[:n_groups].astype(np.int32)
        u_values = values[:n_groups].astype(np.int64)
        u_loc_ids = loc_ids[:n_groups]

        l_pid = loc_pid[:n_locs].astype(np.int64)
        l_addr = (loc_hi[:n_locs].astype(np.uint64) << np.uint64(32)) | loc_lo[
            :n_locs
        ].astype(np.uint64)
        l_row = loc_map_row[:n_locs]

        l_kernel = l_addr >= np.uint64(KERNEL_ADDR_START)
        # u64 arithmetic + per-pid mapping ranks stay on host. Kernel text
        # is never normalized through the mapping table, even if a mapping
        # (e.g. [vsyscall]) covers it — matches the CPU oracle and the
        # formats.py contract.
        hit = (l_row >= 0) & ~l_kernel
        safe = np.maximum(l_row, 0)
        if len(table):
            l_norm = np.where(hit, l_addr - table.bases[safe], l_addr)
            # Global mapping row -> 1-based rank within its pid (rows are
            # sorted by (pid, start): rank = row - first row of pid's block).
            pid_first_row = np.searchsorted(table.pids, table.pids[safe], "left")
            l_map_id = np.where(hit, safe - pid_first_row + 1, 0).astype(np.int32)
        else:
            l_norm = l_addr.copy()
            l_map_id = np.zeros(n_locs, np.int32)

        # Both tables arrive pid-contiguous (device sort order); split them.
        profiles: list[PidProfile] = []
        stack_bounds = np.flatnonzero(np.diff(u_pid)) + 1
        s_starts = np.concatenate(([0], stack_bounds))
        s_ends = np.concatenate((stack_bounds, [n_groups]))
        loc_starts = np.searchsorted(l_pid, u_pid[s_starts], "left")
        loc_ends = np.searchsorted(l_pid, u_pid[s_starts], "right")

        for i, (lo, hi) in enumerate(zip(s_starts, s_ends)):
            pid = int(u_pid[lo])
            llo, lhi = int(loc_starts[i]), int(loc_ends[i])
            profiles.append(
                PidProfile(
                    pid=pid,
                    stack_loc_ids=u_loc_ids[lo:hi],
                    stack_depths=u_depth[lo:hi],
                    values=u_values[lo:hi],
                    loc_address=l_addr[llo:lhi],
                    loc_normalized=l_norm[llo:lhi].astype(np.uint64),
                    loc_mapping_id=l_map_id[llo:lhi],
                    loc_is_kernel=l_kernel[llo:lhi],
                    mappings=_pid_mappings(table, pid),
                    period_ns=snapshot.period_ns,
                    time_ns=snapshot.time_ns,
                    duration_ns=snapshot.window_ns,
                )
            )
        return profiles
