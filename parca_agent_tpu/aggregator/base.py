"""Aggregator interface and output contracts.

An Aggregator turns one WindowSnapshot into per-PID profile tables:
deduplicated stacks with summed counts, deduplicated locations with
normalized addresses and mapping joins. Everything downstream (symbolization,
labeling, pprof encoding) consumes these array-shaped tables — no per-sample
Python objects exist anywhere on the hot path, which is what lets the TPU
backend hand its device arrays straight through.

Output semantics mirror the reference hot loop (pkg/profiler/cpu/cpu.go:
634-718): group samples per PID, dedup identical stacks by summing counts,
dedup addresses into per-profile locations, normalize user-space addresses to
object-relative form, and attach the PID's mappings with 1-based pprof ids.
Two deliberate deviations, both semantics-preserving:

One deliberate deviation, semantics-preserving: location/sample ordering
is sorted (deterministic) rather than first-seen, since pprof consumers
treat these as sets. Normalization is `addr - base` with the ELF-derived
base carried per mapping row (pprof GetBase semantics, reference
pkg/objectfile/object_file.go:156-238); rows with no readable ELF fall
back to base = start - offset (file-offset normalization).
"""

from __future__ import annotations

import dataclasses
from typing import Protocol, Sequence

import numpy as np

from parca_agent_tpu.capture.formats import WindowSnapshot


@dataclasses.dataclass(frozen=True)
class ProfileMapping:
    """One executable mapping of the profiled process (pprof Mapping)."""

    id: int            # 1-based within the profile
    start: int
    end: int
    offset: int
    path: str = ""
    build_id: str = ""
    # Normalization base (pprof GetBase semantics): object virtual address
    # = runtime address - base. Defaults to start - offset (file-offset
    # normalization) when no ELF-derived base is known; they differ by
    # p_vaddr - p_offset of the exec segment (reference
    # pkg/objectfile/object_file.go:156-238).
    base: int | None = None

    def __post_init__(self):
        if self.base is None:
            object.__setattr__(
                self, "base", (self.start - self.offset) % 2**64)


@dataclasses.dataclass
class PidProfile:
    """Aggregated profile tables for one PID over one window."""

    pid: int
    # Samples: S deduplicated stacks.
    stack_loc_ids: np.ndarray   # int32 [S, STACK_SLOTS]; 1-based loc ids, 0 pad
    stack_depths: np.ndarray    # int32 [S]
    values: np.ndarray          # int64 [S]; sample counts
    # Locations: L deduplicated addresses.
    loc_address: np.ndarray     # uint64 [L]; raw runtime address
    loc_normalized: np.ndarray  # uint64 [L]; object-relative (user) or raw (kernel)
    loc_mapping_id: np.ndarray  # int32 [L]; 1-based into mappings, 0 = unmapped
    loc_is_kernel: np.ndarray   # bool [L]
    mappings: list[ProfileMapping]
    period_ns: int
    time_ns: int
    duration_ns: int
    # Symbolization output (filled by parca_agent_tpu.symbolize):
    # functions[i] = (name, system_name, filename, start_line);
    # loc_lines[l] = [(function_id_1based, line_number), ...]
    functions: list[tuple[str, str, str, int]] = dataclasses.field(default_factory=list)
    loc_lines: list[list[tuple[int, int]]] | None = None

    @property
    def n_samples(self) -> int:
        return len(self.values)

    @property
    def n_locations(self) -> int:
        return len(self.loc_address)

    def total(self) -> int:
        return int(self.values.sum())

    def check(self) -> None:
        """Internal-consistency assertions (used by tests and fixtures)."""
        s = self.stack_loc_ids.shape[0]
        assert self.stack_depths.shape == (s,) and self.values.shape == (s,)
        ls = self.n_locations
        assert self.loc_normalized.shape == (ls,)
        assert self.loc_mapping_id.shape == (ls,)
        assert self.loc_is_kernel.shape == (ls,)
        if s:
            assert int(self.stack_loc_ids.max()) <= ls
            idx = np.arange(self.stack_loc_ids.shape[1])[None, :]
            live = idx < self.stack_depths[:, None]
            assert np.all(self.stack_loc_ids[live] >= 1)
            assert np.all(self.stack_loc_ids[~live] == 0)
        if ls:
            assert int(self.loc_mapping_id.max(initial=0)) <= len(self.mappings)


WindowProfiles = Sequence[PidProfile]


class Aggregator(Protocol):
    """Aggregation backend: one snapshot in, per-PID profile tables out."""

    name: str

    def aggregate(self, snapshot: WindowSnapshot) -> list[PidProfile]:
        ...
