"""Data-parallel dict aggregation: the stack dictionary sharded over a
device mesh (SURVEY.md section 2.12 — sharding pids/stack-ids across TPU
cores via shard_map inside the aggregation, not only at fleet merge).

Design (the TPU-native analog of the reference's 3-way unwind-table shard
partition, pkg/profiler/cpu/maps.go:40-43, applied to the hot table):

  * Every key has a HOME SHARD, h2 % n_shards; shard d owns a private
    sub-table of capacity/n_shards slots, and the open-addressing probe
    (h1-based linear chain) runs entirely within the home sub-table. The
    device table is [n_shards, cap_s, 4] sharded over axis 0 of the mesh.
  * The packed feed buffer is PARTITIONED host-side by home shard (the
    home hash h2 % n_shards is already computed for every row): shard d
    receives only its own rows, padded to a shared quarter-pow2 lane
    count sized to the max per-shard row count (~total/N for a uniform
    hash), plus each row's original packed-buffer position so miss
    reports need no reverse mapping. Probe work, H2D bytes, and table
    memory all split N ways — an earlier design replicated the buffer
    and masked, which split memory but MULTIPLIED probe FLOPs by N.
  * The accumulator is PARTIAL per shard ([n_shards, id_cap], sharded):
    shard d accumulates only its keys' counts under the global dense stack
    ids. Window close is ONE collective: psum over the shard axis, then
    the same pack-to-uint{4,8,16} + overflow sideband as the single-chip
    close, fetched once.

The host mirror reuses DictAggregator's arrays with slot = shard * cap_s +
within-shard index, so insertion, rotation, eviction, sketch degradation,
and the unreachable-key prefilter all inherit unchanged; only the slot
placement rule and the four device dispatch hooks differ.
"""

from __future__ import annotations

import functools

import numpy as np

from parca_agent_tpu.aggregator.dict import (
    _PROBES,
    DictAggregator,
    make_close,
)
from parca_agent_tpu.parallel.mesh import FLEET_AXIS, fleet_mesh
from parca_agent_tpu.runtime import device_telemetry as dtel


def route_h2(h2: np.ndarray, pids, shard_of_pid, n_shards: int
             ) -> np.ndarray:
    """Rewrite each row's h2 so ``h2 % n_shards == shard_of_pid(pid)``
    while keeping the rest of the hash: the home-shard rule (everywhere
    ``h2 % n_shards`` is consulted — the host mirror's ``_home_shard``
    and the feed partition) then routes by TENANT instead of by raw
    hash, so one tenant's registry growth lands on its home sub-table
    and parallelizes across chips per tenant (docs/robustness.md
    "multi-tenant admission"). Key identity stays per-(stack, pid):
    every row of a pid carries the same replacement residue, so equal
    stacks still collide into one key and different pids already
    differed in h1/h3. Exact for any n_shards: computed in int64 with
    the top partial block stepped down one stride instead of wrapping
    (a uint32 wrap would break the residue for non-power-of-two shard
    counts)."""
    n = int(n_shards)
    upids, inverse = np.unique(np.asarray(pids, np.int64),
                               return_inverse=True)
    residues = np.array([int(shard_of_pid(int(p))) % n for p in upids],
                        np.int64)
    out = (np.asarray(h2, np.uint32).astype(np.int64) // n) * n \
        + residues[inverse]
    out = np.where(out > 0xFFFFFFFF, out - n, out)
    return out.astype(np.uint32)


@functools.lru_cache(maxsize=8)
def _sharded_feed_program(mesh, n_shards: int, cap_s: int, id_cap: int,
                          n_pad_s: int):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    def node_fn(table, acc, packed, reset):
        # table [1, cap_s, 4]; acc [1, id_cap]; packed [1, 5, n_pad_s] —
        # THIS shard's rows only (host-partitioned by home shard), rows
        # being (h1, h2, h3, count, original packed-buffer position).
        t = table[0]
        a = jnp.where(reset != 0, 0, acc[0])
        h1, h2, h3 = packed[0, 0], packed[0, 1], packed[0, 2]
        cnt = packed[0, 3].astype(jnp.int32)
        orig = packed[0, 4].astype(jnp.int32)
        live = cnt > 0  # pad lanes carry count 0
        mask = jnp.uint32(cap_s - 1)

        def probe(k, state):
            found_id, done = state
            idx = ((h1 + jnp.uint32(k)) & mask).astype(jnp.int32)
            row = t[idx]
            occ = row[:, 3] > 0
            hit = occ & (row[:, 0] == h1) & (row[:, 1] == h2) \
                & (row[:, 2] == h3)
            stop = hit | ~occ
            found_id = jnp.where(hit & ~done,
                                 row[:, 3].astype(jnp.int32) - 1, found_id)
            return found_id, done | stop

        # The probe reads the node-sharded table, so the loop carry is
        # node-varying; mark the (replicated-literal) initial carry to
        # match.
        found_id = jax.lax.pcast(jnp.full(h1.shape, -1, jnp.int32),
                                 (FLEET_AXIS,), to="varying")
        done = jax.lax.pcast(jnp.zeros(h1.shape, bool),
                             (FLEET_AXIS,), to="varying")
        found_id, _ = jax.lax.fori_loop(0, _PROBES, probe, (found_id, done))

        hit = (found_id >= 0) & live
        a = a.at[jnp.where(hit, found_id, id_cap)].add(
            jnp.where(live, cnt, 0), mode="drop")
        miss = live & ~hit
        mtgt = jnp.where(miss, jnp.cumsum(miss.astype(jnp.int32)) - 1,
                         jnp.int32(n_pad_s))
        # Report ORIGINAL packed-buffer positions (the host partitioned
        # the rows, so local lane indices would be meaningless to it).
        miss_rows = jnp.full((n_pad_s,), -1, jnp.int32).at[mtgt].set(
            orig, mode="drop")
        n_miss = miss.astype(jnp.int32).sum()
        return a[None], n_miss[None], miss_rows[None]

    fn = jax.shard_map(
        node_fn,
        mesh=mesh,
        in_specs=(P(FLEET_AXIS, None, None), P(FLEET_AXIS, None),
                  P(FLEET_AXIS, None, None), P()),
        out_specs=(P(FLEET_AXIS, None), P(FLEET_AXIS), P(FLEET_AXIS, None)),
    )
    return jax.jit(fn, donate_argnums=(1,))


@functools.lru_cache(maxsize=24)
def _sharded_close_program(mesh, n_shards: int, id_cap: int, n_fetch: int,
                           width: int, n_over_buf: int):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    pack = make_close(id_cap, n_fetch, width, n_over_buf)

    def node_fn(acc):
        total = jax.lax.psum(acc[0], FLEET_AXIS)  # [id_cap] on every shard
        # Every shard packs the same psum'd total. This is deliberate,
        # not waste: under SPMD lockstep all shards run the pack
        # SIMULTANEOUSLY, so close wall-clock equals one shard packing;
        # serializing the pack onto one shard would idle the rest for the
        # same latency while adding a broadcast. The host fetches one
        # shard's copy (one D2H of the packed buffer, not N).
        return pack(total)[None]

    fn = jax.shard_map(node_fn, mesh=mesh, in_specs=(P(FLEET_AXIS, None),),
                       out_specs=P(FLEET_AXIS, None))
    return jax.jit(fn)


class ShardedDictAggregator(DictAggregator):
    """DictAggregator with the device table and probe work sharded over an
    n-device mesh. Semantics (exact counts, miss/insert protocol, sketch
    degradation, rotation) are identical to the single-chip dict; only
    placement and dispatch differ. aggregate()/window_counts run through
    the streaming feed/close protocol (closing any open window first)."""

    name = "sharded-dict"

    def __init__(self, capacity: int = 1 << 21, id_cap: int | None = None,
                 mesh=None, n_shards: int | None = None,
                 shard_of_pid=None, **kw):
        if mesh is None:
            import jax

            mesh = fleet_mesh(n_shards or len(jax.devices()))
        self._mesh = mesh
        self._n_shards = mesh.devices.size
        if capacity % self._n_shards:
            raise ValueError("capacity must divide by the shard count")
        cap_s = capacity // self._n_shards
        if cap_s & (cap_s - 1):
            raise ValueError("per-shard capacity must be a power of two")
        self._cap_s = cap_s
        # Optional pid -> home-shard router (the admission layer's
        # tenant placement, runtime/admission.py shard_of): with it set,
        # hash_rows rewrites h2's shard residue per pid (route_h2) so
        # both the host mirror's _home_shard and the feed partition
        # place by tenant. Must be stable per pid across windows — a
        # re-route would mint a second key for the same stack (harmless
        # mass-wise, wasteful registry-wise; rotation reclaims it).
        self._shard_of_pid = shard_of_pid
        # n_pad_s -> [buf_a, buf_b, flip]: double-buffered pack scratch
        # (pack N+1 must not overwrite the buffer dispatch N may still
        # be reading through an async H2D).
        self._part_bufs: dict[int, list] = {}
        super().__init__(capacity=capacity, id_cap=id_cap, **kw)
        # Delta-fetch touch tracking is single-chip for now: the sharded
        # close psums partial accumulators across the mesh and fetches
        # the packed full prefix once; its feed program carries no touch
        # flags. Double-buffering (the flip) inherits unchanged.
        self._blk = 0
        self._n_blocks = 0
        self._touch = None
        self._touch_spare = None

    def set_shard_router(self, shard_of_pid) -> None:
        """Install the pid router (tenant placement) at wiring time —
        BEFORE the first feed: keys already inserted under the raw-hash
        rule keep their placement (rotation reclaims them), so a mid-run
        install only fragments the registry, it never corrupts it."""
        self._shard_of_pid = shard_of_pid

    def hash_rows(self, snapshot):
        h1, h2, h3 = super().hash_rows(snapshot)
        return h1, self._route_hashes(h1, h2, h3, snapshot.pids), h3

    def _route_hashes(self, h1, h2, h3, pids):
        # The single source of the h2 shard-residue rewrite: hash_rows
        # above and every externally-computed triple (capture-carried
        # hashes, the feed's post-fold representative hashing) route
        # through here, so identity stays bit-identical regardless of
        # where the triple was computed.
        if self._shard_of_pid is not None:
            return route_h2(h2, pids, self._shard_of_pid, self._n_shards)
        return h2

    # -- host-mirror placement: probe within the key's home sub-table -------

    def _home_shard(self, key: tuple) -> int:
        return key[1] % self._n_shards

    def _shard_free(self) -> np.ndarray:
        """Free slots per shard sub-table (occupancy is per home shard,
        which the GLOBAL capacity check cannot see: a skewed h2
        distribution can fill one sub-table while the table as a whole is
        half empty)."""
        occ = self._occ.reshape(self._n_shards, self._cap_s)
        return self._cap_s - occ.sum(axis=1)

    def _check_shard_demand(self, demand: np.ndarray) -> None:
        """Shared raise tail of both insert-room checks (scalar and
        vectorized): per-sub-table new-key demand vs free slots."""
        free = self._shard_free()
        over = np.flatnonzero(demand > free)
        if len(over):
            s = int(over[0])
            raise RuntimeError(
                f"shard sub-table {s} exhausted ({int(demand[s])} new keys "
                f"vs {int(free[s])} free of {self._cap_s} slots); construct "
                f"with a larger capacity or overflow='sketch'")

    def _check_insert_room(self, classified, seen_batch) -> None:
        if self._overflow != "raise" or not seen_batch:
            return  # sketch mode degrades per key in _try_insert_slot
        demand = np.zeros(self._n_shards, np.int64)
        for key in seen_batch:
            demand[self._home_shard(key)] += 1
        self._check_shard_demand(demand)

    def _try_insert_slot(self, key: tuple) -> int | None:
        base = self._home_shard(key) * self._cap_s
        mask = self._cap_s - 1
        idx = key[0] & mask
        for _ in range(self._cap_s):
            if not self._occ[base + idx]:
                return base + idx
            idx = (idx + 1) & mask
        return None  # sub-table full: caller degrades to the sketch

    def _host_insert_slot(self, key: tuple) -> int:
        # Reached only from rotation rebuild (survivor re-insertion, which
        # can never overflow a sub-table: survivors fit where they sat)
        # and from _try_insert_slot above via the base class.
        slot = self._try_insert_slot(key)
        if slot is None:
            raise RuntimeError("shard sub-table unexpectedly full")
        return slot

    def _chain_dist(self, key: tuple, slot: int) -> int:
        mask = self._cap_s - 1
        within = slot - self._home_shard(key) * self._cap_s
        return (within - (key[0] & mask)) & mask

    def _probe_geometry_vec(self, h1u, h2u):
        # The vectorized settle's probe geometry: chains live entirely
        # within the key's home sub-table (base = home * cap_s), exactly
        # as _try_insert_slot/_chain_dist walk them per key.
        mask = self._cap_s - 1
        base = (h2u.astype(np.int64) % self._n_shards) * self._cap_s
        return base, h1u.astype(np.int64) & mask, mask

    def _check_insert_room_vec(self, h1n, h2n, h3n) -> None:
        # Vectorized twin of _check_insert_room: pre-mutation,
        # raise-mode only (sketch mode degrades per key via the
        # placement overrun fallback); the raise tail is shared.
        if self._overflow != "raise" or not len(h2n):
            return
        self._check_shard_demand(
            np.bincount(h2n.astype(np.int64) % self._n_shards,
                        minlength=self._n_shards))

    # -- device dispatch ------------------------------------------------------

    def _ensure_device(self) -> None:
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        if self._dev is None:
            table = np.zeros((self._cap, 4), np.uint32)
            table[:, 0] = self._h1
            table[:, 1] = self._h2
            table[:, 2] = self._h3
            table[:, 3] = np.where(self._occ, self._ids + 1, 0).astype(
                np.uint32)
            table = table.reshape(self._n_shards, self._cap_s, 4)
            self._dev = jax.device_put(
                table, NamedSharding(self._mesh, P(FLEET_AXIS, None, None)))

    def _new_acc(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        return jax.device_put(
            jnp.zeros((self._n_shards, self._id_cap), jnp.int32),
            NamedSharding(self._mesh, P(FLEET_AXIS, None)))

    def _partition_packed(self, packed: np.ndarray) -> np.ndarray:
        """Split the [4, n_pad] packed buffer into [n_shards, 5, n_pad_s]
        by home shard (h2 % n_shards), appending each row's original
        position as channel 4. Pad lanes are zero (count 0 = dead).

        One vectorized scatter per channel (the per-shard Python slice
        loop this replaces walked the shard axis serially — at 8+ shards
        the loop overhead was a visible slice of the per-drain host
        cost), into a DOUBLE-BUFFERED scratch: the previous drain's
        partition buffer stays untouched while this one packs, so
        pack(N+1) can overlap dispatch(N)'s H2D reads even on backends
        that consume host memory asynchronously."""
        cnt = packed[3]
        live = np.flatnonzero(cnt > 0)
        shard = (packed[1, live] % np.uint32(self._n_shards)).astype(np.int64)
        # Stable sort keeps ascending packed order within each shard, so
        # miss (and therefore id-assignment) order is deterministic.
        order = np.argsort(shard, kind="stable")
        rows = live[order]
        per = np.bincount(shard, minlength=self._n_shards)
        n_max = max(int(per.max(initial=0)), 1)
        # Quarter-pow2 padding (16, 20, 24, 28, 32, 40, ...): full pow2
        # rounding wastes up to 2x probe lanes per shard (a near-uniform
        # hash puts ~total/N rows on each shard, just past a pow2
        # boundary), while still bounding distinct compiled shapes to
        # ~4 per octave of drain size.
        if n_max <= 16:
            n_pad_s = 16
        else:
            step = 1 << max(2, n_max.bit_length() - 3)
            n_pad_s = -(-n_max // step) * step
        # Reuse TWO buffers per lane count, alternating (same rationale
        # as the base feed's _feed_bufs — fresh multi-MB zeroed
        # allocations per drain are pure churn — plus the double-buffer
        # contract above); quarter-pow2 lane sizing bounds the distinct
        # shapes to ~4 per octave of drain size. LRU, not
        # evict-smallest: quarter-pow2 sizing yields ~4 shapes per
        # octave (vs pow2's 1), so a size-ordered policy both thrashes
        # when drains jitter across an octave boundary and pins large
        # stale buffers forever after a burst. 8 recently-used shape
        # slots track the actual working set; re-insertion on hit keeps
        # dict order = recency order.
        pair = self._part_bufs.pop(n_pad_s, None)
        if pair is None:
            if len(self._part_bufs) >= 8:
                self._part_bufs.pop(next(iter(self._part_bufs)))  # LRU
            pair = [None, None, 0]
        flip = pair[2]
        pair[2] = flip ^ 1
        out = pair[flip]
        if out is None:
            out = pair[flip] = np.zeros((self._n_shards, 5, n_pad_s),
                                        np.uint32)
        else:
            out[:] = 0
        self._part_bufs[n_pad_s] = pair
        bounds = np.zeros(self._n_shards + 1, np.int64)
        np.cumsum(per, out=bounds[1:])
        shard_sorted = shard[order]
        lane = np.arange(len(rows), dtype=np.int64) - bounds[shard_sorted]
        for c in range(4):
            out[shard_sorted, c, lane] = packed[c, rows]
        out[shard_sorted, 4, lane] = rows.astype(np.uint32)
        return out

    def _device_put_sharded(self, part: np.ndarray):
        """Ship the partitioned batch: one per-shard device_put per mesh
        device, assembled into the global sharded array — the transfers
        are dispatched back-to-back WITHOUT waiting on each other, so
        the sub-batches travel concurrently instead of through one
        serially-staged global copy. Counted fallback to the single
        staged device_put on any runtime refusal (layouts, committed
        device sets) — never a lost feed."""
        import time as _time

        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        sharding = NamedSharding(self._mesh, P(FLEET_AXIS, None, None))
        t0 = _time.perf_counter()
        try:
            devs = list(self._mesh.devices.reshape(-1))
            shards = [jax.device_put(part[s:s + 1], d)
                      for s, d in enumerate(devs)]
            out = jax.make_array_from_single_device_arrays(
                part.shape, sharding, shards)
        except Exception as e:  # noqa: BLE001 - counted fallback
            self.stats["shard_put_fallbacks"] = \
                self.stats.get("shard_put_fallbacks", 0) + 1
            from parca_agent_tpu.utils.log import get_logger

            get_logger("aggregator.sharded").warn(
                "per-shard concurrent device_put failed; using the "
                "staged global copy", error=repr(e)[:200])
            out = jax.device_put(part, sharding)
        dtel.record("shard_put", _time.perf_counter() - t0,
                    shape=tuple(part.shape), h2d_bytes=part.nbytes)
        return out

    # palint: capture-path — the sharded override of the dispatch-only
    # feed (the base seed's call graph stops at file scope, so the
    # override seeds itself). Device state (one line, no continuations):
    # palint: device-state: _dev, _acc, _touch, _acc_spare, _touch_spare
    def _feed_dispatch_async(self, packed: np.ndarray, n_pad: int,
                             reset: int):
        import time as _time

        part = self._partition_packed(packed)
        prog = _sharded_feed_program(self._mesh, self._n_shards, self._cap_s,
                                     self._id_cap, part.shape[2])
        dev_packed = self._device_put_sharded(part)
        acc = self._acc
        self._acc = None  # donated: invalid if the call throws
        t0 = _time.perf_counter()
        acc, n_miss, miss_rows = prog(self._dev, acc, dev_packed,
                                      np.uint32(reset))
        dtel.record("feed_probe", _time.perf_counter() - t0,
                    shape=("sharded", self._n_shards, self._cap_s,
                           self._id_cap, part.shape[2]))
        self._acc = acc
        return (n_miss, miss_rows)

    # palint: sync-ok — the sharded twin of the base settle boundary.
    def _settle_dispatch(self, handle) -> np.ndarray:
        n_miss, miss_rows = handle
        per_shard = np.asarray(n_miss)  # device sync point
        if not per_shard.any():
            return np.empty(0, np.int64)
        # Each row has exactly one home shard, so the per-shard miss lists
        # are disjoint; concatenate them (original-position indices).
        rows_all = np.asarray(miss_rows)
        return np.concatenate([
            rows_all[s, : int(k)] for s, k in enumerate(per_shard) if k
        ]).astype(np.int64)

    def _close_pack_dispatch(self, acc, n_fetch: int, width: int,
                             n_over_buf: int):
        import time as _time

        prog = _sharded_close_program(self._mesh, self._n_shards,
                                      self._id_cap, n_fetch, width,
                                      n_over_buf)
        t0 = _time.perf_counter()
        out = prog(acc)[0]  # every shard holds the same packed copy
        dtel.record("close_pack", _time.perf_counter() - t0,
                    shape=("sharded", self._n_shards, self._id_cap,
                           n_fetch, width, n_over_buf))
        return out

    def _close_pack_collect(self, out_dev) -> np.ndarray:
        import time as _time

        t0 = _time.perf_counter()
        host = np.asarray(out_dev)
        # Execute-only, same reasoning as the base collect: the compile
        # truth lives in the pack signature, not the fetched shape.
        dtel.record("close_fetch", _time.perf_counter() - t0,
                    d2h_bytes=host.nbytes)
        return host

    def _dev_scatter(self, slots: np.ndarray, vals: np.ndarray) -> None:
        import jax.numpy as jnp

        s_idx = (slots // self._cap_s).astype(np.int32)
        w_idx = (slots % self._cap_s).astype(np.int32)
        self._dev = self._dev.at[jnp.asarray(s_idx), jnp.asarray(w_idx)].set(
            jnp.asarray(vals))
        dtel.transfer("miss_settle", "h2d", 8 * len(slots) + vals.nbytes)

