"""Pluggable window aggregation (the reference's hot loop, re-designed).

The reference folds drained stack counts into per-PID profiles one map entry
at a time inside `obtainProfiles` (reference pkg/profiler/cpu/cpu.go:505-718).
Here aggregation is a pluggable `Aggregator` with four implementations:

  NaiveAggregator  dict-based spec oracle; the executable definition of the
                   semantics, used only in tests
  CPUAggregator    vectorized numpy path; the default backend
  TPUAggregator    stateless batched JAX/XLA path over all PIDs at once
                   (radix hash + sort + segment reductions)
  DictAggregator   the flagship: stateful device-resident stack dictionary;
                   a steady-state window is one batched lookup+count kernel
                   (aggregator/dict.py)

TPUAggregator and DictAggregator import jax lazily; CPU-only deployments
never pay for it.
"""

from parca_agent_tpu.aggregator.base import (  # noqa: F401
    Aggregator,
    PidProfile,
    ProfileMapping,
    WindowProfiles,
)
from parca_agent_tpu.aggregator.cpu import CPUAggregator, NaiveAggregator  # noqa: F401
