"""Pallas TPU re-expression of the hash-table probe loops.

Two kernels, both with an ``interpret=True`` CPU path (exercised by
tier-1 tests on every CPU-only run) and automatic fallback to the
existing lax implementations when Pallas is unavailable or fails to
build (docs/perf.md "sub-RTT close"):

  * :func:`make_batch_probe` — the stack dictionary's bounded linear
    probe (``aggregator/dict.py`` ``make_feed``'s inner ``fori_loop``):
    batched lookup of every row's 96-bit identity against the resident
    ``[cap, 4]`` table. As a single Pallas kernel the 16 probe steps
    fuse into one pass over the row block — XLA's lax lowering
    materializes a full gathered ``[n, 4]`` intermediate per probe step,
     16x the traffic the probe actually needs.
  * :func:`make_loc_table_builder` — the one-shot batch kernel's
    location dedup re-expressed as hash-table build + probe
    (``aggregator/tpu.py``): every live frame's (pid, addr_hi, addr_lo)
    key probes an open-addressing table, claims empty slots (min-lane
    arbitration, deterministic), and records its slot. This replaces
    the f_cap-lane bitonic sort that dominates the stateless kernel
    (~45 s at 26.5 M unique locations, docs/perf.md): the sort that
    remains downstream runs over the cap_l unique TABLE entries, not
    over every frame.

Exactness: identity is compared on the full key in both kernels (the
dict's 96-bit triple; the raw 96-bit (pid, hi, lo) for locations), and
the callers re-sort the deduplicated outputs into the lax paths' exact
output order — byte-identical pprof, enforced by tests and the bench's
``close_overlap`` phase.

Both kernels run whole-array (grid=1) with the operands in
compiler-chosen memory; on a real TPU backend Mosaic fuses the probe
loop into one kernel, and any lowering failure (old jaxlib, unsupported
gather shape) is caught by the callers' fallback — never a wrong
answer, at worst the lax speed.
"""

from __future__ import annotations

import functools

_U32_MAX = 0xFFFFFFFF


@functools.lru_cache(maxsize=1)
def pallas_available() -> bool:
    """True when jax.experimental.pallas imports AND a tiny interpret-mode
    probe round-trips correctly. Cached: the check is per-process."""
    try:
        import numpy as np

        probe = make_batch_probe(8, probes=2, interpret=True)
        import jax.numpy as jnp

        table = np.zeros((8, 4), np.uint32)
        table[3] = (3, 1, 2, 5)  # id 4 at its home slot
        got = np.asarray(probe(jnp.asarray(table),
                               jnp.asarray(np.array([3], np.uint32)),
                               jnp.asarray(np.array([1], np.uint32)),
                               jnp.asarray(np.array([2], np.uint32))))
        return int(got[0]) == 4
    except Exception:  # noqa: BLE001 - any failure means "not available"
        return False


def default_interpret() -> bool:
    """Interpret mode everywhere except a real TPU backend: the CPU
    backend (tests, fallback hosts) runs the kernels through the Pallas
    interpreter, a TPU compiles them via Mosaic."""
    try:
        import jax

        return jax.default_backend() != "tpu"
    except Exception:  # noqa: BLE001 - no backend at all: interpret
        return True


def make_batch_probe(cap: int, probes: int, interpret: bool | None = None):
    """Pallas twin of the dict feed's probe loop: returns
    ``probe(table_u32[cap,4], h1, h2, h3) -> found_id int32`` with
    identical semantics (hit => stored id, miss/empty-slot stop => -1;
    chains past the probe bound stay misses, absorbed host-side)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from parca_agent_tpu.runtime import device_telemetry as dtel

    if interpret is None:
        interpret = default_interpret()
    # The interpret decision is made here, so the flight recorder's
    # per-kernel interpret gauge is latched here (free when telemetry
    # is off; the call sites latch requested/resolved/fallback).
    dtel.note_backend("feed_probe", interpret=interpret)

    def kernel(table_ref, h1_ref, h2_ref, h3_ref, out_ref):
        # Scalar constants are built INSIDE the kernel: a jnp scalar
        # closed over from the wrapper would be a captured constant,
        # which pallas_call rejects.
        mask = jnp.uint32(cap - 1)
        h1 = h1_ref[:]
        h2 = h2_ref[:]
        h3 = h3_ref[:]

        def body(k, state):
            found_id, done = state
            idx = ((h1 + jnp.uint32(k)) & mask).astype(jnp.int32)
            r_h1 = table_ref[idx, 0]
            r_h2 = table_ref[idx, 1]
            r_h3 = table_ref[idx, 2]
            r_id = table_ref[idx, 3]
            occ = r_id > 0
            hit = occ & (r_h1 == h1) & (r_h2 == h2) & (r_h3 == h3)
            stop = hit | ~occ
            found_id = jnp.where(hit & ~done,
                                 r_id.astype(jnp.int32) - 1, found_id)
            return found_id, done | stop

        found_id = jnp.full(h1.shape, -1, jnp.int32)
        done = jnp.zeros(h1.shape, bool)
        found_id, _ = jax.lax.fori_loop(0, probes, body, (found_id, done))
        out_ref[:] = found_id

    def probe(table, h1, h2, h3):
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(h1.shape, jnp.int32),
            interpret=interpret,
        )(table, h1, h2, h3)

    return probe


def make_loc_table_builder(f_cap: int, cap_l: int,
                           interpret: bool | None = None):
    """Hash-table build+probe for the batch kernel's location dedup:
    ``build(kpid, khi, klo, base) -> (slot, tpid, thi, tlo)``.

    Every lane carries one (pid, hi, lo) key (dead lanes: pid ==
    U32_MAX) and its probe base hash. The claim loop is deterministic
    (min-lane arbitration on empty slots) and exact (full 96-bit key
    compare — a base-hash collision only lengthens a chain). ``slot`` is
    -1 for dead lanes AND for lanes that could not place within the
    iteration bound (table effectively full) — the caller treats any
    live -1 as table overflow and retries with a doubled cap, exactly
    like the sort path's l_cap retry."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    from parca_agent_tpu.runtime import device_telemetry as dtel

    if interpret is None:
        interpret = default_interpret()
    dtel.note_backend("loc_dedup", interpret=interpret)
    # Any unplaced lane advances at least once per two iterations (one
    # iteration may be spent re-reading a slot a claim winner just
    # filled), so 2*cap_l + 2 bounds every terminating run; a genuinely
    # full table exits here with live -1 slots for the caller's retry.
    iter_cap = 2 * cap_l + 2

    def kernel(kpid_ref, khi_ref, klo_ref, base_ref,
               slot_ref, tpid_ref, thi_ref, tlo_ref):
        # Built inside the kernel (captured jnp constants are rejected
        # by pallas_call).
        u32max = jnp.uint32(_U32_MAX)
        mask = jnp.uint32(cap_l - 1)
        kpid = kpid_ref[:]
        khi = khi_ref[:]
        klo = klo_ref[:]
        base = base_ref[:]
        lane = jnp.arange(f_cap, dtype=jnp.int32)
        live = kpid != u32max

        def cond(st):
            it, _pos, placed, _slot, _tp, _th, _tl = st
            return (~placed.all()) & (it < iter_cap)

        def body(st):
            it, pos, placed, slot, tpid, thi, tlo = st
            occ_pid = tpid[pos]
            occ = occ_pid != u32max
            match = occ & (occ_pid == kpid) & (thi[pos] == khi) \
                & (tlo[pos] == klo)
            newly = match & ~placed
            slot = jnp.where(newly, pos, slot)
            placed = placed | newly
            # Empty slot: claim it. Min-lane arbitration makes insertion
            # deterministic; losers re-read the slot next iteration (the
            # winner may hold THEIR key) instead of advancing.
            want = ~placed & ~occ
            claim = jnp.full((cap_l,), f_cap, jnp.int32).at[
                jnp.where(want, pos, cap_l)].min(lane, mode="drop")
            won = want & (claim[pos] == lane)
            wtgt = jnp.where(won, pos, cap_l)
            tpid = tpid.at[wtgt].set(kpid, mode="drop")
            thi = thi.at[wtgt].set(khi, mode="drop")
            tlo = tlo.at[wtgt].set(klo, mode="drop")
            slot = jnp.where(won, pos, slot)
            placed = placed | won
            # Advance ONLY past an occupied mismatch (linear chain).
            adv = ~placed & occ & ~match
            pos = jnp.where(adv, (pos + 1) & jnp.int32(cap_l - 1), pos)
            return it + 1, pos, placed, slot, tpid, thi, tlo

        st0 = (
            jnp.int32(0),
            (base & mask).astype(jnp.int32),
            ~live,
            jnp.full((f_cap,), -1, jnp.int32),
            jnp.full((cap_l,), u32max),
            jnp.zeros((cap_l,), jnp.uint32),
            jnp.zeros((cap_l,), jnp.uint32),
        )
        _, _, _, slot, tpid, thi, tlo = jax.lax.while_loop(cond, body, st0)
        slot_ref[:] = slot
        tpid_ref[:] = tpid
        thi_ref[:] = thi
        tlo_ref[:] = tlo

    def build(kpid, khi, klo, base):
        return pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((f_cap,), jnp.int32),
                jax.ShapeDtypeStruct((cap_l,), jnp.uint32),
                jax.ShapeDtypeStruct((cap_l,), jnp.uint32),
                jax.ShapeDtypeStruct((cap_l,), jnp.uint32),
            ),
            interpret=interpret,
        )(kpid, khi, klo, base)

    return build
